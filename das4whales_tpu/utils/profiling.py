"""Profiling and progress surfaces.

The reference's only observability is tqdm bars around hot channel loops
(detect.py:163,191,270,705; SURVEY.md §5.1). Those loops are gone (they
are single XLA programs here), so the equivalents are: real device
profiles via ``jax.profiler`` traces, named trace annotations for the
pipeline stages, a wall-clock timer that accounts for async dispatch, and
a progress wrapper for the remaining host-side loops (files in a
campaign, channels exported, ...).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator

import jax


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a jax.profiler trace viewable in TensorBoard/Perfetto."""
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span that shows up on the device timeline (use around pipeline
    stages inside a step)."""
    return jax.profiler.TraceAnnotation(name)


def block_and_time(fn, *args, repeats: int = 3, **kwargs):
    """Best-of-``repeats`` wall time of ``fn(*args)`` with the result tree
    blocked to completion (JAX dispatch is async; un-blocked timing lies).

    Returns ``(best_seconds, result)``; the warm-up (compile) call is
    excluded. Delegates to THE one timing definition,
    ``telemetry.trace.timed_best`` (ISSUE 11) — each measured repeat is
    a ``timed`` span when tracing is on."""
    from ..telemetry import trace as _trace

    return _trace.timed_best(
        (lambda *a: fn(*a, **kwargs)) if kwargs else fn,
        *args, repeats=repeats,
    )


@dataclass
class StageTimer:
    """Accumulates named wall-clock spans across a run (host-side)."""

    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def report(self) -> str:
        lines = [
            f"  {name:<28s} {self.totals[name]:8.3f} s  (x{self.counts[name]})"
            for name in sorted(self.totals, key=self.totals.get, reverse=True)
        ]
        return "\n".join(lines)


def progress(iterable: Iterable, desc: str | None = None, total: int | None = None) -> Iterator:
    """DEPRECATED alias of ``telemetry.progress.progress`` — import from
    there. The old no-tqdm fallback here returned a bare ``iter()``,
    dropping ``total``/``desc`` and ``len()`` (the ISSUE 11 satellite);
    the telemetry version preserves them and records a ``progress`` span
    when tracing is on."""
    import warnings

    warnings.warn(
        "das4whales_tpu.utils.profiling.progress is deprecated; use "
        "das4whales_tpu.telemetry.progress.progress",
        DeprecationWarning, stacklevel=2,
    )
    from ..telemetry.progress import progress as _progress

    return _progress(iterable, desc=desc, total=total)
