"""Cross-cutting utilities: audio export, profiling/progress, logging,
design checkpointing."""

from . import artifacts, audio, checkpoint, locks, log, profiling, views  # noqa: F401
from .artifacts import append_record, atomic_bytes, atomic_json, read_records  # noqa: F401
from .audio import export_audio, read_audio  # noqa: F401
from .checkpoint import load_design, register_design, save_design  # noqa: F401
from .log import get_logger, log_metadata  # noqa: F401
from ..telemetry.progress import progress  # noqa: F401
from .profiling import StageTimer, annotate, block_and_time, device_trace  # noqa: F401
