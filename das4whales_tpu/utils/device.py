"""Accelerator probing and safe CPU-mesh fallback.

This image's ``sitecustomize`` registers an experimental TPU platform at
interpreter start that can hang ``jax.devices()`` indefinitely when the
tunnel is wedged. Every entry point that must not hang (the benchmark,
the driver's multi-chip dry run) probes the backend in a subprocess with
a timeout first, and falls back to a virtual CPU host mesh — forcing the
platform through the live config, because the ``JAX_PLATFORMS`` env var
alone is applied too late under that sitecustomize.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "jnp.ones((8, 8)).sum().block_until_ready();"
    "print(len(jax.devices()))"
)


def probe_backend(timeout_s: float) -> int:
    """Number of devices the default JAX backend exposes, or 0 if it fails
    to initialize and run one op within ``timeout_s``. Probed in a
    subprocess so a wedged accelerator cannot hang the caller."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            timeout=timeout_s, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            return 0
        # parse the last stdout token: runtimes/plugins may print banners
        # before our count, and a healthy backend must not be mistaken for
        # a dead one over stray output
        tokens = proc.stdout.split()
        return int(tokens[-1]) if tokens else 0
    except (subprocess.TimeoutExpired, ValueError):
        return 0


#: Raised CPU rendezvous timeouts for virtual-mesh runs (see
#: force_cpu_host_devices). Not every jaxlib build knows these flags, and
#: XLA hard-aborts the whole process on an unknown XLA_FLAGS entry, so
#: they are probed in a subprocess before first use.
_CPU_TIMEOUT_FLAGS = (
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=120",
    "--xla_cpu_collective_call_terminate_timeout_seconds=1200",
)

_TIMEOUT_FLAGS_ENV = "_DAS_XLA_CPU_TIMEOUT_FLAGS"


def _supported_cpu_timeout_flags(timeout_s: float = 60.0) -> tuple:
    """The subset of :data:`_CPU_TIMEOUT_FLAGS` this jaxlib accepts —
    all or nothing, decided by one subprocess probe (cached in the
    environment so nested subprocesses and repeat callers skip it)."""
    cached = os.environ.get(_TIMEOUT_FLAGS_ENV)
    if cached is not None:
        return tuple(f for f in cached.split() if f)
    env = dict(os.environ,
               XLA_FLAGS=" ".join(_CPU_TIMEOUT_FLAGS), JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, timeout=timeout_s, capture_output=True,
        )
        flags = _CPU_TIMEOUT_FLAGS if proc.returncode == 0 else ()
    except subprocess.TimeoutExpired:
        flags = ()
    os.environ[_TIMEOUT_FLAGS_ENV] = " ".join(flags)
    return flags


def force_cpu_host_devices(n_devices: int) -> None:
    """Point this process at a virtual CPU mesh of AT LEAST ``n_devices``.

    Must run before the first JAX backend use. A stale smaller
    ``--xla_force_host_platform_device_count`` flag is raised to
    ``n_devices`` (it would silently cap the mesh), but a LARGER
    pre-set count is kept: a caller that only needs one device (the
    bench fallback) must not collapse a deliberately requested 8-device
    mesh (the multi-chip dry run, tests/conftest.py).
    """
    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    count = max(n_devices, int(m.group(1)) if m else 0)
    flag = f"--xla_force_host_platform_device_count={count}"
    if m:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    # N virtual devices share ONE core, so a device thread can
    # legitimately take minutes of serialized compute between
    # collectives; XLA's CPU rendezvous would hard-abort the process
    # after 40 s ("Termination timeout ... Exiting to ensure a
    # consistent program state" — observed killing the canonical-shape
    # long-record certification). Raise both rendezvous timeouts for
    # every virtual-mesh run; real multi-host backends are unaffected.
    # Only builds that accept the flags get them — an unknown XLA_FLAGS
    # entry is itself a hard abort at backend init.
    for tflag in _supported_cpu_timeout_flags():
        if tflag.split("=")[0] not in flags:
            flags = (flags + " " + tflag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")


def ensure_devices(n_devices: int, probe_timeout_s: float | None = None) -> None:
    """Guarantee ``jax.devices()`` will return >= n_devices working devices,
    falling back to a virtual CPU host mesh whenever the default backend is
    unreachable or exposes fewer than ``n_devices`` real chips."""
    import jax

    if probe_timeout_s is None:
        probe_timeout_s = float(os.environ.get("DAS_PROBE_TIMEOUT", 30.0))
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        need_cpu = True  # explicit CPU request still needs enough host devices
    else:
        need_cpu = probe_backend(probe_timeout_s) < n_devices

    if need_cpu:
        force_cpu_host_devices(n_devices)

    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(jax.devices())} "
            f"on platform {jax.devices()[0].platform}"
        )
