"""AOT memory preflight: compile-time HBM accounting for campaign shapes.

The round-2 bench OOM (tests/test_memory_budget.py) established the
pattern: ``jit(fn).lower(avals).compile().memory_analysis()`` prices a
program's device footprint BEFORE the first dispatch. This module turns
that test-only pattern into campaign machinery — the batched runner
(``workflows.campaign.run_campaign_batched(preflight=True)``) prices
every candidate ``(bucket, B)`` batched program against the SAME
``DAS_HBM_BUDGET_GB`` budget the detector's monolithic-vs-tiled router
uses (``config.hbm_budget_bytes``), starts each bucket at the largest
batch that fits, and skips shapes that fit at no rung — so the elastic
downshift ladder (docs/ROBUSTNESS.md "Resource ladder") becomes the
recovery path for *surprises*, not the scheduler for *known* overflows.

Caveat (same as tests/test_memory_budget.py): on the CPU backend the
numbers come from CPU buffer assignment — a lower-bound heuristic for
the TPU footprint, not a reproduction of it. On a real TPU backend the
analysis prices the actual TPU executable.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Sequence

import jax

__all__ = [
    "MemoryStats",
    "ProgramAnalysis",
    "aot_memory_stats",
    "aot_program_analysis",
    "batched_program_analysis",
    "batched_program_memory",
    "max_fitting_batch",
]


@dataclass(frozen=True)
class MemoryStats:
    """One compiled program's static device-memory footprint (bytes),
    from ``compiled.memory_analysis()``. ``peak`` (temps + outputs) is
    the routing/preflight figure — argument buffers are priced
    separately because campaign inputs (the slab) are alive regardless
    of which program consumes them."""

    temp_bytes: int
    output_bytes: int
    argument_bytes: int
    generated_code_bytes: int

    @property
    def peak(self) -> int:
        return self.temp_bytes + self.output_bytes

    @property
    def total(self) -> int:
        return self.peak + self.argument_bytes + self.generated_code_bytes

    def fits(self, budget_bytes: int) -> bool:
        return self.peak < int(budget_bytes)


def _analysis_int(analysis, name: str) -> int:
    """Best-effort field read: ``memory_analysis()`` fields vary across
    jaxlib versions/backends; absent ones read 0."""
    try:
        return int(getattr(analysis, name))
    except (AttributeError, TypeError, ValueError):
        return 0


@dataclass(frozen=True)
class ProgramAnalysis:
    """One AOT compile's full device-truth record (ISSUE 14): the
    :class:`MemoryStats` footprint (None where ``memory_analysis()`` is
    unsupported), XLA's own ``cost_analysis()`` totals, and the
    measured compile wall — everything ``telemetry.costs`` needs for a
    cost card, captured at the one ``lower().compile()`` boundary the
    memory preflight already crosses."""

    memory: MemoryStats | None
    flops: float
    bytes_accessed: float
    transcendentals: float
    compile_seconds: float
    #: IR text pair for the program-contract audit (analysis/programs.py,
    #: ISSUE 16) — populated only under ``capture_ir=True`` so the plain
    #: preflight never holds megabytes of HLO text per priced rung
    jaxpr_text: str | None = None
    hlo_text: str | None = None


def _cost_float(cost, name: str) -> float:
    """Best-effort ``cost_analysis()`` field: absent keys read 0 (the
    dict keys vary across jaxlib versions/backends)."""
    try:
        return float(cost.get(name, 0.0) or 0.0)
    except (AttributeError, TypeError, ValueError):
        return 0.0


def aot_program_analysis(fn, *avals, static_kwargs=None,
                         capture_ir: bool = False) -> ProgramAnalysis | None:
    """AOT-compile ``fn`` at ``avals`` and return its full
    :class:`ProgramAnalysis` — or None where the backend/jaxlib cannot
    even compile it. ``memory_analysis()``/``cost_analysis()`` fields
    that this jaxlib does not expose read as None/0 rather than
    failing: a partial card is still device truth.

    ``fn`` may already be a ``jax.jit`` wrapper (lowered as-is) or a
    plain callable (jitted here with ``static_kwargs`` as
    ``static_argnames`` values). ``capture_ir=True`` additionally
    records the jaxpr and compiled-HLO text for the program-contract
    audit — the SAME trace → lower → compile crossing, zero extra
    compiles (the analysis side is free; only the text retention
    costs, which is why it is opt-in).
    """
    jaxpr_text = hlo_text = None
    try:
        # AOT pricing only: lowered+compiled for the analyses, never
        # dispatched — no hot-path compile cache to miss
        jitted = fn if hasattr(fn, "lower") else jax.jit(  # daslint: allow[R2]
            fn, static_argnames=tuple(static_kwargs or ())
        )
        t0 = time.perf_counter()
        if capture_ir and hasattr(jitted, "trace"):
            traced = jitted.trace(*avals, **(static_kwargs or {}))
            try:
                jaxpr_text = str(traced.jaxpr)
            except Exception:  # noqa: BLE001 — text capture is best-effort
                jaxpr_text = None
            lowered = traced.lower()
        else:
            lowered = jitted.lower(*avals, **(static_kwargs or {}))
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        if capture_ir:
            try:
                hlo_text = compiled.as_text()
            except Exception:  # noqa: BLE001
                hlo_text = None
    except Exception:  # noqa: BLE001 — unsupported backend/jaxlib: no gate
        return None
    try:
        analysis = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        analysis = None
    memory = None
    if analysis is not None:
        memory = MemoryStats(
            temp_bytes=_analysis_int(analysis, "temp_size_in_bytes"),
            output_bytes=_analysis_int(analysis, "output_size_in_bytes"),
            argument_bytes=_analysis_int(analysis, "argument_size_in_bytes"),
            generated_code_bytes=_analysis_int(
                analysis, "generated_code_size_in_bytes"),
        )
    try:
        cost = compiled.cost_analysis()
        # older jaxlibs return a one-element list of dicts
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
    except Exception:  # noqa: BLE001
        cost = {}
    return ProgramAnalysis(
        memory=memory,
        flops=_cost_float(cost, "flops"),
        bytes_accessed=_cost_float(cost, "bytes accessed"),
        transcendentals=_cost_float(cost, "transcendentals"),
        compile_seconds=compile_s,
        jaxpr_text=jaxpr_text,
        hlo_text=hlo_text,
    )


def aot_memory_stats(fn, *avals, static_kwargs=None) -> MemoryStats | None:
    """AOT-compile ``fn`` at ``avals`` (``jax.ShapeDtypeStruct``\\ s) and
    return its :class:`MemoryStats` — or None where this jaxlib/backend
    does not support ``memory_analysis()`` (callers proceed unpreflighted,
    trusting the downshift ladder). The memory half of
    :func:`aot_program_analysis` (one compile, one definition)."""
    an = aot_program_analysis(fn, *avals, static_kwargs=static_kwargs)
    return an.memory if an is not None else None


def _aval_of(arr) -> jax.ShapeDtypeStruct:
    import numpy as np

    a = np.asarray(arr) if not hasattr(arr, "dtype") else arr
    return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)


def _batched_program_spec(bdet, batch: int, stack_dtype, *,
                          with_health: bool = False,
                          health_clip: float | None = None,
                          donate: bool = False):
    """The batched program's AOT pricing spec — ``(jitted, avals,
    static_kwargs)`` — shared by :func:`batched_program_memory` (the
    preflight), :func:`batched_program_analysis` (the cost
    observatory), and the program-contract audit, so the three can
    never price different programs. ``donate=True`` prices the
    slab-donating spelling (``donate_argnums=(0,)``) — the R12
    donation-effectiveness audit inspects its alias table.

    Family facades (``parallel.batch._BatchedFamilyDetector`` —
    spectro/gabor/learned) carry their own ``program_spec``; dispatching
    to it here keeps preflight, cost cards, and the contract audit on
    the SAME ``lower().compile()`` boundary for every family. The
    matched-filter spelling below stays inline because its spec reads a
    dozen detector internals this module already documents."""
    if hasattr(bdet, "program_spec"):
        return bdet.program_spec(
            batch, stack_dtype, with_health=with_health,
            health_clip=health_clip, donate=donate,
        )
    import jax.numpy as jnp
    import numpy as np

    from ..ops import peaks as peak_ops
    from ..parallel.batch import _STATIC, _batched_body

    det = bdet.det
    C, T = det.design.trace_shape
    nT = det.design.templates.shape[0]
    cap = int(min(C * det.max_peaks, det.pick_pack_cap))
    tile = det.effective_channel_tile if det._route() == "tiled" else None
    program_mask = getattr(det, "_program_mask_dev", det._mask_band_dev)
    mf_fused = getattr(det, "_mf_fused_dev", None)
    compute_dtype = det._mask_band_dev.dtype
    avals = (
        jax.ShapeDtypeStruct((int(batch), C, T), np.dtype(stack_dtype)),
        _aval_of(program_mask),
        _aval_of(det._gain_dev),
        _aval_of(det._templates_true),
        _aval_of(det._template_mu),
        _aval_of(det._template_scale),
        jax.ShapeDtypeStruct((nT,), compute_dtype),       # thr_in
        _aval_of(det._cond_scale),
        jax.ShapeDtypeStruct((int(batch),), jnp.int32),   # n_real
        # fk_dft: the DFT-matmul pair is program input on the matmul
        # f-k engine — priced so the preflight sees its residency too
        (tuple(_aval_of(a) for a in det._fk_dft_dev)
         if getattr(det, "_fk_dft_dev", None) is not None else None),
        # the bank's per-template threshold-factor vector: the T axis
        # is part of the priced program (a T=32 bank's correlate /
        # envelope / pick temps all scale with it)
        jax.ShapeDtypeStruct((nT,), compute_dtype),       # thr_factors
        # mf_fused: the tap-folded engine's (folded_taps, tcum) pair —
        # priced so the preflight sees the widened-tap operand residency
        (tuple(_aval_of(a) for a in mf_fused)
         if mf_fused is not None else None),
    )
    static = dict(
        band_lo=det._band_lo, band_hi=det._band_hi,
        bp_padlen=det.design.bp_padlen, pad_rows=det.fk_pad_rows,
        staged_bp=getattr(det, "_program_staged_bp",
                          not det.fused_bandpass), tile=tile,
        max_peaks=det.max_peaks, capacity=cap, use_threshold=False,
        pick_method=peak_ops.escalation_method(det.max_peaks,
                                               det.max_peaks),
        condition=det.wire == "raw", serial=bdet.serial,
        with_health=with_health,
        mf_engine=getattr(det, "mf_engine", "fft"),
        fk_engine=getattr(det, "fk_engine", "fft"),
        thr_scope=getattr(det, "threshold_scope", "global"),
        fir_half=getattr(det, "_mf_fir_half", 0),
    )
    kwargs = {k: v for k, v in static.items() if k in _STATIC}
    if with_health and health_clip is not None:
        kwargs["health_clip"] = jnp.float32(health_clip)
    # a dedicated jit wrapper (never dispatched): .lower() on the live
    # batched_detect_picks_program would be equivalent, but keeping the
    # preflight's lowering separate means a preflight failure can never
    # poison the hot path's jit cache
    jitted = jax.jit(  # daslint: allow[R2] AOT pricing only — see aot_memory_stats
        _batched_body, static_argnames=_STATIC,
        donate_argnums=((0,) if donate else ()),
    )
    return jitted, avals, kwargs


def batched_program_memory(
    bdet, batch: int, stack_dtype, *, with_health: bool = False,
    health_clip: float | None = None,
) -> MemoryStats | None:
    """Price the batched detection program (``parallel.batch``) for
    ``bdet`` (a ``BatchedMatchedFilterDetector``) at batch size
    ``batch`` and wire dtype ``stack_dtype`` — the preflight unit the
    batched campaign compares against ``config.hbm_budget_bytes()``.

    Prices the FULL-CAPACITY (escalation) variant: the K0 attempt is
    strictly smaller, so a fitting full program certifies both.
    """
    jitted, avals, kwargs = _batched_program_spec(
        bdet, batch, stack_dtype, with_health=with_health,
        health_clip=health_clip,
    )
    return aot_memory_stats(jitted, *avals, static_kwargs=kwargs)


def batched_program_analysis(
    bdet, batch: int, stack_dtype, *, with_health: bool = False,
    health_clip: float | None = None, capture_ir: bool = False,
    donate: bool = False,
) -> ProgramAnalysis | None:
    """:func:`batched_program_memory`'s full-record twin: the SAME
    priced program's :class:`ProgramAnalysis` (memory + XLA cost
    totals + compile wall) for the cost observatory
    (``telemetry.costs.capture_batched``). ``capture_ir`` adds the
    jaxpr/HLO text pair for the program-contract audit; ``donate``
    prices the slab-donating spelling (the R12 probe)."""
    jitted, avals, kwargs = _batched_program_spec(
        bdet, batch, stack_dtype, with_health=with_health,
        health_clip=health_clip, donate=donate,
    )
    return aot_program_analysis(jitted, *avals, static_kwargs=kwargs,
                                capture_ir=capture_ir)


def first_fitting(price, candidates, budget_bytes: int):
    """THE preflight fitting policy, in one place: walk ``candidates``
    in the given (ladder) order and return the first whose priced
    program fits ``budget_bytes`` (``stats.peak < budget``). A
    candidate whose pricing is unsupported (None) is treated as fitting
    — no gate is better than a false one; the downshift ladder still
    protects the run. Returns None when every candidate is priced AND
    over budget. ``price(candidate) -> MemoryStats | None``; candidates
    may be batch sizes, rung tuples, or any key the pricer understands
    (the batched campaign walks interleaved ``("batched", B)`` /
    ``("bank", B)`` rungs through here)."""
    for cand in candidates:
        stats = price(cand)
        if stats is None or stats.fits(budget_bytes):
            return cand
    return None


def max_fitting_batch(
    price: Callable[[int], MemoryStats | None],
    candidates: Sequence[int],
    budget_bytes: int,
) -> int | None:
    """The largest batch in ``candidates`` whose priced program fits
    ``budget_bytes`` — :func:`first_fitting` over the batch sizes,
    largest first (the pre-bank preflight chooser, kept for callers
    without a bank axis)."""
    return first_fitting(
        price, sorted({int(c) for c in candidates}, reverse=True),
        budget_bytes,
    )
