"""Traced locks: the runtime half of the concurrency hazard pass.

The static rules (R8–R10, ``analysis/concurrency.py``) prove what they
can from the AST; this module observes what actually happens at run
time. :class:`TracedLock` wraps a ``threading.Lock`` with a NAME and
three behaviors:

* **lock-order recording** — every acquisition taken while the thread
  already holds other traced locks adds ``held -> acquired`` edges to a
  process-wide graph keyed by lock name (a lock *class*, not an
  instance: two tenants' ring locks share the node ``ring``, so an
  AB/BA nesting between any two instances of two classes is caught).
  An edge that closes a cycle is recorded as an INVERSION — the static
  R9 pass's dynamic complement, asserted empty by the ``race_guard``
  fixture (``analysis/concurrency_runtime.py``).
* **contention metrics** — acquire wait and hold duration land in the
  ``das_lock_wait_seconds{name}`` / ``das_lock_held_seconds{name}``
  histograms (``telemetry/metrics.py``), so the service's ``/metrics``
  exposition shows WHERE serving threads queue (docs/OBSERVABILITY.md;
  the TPU_RUNBOOK "lock wait p95 is climbing" triage reads these).
* **yield injection** — an optional pre-acquire hook (installed by
  ``race_guard`` with a seeded RNG) that sleeps(0) at instrumented
  acquisitions, shaking thread interleavings so seeded tests explore
  schedules the happy path never hits.

``new_lock(name)`` is the factory the service stack uses for every
shared-state lock (``service/``, the manifest line index). The
telemetry registry's own lock stays a plain ``threading.Lock`` — it is
the hottest lock in the process and the histograms write through it,
so tracing it would recurse.

A :class:`TracedLock` is Condition-compatible: ``threading.Condition(
new_lock("ring"))`` routes the condition's acquire/release (including
the release/re-acquire inside ``wait``) through the tracing, so held
time excludes the wait — exactly the semantics a contention dashboard
wants.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..telemetry import metrics

__all__ = [
    "TracedLock", "find_cycle", "inversions", "new_lock", "order_edges",
    "reset_order_graph", "set_yield",
]

#: lock waits/holds run microseconds..seconds — finer buckets than the
#: span-flavored defaults (a 1 ms floor would hide all healthy waits in
#: the first bucket).
_LOCK_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5,
                 1.0, 5.0, 30.0)

_h_wait = metrics.histogram(
    "das_lock_wait_seconds",
    "seconds spent waiting to acquire a traced lock, by lock name "
    "(contention: a climbing p95 means serving threads queue here)",
    ("name",), buckets=_LOCK_BUCKETS,
)
_h_held = metrics.histogram(
    "das_lock_held_seconds",
    "seconds a traced lock was held per acquisition, by lock name "
    "(long holds under load are the blocking-under-lock smell R9 "
    "hunts statically)",
    ("name",), buckets=_LOCK_BUCKETS,
)

# -- the process-wide acquisition-order graph --------------------------------

_graph_lock = threading.Lock()     # plain: guards the graph itself
_edges: Dict[str, Set[str]] = {}   # held name -> {acquired name}
_edge_sites: Dict[Tuple[str, str], str] = {}   # edge -> first thread seen
_inversions: List[Dict] = []       # recorded cycles (never trimmed)

_tls = threading.local()           # per-thread held-lock stack


def _held_stack() -> List[List]:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


# yield-injection hook (race_guard): called before every traced acquire
_yield_hook: Optional[Callable[[], None]] = None


def set_yield(hook: Optional[Callable[[], None]]) -> None:
    """Install (or clear, with None) the pre-acquire yield hook."""
    global _yield_hook
    _yield_hook = hook


def _reach(src: str, dst: str, edges: Dict[str, Set[str]],
           path: List[str]) -> Optional[List[str]]:
    """DFS: a path src -> ... -> dst through ``edges``, or None."""
    if src == dst:
        return path + [dst]
    for nxt in edges.get(src, ()):
        if nxt in path:
            continue
        found = _reach(nxt, dst, edges, path + [src])
        if found is not None:
            return found
    return None


def _note_acquire(name: str, held_names: List[str]) -> None:
    """Record held->name edges; an edge closing a cycle is an inversion."""
    tname = threading.current_thread().name
    with _graph_lock:
        for h in held_names:
            if h == name:
                # same lock CLASS nested (two ring instances inside each
                # other): an AB/BA hazard between any two instances —
                # recorded as a self-cycle inversion
                _inversions.append({
                    "cycle": [name, name], "thread": tname,
                    "note": "nested acquisition of two instances of the "
                            f"same lock class {name!r}",
                })
                continue
            if name not in _edges.get(h, ()):
                # would h -> name close a cycle? (name already reaches h)
                cyc = _reach(name, h, _edges, [])
                if cyc is not None:
                    _inversions.append({
                        "cycle": cyc + [name], "thread": tname,
                        "note": f"acquiring {name!r} while holding {h!r} "
                                f"inverts the established order "
                                f"{' -> '.join(cyc)}",
                    })
                _edges.setdefault(h, set()).add(name)
                _edge_sites.setdefault((h, name), tname)


def order_edges() -> Dict[str, Tuple[str, ...]]:
    """The observed acquisition-order graph (name -> successors)."""
    with _graph_lock:
        return {k: tuple(sorted(v)) for k, v in _edges.items()}


def inversions() -> List[Dict]:
    """Every lock-order inversion recorded since the last reset."""
    with _graph_lock:
        return [dict(i) for i in _inversions]


def reset_order_graph() -> None:
    """Clear the graph and inversion log (race_guard entry / tests)."""
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()
        _inversions.clear()


def find_cycle() -> Optional[List[str]]:
    """A cycle in the current graph, if one exists (diagnostics)."""
    with _graph_lock:
        edges = {k: set(v) for k, v in _edges.items()}
    for start in edges:
        for nxt in edges.get(start, ()):
            path = _reach(nxt, start, edges, [])
            if path is not None:
                return [start] + path
    return None


class TracedLock:
    """A named ``threading.Lock`` wrapper: order-graph recording,
    wait/held histograms, and the race_guard yield point. Supports the
    context-manager protocol and the ``acquire``/``release``/``locked``
    surface ``threading.Condition`` needs."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        hook = _yield_hook
        if hook is not None:
            hook()
        held = _held_stack()
        t0 = time.perf_counter()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            t1 = time.perf_counter()
            _h_wait.observe(t1 - t0, name=self.name)
            if held:
                _note_acquire(self.name, [e[0] for e in held])
            held.append([self.name, t1])
        return ok

    def release(self) -> None:
        held = _held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                _, t_acq = held.pop(i)
                _h_held.observe(time.perf_counter() - t_acq, name=self.name)
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"TracedLock({self.name!r}, locked={self.locked()})"


def new_lock(name: str) -> TracedLock:
    """The service stack's lock factory: every shared-state lock gets a
    NAME so metrics, traces and the order graph attribute contention to
    a component instead of an anonymous ``<locked _thread.lock>``."""
    return TracedLock(name)
