"""The one durable-write layer: crash-only artifact persistence.

Every durable artifact this package writes — picks ``.npz``, manifest /
event ledger lines, ``cost_cards.json`` / ``quality.json`` /
``trace.json`` / ``summary.json`` exports, design checkpoints — goes
through this module, so the whole repo has exactly ONE implementation
of each durability idiom (daslint R14 enforces the funnel statically):

* :func:`atomic_file` / :func:`atomic_bytes` / :func:`atomic_json` —
  write-then-rename: tmp sibling (``<path>.tmp-<pid>``) + ``fsync`` +
  ``os.replace`` + best-effort directory fsync. A crash at ANY
  instruction leaves either the old artifact or the new one, never a
  torn file; at worst an orphan tmp remains for the startup sweep /
  ``fsck`` (generalizes the picks writer that lived in
  ``workflows.campaign._save_picks``).
* :func:`append_record` — append-only JSON-lines ledger write with an
  optional per-line CRC32 suffix (``DAS_MANIFEST_CRC=1``; OFF by
  default so manifests stay bitwise-identical to the pre-durability
  format) and a bounded fsync policy (``DAS_APPEND_FSYNC=
  bounded|always|never``, default ``bounded``: at most one fsync per
  path per ``DAS_APPEND_FSYNC_S`` seconds — durability without a
  syscall per record). Failed appends truncate back to the record
  boundary, so an in-process write error (ENOSPC mid-line) cannot tear
  the ledger; only SIGKILL can, and only at the tail.
* :func:`parse_record` / :func:`read_records` / :func:`scan_ledger` —
  the torn-tail-tolerant, checksum-verifying reader shared by
  ``_load_settled``, ``summarize_campaign``, the service NDJSON
  long-poll and ``fsck``. Accepts plain and CRC-suffixed lines
  interchangeably; a corrupt interior line or torn tail is skipped (and
  reported), never raised.
* :func:`sweep_orphan_tmps` — find/remove ``*.tmp-<pid>`` residue of a
  kill between write and rename.

Each boundary announces itself to :mod:`..crashpoints` (one tuple
compare when disarmed), which is how the SIGKILL crash-point matrix in
``tests/test_durability.py`` proves the crash-only claim rather than
asserting it.

Stdlib-only (json/os/zlib/threading): importable from the lightest
contexts (``fsck`` CLI, service API thread) without touching jax.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from .. import crashpoints

#: Infix of every atomic-write tmp sibling; the orphan sweep and fsck
#: key on it.
TMP_MARKER = ".tmp-"

#: Separator between the JSON body and the CRC32 suffix of a checksummed
#: ledger line. A raw TAB cannot appear inside ``json.dumps`` output
#: (control characters are escaped), so ``rsplit`` on the LAST tab is
#: unambiguous.
CRC_TAG = "\t#crc32:"


def _tmp_path(path: str) -> str:
    return f"{path}{TMP_MARKER}{os.getpid()}"


def _fsync_dir(path: str) -> None:
    """Best-effort fsync of ``path``'s containing directory — the step
    that makes the *rename itself* durable. Best-effort because some
    filesystems (and all of Windows) refuse O_RDONLY directory fds."""
    dirname = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_file(path: str, mode: str = "wb") -> Iterator[Any]:
    """Yield a handle onto a tmp sibling of ``path``; on clean exit the
    data is fsynced, renamed over ``path``, and the directory entry is
    fsynced. On ANY failure — an exception from the body, an injected
    write fault, SIGKILL at any instruction — ``path`` is never
    partially written: either the old content survives or the new
    content is complete. The only possible residue is an orphan
    ``*.tmp-<pid>`` (swept at startup; ``fsck`` kind ``orphan-tmp``)."""
    crashpoints.hit("pre-write")
    tmp = _tmp_path(path)
    try:
        with open(tmp, mode) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        crashpoints.hit("post-tmp")
        crashpoints.hit("pre-rename")
        os.replace(tmp, path)
        crashpoints.hit("post-rename")
        crashpoints.hit("pre-dirsync")
        _fsync_dir(path)
    finally:
        if os.path.exists(tmp):
            with contextlib.suppress(OSError):
                os.unlink(tmp)


def atomic_bytes(path: str, data: bytes) -> str:
    """Durably replace ``path`` with ``data`` (see :func:`atomic_file`)."""
    with atomic_file(path, "wb") as fh:
        fh.write(data)
    return path


def atomic_json(path: str, payload: Any, indent: int | None = None) -> str:
    """Durably replace ``path`` with ``json.dumps(payload)`` — the
    byte-exact serialization the direct ``json.dump`` writers produced,
    so migrating an export site onto this layer changes no bytes."""
    return atomic_bytes(
        path, json.dumps(payload, indent=indent).encode("utf-8"))


# ------------------------------------------------------------- appends

def crc_enabled() -> bool:
    """Whether ledger lines get a CRC32 suffix (``DAS_MANIFEST_CRC=1``).
    Off by default: with it off every line is exactly
    ``json.dumps(rec) + "\\n"`` — bitwise-identical to the
    pre-durability manifest format."""
    return os.environ.get("DAS_MANIFEST_CRC", "") not in ("", "0", "false")


def _fsync_policy() -> str:
    pol = os.environ.get("DAS_APPEND_FSYNC", "bounded").strip() or "bounded"
    return pol if pol in ("always", "bounded", "never") else "bounded"


def _fsync_interval_s() -> float:
    try:
        return float(os.environ.get("DAS_APPEND_FSYNC_S", "0.5"))
    except ValueError:
        return 0.5


_append_lock = threading.Lock()
_last_fsync: Dict[str, float] = {}      # abspath -> monotonic stamp
_tail_checked: set = set()              # abspaths verified newline-clean


def _ensure_newline_tail(path: str) -> None:
    """Before this process's FIRST append to ``path``: if a previous
    unclean death left the file without a trailing newline, terminate
    the stranded line so the new record cannot concatenate onto it
    (which would corrupt BOTH records). Crash-only discipline: the torn
    half-line itself stays for the reader to skip / fsck to repair —
    this only guarantees record isolation."""
    apath = os.path.abspath(path)
    with _append_lock:
        if apath in _tail_checked:
            return
        _tail_checked.add(apath)
    try:
        size = os.path.getsize(path)
        if size == 0:
            return
        with open(path, "rb+") as fh:
            fh.seek(-1, os.SEEK_END)
            if fh.read(1) != b"\n":
                fh.write(b"\n")
    except OSError:
        pass


def format_record(record: Dict, crc: bool | None = None) -> str:
    """Serialize one ledger line (without the trailing newline):
    ``json.dumps(record)`` plus, when CRC is on, the
    ``\\t#crc32:<8 hex>`` suffix over the JSON bytes."""
    line = json.dumps(record)
    if crc is None:
        crc = crc_enabled()
    if crc:
        line = f"{line}{CRC_TAG}{zlib.crc32(line.encode('utf-8')):08x}"
    return line


def append_record(path: str, record: Dict, *,
                  crc: bool | None = None) -> None:
    """Append one record to the JSON-lines ledger at ``path``.

    Durability: the write is flushed to the OS every time; fsync
    follows the bounded policy (module docstring). Atomicity: an
    in-process write failure truncates back to the pre-append offset,
    so a raised ENOSPC/EIO cannot leave a torn line mid-file; SIGKILL
    can tear only the final line, which every reader tolerates and the
    startup check / fsck repairs."""
    _ensure_newline_tail(path)
    data = (format_record(record, crc) + "\n").encode("utf-8")
    with open(path, "ab") as fh:
        pos = fh.tell()
        try:
            if crashpoints.pending("append-mid-line"):
                half = max(1, len(data) // 2)
                fh.write(data[:half])
                fh.flush()
                crashpoints.hit("append-mid-line")
                fh.write(data[half:])
            else:
                fh.write(data)
            fh.flush()
            policy = _fsync_policy()
            if policy == "always":
                os.fsync(fh.fileno())
            elif policy == "bounded":
                apath, now = os.path.abspath(path), time.monotonic()
                with _append_lock:
                    due = (now - _last_fsync.get(apath, 0.0)
                           >= _fsync_interval_s())
                    if due:
                        _last_fsync[apath] = now
                if due:
                    os.fsync(fh.fileno())
        except Exception:
            # a failed append must not tear the ledger: rewind to the
            # record boundary (suppressed OSError: nothing more we can
            # do on a dead filesystem — the reader still tolerates it)
            with contextlib.suppress(OSError):
                fh.truncate(pos)
            raise


# -------------------------------------------------------------- readers

def parse_record(line: str) -> Tuple[Optional[Dict], str]:
    """Parse one ledger line into ``(record, verdict)``.

    Verdicts: ``"ok"`` (record is a dict), ``"blank"`` (skip silently),
    ``"crc-mismatch"`` (CRC suffix present but wrong — the body was
    altered), ``"unparseable"`` (torn / foreign / non-object line).
    Plain and CRC-suffixed lines are both accepted — readers never need
    to know whether the writer had ``DAS_MANIFEST_CRC`` on."""
    text = line.rstrip("\r\n")
    if not text.strip():
        return None, "blank"
    if "\t" in text:
        body, _, tag = text.rpartition("\t")
        if tag.startswith("#crc32:"):
            try:
                want = int(tag[len("#crc32:"):], 16)
            except ValueError:
                return None, "crc-mismatch"
            if zlib.crc32(body.encode("utf-8")) != want:
                return None, "crc-mismatch"
            text = body
    try:
        rec = json.loads(text)
    except json.JSONDecodeError:
        return None, "unparseable"
    if not isinstance(rec, dict):
        return None, "unparseable"
    return rec, "ok"


def read_records(path: str,
                 on_bad: Callable[[int, str, str], None] | None = None,
                 ) -> List[Dict]:
    """Read every parseable record from the ledger at ``path``.

    Torn-tail tolerant and checksum-verifying: a line that fails to
    parse (half-written tail of a killed run, CRC mismatch) is skipped
    — resume semantics degrade to "re-run that file", never "refuse to
    start". Each bad line is reported through ``on_bad(lineno, verdict,
    line)`` (1-based) for the caller to warn/count. Missing file: []."""
    records: List[Dict] = []
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            for lineno, line in enumerate(fh, 1):
                rec, verdict = parse_record(line)
                if rec is not None:
                    records.append(rec)
                elif verdict != "blank" and on_bad is not None:
                    on_bad(lineno, verdict, line)
    except FileNotFoundError:
        pass
    return records


@dataclass
class LedgerScan:
    """Byte-accurate scan of a ledger file (the fsck view): parsed
    records with their raw line bytes, corrupt interior lines, and the
    offset of a torn (newline-less) tail if one exists."""

    path: str
    size: int = 0
    #: (byte offset, raw line bytes incl. newline, parsed record)
    good: List[Tuple[int, bytes, Dict]] = field(default_factory=list)
    #: (byte offset, raw line bytes, verdict) for complete-but-corrupt
    #: lines (``crc-mismatch`` / ``unparseable``)
    bad: List[Tuple[int, bytes, str]] = field(default_factory=list)
    #: byte offset of an unterminated final segment that does NOT parse
    #: (the SIGKILL-mid-append residue); None when the tail is clean.
    torn_tail: Optional[int] = None

    @property
    def records(self) -> List[Dict]:
        return [rec for _, _, rec in self.good]


def scan_ledger(path: str) -> LedgerScan:
    """Scan ``path`` byte-accurately (see :class:`LedgerScan`). An
    unterminated final segment that still parses is counted as a good
    record (the data is complete; only its newline was lost — the
    append layer restores it before the next write)."""
    scan = LedgerScan(path=path)
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return scan
    scan.size = len(data)
    offset = 0
    while offset < len(data):
        nl = data.find(b"\n", offset)
        raw = data[offset:] if nl < 0 else data[offset:nl + 1]
        text = raw.decode("utf-8", errors="replace")
        rec, verdict = parse_record(text)
        if rec is not None:
            scan.good.append((offset, raw, rec))
        elif verdict != "blank":
            if nl < 0:
                scan.torn_tail = offset
            else:
                scan.bad.append((offset, raw, verdict))
        offset = len(data) if nl < 0 else nl + 1
    return scan


# ------------------------------------------------------------ tmp sweep

def sweep_orphan_tmps(root: str, remove: bool = True) -> List[str]:
    """Find (and by default unlink) ``*.tmp-<pid>`` residue under
    ``root`` — the footprint of a process killed between tmp write and
    rename. Safe at any time: a LIVE writer's tmp is renamed away
    atomically, and this sweep runs before any writer starts (campaign
    / tenant startup), so nothing racing can lose data."""
    found: List[str] = []
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            stem, sep, pid = name.rpartition(TMP_MARKER)
            if sep and stem and pid.isdigit():
                p = os.path.join(dirpath, name)
                found.append(p)
                if remove:
                    with contextlib.suppress(OSError):
                        os.unlink(p)
    return sorted(found)
