"""Checkpointing of design artifacts.

The reference recomputes filters/templates every run and its tutorial
explicitly motivates design-once/apply-many reuse across files
(tutorial.md:93; SURVEY.md §5.4). Design dataclasses here are flat bags of
numpy arrays + static Python fields, so checkpoints are a single ``.npz``:
array fields stored natively, static fields in an embedded JSON header.
No pickle — files are portable and safe to load.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

import numpy as np

from . import artifacts

_REGISTRY: Dict[str, Type] = {}


def register_design(cls: Type) -> Type:
    """Register a dataclass so checkpoints can name their type."""
    _REGISTRY[cls.__name__] = cls
    return cls


def _builtin(value):
    if isinstance(value, tuple):
        return list(value)
    if isinstance(value, (np.integer,)):
        return int(value)
    if isinstance(value, (np.floating,)):
        return float(value)
    return value


def save_design(path: str, design: Any) -> str:
    """Write a design dataclass to ``path`` (.npz). Returns the path."""
    if not dataclasses.is_dataclass(design):
        raise TypeError(f"save_design expects a dataclass, got {type(design)}")
    arrays = {}
    static: Dict[str, Any] = {}
    for f in dataclasses.fields(design):
        value = getattr(design, f.name)
        if isinstance(value, np.ndarray) or hasattr(value, "__array_namespace__") or (
            hasattr(value, "shape") and hasattr(value, "dtype")
        ):
            arrays[f.name] = np.asarray(value)
        else:
            static[f.name] = _builtin(value)
    header = json.dumps({"type": type(design).__name__, "static": static})
    if not path.endswith(".npz"):
        path += ".npz"   # np.savez(str) appended it; the durable writer
        # takes a file handle, so preserve that contract explicitly
    with artifacts.atomic_file(path, "wb") as fh:
        np.savez(fh, __header__=np.frombuffer(header.encode(),
                                              dtype=np.uint8), **arrays)
    return path


def load_design(path: str, cls: Type | None = None) -> Any:
    """Load a design checkpoint written by :func:`save_design`.

    ``cls`` overrides the registry lookup (needed only for unregistered
    types)."""
    with np.load(path) as data:
        header = json.loads(bytes(data["__header__"].tobytes()).decode())
        fields: Dict[str, Any] = dict(header["static"])
        for key in data.files:
            if key != "__header__":
                fields[key] = data[key]
    if cls is None:
        cls = _REGISTRY.get(header["type"])
        if cls is None:
            raise KeyError(
                f"design type {header['type']!r} is not registered; pass cls= explicitly")
    # dataclasses with tuple-typed fields get lists back from JSON; coerce
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in fields:
            # a field added after this checkpoint was written (e.g. the
            # template-bank threshold_factors/threshold_scope pair):
            # the dataclass default/__post_init__ reconstructs the
            # legacy value, so old artifacts keep loading
            continue
        value = fields[f.name]
        if isinstance(value, list):
            value = tuple(value)
        kwargs[f.name] = value
    return cls(**kwargs)
