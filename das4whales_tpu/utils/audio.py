"""Audio export of DAS channels.

The reference tutorial plays a filtered channel with
``IPython.display.Audio(data=trf_fk[idx, :], rate=fs*5)`` — deliberate 5x
time compression so 15-30 Hz fin-whale calls land in the audible band
(SURVEY.md §3.4). This module provides that capability as a file export
with no IPython/soundfile dependency: normalized 16-bit PCM WAV via the
stdlib ``wave`` module.
"""

from __future__ import annotations

import wave

import numpy as np


def channel_to_pcm16(channel, normalize: bool = True) -> np.ndarray:
    """Scale a strain channel to int16 PCM samples."""
    x = np.asarray(channel, dtype=np.float64)
    if normalize:
        peak = np.max(np.abs(x))
        if peak > 0:
            x = x / peak
    x = np.clip(x, -1.0, 1.0)
    return (x * 32767.0).astype(np.int16)


def export_audio(channel, fs: float, path: str, speed: float = 5.0,
                 normalize: bool = True) -> str:
    """Write one channel as a WAV file at ``fs * speed`` playback rate.

    ``speed=5`` reproduces the tutorial's audible time compression.
    Returns the path written.
    """
    pcm = channel_to_pcm16(channel, normalize=normalize)
    rate = int(round(fs * speed))
    with wave.open(path, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())
    return path


def read_audio(path: str):
    """Read back a mono 16-bit WAV written by :func:`export_audio`.

    Returns ``(samples_float64_in_[-1,1], rate_hz)``.
    """
    with wave.open(path, "rb") as w:
        assert w.getnchannels() == 1 and w.getsampwidth() == 2
        rate = w.getframerate()
        pcm = np.frombuffer(w.readframes(w.getnframes()), dtype=np.int16)
    return pcm.astype(np.float64) / 32767.0, rate
