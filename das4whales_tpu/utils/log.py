"""Structured logging (replaces the reference's print()-only observability,
SURVEY.md §5.5)."""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"


def get_logger(name: str = "das4whales_tpu",
               level: int | None = None) -> logging.Logger:
    """Package logger with a single stderr handler (idempotent).

    ``level=None`` (the default) sets INFO on first creation and leaves
    an existing logger's level ALONE — so the many internal
    ``get_logger(name)`` call sites can never clobber a level an
    operator configured. An EXPLICIT ``level`` is honored on every call
    (it used to be silently ignored once the handler existed — the
    ISSUE 11 satellite fix)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO if level is None else level)
        logger.propagate = False
    elif level is not None:
        logger.setLevel(level)
    return logger


def log_metadata(metadata, logger: logging.Logger | None = None) -> None:
    """Log an acquisition-metadata summary (the reference prints this by
    hand in every script prologue, main_mfdetect.py:16-22)."""
    log = logger or get_logger()
    meta = metadata if isinstance(metadata, dict) else getattr(metadata, "__dict__", {})
    fs = meta.get("fs")
    dx = meta.get("dx")
    nx = meta.get("nx")
    ns = meta.get("ns")
    log.info(
        "acquisition: fs=%s Hz, dx=%s m, nx=%s channels, ns=%s samples (%s s, %.1f km)",
        fs, dx, nx, ns,
        None if not (fs and ns) else ns / fs,
        0.0 if not (dx and nx) else nx * dx / 1e3,
    )
