"""Cached shallow detector views (the resource ladder's rung views).

Every detector family exposes memory-lean "views" of itself for the
planner's downshift ladder (``workflows.planner``): a shallow copy
sharing the design/device arrays with ONE knob changed (channel tile,
spectrogram chunk, classifier row chunk, host placement). The
copy-pop-mutate-cache dance is identical everywhere — one
implementation here so the idiom cannot diverge per family.
"""

from __future__ import annotations

import copy

#: every view-cache slot a shallow copy must shed: a view must never
#: inherit its parent's cached views (a tiled view's host_view must be
#: derived from the tiled knobs, not aliased to the parent's; a bank
#: view's tiled/host views from the sub-bank slice, and vice versa)
_VIEW_CACHE_ATTRS = ("_tiled_view_cache", "_host_view_cache",
                     "_bank_view_cache")


def cached_shallow_view(obj, cache_attr: str, mutate):
    """Return (and memoize on ``obj.__dict__[cache_attr]``) a shallow
    copy of ``obj`` with ``mutate(view)`` applied. The copy sheds every
    known view-cache slot before mutation; repeated calls return the
    SAME view object (the ladder's rung views are sticky, so identity
    caching keeps one compiled program per rung)."""
    cached = obj.__dict__.get(cache_attr)
    if cached is not None:
        return cached
    view = copy.copy(obj)
    for attr in _VIEW_CACHE_ATTRS:
        view.__dict__.pop(attr, None)
    mutate(view)
    obj.__dict__[cache_attr] = view
    return view
