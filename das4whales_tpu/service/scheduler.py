"""Fair multi-stream scheduling over one shared dispatch pipeline.

``parallel.dispatch.PipelinedDispatch`` keeps ONE campaign's device
queue non-empty; this module generalizes it to N tenants: every
tenant's slabs ride the same bounded in-flight queue, interleaved by
DEFICIT ROUND-ROBIN, so the H2D, compute and D2H of *different*
tenants' slabs overlap exactly like one campaign's consecutive slabs
do — the chip never idles because one tenant's ring ran dry.

Per tenant (:class:`TenantRuntime`), the batch campaign's whole
resilience stack applies independently:

* **admission** — the AOT memory preflight (``utils.memory``) prices
  every candidate ``(bucket, B)`` program against the TENANT's own HBM
  share before its first dispatch, so one tenant's huge chirp-grid
  bank pins ITSELF to a leaner rung (or is refused) instead of evicting
  another tenant's steady stream;
* **the downshift ladder, per tenant** — a resource-class failure
  downshifts only the culprit tenant's bucket (sticky, ledgered in
  that tenant's manifest); other tenants stay on their fast rung;
* **classified disposition** — retry/quarantine/timeout/degrade per
  file, through the same ``_Resilience`` machinery, into the same
  per-tenant ``manifest.jsonl`` + ``picks/*.npz`` artifacts the batch
  campaign writes — which is what makes service picks bit-identical to
  each tenant's standalone ``run_campaign_batched`` run
  (tests/test_service.py pins it).

Fairness (:class:`StreamScheduler`): textbook DRR — each tenant holds a
deficit counter in megasamples; a scheduling round credits each active
tenant its quantum (weighted by ``TenantSpec.weight``) and serves ready
slabs while the deficit covers their cost, so a tenant with 4× the
channels doesn't get 4× the slab slots — byte-fairness, not slab-count
fairness.
"""

from __future__ import annotations

import itertools
import os
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from .. import faults
from .. import fsck
from ..parallel.dispatch import PipelinedDispatch, resolve_watchdogged
from ..telemetry import costs as tcosts
from ..telemetry import metrics, trace as telemetry
from ..telemetry import quality as tquality
from ..telemetry import slo as tslo
from ..utils import artifacts, locks
from ..utils.log import get_logger
from ..workflows import campaign as camp
from ..workflows.planner import (
    DetectorProgram,
    DownshiftLadder,
    family_ladder_stages,
    program_for,
)
from .ingest import IngestItem, RingBuffer, SlabSlicer

log = get_logger("service.scheduler")

_c_slabs = metrics.counter(
    "das_service_slabs_total",
    "slabs resolved by the service scheduler",
    ("tenant",),
)
_c_overlapped = metrics.counter(
    "das_service_overlapped_slabs_total",
    "slabs whose resolve overlapped another in-flight dispatch (the "
    "multi-stream pipelining win; fraction of das_service_slabs_total)",
    ("tenant",),
)
_c_files = metrics.counter(
    "das_service_files_total",
    "files dispositioned by the service, by tenant and status",
    ("tenant", "status"),
)
_g_deficit = metrics.gauge(
    "das_service_deficit_msamples",
    "each tenant's DRR deficit counter (megasamples of credit)",
    ("tenant",),
)


class TenantRuntime:
    """One tenant's continuous detection state: ring → slicer → the
    batch campaign's per-slab executor, running forever.

    ``spec`` is a ``service.runner.TenantSpec``; ``outdir`` is the
    tenant's own manifest/picks directory (resume-compatible with —
    and bit-identical to — a ``run_campaign_batched`` run over the
    same files). ``fault_plan`` injects the chaos harness per tenant.
    """

    def __init__(self, spec, outdir: str, *, resume: bool = True,
                 fault_plan=None):
        self.spec = spec
        self.name = spec.name
        self.outdir = outdir
        os.makedirs(outdir, exist_ok=True)
        # crash-only startup: sweep orphan tmps, heal a torn manifest
        # tail, refuse to resume over deeper corruption (fsck module)
        fsck.startup_check(outdir, label=f"tenant {spec.name}")
        self.records: List[camp.FileRecord] = []
        self.fault_plan = fault_plan
        self.rz = camp._Resilience(outdir, self.records, spec.max_failures,
                                   spec.retry, spec.health)
        # the tenant's detector family (TenantSpec.family; "mf" default
        # keeps pre-family specs working) — every manifest record,
        # downshift event and watchdog attribution carries it, and the
        # ladder is filtered to the family program's declared stages
        self.family = getattr(spec, "family", "mf")
        self.rz.family = self.family
        self.ladder = DownshiftLadder(self.rz, outdir, batch=spec.batch,
                                      family=self.family,
                                      stages=family_ladder_stages(self.family))
        self.ring = RingBuffer(spec.name, capacity=spec.ring_capacity,
                               policy=spec.overflow)
        self.slicer = SlabSlicer(spec.batch, bucket=spec.bucket,
                                 linger_s=spec.linger_s)
        self.ready: deque = deque()       # BatchSlab | IngestItem(error)
        # guards the snapshot-visible scheduler state below: the DRR
        # deficit and the abort marker are written by the scheduler
        # thread and read by HTTP handler threads through snapshot()
        # (ISSUE 13 — the R8 discipline the race_guard drill exercises)
        self._lock = locks.new_lock("tenant-state")
        self.deficit = 0.0
        self.aborted: Optional[str] = None
        self.settled = camp.load_settled(outdir) if resume else set()
        for path in sorted(self.settled):
            rec = camp.FileRecord(path=path, status="skipped")
            self.records.append(rec)
            _c_files.inc(tenant=self.name, status="skipped")
        self._dets: Dict[tuple, object] = {}
        self._progs: Dict[tuple, DetectorProgram] = {}
        self._skip_buckets: Dict[tuple, str] = {}
        self._finished = False
        # freshness SLO (ISSUE 14, telemetry.slo): ring-admission stamps
        # per path (scheduler-thread-confined — pump() writes, the
        # settled hook pops) and the rolling burn-rate evaluator when
        # the tenant configured a target (TenantSLO locks internally
        # for the /slo + /readyz HTTP readers)
        self._ingest_t: Dict[str, float] = {}
        policy = spec.slo_policy() if hasattr(spec, "slo_policy") else None
        self.slo = (tslo.TenantSLO(spec.name, policy)
                    if policy is not None else None)
        # un-named live pushes get a per-tenant monotonic sequence: the
        # name IS the manifest/retry/artifact identity key, so two
        # pushes must never collide (a timestamp can, within one ms)
        self._live_seq = itertools.count()
        # science-quality observatory (ISSUE 15, telemetry.quality):
        # when armed (ServiceConfig.quality / DAS_QUALITY), this
        # tenant's serving lifetime gets a FRESH drift baseline — one
        # tenant's regime change flips only its own das_quality_drift
        # (the SLO isolation contract, verbatim); None = one attribute
        # check per settled file
        self.quality = (tquality.OBSERVATORY.fresh(spec.name)
                        if tquality.enabled() else None)

    def next_live_name(self) -> str:
        return f"{self.name}-live-{next(self._live_seq)}"

    # -- scheduler-visible state (written by the scheduler thread, read
    # -- by HTTP snapshot threads: every mutation goes through _lock) ------

    def credit(self, quantum: float) -> None:
        """One DRR round's credit (weighted by ``TenantSpec.weight``).
        The deficit gauge rides every guarded mutation, so the metric
        and the field can never disagree."""
        with self._lock:
            self.deficit += quantum * self.spec.weight
            _g_deficit.set(round(self.deficit, 3), tenant=self.name)

    def forfeit(self) -> None:
        """Classic DRR: an empty queue forfeits accumulated credit."""
        with self._lock:
            self.deficit = 0.0
            _g_deficit.set(0.0, tenant=self.name)

    def try_spend(self, cost: float) -> bool:
        """Spend ``cost`` megasamples of deficit if covered."""
        with self._lock:
            if cost > self.deficit:
                return False
            self.deficit -= cost
            _g_deficit.set(round(self.deficit, 3), tenant=self.name)
            return True

    def mark_aborted(self, reason: str) -> None:
        with self._lock:
            self.aborted = reason

    # -- ingest side -------------------------------------------------------

    def replay_files(self) -> List[str]:
        """The tenant's file list minus manifest-settled paths (crash
        resume: settled files are skipped at the SOURCE, so a restarted
        service never re-reads them)."""
        return camp.pending_files(self.spec.files, settled=self.settled)

    def pump(self) -> None:
        """Move ring items through the slicer into the ready queue."""
        while True:
            item = self.ring.pop()
            if item is None:
                break
            if item.t_ingest is not None:
                # the ring's admission stamp survives slicing: settled
                # picks look their path up here for the freshness SLO
                self._ingest_t[item.path] = item.t_ingest
            self.ready.extend(self.slicer.offer(item))
        if self.slicer.pending() and (
                self.ring.exhausted() or self.slicer.linger_expired()):
            slab = self.slicer.flush_partial()
            if slab is not None:
                self.ready.append(slab)

    def idle(self) -> bool:
        """Nothing buffered, nothing sliceable, source finished."""
        return (not self.ready and self.slicer.pending() == 0
                and self.ring.exhausted())

    def _drop_ingest_stamp(self, path: str) -> None:
        """Release a file's admission stamp on a TERMINAL non-done
        disposition (failed/quarantined/timeout/admission-skip): those
        are not freshness samples — their own counters track them — but
        the stamp must not outlive the file, or a chronically failing
        source grows ``_ingest_t`` for the process lifetime."""
        self._ingest_t.pop(path, None)

    def _note_pick_settled(self, path: str) -> None:
        """Ingest→pick-settled freshness for one done file: the ring's
        admission stamp to now, into ``das_pick_latency_seconds`` and
        the tenant's burn-rate evaluator (``telemetry.slo``). No stamp
        (live push predating the stamp, resumed file) — no sample."""
        t0 = self._ingest_t.pop(path, None)
        if t0 is None:
            return
        latency = time.monotonic() - t0
        tslo.observe_pick_latency(self.name, latency)
        if self.slo is not None:
            self.slo.observe(latency)

    def slo_snapshot(self) -> Dict:
        """This tenant's ``/slo`` row (a no-target tenant reports
        ``state="ok"`` with no burn windows — the histogram still
        records its latencies)."""
        if self.slo is None:
            return {"tenant": self.name, "target_s": None,
                    "state": "ok", "burn_rates": {}}
        return self.slo.snapshot()

    def quality_snapshot(self) -> Optional[Dict]:
        """This tenant's ``/quality`` row (None when the observatory is
        not armed — the ``/tenants`` block then reads ``null``)."""
        if self.quality is None:
            return None
        return self.quality.snapshot()

    # -- detection side (the batch campaign's per-slab contract) -----------

    def _bucket_key(self, slab) -> tuple:
        return (slab.stack.shape[1], slab.bucket_ns,
                np.dtype(np.asarray(slab.blocks[0].trace).dtype).name)

    def _hbm_budget(self) -> int:
        from ..config import hbm_budget_bytes

        if self.spec.hbm_share_gb is not None:
            return int(self.spec.hbm_share_gb * 2**30)
        return hbm_budget_bytes()

    def _admit_bucket(self, key, bdet, slab) -> None:
        """Per-tenant HBM admission: the AOT preflight against THIS
        tenant's share (``TenantSpec.hbm_share_gb``; default the
        process budget). Mirrors the batch campaign's
        ``preflight_bucket`` walk — full bank at each B, bank-split
        where splittable, tiled last — but every pin/skip is ledgered
        against the tenant so admission decisions are auditable per
        stream."""
        from ..parallel.batch import BatchedMatchedFilterDetector
        from ..utils import memory as memutils

        budget = self._hbm_budget()
        dt = np.asarray(slab.blocks[0].trace).dtype
        cands, b = [], self.spec.batch
        while b >= 1:
            cands.append(b)
            b //= 2
        split = getattr(bdet.det, "supports_bank_split", False)
        rung_cands = []
        for b_ in cands:
            rung_cands.append(("batched", b_))
            if split:
                rung_cands.append(("bank", b_))

        def price_rung(rung_):
            stage_, b_ = rung_
            bd = bdet.split_views()[0] if stage_ == "bank" else bdet
            with_health = self.rz.health_cfg is not None
            clip = (self.rz.health_cfg.clip_abs
                    if self.rz.health_cfg is not None else None)
            if tcosts.enabled():
                # admission pricing doubles as cost-card capture: one
                # lower().compile() per candidate serves both (ISSUE 14)
                st = tcosts.capture_batched(
                    bd, b_, dt, bucket=tcosts.bucket_label(key),
                    program=faults.rung_label(rung_),
                    with_health=with_health, health_clip=clip,
                )
            else:
                st = memutils.batched_program_memory(
                    bd, b_, dt, with_health=with_health, health_clip=clip,
                )
            if st is not None:
                # the same HBM high-water the batch campaign's preflight
                # feeds: a service-only process must still move the
                # das_preflight_hbm_peak_bytes headroom signal
                camp._g_preflight_hwm.max(float(st.peak))
            return st

        best = memutils.first_fitting(price_rung, rung_cands, budget)
        if best is not None:
            stage_, b_ = best
            if stage_ == "bank":
                self.ladder.pin(key, ("bank", b_), (
                    f"admission: tenant {self.name} full "
                    f"T={len(bdet.det.bank)} bank over its "
                    f"{budget / 2**30:.2f} GiB share at B={b_}; T/2 "
                    "sub-banks fit"
                ))
            elif b_ < self.spec.batch:
                self.ladder.pin(
                    key, ("batched", b_) if b_ > 1 else ("file", 1),
                    f"admission: tenant {self.name} largest fitting batch "
                    f"B={b_} under its {budget / 2**30:.2f} GiB share",
                )
            return
        if self.family != "mf":
            # family facades have no batched-tiled program to price; the
            # per-file rung starts the family's own ladder (the batch
            # campaign's preflight_bucket rule, per tenant)
            self.ladder.pin(key, ("file", 1), (
                f"admission: no (bucket, B) {self.family} program fits "
                f"tenant {self.name}'s {budget / 2**30:.2f} GiB share; "
                "per-file ladder takes over"
            ))
            return
        tiled = BatchedMatchedFilterDetector(
            bdet.det.tiled_view(), serial=bdet.serial
        )
        with_health = self.rz.health_cfg is not None
        clip = (self.rz.health_cfg.clip_abs
                if self.rz.health_cfg is not None else None)
        if tcosts.enabled():
            # the campaign's exact mirror (workflows/campaign.py): a
            # tiled-pinned tenant is the memory-constrained case the
            # observatory targets — it must get a card too
            tstats = tcosts.capture_batched(
                tiled, 1, dt, bucket=tcosts.bucket_label(key),
                program="tiled", with_health=with_health, health_clip=clip,
            )
        else:
            tstats = memutils.batched_program_memory(
                tiled, 1, dt, with_health=with_health, health_clip=clip,
            )
        if tstats is None or tstats.fits(budget):
            self.ladder.pin(key, ("tiled", 1), (
                f"admission: tenant {self.name} only the tiled per-file "
                f"program fits its {budget / 2**30:.2f} GiB share"
            ))
            return
        reason = (
            f"admission: no (bucket, B) program shape fits tenant "
            f"{self.name}'s HBM share ({budget / 2**30:.2f} GiB); "
            f"smallest candidate needs {tstats.peak / 2**30:.2f} GiB — "
            "stream refused before dispatch"
        )
        self._skip_buckets[key] = reason
        camp._append_event(self.outdir, {
            "event": "admission_skip", "tenant": self.name,
            "bucket": key if isinstance(key, str) else list(key),
            "reason": reason,
        })
        log.warning("tenant %s bucket %s: %s", self.name, key, reason)

    def _detector_for(self, slab):
        from ..parallel.batch import batched_detector_for

        key = self._bucket_key(slab)
        bdet = self._dets.get(key)
        if bdet is None:
            kwargs = dict(self.spec.detector_kwargs)
            if self.spec.bank is not None:
                kwargs.setdefault("templates", self.spec.bank)
            per_file_det = camp.family_detector(
                self.family, slab.blocks[0].metadata, self.spec.channels,
                (key[0], slab.bucket_ns), wire=self.spec.wire, **kwargs,
            )
            bdet = batched_detector_for(
                per_file_det, serial=self.spec.serial,
                trace_shape=(key[0], slab.bucket_ns),
            )
            if hasattr(bdet, "_resolve_engines"):
                # family facades: the per-shape engine decision (A/B
                # router, ops.mxu) resolves EAGERLY — never under the
                # admission preflight's trace
                bdet._resolve_engines(
                    (self.spec.batch, key[0], slab.bucket_ns)
                )
            self._dets[key] = bdet
            self._progs[key] = program_for(per_file_det)
            self.ladder.set_engines(key, self._progs[key].engines)
            if getattr(bdet.det, "supports_bank_split", False):
                self.ladder.enable_bank_split(key)
            if self.spec.admission:
                with telemetry.span("preflight", bucket=str(key),
                                    tenant=self.name):
                    self._admit_bucket(key, bdet, slab)
            if tcosts.enabled() and key not in self._skip_buckets:
                # the starting rung always has a card, admission or not
                # (the batch campaign's detector_for plays the same
                # ensure — no-op when the admission walk captured it)
                rung0 = self.ladder.current(key)
                stage0, b0 = rung0
                if stage0 in ("batched", "bank", "file"):
                    bd0 = (bdet.split_views()[0] if stage0 == "bank"
                           else bdet)
                    tcosts.ensure_batched_card(
                        bd0, max(1, int(b0)),
                        np.asarray(slab.blocks[0].trace).dtype,
                        bucket=tcosts.bucket_label(key),
                        program=faults.rung_label(rung0),
                        with_health=self.rz.health_cfg is not None,
                        health_clip=(self.rz.health_cfg.clip_abs
                                     if self.rz.health_cfg is not None
                                     else None),
                    )
        return bdet

    def try_dispatch(self, slab):
        """Async K0 launch at the tenant's healthy top rung (the
        multi-stream pipeline's dispatch phase); None routes the slab
        to the synchronous path with identical attribution."""
        if self.aborted or self.spec.batch < 2:
            return None
        try:
            bdet = self._detector_for(slab)
            key = self._bucket_key(slab)
            if (key in self._skip_buckets
                    or self.ladder.current(key)
                    != ("batched", self.spec.batch)):
                return None
            return bdet.dispatch_batch(
                slab.stack, n_real=slab.n_real, n_valid=slab.n_valid,
                with_health=self.rz.health_cfg is not None,
                health_clip=(self.rz.health_cfg.clip_abs
                             if self.rz.health_cfg is not None else None),
            )
        except camp.CampaignAborted:
            raise
        except Exception:  # noqa: BLE001 — surfaces on the sync path
            return None

    def _dispatched(self, paths, rung, fn):
        return resolve_watchdogged(fn, paths, rung,
                                   self.spec.dispatch_deadline_s,
                                   self.fault_plan, family=self.family)

    def _per_file_fallback(self, slab, k, prog, rung=("file", 1)):
        with_health = self.rz.health_cfg is not None
        clip = self.rz.health_cfg.clip_abs if with_health else None
        tr = np.asarray(slab.blocks[k].trace)
        padded = np.zeros((tr.shape[0], slab.bucket_ns), tr.dtype)
        padded[:, : tr.shape[1]] = tr

        def fn():
            return prog.detect(rung, padded, n_real=slab.n_real[k],
                               with_health=with_health, clip=clip)

        return self._dispatched([slab.paths[k]], rung, fn)

    def _run_rung(self, slab, rung, bdet, ok, inflight=None):
        """The slab's entries at one ladder rung — the batch campaign's
        ``run_rung`` contract (campaign.py documents the cases); raises
        on the rung's failure for the caller's ladder."""
        from ..io.stream import subdivide_slab

        prog = self._progs[self._bucket_key(slab)]
        with_health = self.rz.health_cfg is not None
        clip = self.rz.health_cfg.clip_abs if with_health else None
        stage, b = rung
        if stage == "batched":
            if b >= self.spec.batch:
                if inflight is not None:
                    return self._dispatched(list(slab.paths), rung,
                                            inflight.resolve)
                subs = [slab]
            else:
                subs = subdivide_slab(slab, b)
            entries = []
            for sub in subs:
                def fn(sub=sub):
                    return bdet.detect_batch(
                        sub.stack, n_real=sub.n_real, n_valid=sub.n_valid,
                        with_health=with_health, health_clip=clip,
                    )
                entries.extend(
                    self._dispatched(list(sub.paths), rung, fn)[: sub.n_valid]
                )
            return entries
        if stage == "bank":
            subs = ([slab] if b >= self.spec.batch
                    else subdivide_slab(slab, b))
            half_a, half_b = bdet.split_views()
            entries = []
            for sub in subs:
                halves = []
                for j, hdet in enumerate((half_a, half_b)):
                    # health stats describe the input block: first half
                    # only (the batch campaign's rule)
                    def fn(sub=sub, hdet=hdet, j=j):
                        return hdet.detect_batch(
                            sub.stack, n_real=sub.n_real,
                            n_valid=sub.n_valid,
                            with_health=with_health and j == 0,
                            health_clip=clip,
                        )
                    halves.append(
                        self._dispatched(list(sub.paths), rung,
                                         fn)[: sub.n_valid]
                    )
                for ea, eb in zip(*halves):
                    if ea is None or eb is None:
                        entries.append(None)
                        continue
                    merged = ({**ea[0], **eb[0]}, {**ea[1], **eb[1]})
                    entries.append(
                        merged + (ea[2],) if with_health else merged
                    )
            return entries
        entries = []
        for k in range(slab.n_valid):
            if not ok[k]:
                entries.append(None)
                continue
            tr = np.asarray(slab.blocks[k].trace)
            padded = np.zeros((tr.shape[0], slab.bucket_ns), tr.dtype)
            padded[:, : tr.shape[1]] = tr

            def fn(padded=padded, k=k):
                return prog.detect(rung, padded, n_real=slab.n_real[k],
                                   with_health=with_health, clip=clip)
            entries.append(self._dispatched([slab.paths[k]], rung, fn))
        return entries

    def handle_error_item(self, item: IngestItem) -> None:
        """Disposition a source-side read failure at its own position
        (the campaign's SlabReadError contract at ring granularity).
        Transient classes disposition terminally here — the replay
        source has already moved past the file, so the in-run retry is
        structurally impossible; ``failed``/``timeout`` are NOT settled
        statuses, so a service restart re-serves the file: the durable
        analog of the campaign's in-run retry (docs/SERVICE.md)."""
        exc = item.error
        self._drop_ingest_stamp(item.path)   # never settles done
        self.rz.attempt(item.path)
        try:
            fclass = faults.classify_failure(exc)
            if fclass == "fatal":
                raise exc
            if isinstance(exc, faults.DeadlineExceeded):
                faults.count("timeouts")
                self.rz.fail(item.path, exc, status="timeout")
            elif fclass == "data":
                faults.count("quarantined")
                self.rz.fail(item.path, exc, status="quarantined",
                             health=getattr(exc, "stats", None))
            else:
                self.rz.fail(item.path, exc)
            _c_files.inc(tenant=self.name,
                         status=self.records[-1].status)
        except camp.CampaignAborted as aexc:
            self.mark_aborted(str(aexc))

    def handle_slab(self, slab, inflight=None) -> None:
        """One slab through the elastic ladder + per-file degrade +
        health gate + artifact/manifest bookkeeping — the batch
        campaign's ``handle_slab`` contract, per tenant."""
        fail = self.rz.fail
        with_health = self.rz.health_cfg is not None
        clip = self.rz.health_cfg.clip_abs if with_health else None
        try:
            bdet = self._detector_for(slab)
        except Exception as exc:  # noqa: BLE001 — whole-slab guard
            if faults.classify_failure(exc) == "fatal":
                raise
            for path in slab.paths:
                fail(path, exc)
                _c_files.inc(tenant=self.name, status="failed")
                self._drop_ingest_stamp(path)
            return
        det = bdet.det
        key = self._bucket_key(slab)
        if key in self._skip_buckets:
            for k in range(slab.n_valid):
                fail(slab.paths[k], RuntimeError(self._skip_buckets[key]))
                _c_files.inc(tenant=self.name, status="failed")
                self._drop_ingest_stamp(slab.paths[k])
            return
        ok = []
        for k in range(slab.n_valid):
            meta_k = slab.blocks[k].metadata
            if (self.spec.wire == "raw" and meta_k is not None
                    and meta_k.scale_factor != det.metadata.scale_factor):
                fail(slab.paths[k], ValueError(
                    f"scale_factor {meta_k.scale_factor!r} != detector "
                    f"scale {det.metadata.scale_factor!r}; wire='raw' "
                    "conditions with one scale"
                ))
                _c_files.inc(tenant=self.name, status="failed")
                self._drop_ingest_stamp(slab.paths[k])
                ok.append(False)
            else:
                ok.append(True)
        t0 = time.perf_counter()
        degraded = recovered = False
        results = None
        try:
            if self.fault_plan is not None:
                for k in range(slab.n_valid):
                    if ok[k]:
                        try:
                            self.fault_plan.on_transfer(slab.paths[k])
                            self.fault_plan.on_detect(slab.paths[k])
                        except Exception:
                            self.rz.attempt(slab.paths[k])
                            raise
            rung = self.ladder.current(key)
            if inflight is not None and rung != ("batched", self.spec.batch):
                inflight = None   # downshifted between dispatch and resolve
            shape = (int(slab.stack.shape[1]), slab.bucket_ns)
            while True:   # the elastic ladder, per tenant
                try:
                    results = self._run_rung(slab, rung, bdet, ok,
                                             inflight=inflight)
                    break
                except Exception as exc:  # noqa: BLE001
                    inflight = None
                    fclass = faults.classify_failure(exc)
                    if fclass == "fatal":
                        raise
                    if fclass == "resource":
                        nxt = self.ladder.downshift(key, rung, exc, shape)
                        if nxt is not None:
                            rung = nxt
                            recovered = True
                            continue
                    raise
        except camp.CampaignAborted:
            raise
        except Exception as exc:  # noqa: BLE001 — degrade per file
            if faults.classify_failure(exc) == "fatal":
                raise
            faults.count("degradations")
            log.warning("tenant %s: slab of %d files failed (%s: %s); "
                        "degrading to the per-file route", self.name,
                        slab.n_valid, type(exc).__name__, exc)
            degraded = True
        wall = time.perf_counter() - t0
        camp._h_slab_wall.observe(wall)
        if tcosts.enabled() and not degraded and results is not None:
            # live utilization per tenant slab (the batch campaign's
            # exact hook): predicted-at-peaks over measured
            tcosts.note_slab_resolved(
                tcosts.bucket_label(key), faults.rung_label(rung),
                tcosts._program_engine(bdet), wall,
            )
        shape = (int(slab.stack.shape[1]), slab.bucket_ns)
        from ..parallel.batch import trim_picks

        for k in range(slab.n_valid):
            if not ok[k]:
                continue
            path = slab.paths[k]
            use_fallback = degraded or results[k] is None
            pf_rung = max(("file", 1), self.ladder.current(key),
                          key=faults.rung_rank)
            file_recovered = recovered
            while True:
                self.rz.attempt(path)
                try:
                    if use_fallback:
                        if self.fault_plan is not None and degraded:
                            self.fault_plan.on_transfer(path)
                            self.fault_plan.on_detect(path)
                        picks, thresholds, stats = self._per_file_fallback(
                            slab, k, self._progs[key], rung=pf_rung
                        )
                        exec_rung = pf_rung
                    else:
                        entry = results[k]
                        picks, thresholds = entry[0], entry[1]
                        stats = (entry[2] if with_health
                                 and len(entry) > 2 else {})
                        exec_rung = rung
                    self.rz.check_health(path, stats,
                                         rung=faults.rung_label(exec_rung))
                    picks = trim_picks(picks, slab.n_real[k])
                    if self.fault_plan is not None:
                        self.fault_plan.detect_succeeded()
                    camp._file_record(
                        self.outdir, path, picks, thresholds,
                        round(wall / max(slab.n_valid, 1), 3), self.records,
                        attempts=self.rz.state.n_attempts(path),
                        health=dict(stats or {}), family=bdet.family,
                        rung=faults.rung_label(exec_rung),
                    )
                    _c_files.inc(tenant=self.name, status="done")
                    self._note_pick_settled(path)
                    if self.quality is not None:
                        # the campaign's exact derivation, under this
                        # tenant's own label/baseline
                        camp._observe_quality(
                            self.name, bdet.det, path, picks, thresholds,
                            stats, slab.n_real[k],
                        )
                    if file_recovered:
                        self.rz.tally("oom_recoveries")
                except camp.CampaignAborted:
                    raise
                except Exception as exc:  # noqa: BLE001 — per-file isolation
                    if (use_fallback
                            and faults.classify_failure(exc) == "resource"):
                        nxt = self.ladder.downshift(key, pf_rung, exc, shape)
                        if nxt is not None:
                            self.rz.state.unattempt(path)
                            pf_rung = nxt
                            file_recovered = True
                            continue
                    if self.rz.dispose(path, exc) == "retry":
                        use_fallback = True
                        continue
                    _c_files.inc(tenant=self.name,
                                 status=self.records[-1].status)
                    self._drop_ingest_stamp(path)   # terminal, not done
                break

    def cost_summary(self) -> Dict:
        """This tenant's placement footprint for the fleet supervisor
        (ISSUE 20): max priced HBM peak and roofline-predicted wall
        across the cost cards of every bucket this tenant dispatched.
        ``priced=False`` means the cost observatory was off or nothing
        dispatched yet — the supervisor then falls back to the declared
        ``hbm_share_gb``, never a guess."""
        labels = {tcosts.bucket_label(k) for k in list(self._dets)}
        peak, wall, n = 0, 0.0, 0
        if tcosts.enabled() and labels:
            peaks = tcosts.device_peaks()
            for card in tcosts.REGISTRY.cards():
                if card.bucket not in labels:
                    continue
                n += 1
                peak = max(peak, int(card.peak_bytes + card.argument_bytes))
                wall = max(wall, float(card.predicted_wall_s(peaks)))
        return {
            "tenant": self.name,
            "priced": n > 0,
            "n_cards": n,
            "peak_bytes": peak,
            "predicted_wall_s": round(wall, 6),
            "hbm_share_gb": self.spec.hbm_share_gb,
        }

    def finish(self) -> None:
        """Flush the end-of-run counters event (idempotent), and leave
        the tenant's placement footprint next to its manifest — the
        fleet supervisor's bin-packing input when this outdir is later
        adopted by another worker (ISSUE 20)."""
        if not self._finished:
            self._finished = True
            self.rz.flush_tallies()
            try:
                artifacts.atomic_json(
                    os.path.join(self.outdir, "cost_card.json"),
                    self.cost_summary(),
                )
            except OSError as exc:
                log.warning("tenant %s: cost_card.json not written: %s",
                            self.name, exc)

    # -- reporting ---------------------------------------------------------

    def result(self) -> camp.CampaignResult:
        # list(...) is a C-atomic copy: an HTTP thread's result() while
        # the scheduler appends a record must never tear (daslint R8)
        return camp.CampaignResult(outdir=self.outdir,
                                   records=list(self.records))

    def snapshot(self) -> Dict:
        """The /tenants view, safe against the scheduler thread: counts
        come from a C-atomic copy of the records list, sticky rungs
        from the ladder's own copy-on-read (`rung_snapshot`), and the
        lock brackets the mutable scalars (deficit, abort marker) so a
        poll observes one consistent DRR round."""
        res = self.result()
        rungs = self.ladder.rung_snapshot()
        with self._lock:
            aborted = self.aborted
            deficit = self.deficit
        return {
            "tenant": self.name,
            "n_done": res.n_done, "n_failed": res.n_failed,
            "n_skipped": res.n_skipped,
            "n_quarantined": res.n_quarantined, "n_timeout": res.n_timeout,
            "ring_depth": len(self.ring),
            "ring_closed": self.ring.closed,
            "ready_slabs": len(self.ready),
            "aborted": aborted,
            "rungs": {str(k): faults.rung_label(r)
                      for k, r in rungs.items()},
            "deficit_msamples": round(deficit, 3),
            "slo": self.slo_snapshot(),
            "quality": self.quality_snapshot(),
        }


class StreamScheduler:
    """Deficit-round-robin over tenants, one shared in-flight pipeline.

    One :class:`~das4whales_tpu.parallel.dispatch.PipelinedDispatch`
    serves every tenant: slab tokens are ``(tenant_name, slab)``, so
    while tenant A's slab computes, tenant B's next slab is already
    dispatching — the cross-tenant overlap is the same mechanism as the
    single-campaign depth-D pipeline, reached through the public
    ``pending()``/``in_flight()`` accessors. A tenant that leaves its
    top rung (or whose dispatch fails) falls back to the synchronous
    path with the campaign's exact attribution.
    """

    def __init__(self, tenants, dispatch_depth: int | None = None):
        self.tenants: Dict[str, TenantRuntime] = {t.name: t for t in tenants}
        if len(self.tenants) != len(list(tenants)):
            raise ValueError("tenant names must be unique")
        self.pipe = PipelinedDispatch(dispatch_depth)
        self._rotation = deque(self.tenants)
        self._base_quantum = 1.0   # megasamples; adapts to the largest slab
        # fleet admin (ISSUE 20): HTTP threads enqueue add/retire ops;
        # the scheduler thread applies them at the top of each round, so
        # the tenants dict and rotation stay scheduler-thread-confined
        # (the R8 discipline — handlers never mutate them directly)
        self._admin: deque = deque()
        self._retiring: Dict[str, object] = {}   # name -> threading.Event

    @staticmethod
    def _cost(slab) -> float:
        return float(np.asarray(slab.stack).size) / 1e6

    def _finalize(self, token, inflight) -> None:
        name, slab = token
        t = self.tenants[name]
        overlapped = inflight is not None and self.pipe.in_flight() > 0
        _c_slabs.inc(tenant=name)
        if overlapped:
            _c_overlapped.inc(tenant=name)
        try:
            with telemetry.span("slab", tenant=name, index0=slab.index0,
                                n_files=slab.n_valid,
                                bucket_ns=slab.bucket_ns,
                                pipelined=inflight is not None):
                t.handle_slab(slab, inflight)
        except camp.CampaignAborted as exc:
            # one tenant's max_failures abort stops THAT stream only
            t.mark_aborted(str(exc))
            log.error("tenant %s aborted: %s", name, exc)
        except Exception as exc:  # noqa: BLE001 — whole-slab guard
            if faults.classify_failure(exc) == "fatal":
                raise
            dispositioned = {r.path for r in t.records}
            for path in slab.paths:
                if path not in dispositioned:
                    try:
                        t.rz.fail(path, exc)
                        _c_files.inc(tenant=name, status="failed")
                    except camp.CampaignAborted as aexc:
                        t.mark_aborted(str(aexc))
                        break

    def _drain_pipe(self) -> None:
        for token, inflight in self.pipe.drain():
            self._finalize(token, inflight)

    def _serve(self, t: TenantRuntime, slab) -> None:
        infl = None if t.aborted else t.try_dispatch(slab)
        if infl is None:
            self._drain_pipe()
            if t.aborted:
                # an aborted tenant's remaining slabs are not detected;
                # their files stay unrecorded (resume-able)
                return
            self._finalize((t.name, slab), None)
        else:
            for token in self.pipe.submit((t.name, slab), infl):
                self._finalize(*token)

    # -- fleet admin (ISSUE 20) -------------------------------------------

    def add_tenant(self, t: TenantRuntime) -> None:
        """Enqueue a freshly adopted tenant; it joins the rotation at
        the top of the next :meth:`step` (never mid-round)."""
        self._admin.append(("add", t))

    def retire_when_idle(self, name: str, done) -> None:
        """Enqueue a tenant's retirement: once its source is exhausted
        and none of its slabs ride the pipe, it is ``finish()``-ed,
        removed from the rotation, and ``done`` (a threading.Event) is
        set — the ``/drain`` verb's completion gate."""
        self._admin.append(("retire", (name, done)))

    def _apply_admin(self) -> None:
        while self._admin:
            op, payload = self._admin.popleft()
            if op == "add":
                t = payload
                self.tenants[t.name] = t
                if t.name not in self._rotation:
                    self._rotation.append(t.name)
            else:
                name, done = payload
                self._retiring[name] = done

    def _check_retiring(self) -> None:
        if not self._retiring:
            return
        busy = {tok[0] for tok in self.pipe.pending()}
        for name in list(self._retiring):
            t = self.tenants.get(name)
            if t is None:
                self._retiring.pop(name).set()
                continue
            t.pump()
            if (t.idle() or t.aborted) and name not in busy:
                t.finish()
                del self.tenants[name]
                try:
                    self._rotation.remove(name)
                except ValueError:
                    pass
                self._retiring.pop(name).set()

    def step(self) -> bool:
        """One DRR round: credit each tenant, serve what the deficits
        cover. Returns True when any slab or error item was served (the
        runner idles briefly on False)."""
        any_work = False
        self._apply_admin()
        self._check_retiring()
        for _ in range(len(self._rotation)):
            name = self._rotation[0]
            self._rotation.rotate(-1)
            t = self.tenants[name]
            t.pump()
            # error items carry no device cost: disposition immediately
            while t.ready and isinstance(t.ready[0], IngestItem):
                t.handle_error_item(t.ready.popleft())
                any_work = True
            if not t.ready:
                t.forfeit()   # classic DRR: empty queue forfeits credit
                continue
            head_cost = self._cost(t.ready[0])
            self._base_quantum = max(self._base_quantum, head_cost)
            t.credit(self._base_quantum)
            while t.ready:
                if isinstance(t.ready[0], IngestItem):
                    t.handle_error_item(t.ready.popleft())
                    any_work = True
                    continue
                if not t.try_spend(self._cost(t.ready[0])):
                    break
                slab = t.ready.popleft()
                self._serve(t, slab)
                any_work = True
        return any_work

    def drain(self) -> None:
        """Finish in-flight slabs (the graceful half of SIGTERM): every
        dispatched-unresolved token resolves through its own tenant's
        executor; nothing new is dispatched."""
        self._drain_pipe()

    def run_until_idle(self, idle_sleep_s: float = 0.01,
                       should_stop=None) -> None:
        """Serve until every tenant's source is exhausted and all work
        is resolved, or ``should_stop()``. In-flight tokens left by a
        stop are the caller's to :meth:`drain` (the runner's graceful
        exit path owns that, plus the per-tenant ``finish()``)."""
        while True:
            if should_stop is not None and should_stop():
                return
            worked = self.step()
            if not worked:
                if self.pipe.in_flight():
                    self._drain_pipe()
                    continue
                if all(t.idle() or t.aborted
                       for t in list(self.tenants.values())):
                    return
                time.sleep(idle_sleep_s)
