"""Continuous ingest: bounded ring buffers, file replay, slab slicing.

The service's input side, per tenant:

* :class:`RingBuffer` — a BOUNDED per-stream queue of ingest items with
  an explicit backpressure contract (docs/SERVICE.md): a full ring
  either REJECTS the push (the HTTP surface answers 429 and the
  interrogator retries) or DROPS THE OLDEST item to admit the newest
  (live monitoring prefers fresh data over complete data) — per tenant
  config, with every drop counted as
  ``das_ingest_dropped_total{tenant}``. Unbounded growth is the one
  thing a week-long service may never do.
* :class:`FileReplaySource` — replays existing HDF5/TDMS files through
  ``io.stream.stream_strain_blocks`` at a configurable real-time
  factor: 60 s files at factor 1.0 arrive once a minute (a live
  interrogator rehearsal), factor 0/None replays as fast as the reader
  runs (tests, bench, backfill). Read failures become items carrying
  the error, so the scheduler dispositions them with the campaign's
  classified-failure contract instead of killing the source thread.
* :class:`SlabSlicer` — the continuous analog of the batch campaign's
  slab assembler: consecutive same-bucket blocks coalesce into
  ``[B, channel, time]`` host slabs through the SAME
  ``io.stream.assemble_slab`` bucket/padding rule, so a slab formed
  from a ring buffer is bit-identical to one the batch campaign would
  have formed from the same files in the same order — the foundation
  of the service's picks-parity guarantee (tests/test_service.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..config import as_bucket_config
from ..telemetry import metrics
from ..utils import locks
from ..utils.log import get_logger

log = get_logger("service.ingest")

_c_dropped = metrics.counter(
    "das_ingest_dropped_total",
    "ingest items dropped by a full ring buffer (drop-oldest policy)",
    ("tenant",),
)
_c_rejected = metrics.counter(
    "das_ingest_rejected_total",
    "ingest pushes rejected by a full ring buffer (reject policy -> 429)",
    ("tenant",),
)
_c_accepted = metrics.counter(
    "das_ingest_accepted_total",
    "ingest items accepted into a tenant's ring buffer",
    ("tenant",),
)
_g_depth = metrics.gauge(
    "das_ingest_ring_depth",
    "items currently buffered in a tenant's ring",
    ("tenant",),
)

#: ring overflow policies (TenantSpec.overflow)
OVERFLOW_POLICIES = ("reject", "drop_oldest")


@dataclass
class IngestItem:
    """One unit of ingest: a named block, or a read failure.

    ``block`` is anything with ``.trace`` (host ``[channel, time]``
    array) and ``.metadata`` (``config.AcquisitionMetadata``) — the
    stream's ``StrainBlock`` for replay, a live push's assembled block
    for the HTTP feed. ``error`` carries a source-side failure for the
    scheduler to disposition at this item's position (the campaign's
    per-file attribution contract, kept at ring granularity).
    ``t_ingest`` is the ``time.monotonic()`` CAPTURE STAMP the ring
    writes at admission (``RingBuffer.push``/``push_wait``) — the zero
    point of the ingest→pick-settled freshness SLO
    (``telemetry.slo``, docs/SERVICE.md); a caller-provided stamp is
    kept (a source that knows the true capture time may pre-stamp)."""

    path: str
    block: object | None = None
    error: Exception | None = None
    t_ingest: float | None = None


class RingBuffer:
    """Bounded FIFO of :class:`IngestItem`\\ s with counted backpressure.

    ``policy="reject"``: a full ring refuses the push (returns False —
    the HTTP ingest surface maps that to 429 + Retry-After).
    ``policy="drop_oldest"``: the oldest buffered item is evicted to
    admit the newest, counted as ``das_ingest_dropped_total{tenant}``
    (a dropped item gets no manifest record: it was never admitted to
    detection — the counter is its only trace, by design).

    ``close()`` marks the stream ended (replay finished / drain):
    pushes are refused and consumers can distinguish "empty for now"
    from "no more data ever" (:meth:`exhausted`).
    """

    def __init__(self, tenant: str, capacity: int = 8,
                 policy: str = "reject"):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if policy not in OVERFLOW_POLICIES:
            raise ValueError(
                f"unknown overflow policy {policy!r}; expected one of "
                f"{OVERFLOW_POLICIES}"
            )
        self.tenant = tenant
        self.capacity = int(capacity)
        self.policy = policy
        self._q: deque = deque()
        # a TracedLock (utils.locks): ring contention lands in the
        # das_lock_wait/held_seconds{name="ring"} histograms and the
        # lock-order graph the race_guard fixture asserts acyclic
        self._lock = locks.new_lock("ring")
        self._not_empty = threading.Condition(self._lock)
        # notified by pop(): push_wait blocks HERE instead of
        # sleep-polling (daslint R10 sleep-polling)
        self._space = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def exhausted(self) -> bool:
        """No more data ever: closed AND drained."""
        with self._lock:
            return self._closed and not self._q

    def close(self) -> None:
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()
            self._space.notify_all()   # blocked push_wait callers: drain

    def push(self, item: IngestItem) -> bool:
        """Admit ``item`` under the ring's overflow policy. Returns True
        when the item is buffered, False when it was refused (full ring
        under ``reject``, or a closed ring)."""
        with self._not_empty:
            if self._closed:
                return False
            if len(self._q) >= self.capacity:
                if self.policy == "reject":
                    _c_rejected.inc(tenant=self.tenant)
                    return False
                self._q.popleft()   # drop-oldest: newest data wins
                _c_dropped.inc(tenant=self.tenant)
            if item.t_ingest is None:
                item.t_ingest = time.monotonic()   # the SLO's zero point
            self._q.append(item)
            _c_accepted.inc(tenant=self.tenant)
            _g_depth.set(len(self._q), tenant=self.tenant)
            self._not_empty.notify()
            return True

    def push_wait(self, item: IngestItem, poll_s: float | None = None,
                  timeout_s: float | None = None) -> bool:
        """Blocking push for sources that must never lose items (the
        file-replay source): wait for space instead of dropping. Blocks
        on the ``_space`` condition ``pop()`` notifies (no sleep-poll —
        the waiter wakes the moment a slot frees). Returns False only
        when the ring closes (drain) or ``timeout_s`` expires.
        ``poll_s`` is accepted for back-compat and ignored."""
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._space:
            while True:
                if self._closed:
                    return False
                if len(self._q) < self.capacity:
                    if item.t_ingest is None:
                        item.t_ingest = time.monotonic()
                    self._q.append(item)
                    _c_accepted.inc(tenant=self.tenant)
                    _g_depth.set(len(self._q), tenant=self.tenant)
                    self._not_empty.notify()
                    return True
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                # bounded wait even without a caller timeout: a missed
                # notify (or a consumer that died) must not hang the
                # replay thread forever (daslint R10 unbounded-wait)
                self._space.wait(min(remaining or 1.0, 1.0))

    def pop(self) -> Optional[IngestItem]:
        """The oldest buffered item, or None when the ring is empty
        (non-blocking: the scheduler decides how to idle)."""
        with self._space:
            if not self._q:
                return None
            item = self._q.popleft()
            _g_depth.set(len(self._q), tenant=self.tenant)
            self._space.notify()   # a blocked push_wait can land now
            return item


class FileReplaySource:
    """Replay ``files`` into a ring buffer at a real-time factor.

    The test/bench stand-in for a live interrogator feed — and the
    backfill path for recorded archives. Blocks are read in order via
    ``io.stream.stream_strain_blocks`` (host numpy; the slicer owns the
    eventual H2D) and pushed with :meth:`RingBuffer.push_wait`, so a
    slow consumer backpressures the reader instead of losing files.

    ``realtime_factor``: 1.0 paces the replay at the recording's own
    rate (each block sleeps ``record_seconds / factor`` before the
    next); 2.0 replays twice as fast; 0/None replays as fast as the
    reader runs. A read failure is pushed as an error item at the
    failing file's own position and the replay CONTINUES past it — the
    campaign's per-file isolation, source-side.
    """

    def __init__(self, ring: RingBuffer, files, selected_channels,
                 metadata=None, *, interrogator: str = "optasense",
                 engine: str = "h5py", wire: str = "conditioned",
                 prefetch: int = 2, realtime_factor: float | None = None,
                 read_deadline_s: float | None = None, fault_plan=None,
                 close_when_done: bool = True):
        self.ring = ring
        self.files = list(files)
        self.sel = selected_channels
        self.metadata = metadata
        self.interrogator = interrogator
        self.engine = engine
        self.wire = wire
        self.prefetch = prefetch
        self.factor = float(realtime_factor or 0.0)
        self.read_deadline_s = read_deadline_s
        self.fault_plan = fault_plan
        self.close_when_done = close_when_done
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "FileReplaySource":
        self._thread = threading.Thread(
            target=self._run, name=f"replay-{self.ring.tenant}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _run(self) -> None:
        from ..io.stream import stream_strain_blocks

        i = 0
        try:
            while i < len(self.files) and not self._stop.is_set():
                stream = stream_strain_blocks(
                    self.files[i:], self.sel, self._metas(i),
                    interrogator=self.interrogator, engine=self.engine,
                    prefetch=self.prefetch, as_numpy=True, wire=self.wire,
                    read_deadline_s=self.read_deadline_s,
                    fault_plan=self.fault_plan,
                )
                while not self._stop.is_set():
                    path = self.files[i] if i < len(self.files) else None
                    try:
                        block = next(stream)
                    except StopIteration:
                        i = len(self.files)
                        break
                    except Exception as exc:  # noqa: BLE001 — per-file isolation
                        # the failure surfaces at ITS file's ring slot;
                        # the stream restarts past the culprit (exactly
                        # the campaign runner's restart discipline)
                        self.ring.push_wait(IngestItem(path=path, error=exc))
                        i += 1
                        break
                    if not self.ring.push_wait(
                            IngestItem(path=path, block=block)):
                        return   # ring closed: drain in progress
                    i += 1
                    if self.factor > 0 and block is not None:
                        dur = block_duration_s(block)
                        if dur > 0:
                            # pace on the stop Event, not time.sleep: a
                            # drain request wakes the replay immediately
                            # instead of after the block's remaining
                            # real-time budget
                            self._stop.wait(dur / self.factor)
                del stream
        finally:
            if self.close_when_done:
                self.ring.close()

    def _metas(self, i: int):
        if self.metadata is None or not isinstance(self.metadata,
                                                   (list, tuple)):
            return self.metadata
        return list(self.metadata[i:])


class SlabSlicer:
    """Coalesce a tenant's ordered ingest items into batch slabs.

    The continuous analog of ``io.stream.stream_batched_slabs``'s host
    assembler: consecutive blocks sharing a bucket key ``(channels,
    bucket_ns, dtype)`` group into ``[batch, C, T]`` host stacks via
    ``io.stream.assemble_slab`` — THE shared bucket/padding rule — so
    service slabs are bit-identical to batch-campaign slabs over the
    same blocks in the same order. A bucket change flushes the partial
    group first (stream order is slab order, like the assembler).

    Because the stream is unbounded there is no end-of-list flush;
    instead ``linger_s`` bounds how long a partial group may wait for
    batch-mates: :meth:`take_ready` flushes it once the linger expires
    (or immediately when ``force``/the ring is exhausted). Error items
    surface in order as ``(None, [error items...])`` markers so the
    scheduler dispositions them exactly where the campaign would have.
    """

    def __init__(self, batch: int, bucket="pow2", linger_s: float = 0.25):
        if batch < 1:
            raise ValueError("batch must be >= 1")
        self.batch = int(batch)
        self.bucket_cfg = as_bucket_config(bucket)
        self.linger_s = float(linger_s)
        self._pending: List[IngestItem] = []
        self._cur_key: Tuple | None = None
        self._first_at: float = 0.0
        self._index = 0   # running per-tenant file index (slab.index0)

    def _flush(self):
        from ..io.stream import assemble_slab

        group = self._pending
        self._pending = []
        _C, b_ns, _dt = self._cur_key
        slab = assemble_slab(
            [it.block for it in group], [it.path for it in group],
            self._index, self.batch, b_ns,
        )
        self._index += len(group)
        return slab

    def offer(self, item: IngestItem):
        """Feed one ingest item; returns a list of outputs ready NOW —
        each either a flushed ``BatchSlab`` or the error item itself
        (surfaced after any earlier healthy partial slab, preserving
        stream-order attribution)."""
        out: list = []
        if item.error is not None:
            if self._pending:
                out.append(self._flush())
            self._index += 1   # the failed slot consumes its position
            out.append(item)
            return out
        tr = np.asarray(item.block.trace)
        b_ns = self.bucket_cfg.bucket_ns(tr.shape[1])
        key = (tr.shape[0], b_ns, tr.dtype)
        if self._pending and key != self._cur_key:
            out.append(self._flush())
        if not self._pending:
            self._first_at = time.monotonic()
        self._cur_key = key
        self._pending.append(item)
        if len(self._pending) == self.batch:
            out.append(self._flush())
        return out

    def pending(self) -> int:
        return len(self._pending)

    def linger_expired(self) -> bool:
        return bool(self._pending) and (
            time.monotonic() - self._first_at >= self.linger_s
        )

    def flush_partial(self):
        """Force the partial group out (linger expiry, ring exhausted,
        drain). None when nothing is pending."""
        return self._flush() if self._pending else None


def block_duration_s(block) -> float:
    """A block's recorded duration (for replay pacing / bench rates)."""
    meta = getattr(block, "metadata", None)
    fs = float(getattr(meta, "fs", 0.0) or 0.0)
    ns = int(np.asarray(block.trace).shape[-1])
    return ns / fs if fs > 0 else 0.0


@dataclass
class LiveBlock:
    """A minimal block for the HTTP live-ingest path: the service's
    slicer and executor only need ``.trace`` + ``.metadata`` (the
    replay path's ``StrainBlock`` carries more axes the service never
    reads)."""

    trace: np.ndarray
    metadata: object = None
    wire: str = "conditioned"
    t0_utc: object = field(default=None)
