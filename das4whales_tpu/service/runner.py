"""Service lifecycle: tenant registry, run loop, drain, resume.

``python -m das4whales_tpu serve tenants.json`` builds a
:class:`DetectionService` from a JSON tenant registry and runs it until
SIGTERM/SIGINT. The registry (docs/SERVICE.md) is::

    {
      "outdir": "out_service",
      "host": "127.0.0.1", "port": 8080,
      "dispatch_depth": 2, "trace": false,
      "tenants": [
        {"name": "array-a", "files": ["day1/*.h5 paths..."],
         "channels": [0, 9000, 1], "batch": 4, "bucket": "pow2",
         "bank": "fin", "hbm_share_gb": 8.0, "weight": 1.0,
         "ring_capacity": 8, "overflow": "reject",
         "realtime_factor": 1.0},
        ...
      ]
    }

Lifecycle contract (pinned by tests/test_service.py):

* **SIGTERM graceful drain** — sources stop, rings close, every
  dispatched-unresolved slab resolves through its own tenant's
  executor, per-tenant counters events flush, and the span trace
  exports to ``<outdir>/trace.json`` (when tracing is on). Files that
  were ingested but never detected simply have no manifest record.
* **crash/drain resume** — on the next start each tenant loads its
  settled set from its own manifest (the PR 4 semantics: done +
  quarantined settle; failed/timeout retry) and the replay source
  skips settled files at the source, so nothing re-runs and nothing is
  lost.
* per-tenant picks are bit-identical to a standalone
  ``run_campaign_batched`` over the same files — the service is the
  same math on the same slabs, scheduled differently.
"""

from __future__ import annotations

import json
import os
import signal
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..telemetry import trace as telemetry
from ..utils.log import get_logger
from .api import ServiceAPI
from .ingest import FileReplaySource
from .scheduler import StreamScheduler, TenantRuntime

log = get_logger("service.runner")


@dataclass
class TenantSpec:
    """One tenant (fiber array × subscriber configuration) in the
    registry. ``files`` is the replay/backfill source (empty for a
    live-ingest-only tenant); ``metadata`` (dict of
    ``config.AcquisitionMetadata`` fields) is required for live ingest
    and optional for replay (probed from the files otherwise)."""

    name: str
    files: List[str] = field(default_factory=list)
    #: explicit manifest/picks directory (default
    #: ``<service outdir>/<name>``). The fleet supervisor (ISSUE 20)
    #: pins every tenant to a STABLE fleet-level directory so the
    #: manifest — and with it every ``/picks`` cursor — survives
    #: migration between workers unchanged.
    outdir: str | None = None
    channels: List[int] | None = None
    batch: int = 4
    bucket: object = "pow2"
    #: detector family this tenant runs ("mf" | "spectro" | "gabor" |
    #: "learned" — ``workflows.campaign.FAMILIES``). Non-MF tenants
    #: require ``wire="conditioned"`` and bucket exactly (coerced, same
    #: rule as ``run_campaign_batched``: padded records would change
    #: their data-dependent thresholds/windows).
    family: str = "mf"
    bank: str | None = None
    wire: str = "conditioned"
    interrogator: str = "optasense"
    engine: str = "h5py"
    metadata: Dict | None = None
    #: DRR weight: 2.0 gets twice the megasample credit per round
    weight: float = 1.0
    #: this tenant's own HBM admission budget (None: the process
    #: DAS_HBM_BUDGET_GB) — the AOT preflight prices against it
    hbm_share_gb: float | None = None
    admission: bool = True
    ring_capacity: int = 8
    #: "reject" (full ring -> 429) or "drop_oldest" (evict + count)
    overflow: str = "reject"
    #: freshness SLO target: ``slo_objective`` of this tenant's picks
    #: must settle within ``slo_p95_s`` seconds of ring admission
    #: (None: no SLO evaluated — the latency histogram still records).
    #: Burn rates are evaluated over ``slo_windows`` seconds
    #: (``telemetry.slo``, docs/SERVICE.md "Serving SLOs").
    slo_p95_s: float | None = None
    slo_objective: float = 0.95
    slo_windows: List[float] | None = None
    #: replay pacing: 1.0 = real time, 0/None = as fast as the reader
    realtime_factor: float | None = None
    linger_s: float = 0.25
    retry: object = None
    health: object = True
    max_failures: int | None = None
    read_deadline_s: float | None = None
    dispatch_deadline_s: float | None = None
    serial: bool | None = None
    detector_kwargs: Dict = field(default_factory=dict)

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        from ..workflows.campaign import FAMILIES

        if self.family not in FAMILIES:
            raise ValueError(
                f"tenant {self.name!r}: unknown detector family "
                f"{self.family!r}; expected one of {FAMILIES}"
            )
        if self.family != "mf":
            if self.wire != "conditioned":
                raise ValueError(
                    f"tenant {self.name!r}: family={self.family!r} requires "
                    "wire='conditioned' (the family's prefilter consumes "
                    f"strain, not stored-dtype counts; got {self.wire!r})"
                )
            if self.bank is not None:
                raise ValueError(
                    f"tenant {self.name!r}: 'bank' is a matched-filter "
                    f"template grid; family={self.family!r} takes its "
                    "configuration through detector_kwargs"
                )
            if self.bucket != "exact":
                # the run_campaign_batched rule: non-MF families are not
                # padding-invariant (data-dependent thresholds/windows)
                log.info("tenant %s: family=%s buckets exactly (overriding "
                         "bucket=%r)", self.name, self.family, self.bucket)
                self.bucket = "exact"
        if self.dispatch_deadline_s is None:
            from ..config import dispatch_deadline_default

            self.dispatch_deadline_s = dispatch_deadline_default()

    def slo_policy(self):
        """The tenant's :class:`telemetry.slo.SLOPolicy`, or None when
        no ``slo_p95_s`` target is configured."""
        if self.slo_p95_s is None:
            return None
        from ..telemetry import slo as slo_mod

        windows = (tuple(float(w) for w in self.slo_windows)
                   if self.slo_windows else slo_mod.DEFAULT_WINDOWS)
        return slo_mod.SLOPolicy(
            target_s=float(self.slo_p95_s),
            objective=float(self.slo_objective), windows=windows,
        )

    def live_metadata(self):
        """Metadata for live-ingested blocks (the HTTP feed carries
        samples, not headers)."""
        if self.metadata is None:
            return None
        from ..config import as_metadata

        return as_metadata(self.metadata)


@dataclass
class ServiceConfig:
    tenants: List[TenantSpec]
    outdir: str = "out_service"
    host: str = "127.0.0.1"
    #: 0 binds an ephemeral port (tests); the bound port is
    #: ``DetectionService.api.port``
    port: int = 0
    dispatch_depth: int | None = None
    trace: bool | None = None
    #: arm the cost observatory (``telemetry.costs``) for this service
    #: process: None defers to ``DAS_COST_CARDS``; True enables — cost
    #: cards, live roofline fractions and ``cost_cards.json`` at drain
    cost_cards: bool | None = None
    #: arm the science-quality observatory (``telemetry.quality``,
    #: ISSUE 15): None defers to ``DAS_QUALITY``; True enables — pick
    #: stream/SNR/health telemetry, per-tenant drift baselines,
    #: ``GET /quality`` rows, and ``quality.json`` at drain. Drift
    #: never touches readiness, scheduling, or picks (docs/SERVICE.md)
    quality: bool | None = None
    resume: bool = True
    persistent_cache: bool | str = True


_TENANT_KEYS = {f.name for f in TenantSpec.__dataclass_fields__.values()}


def load_service_config(path: str) -> ServiceConfig:
    """Parse a JSON tenant registry into a :class:`ServiceConfig`
    (unknown keys fail loudly — a typo'd knob must not silently run
    with the default)."""
    with open(path) as fh:
        raw = json.load(fh)
    tenants = []
    for t in raw.get("tenants", []):
        unknown = set(t) - _TENANT_KEYS
        if unknown:
            raise ValueError(
                f"unknown tenant keys {sorted(unknown)} for "
                f"{t.get('name', '?')!r}; known: {sorted(_TENANT_KEYS)}"
            )
        tenants.append(TenantSpec(**t))
    if not tenants and not raw.get("allow_empty"):
        # a fleet spare worker (ISSUE 20) starts empty on purpose and
        # receives its tenants via POST /adopt — it opts in explicitly
        raise ValueError(f"{path}: no tenants configured")
    known = {"tenants", "outdir", "host", "port", "dispatch_depth", "trace",
             "cost_cards", "quality", "resume", "persistent_cache",
             "allow_empty"}
    unknown = set(raw) - known
    if unknown:
        raise ValueError(f"unknown service keys {sorted(unknown)}; "
                         f"known: {sorted(known)}")
    return ServiceConfig(
        tenants=tenants, outdir=raw.get("outdir", "out_service"),
        host=raw.get("host", "127.0.0.1"), port=int(raw.get("port", 0)),
        dispatch_depth=raw.get("dispatch_depth"),
        trace=raw.get("trace"), cost_cards=raw.get("cost_cards"),
        quality=raw.get("quality"),
        resume=bool(raw.get("resume", True)),
        persistent_cache=raw.get("persistent_cache", True),
    )


class DetectionService:
    """The persistent process: N tenants, one scheduler, one API.

    ``fault_plans`` maps tenant name -> ``faults.FaultPlan`` (the chaos
    harness, per tenant — tests only). Start with :meth:`start` (API +
    sources), run the scheduler with :meth:`run`; :meth:`request_stop`
    (the SIGTERM handler) begins the graceful drain.
    """

    def __init__(self, config: ServiceConfig, fault_plans=None):
        self.config = config
        os.makedirs(config.outdir, exist_ok=True)
        # the cost/quality observatories are process switches (their
        # consumers — dispatch brackets, scheduler resolves — read the
        # module flags): a service that asks for them turns them on for
        # its serving lifetime, and restores whatever it flipped at
        # stop() — the process may outlive the service (embedded/test
        # use), and a later campaign must not inherit this service's
        # switches
        self._restore_switches: list = []
        if config.cost_cards:
            from ..telemetry import costs as tcosts

            if not tcosts.enabled():
                self._restore_switches.append(tcosts.disable)
            tcosts.enable()
        if config.quality:
            # the enable must precede the tenant loop: TenantRuntime
            # reads the module flag at construction below
            from ..telemetry import quality as tquality

            if not tquality.enabled():
                self._restore_switches.append(tquality.disable)
            tquality.enable()
        if config.persistent_cache:
            from ..config import enable_persistent_compilation_cache

            enable_persistent_compilation_cache(
                config.persistent_cache
                if isinstance(config.persistent_cache, str) else None
            )
        fault_plans = fault_plans or {}
        self.tenants: Dict[str, TenantRuntime] = {}
        self.sources: Dict[str, FileReplaySource] = {}
        for spec in config.tenants:
            t = TenantRuntime(
                spec, spec.outdir or os.path.join(config.outdir, spec.name),
                resume=config.resume, fault_plan=fault_plans.get(spec.name),
            )
            self.tenants[spec.name] = t
            files = t.replay_files()
            if files:
                self.sources[spec.name] = FileReplaySource(
                    t.ring, files, spec.channels, spec.metadata,
                    interrogator=spec.interrogator, engine=spec.engine,
                    wire=spec.wire,
                    realtime_factor=spec.realtime_factor,
                    read_deadline_s=spec.read_deadline_s,
                    fault_plan=fault_plans.get(spec.name),
                )
            elif spec.files:
                # replay tenant with every file already settled: nothing
                # will ever arrive — close the ring so until_idle runs
                # (and the resume drill) terminate
                t.ring.close()
            # tenants with NO files configured are live-only: their ring
            # stays open for HTTP ingest until drain
        self.scheduler = StreamScheduler(self.tenants.values(),
                                         dispatch_depth=config.dispatch_depth)
        self.api = ServiceAPI(self, host=config.host, port=config.port)
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._started = False
        # brackets tenant-registry mutation from HTTP admin verbs
        # (/drain, /adopt): two concurrent adopts of the same name must
        # serialize through the registry check (ISSUE 20)
        self._admin_lock = threading.Lock()

    # -- the API's view ----------------------------------------------------

    def tenant(self, name: str) -> Optional[TenantRuntime]:
        return self.tenants.get(name)

    def snapshot(self) -> Dict:
        from ..telemetry import probes

        return {
            "outdir": self.config.outdir,
            "draining": self._stop.is_set(),
            "drained": self._drained.is_set(),
            "probes": probes.snapshot(),
            "in_flight_slabs": self.scheduler.pipe.in_flight(),
            # list(...) snapshots the registry: /drain and /adopt mutate
            # it from other HTTP threads (ISSUE 20)
            "tenants": [t.snapshot() for t in list(self.tenants.values())],
        }

    def slo_report(self) -> Dict:
        """The ``/slo`` surface: every tenant's SLO verdict (targets,
        multi-window burn rates, state) plus the burning list the
        ``/readyz`` detail embeds (docs/SERVICE.md)."""
        tenants = [t.slo_snapshot() for t in list(self.tenants.values())]
        return {
            "tenants": tenants,
            "burning": [s["tenant"] for s in tenants
                        if s.get("state") == "burning"],
        }

    def slo_burning(self) -> List[str]:
        return self.slo_report()["burning"]

    def quality_report(self) -> Dict:
        """The ``GET /quality`` surface (``telemetry.quality``): every
        scored tenant's quality row — pick totals, SNR percentiles,
        per-signal drift verdicts — plus the drifting list the
        ``/readyz`` detail embeds. Same records as ``quality.json`` and
        ``trace_report --quality``, by construction (one observatory)."""
        from ..telemetry import quality as tquality

        return tquality.OBSERVATORY.snapshot(tenants=list(self.tenants))

    def quality_drifting(self) -> List[str]:
        """The drifting names alone — ``/readyz`` polls this, so it
        reads one flag per tenant instead of building the full
        snapshot (SNR-tail sorts and all) per probe."""
        from ..telemetry import quality as tquality

        return tquality.OBSERVATORY.drifting_tenants(list(self.tenants))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "DetectionService":
        from ..telemetry import probes

        # a service start is a new serving lifetime: the probe streaks
        # describe THIS process-as-a-service, not whatever batch
        # campaigns ran in the process before (in production the two
        # coincide; embedded/tests they need not) — a freshly started
        # service must answer /livez healthy until ITS dispatches say
        # otherwise
        probes.reset()
        self.api.start()
        with self._admin_lock:
            self._started = True
            sources = list(self.sources.values())
        for src in sources:
            src.start()
        log.info("service up: %d tenant(s), api %s",
                 len(self.tenants), self.api.url)
        return self

    def request_stop(self) -> None:
        """Begin the graceful drain (idempotent; the SIGTERM handler).
        Sources stop, rings close (new ingest answers 429 'draining');
        the run loop finishes in-flight slabs and exits."""
        if self._stop.is_set():
            return
        log.info("drain requested: stopping sources, closing rings")
        self._stop.set()
        with self._admin_lock:
            sources = list(self.sources.values())
            tenants = list(self.tenants.values())
        for src in sources:
            src.stop()
        for t in tenants:
            t.ring.close()

    def run(self, until_idle: bool = True) -> Dict:
        """The scheduler loop, on the caller's thread, inside the trace
        harness. ``until_idle=True`` (replay/bench/backfill) returns
        once every source is exhausted and resolved; ``False`` (serve)
        runs until :meth:`request_stop`. Either way the exit path IS
        the drain: in-flight slabs resolve, tallies flush, the trace
        exports to ``<outdir>/trace.json``."""
        with telemetry.campaign_trace(
            self.config.outdir, self.config.trace, kind="service",
            n_tenants=len(self.tenants),
        ):
            try:
                self.scheduler.run_until_idle(should_stop=self._stop.is_set)
                if not until_idle:
                    # serve mode: stay up past idle (a live tenant's next
                    # HTTP push re-fills its ring) until a drain is
                    # requested
                    while not self._stop.is_set():
                        self._stop.wait(0.05)
                        self.scheduler.run_until_idle(
                            should_stop=self._stop.is_set
                        )
            finally:
                # the drain half that must happen on EVERY exit path:
                # finish in-flight slabs, flush per-tenant counters
                self.scheduler.drain()
                for t in list(self.tenants.values()):
                    t.finish()
                from ..telemetry import costs as tcosts

                if tcosts.enabled() and tcosts.REGISTRY.cards():
                    try:
                        tcosts.export_json(os.path.join(
                            self.config.outdir, "cost_cards.json"))
                    except OSError:
                        pass   # the drain outcome wins
                from ..telemetry import quality as tquality

                if tquality.enabled():
                    try:
                        # the quality observatory's durable artifact,
                        # next to cost_cards.json (docs/SERVICE.md)
                        tquality.export_json(
                            os.path.join(self.config.outdir,
                                         "quality.json"),
                            tenants=list(self.tenants),
                        )
                    except Exception:  # noqa: BLE001 — decorative export:
                        # the drain outcome (and _drained below) wins,
                        # same hardening as the campaign's _flush_quality
                        log.debug("quality export failed at drain",
                                  exc_info=True)
                self._drained.set()
        return {name: t.result() for name, t in list(self.tenants.items())}

    # -- fleet verbs (ISSUE 20: the two sides of one migration) -----------

    def drain_tenant(self, name: str, timeout_s: float = 30.0) -> Dict:
        """Gracefully drain ONE tenant (migration's sending verb, the
        ``POST /drain/<tenant>`` body). Its source stops and its ring
        closes (new ingest answers 429), buffered work resolves through
        the scheduler, the counters event and ``cost_card.json`` flush,
        and the settled manifest is left complete on disk — then the
        tenant leaves the rotation. Returns its final counts + outdir
        (everything the adopting worker needs)."""
        import time

        with self._admin_lock:
            t = self.tenants.get(name)
            if t is None:
                raise KeyError(name)
            src = self.sources.pop(name, None)
        if src is not None:
            src.stop()
        t.ring.close()
        done = threading.Event()
        self.scheduler.retire_when_idle(name, done)
        deadline = time.monotonic() + timeout_s
        while not done.wait(0.05):
            if self._drained.is_set():
                break   # the run loop's own drain already finished it
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"tenant {name!r} did not drain within {timeout_s:.0f}s"
                )
        with self._admin_lock:
            self.tenants.pop(name, None)
        res = t.result()
        return {
            "tenant": name, "outdir": t.outdir,
            "n_done": res.n_done, "n_failed": res.n_failed,
            "n_skipped": res.n_skipped,
            "n_quarantined": res.n_quarantined, "n_timeout": res.n_timeout,
        }

    def adopt_tenant(self, spec, outdir: str | None = None,
                     fault_plan=None) -> Dict:
        """Adopt a tenant from an existing outdir (migration's
        receiving verb, the ``POST /adopt`` body). ``spec`` is a
        :class:`TenantSpec` or registry dict. The outdir gets an
        EXPLICIT ``fsck.startup_check`` before the runtime touches it —
        a dead worker's directory must prove itself safe to resume —
        then the tenant joins the scheduler rotation and its un-settled
        files start replaying (settled ones skip at the source, so
        nothing re-runs: exactly the crash-resume semantics)."""
        from .. import fsck

        if isinstance(spec, dict):
            unknown = set(spec) - _TENANT_KEYS
            if unknown:
                raise ValueError(
                    f"unknown tenant keys {sorted(unknown)} for "
                    f"{spec.get('name', '?')!r}; known: "
                    f"{sorted(_TENANT_KEYS)}"
                )
            spec = TenantSpec(**spec)
        outdir = (outdir or spec.outdir
                  or os.path.join(self.config.outdir, spec.name))
        os.makedirs(outdir, exist_ok=True)
        fsck.startup_check(outdir, label=f"adopt {spec.name}")
        with self._admin_lock:
            if spec.name in self.tenants:
                raise ValueError(
                    f"tenant {spec.name!r} already registered")
            t = TenantRuntime(spec, outdir, resume=True,
                              fault_plan=fault_plan)
            self.tenants[spec.name] = t
            files = t.replay_files()
            if files:
                src = FileReplaySource(
                    t.ring, files, spec.channels, spec.metadata,
                    interrogator=spec.interrogator, engine=spec.engine,
                    wire=spec.wire, realtime_factor=spec.realtime_factor,
                    read_deadline_s=spec.read_deadline_s,
                    fault_plan=fault_plan,
                )
                self.sources[spec.name] = src
                if self._started:
                    src.start()
            elif spec.files:
                # every file already settled elsewhere: close the ring
                # so idle checks (and until_idle runs) terminate
                t.ring.close()
        self.scheduler.add_tenant(t)
        return {"tenant": spec.name, "outdir": outdir,
                "pending": len(files), "settled": len(t.settled)}

    def stop(self) -> None:
        """Tear down the API server (after :meth:`run` returned) and
        restore any observatory process-switch this service flipped on
        at construction (end of the serving lifetime)."""
        self.api.stop()
        for restore in self._restore_switches:
            restore()
        self._restore_switches = []

    def results(self) -> Dict:
        return {name: t.result() for name, t in list(self.tenants.items())}


def serve(config: ServiceConfig | str, until_idle: bool = False,
          install_signal_handlers: bool = True) -> Dict:
    """Run a service to completion: the ``python -m das4whales_tpu
    serve`` body. SIGTERM/SIGINT trigger the graceful drain."""
    if isinstance(config, str):
        config = load_service_config(config)
    svc = DetectionService(config)
    if install_signal_handlers:
        def _handler(signum, _frame):
            log.info("signal %d: draining", signum)
            svc.request_stop()

        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
    svc.start()
    try:
        return svc.run(until_idle=until_idle)
    finally:
        svc.stop()
