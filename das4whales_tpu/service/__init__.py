"""Streaming multi-tenant detection service (ROADMAP item 1).

The batch campaigns (``workflows.campaign``) terminate: one call, one
file list, one manifest. This package turns the same machinery into a
PERSISTENT process serving N fiber arrays × M subscribers — the
detector as a continuous operator over unbounded input, not a script
over files:

* :mod:`~das4whales_tpu.service.ingest` — bounded per-stream ring
  buffers (drop-oldest or reject backpressure, counted), a file-replay
  source for tests/bench, and the continuous slab slicer that reuses
  the batch campaign's bucket/padding rules bit-for-bit.
* :mod:`~das4whales_tpu.service.scheduler` — the multi-stream
  generalization of ``parallel.dispatch.PipelinedDispatch``:
  deficit-round-robin across tenants over ONE shared in-flight queue,
  per-tenant HBM admission via the AOT preflight, and the downshift
  ladder applied per tenant.
* :mod:`~das4whales_tpu.service.api` — a stdlib-only HTTP surface:
  NDJSON pick streams with cursor resume, ``/metrics`` (Prometheus),
  ``/livez``/``/readyz`` (``telemetry.probes``), and a live-ingest
  endpoint with explicit 429 backpressure.
* :mod:`~das4whales_tpu.service.runner` — lifecycle: the config-file
  tenant registry, SIGTERM graceful drain, crash-resume via the
  settled-manifest semantics, trace export.

``python -m das4whales_tpu serve tenants.json`` is the entry point;
docs/SERVICE.md is the operator contract.
"""

from .ingest import FileReplaySource, IngestItem, RingBuffer, SlabSlicer
from .runner import (
    DetectionService,
    ServiceConfig,
    TenantSpec,
    load_service_config,
)
from .scheduler import StreamScheduler, TenantRuntime

__all__ = [
    "DetectionService", "FileReplaySource", "IngestItem", "RingBuffer",
    "ServiceConfig", "SlabSlicer", "StreamScheduler", "TenantRuntime",
    "TenantSpec", "load_service_config",
]
