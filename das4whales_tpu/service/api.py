"""The served surface: picks streams, metrics, probes, live ingest.

Pure stdlib (``http.server``) so the service has zero web-framework
dependencies — the same discipline as ``telemetry`` (Prometheus text is
just text). Endpoints (docs/SERVICE.md):

``GET /livez`` / ``GET /readyz``
    ``telemetry.probes`` verdicts as 200/503 + JSON detail — the exact
    truth table PR 10 pinned (healthy / watchdog-tripped /
    quarantine-breached), now actually answerable by a load balancer.
    ``/readyz`` additionally carries ``slo_burning`` (tenants burning
    their error budget) as detail — informational, never a 503.
``GET /slo``
    Per-tenant serving-SLO verdicts (``telemetry.slo``): freshness
    target, multi-window burn rates, ``ok``/``warn``/``burning`` state,
    and the service-level burning list (docs/SERVICE.md).
``GET /quality``
    Per-tenant science-quality rows (``telemetry.quality``): pick
    totals, SNR percentiles, noise floor / dead-channel signals and
    the EWMA drift verdicts, plus the drifting list the ``/readyz``
    detail embeds — informational, never a 503 (docs/SERVICE.md).
``GET /metrics``
    The whole labeled registry as Prometheus text exposition 0.0.4
    (``telemetry.metrics.prometheus_text``).
``GET /tenants``
    JSON service snapshot: per-tenant disposition counts, ring depth,
    sticky rungs, DRR deficits.
``GET /picks/<tenant>?cursor=N&wait_s=S&limit=M&picks=1``
    The tenant's pick stream as NDJSON with CURSOR RESUME, backed by
    the append-only manifest: each line is one manifest record plus a
    ``cursor`` field naming the NEXT line to request, so a subscriber
    that reconnects with its last cursor misses nothing and re-reads
    nothing — the manifest IS the stream, no second bookkeeping.
    ``wait_s`` long-polls: with no new records the response blocks up
    to that long before returning (possibly empty), so a subscriber
    holds one cheap request open instead of hammering. ``picks=1``
    embeds the pick arrays from the ``.npz`` artifact into each
    ``done`` record.
``POST /ingest/<tenant>``
    One live block (binary body, shape/dtype in headers) into the
    tenant's ring buffer. A full ring under the tenant's ``reject``
    policy answers **429** with ``Retry-After`` — explicit
    backpressure the interrogator can act on; under ``drop_oldest``
    the push always lands (202) and the evicted block is counted as
    ``das_ingest_dropped_total{tenant}``.
``POST /drain/<tenant>?timeout_s=S``
    Gracefully drain ONE tenant (ISSUE 20: migration's sending verb):
    source stops, ring closes, buffered work resolves, counters and
    ``cost_card.json`` flush, settled manifest left complete — 200
    with final counts + outdir; 404 unknown tenant; 503 +
    ``Retry-After`` when the drain missed its deadline.
``POST /adopt``
    Register a tenant from an existing outdir (migration's receiving
    verb). JSON body: a tenant-registry spec, optionally wrapped as
    ``{"spec": {...}, "outdir": "..."}``. ``fsck.startup_check`` runs
    FIRST — 409 when the directory refuses (corruption), 400 on a bad
    spec, 200 with ``{pending, settled}`` counts on success.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..telemetry import metrics, probes
from ..utils import artifacts, locks
from ..utils.log import get_logger
from .ingest import IngestItem, LiveBlock

log = get_logger("service.api")

#: Retry-After seconds suggested on a 429 (reject-policy full ring).
RETRY_AFTER_S = 1


class _NamedThreadingHTTPServer(ThreadingHTTPServer):
    """``ThreadingHTTPServer`` whose per-request handler threads carry a
    component name (``http-handler-N``) instead of ``Thread-N``, so
    traces, logs and the ``das_lock_*`` metrics attribute a slow
    subscriber to the HTTP surface (daslint R10 ``unnamed-thread``)."""

    _handler_seq = itertools.count()

    def process_request(self, request, client_address):
        # socketserver.ThreadingMixIn.process_request, plus a name; the
        # non-daemon ``_threads`` bookkeeping is irrelevant here — the
        # service always runs ``daemon_threads = True``
        t = threading.Thread(
            target=self.process_request_thread,
            args=(request, client_address),
            name=f"http-handler-{next(self._handler_seq)}",
            daemon=self.daemon_threads,
        )
        t.start()


def _probe_payload(result) -> dict:
    return {"ok": bool(result), "reason": result.reason,
            "detail": result.detail}


# ---------------------------------------------------------------------------
# The per-manifest NDJSON line index
# ---------------------------------------------------------------------------

class _ManifestIndex:
    """One manifest's line-offset index: ``offsets[i]`` is the byte
    offset of line ``i``; ``offsets[-1]`` is the scan-resume offset.
    The manifest is APPEND-ONLY, so offsets never invalidate; each poll
    reads only bytes past the last indexed complete line — O(new data),
    not O(file). Memory: one int per manifest line.

    The lock is PER MANIFEST (daslint R9's first real catch, ISSUE 13):
    the index lock used to be one class-level ``_index_lock`` shared by
    every handler thread, so one slow tenant's manifest read serialized
    ALL tenants' NDJSON polls. Now contention scopes to one tenant's
    stream — and the file IO happens OUTSIDE the lock besides."""

    __slots__ = ("lock", "offsets")

    def __init__(self):
        self.lock = locks.new_lock("manifest-index")
        self.offsets = [0]


_indexes: dict = {}
_indexes_lock = locks.new_lock("manifest-index-registry")


def _index_for(path: str) -> _ManifestIndex:
    """The (created-once) index of one manifest path. The registry lock
    guards only the dict lookup — never any IO."""
    with _indexes_lock:
        idx = _indexes.get(path)
        if idx is None:
            idx = _indexes[path] = _ManifestIndex()
        return idx


def _extend_index(path: str) -> list:
    """Index any newly appended complete lines; returns a snapshot of
    the offsets list. Only COMPLETE (newline-terminated) lines are
    indexed: a torn final line — a crash mid-append — stays invisible
    until its rewrite completes on resume.

    The file read runs OUTSIDE the index lock (R9 blocking-under-lock):
    the lock brackets only the offset bookkeeping, so a slow disk never
    queues other subscriber threads of the same tenant. A concurrent
    extender that raced us simply discards its overlap (the guard on
    the scan-resume offset); the next poll picks up anything dropped."""
    idx = _index_for(path)
    with idx.lock:
        start = idx.offsets[-1]
    try:
        with open(path, "rb") as fh:
            fh.seek(start)
            tail = fh.read()
    except OSError:
        with idx.lock:
            return list(idx.offsets)
    # one pass with a running offset — a cold index against a week-long
    # tenant's multi-MB manifest must not re-copy the tail per line
    new = []
    pos = 0
    while True:
        nl = tail.find(b"\n", pos)
        if nl < 0:
            break
        pos = nl + 1
        new.append(start + pos)
    with idx.lock:
        if new and idx.offsets[-1] == start:
            idx.offsets.extend(new)
        return list(idx.offsets)


def _manifest_since(outdir: str, cursor: int, limit: int, wait_s: float):
    """Manifest records past line ``cursor`` (the append-only file is
    the stream). Long-polls up to ``wait_s`` when nothing is new."""
    path = os.path.join(outdir, "manifest.jsonl")
    deadline = time.monotonic() + max(0.0, wait_s)
    while True:
        idx = _extend_index(path)
        n_complete = len(idx) - 1
        recs = []
        consumed = 0
        if cursor < n_complete:
            stop = min(cursor + limit, n_complete)
            try:
                with open(path, "rb") as fh:
                    fh.seek(idx[cursor])
                    chunk = fh.read(idx[stop] - idx[cursor])
                for line in chunk.splitlines():
                    consumed += 1
                    # the shared checksum-verifying ledger parser:
                    # accepts plain and CRC-suffixed lines; a corrupt
                    # line is skipped but still advances the cursor
                    # (a poisoned record must not wedge the stream)
                    rec, _verdict = artifacts.parse_record(
                        line.decode("utf-8", errors="replace"))
                    if rec is not None:
                        recs.append(rec)
            except OSError:
                recs, consumed = [], 0   # raced a rewrite: retry below
        if recs or consumed or time.monotonic() >= deadline:
            return recs, cursor + consumed
        time.sleep(0.05)


class ServiceAPI:
    """The HTTP server bound to one running service (``runner``)."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        self.service = service
        api = self

        class Handler(BaseHTTPRequestHandler):
            # one service, many subscriber threads: ThreadingHTTPServer
            # below serves each request on its own daemon thread
            def log_message(self, fmt, *args):  # noqa: D401, N802
                log.debug("http: " + fmt, *args)

            def _send(self, code: int, body: bytes,
                      ctype: str = "application/json",
                      extra: dict | None = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, payload,
                           extra: dict | None = None) -> None:
                self._send(code, (json.dumps(payload) + "\n").encode(),
                           extra=extra)

            def do_GET(self):  # noqa: N802
                try:
                    api._get(self)
                except BrokenPipeError:   # subscriber went away mid-write
                    pass
                except Exception as exc:  # noqa: BLE001 — 500, keep serving
                    log.warning("http GET %s failed: %s", self.path, exc)
                    try:
                        self._send_json(500, {"error": str(exc)})
                    except Exception:  # noqa: BLE001
                        pass

            def do_POST(self):  # noqa: N802
                try:
                    api._post(self)
                except Exception as exc:  # noqa: BLE001
                    log.warning("http POST %s failed: %s", self.path, exc)
                    try:
                        self._send_json(500, {"error": str(exc)})
                    except Exception:  # noqa: BLE001
                        pass

        self._server = _NamedThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServiceAPI":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="service-api",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- request routing ---------------------------------------------------

    def _get(self, h) -> None:
        url = urlparse(h.path)
        parts = [p for p in url.path.split("/") if p]
        if url.path == "/livez":
            res = probes.liveness()
            h._send_json(200 if res else 503, _probe_payload(res))
        elif url.path == "/readyz":
            res = probes.readiness()
            payload = _probe_payload(res)
            # SLO burn detail rides the readiness answer (ISSUE 14): a
            # tenant burning its error budget never flips readiness —
            # the process is healthy, its latency objective is not —
            # but the operator polling /readyz sees WHO is burning
            # without a second request (docs/SERVICE.md)
            burning = self.service.slo_burning()
            if burning:
                payload["slo_burning"] = burning
            # quality-drift detail rides the same way (ISSUE 15): a
            # drifting tenant NEVER flips readiness — the process is
            # healthy, the science may not be — but the operator
            # polling /readyz sees WHO is drifting without a second
            # request (docs/SERVICE.md)
            drifting = self.service.quality_drifting()
            if drifting:
                payload["quality_drifting"] = drifting
            h._send_json(200 if res else 503, payload)
        elif url.path == "/slo":
            h._send_json(200, self.service.slo_report())
        elif url.path == "/quality":
            h._send_json(200, self.service.quality_report())
        elif url.path == "/metrics":
            # burn gauges refresh at evaluation time, not per pick: a
            # scrape must see the CURRENT window (breaches aging out
            # decay the gauge even with no new picks), so evaluate
            # every tenant's SLO before rendering the exposition
            self.service.slo_report()
            h._send(200, metrics.prometheus_text().encode(),
                    ctype="text/plain; version=0.0.4")
        elif url.path == "/tenants":
            h._send_json(200, self.service.snapshot())
        elif len(parts) == 2 and parts[0] == "picks":
            self._get_picks(h, parts[1], parse_qs(url.query))
        else:
            h._send_json(404, {"error": f"no route {url.path}"})

    def _get_picks(self, h, tenant: str, q) -> None:
        t = self.service.tenant(tenant)
        if t is None:
            h._send_json(404, {"error": f"unknown tenant {tenant!r}"})
            return
        cursor = int(q.get("cursor", ["0"])[0])
        wait_s = float(q.get("wait_s", ["0"])[0])
        limit = int(q.get("limit", ["1000"])[0])
        embed = q.get("picks", ["0"])[0] not in ("0", "", "false")
        lines, cursor = _manifest_since(t.outdir, cursor, limit, wait_s)
        out = []
        next_cursor = cursor - len(lines)
        for rec in lines:
            next_cursor += 1
            rec["cursor"] = next_cursor
            if embed and rec.get("status") == "done" and rec.get("picks_file"):
                try:
                    from ..workflows.campaign import load_picks

                    rec["picks"] = {
                        name: np.asarray(pk).tolist()
                        for name, pk in load_picks(rec["picks_file"]).items()
                    }
                except OSError:
                    rec["picks"] = None
            out.append(json.dumps(rec))
        body = ("\n".join(out) + ("\n" if out else "")).encode()
        h._send(200, body, ctype="application/x-ndjson",
                extra={"X-DAS-Cursor": cursor})

    def _post(self, h) -> None:
        url = urlparse(h.path)
        parts = [p for p in url.path.split("/") if p]
        if len(parts) == 2 and parts[0] == "drain":
            self._post_drain(h, parts[1], parse_qs(url.query))
            return
        if len(parts) == 1 and parts[0] == "adopt":
            self._post_adopt(h)
            return
        if len(parts) != 2 or parts[0] != "ingest":
            h._send_json(404, {"error": f"no route {h.path}"})
            return
        t = self.service.tenant(parts[1])
        if t is None:
            h._send_json(404, {"error": f"unknown tenant {parts[1]!r}"})
            return
        try:
            shape = tuple(int(v) for v in
                          h.headers.get("X-DAS-Shape", "").split(","))
            dtype = np.dtype(h.headers.get("X-DAS-Dtype", "float32"))
            if len(shape) != 2:
                raise ValueError("X-DAS-Shape must be 'channels,samples'")
            n = int(h.headers.get("Content-Length", 0))
            raw = h.rfile.read(n)
            arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
        except Exception as exc:  # noqa: BLE001 — bad payload is a 400
            h._send_json(400, {"error": f"bad block: {exc}"})
            return
        # the name is the manifest/retry/artifact identity key: un-named
        # pushes draw a per-tenant monotonic sequence (a wall-clock
        # default can collide within one millisecond)
        name = h.headers.get("X-DAS-Name") or t.next_live_name()
        block = LiveBlock(trace=arr, metadata=t.spec.live_metadata(),
                          wire=t.spec.wire)
        if t.ring.push(IngestItem(path=name, block=block)):
            h._send_json(202, {"accepted": name, "ring_depth": len(t.ring)})
        else:
            # explicit backpressure: the ring is full under the reject
            # policy (or closed during drain) — the interrogator should
            # back off and retry (docs/SERVICE.md)
            h._send_json(429, {
                "error": "ring buffer full (reject policy)"
                if not t.ring.closed else "service draining",
                "ring_depth": len(t.ring),
            }, extra={"Retry-After": RETRY_AFTER_S})

    # -- fleet verbs (ISSUE 20) -------------------------------------------

    def _post_drain(self, h, tenant: str, q) -> None:
        timeout_s = float(q.get("timeout_s", ["30"])[0])
        try:
            summary = self.service.drain_tenant(tenant, timeout_s=timeout_s)
        except KeyError:
            h._send_json(404, {"error": f"unknown tenant {tenant!r}"})
            return
        except TimeoutError as exc:
            # the drain is still in progress (retire stays queued): the
            # caller should retry, NOT conclude the tenant moved
            h._send_json(503, {"error": str(exc)},
                         extra={"Retry-After": RETRY_AFTER_S})
            return
        h._send_json(200, summary)

    def _post_adopt(self, h) -> None:
        try:
            n = int(h.headers.get("Content-Length", 0))
            body = json.loads(h.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("adopt body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as exc:
            h._send_json(400, {"error": f"bad adopt body: {exc}"})
            return
        spec = body.get("spec", body)
        outdir = body.get("outdir") if "spec" in body else None
        try:
            summary = self.service.adopt_tenant(spec, outdir=outdir)
        except (TypeError, ValueError) as exc:
            h._send_json(400, {"error": str(exc)})
            return
        except RuntimeError as exc:
            # fsck.startup_check refused the directory: adopting it
            # would resume over corruption — surface, do not register
            h._send_json(409, {"error": str(exc)})
            return
        h._send_json(200, summary)
