"""Unified command line: ``python -m das4whales_tpu <workflow> [options]``.

The reference ships its pipelines as separate scripts
(``scripts/main_mfdetect.py``, ``main_spectrodetect.py``, ...); here the
same six workflows hang off one discoverable entry point. Every workflow
runs fully offline on a synthetic OOI-like scene when no URL/file is
given, or on a real OptaSense/Silixa file when one is.

Examples::

    python -m das4whales_tpu mfdetect --outdir out            # offline demo
    python -m das4whales_tpu mfdetect https://.../file.h5
    python -m das4whales_tpu mfdetect --no-snr
    python -m das4whales_tpu longrecord seg0.h5 seg1.h5       # one record
    python -m das4whales_tpu campaign *.h5 --outdir out_camp
    python -m das4whales_tpu list
"""

from __future__ import annotations

import argparse
import importlib
import sys

WORKFLOWS = {
    "mfdetect": "matched-filter detection (flagship: bandpass -> f-k -> "
                "HF/LF correlograms -> envelope peak picks)",
    "spectrodetect": "spectrogram-correlation detection (hat kernels)",
    "gabordetect": "Gabor / image-processing detection",
    "fkcomp": "f-k filter design comparison figures",
    "plots": "exploratory t-x / f-x / spectrogram plots",
    "bathynoise": "bathymetry-referenced noise maps",
}


def _add_route_flags(p, default, extra=""):
    """The one filter-route knob, spelled once: --fused (library default)
    vs --staged (the golden-validation baseline route)."""
    p.add_argument("--fused", dest="fused", action="store_true", default=default,
                   help="fused bandpass∘f-k route" + extra)
    p.add_argument("--staged", dest="fused", action="store_false",
                   help="opt back to the staged bandpass->f-k route")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="das4whales_tpu",
        description=__doc__.split("\n\n")[0],
    )
    sub = ap.add_subparsers(dest="workflow", required=True)
    sub.add_parser("list", help="list available workflows")
    pf = sub.add_parser(
        "fsck",
        help="verify (and with --repair fix) campaign/service artifact "
             "state after an unclean death: orphan tmps, torn or "
             "checksum-failed manifest records, truncated JSON exports, "
             "manifest<->picks mismatches (docs/ROBUSTNESS.md "
             "\"Durability contract\")",
    )
    pf.add_argument("outdir", help="campaign outdir or service root")
    pf.add_argument("--repair", action="store_true",
                    help="fix what was found: truncate torn tails, "
                         "quarantine corrupt lines into "
                         "manifest.corrupt.jsonl, remove orphans")
    pf.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    pe = sub.add_parser(
        "evaluate",
        help="detection-quality sweep: injection recall/precision vs SNR "
             "on the production matched-filter detector (das4whales_tpu.eval)",
    )
    pe.add_argument("--amplitudes", default="0.02,0.05,0.15,0.5,1.0",
                    help="comma-separated call amplitudes (noise RMS 0.05)")
    pe.add_argument("--seeds", default="0", help="comma-separated noise seeds")
    pe.add_argument("--nx", type=int, default=256)
    pe.add_argument("--ns", type=int, default=6000)
    pe.add_argument("--family", default="mf",
                    choices=("mf", "spectro", "gabor", "learned", "all"),
                    help="detector family to score (all: cross-family table; "
                         "learned trains its CNN on synthetic scenes first)")
    pe.add_argument("--time-tol", type=float, default=0.5,
                    help="pick-to-arrival match tolerance [s]")
    pe.add_argument("--out", default=None,
                    help="also write the sweep JSON here")
    pe.add_argument("--figure", default=None,
                    help="also render recall/precision curves (PNG; "
                         "per-family suffix with --family all)")
    _add_route_flags(pe, default=True, extra=" (the library default)")
    pc = sub.add_parser(
        "campaign",
        help="fault-tolerant resumable detection over many files "
             "(workflows.campaign: manifest + per-file picks artifacts)",
    )
    pc.add_argument("files", nargs="+", help="HDF5/TDMS file paths, in order")
    pc.add_argument("--outdir", default="out_campaign")
    pc.add_argument("--channels", default=None,
                    help="start,stop,step channel selection (default: all of file 0)")
    pc.add_argument("--max-failures", type=int, default=None)
    pc.add_argument("--trace", action="store_true", default=None,
                    help="arm the flight recorder: span-trace the campaign "
                         "and export <outdir>/trace.json "
                         "(Perfetto/Chrome-trace; same as DAS_TRACE=1 — "
                         "docs/OBSERVABILITY.md). Single-chip campaigns "
                         "only; ignored with a warning under "
                         "--sharded/--multihost")
    pc.add_argument("--no-resume", action="store_true",
                    help="reprocess files already recorded done in the manifest")
    pc.add_argument("--interrogator", default="optasense")
    pc.add_argument("--sharded", action="store_true",
                    help="detect batches on a (file x channel) device mesh "
                         "(workflows.campaign.run_campaign_sharded)")
    pc.add_argument("--multihost", action="store_true",
                    help="one SPMD campaign across ALL processes of a "
                         "multi-process JAX runtime (launch every host "
                         "with JAX_COORDINATOR/JAX_NUM_PROCESSES/"
                         "JAX_PROCESS_ID and the same command; "
                         "workflows.campaign.run_campaign_multiprocess)")
    pc.add_argument("--bank", default=None,
                    help="mf-family TEMPLATE BANK: a registered name "
                         "(fin, fin-variants, blue) or a "
                         "'chirp-grid:T[:fmin-fmax[:durs]]' spec — all T "
                         "templates detect in ONE dispatch per file/slab "
                         "(models/templates.py; default: "
                         "DAS_TEMPLATE_BANK, else the reference fin pair)")
    pc.add_argument("--family", default="mf",
                    choices=("mf", "spectro", "gabor", "learned"),
                    help="detector family (spectro/gabor run through the "
                         "shared bandpass+f-k front end; learned needs "
                         "--model; all three single-chip only)")
    pc.add_argument("--model", default=None,
                    help="trained learned-family model (.npz from "
                         "models.learned.save_params; required for "
                         "--family learned)")
    _add_route_flags(pc, default=True,
                     extra=" (library default; also governs the spectro/"
                           "gabor families' shared bandpass+f-k front end)")
    ps = sub.add_parser(
        "serve",
        help="run the streaming multi-tenant detection service: "
             "continuous ingest, fair multi-stream scheduling, and the "
             "picks/health HTTP API (das4whales_tpu.service; "
             "docs/SERVICE.md)",
    )
    ps.add_argument("config",
                    help="JSON tenant registry (tenants, outdir, port — "
                         "schema in docs/SERVICE.md)")
    ps.add_argument("--port", type=int, default=None,
                    help="override the registry's API port (0: ephemeral)")
    ps.add_argument("--outdir", default=None,
                    help="override the registry's output root")
    ps.add_argument("--until-idle", action="store_true",
                    help="exit once every replay source is exhausted and "
                         "resolved (backfill mode) instead of serving "
                         "until SIGTERM")
    ps.add_argument("--no-resume", action="store_true",
                    help="reprocess files already settled in the tenant "
                         "manifests")
    ps.add_argument("--trace", action="store_true", default=None,
                    help="arm the flight recorder for the whole service "
                         "run (exports <outdir>/trace.json at drain)")
    pf = sub.add_parser(
        "fleet",
        help="run a supervised multi-worker fleet: N DetectionService "
             "subprocesses, cost-card placement, failure detection, "
             "migration-as-recovery, and the tenant-keyed router "
             "(das4whales_tpu.fleet; docs/FLEET.md)",
    )
    pf.add_argument("config",
                    help="JSON fleet registry (tenants, workers, root — "
                         "schema in docs/FLEET.md)")
    pf.add_argument("--port", type=int, default=None,
                    help="override the router port (0: ephemeral)")
    pf.add_argument("--root", default=None,
                    help="override the fleet root directory")
    pf.add_argument("--workers", type=int, default=None,
                    help="override the worker count")
    pf.add_argument("--until-settled", action="store_true",
                    help="exit once every tenant's file list is "
                         "manifest-settled fleet-wide (backfill mode) "
                         "instead of serving until SIGTERM")
    pf.add_argument("--settle-timeout", type=float, default=600.0,
                    help="--until-settled deadline in seconds")
    pl = sub.add_parser(
        "longrecord",
        help="continuous detection across file boundaries: consecutive "
             "files become ONE time-sharded record (workflows.longrecord; "
             "boundary-straddling calls the per-file reference mode loses)",
    )
    pl.add_argument("files", nargs="+",
                    help="consecutive segments of one recording, in order")
    pl.add_argument("--outdir", default="out_longrecord")
    pl.add_argument("--channels", default=None,
                    help="start,stop,step channel selection (default: all of file 0)")
    pl.add_argument("--family", default="mf",
                    choices=("mf", "spectro", "gabor", "learned"))
    pl.add_argument("--model", default=None,
                    help="trained learned-family model (.npz; required for "
                         "--family learned)")
    pl.add_argument("--halo", type=int, default=512,
                    help="time-shard halo samples for the STAGED bandpass "
                         "(all families; the mf fused default has no "
                         "halo-exchange bandpass and ignores it — pass "
                         "--staged to make --halo effective)")
    _add_route_flags(pl, default=None,
                     extra=" (mf-family default; spectro/gabor design "
                           "their own bandpass)")
    pl.add_argument("--max-peaks", type=int, default=512,
                    help="pick capacity per channel")
    pl.add_argument("--interrogator", default="optasense")
    for name, help_text in WORKFLOWS.items():
        p = sub.add_parser(name, help=help_text)
        p.add_argument("url", nargs="?", default=None,
                       help="HDF5/TDMS file path or URL (omit: offline synthetic scene)")
        p.add_argument("--outdir", default=f"out_{name}",
                       help="directory for figures/artifacts (default: out_<workflow>)")
        p.add_argument("--show", action="store_true", help="show figures interactively")
        if name in ("mfdetect",):
            p.add_argument("--no-snr", action="store_true", help="skip SNR matrices")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.workflow == "list":
        for name, help_text in WORKFLOWS.items():
            print(f"{name:15s} {help_text}")
        return 0
    if args.workflow == "fsck":
        # host-only verify/repair: dispatched before any jax/runtime
        # setup so a corrupt outdir can be inspected from anywhere
        import json as _json

        from das4whales_tpu.fsck import fsck_outdir, render_findings

        findings = fsck_outdir(args.outdir, repair=args.repair)
        if args.as_json:
            print(_json.dumps([f.as_dict() for f in findings], indent=1))
        else:
            print(render_findings(findings))
        return 1 if any(not f.repaired for f in findings) else 0
    # honor JAX_PLATFORMS through the live config too: some environments
    # register an accelerator plugin from sitecustomize that the env var
    # alone cannot keep jax off (see tests/conftest.py) — a CLI run pinned
    # to CPU must never hang on an unreachable accelerator
    import os

    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # multi-host launches: form the multi-process runtime when the
    # JAX_COORDINATOR/JAX_NUM_PROCESSES/JAX_PROCESS_ID env is present
    # (no-op on a single host)
    from das4whales_tpu.parallel.distributed import initialize_from_env

    initialize_from_env()
    if args.workflow == "evaluate":
        import json

        from das4whales_tpu.eval import (
            GaborEvalAdapter,
            SpectroEvalAdapter,
            amplitude_sweep,
            default_eval_scene,
        )
        from das4whales_tpu.models.matched_filter import MatchedFilterDetector

        scene = default_eval_scene(nx=args.nx, ns=args.ns)
        mf = MatchedFilterDetector(
            scene.metadata, [0, scene.nx, 1], (scene.nx, scene.ns),
            fused_bandpass=args.fused,
        )
        detectors = {"mf": mf}
        if args.family in ("spectro", "all"):
            from das4whales_tpu.models.spectro import SpectroCorrDetector

            detectors["spectro"] = SpectroEvalAdapter(
                mf, SpectroCorrDetector(scene.metadata)
            )
        if args.family in ("gabor", "all"):
            from das4whales_tpu.models.gabor import GaborDetector

            detectors["gabor"] = GaborEvalAdapter(
                mf, GaborDetector(scene.metadata, [0, scene.nx, 1])
            )
        if args.family in ("learned", "all"):
            # trained on the fly: synthetic scenes disjoint from the eval
            # scene (different seeds/geometry), ~a minute on one core
            from das4whales_tpu.io.synth import SyntheticCall, SyntheticScene
            from das4whales_tpu.models import learned

            cfg = learned.LearnedConfig()
            train_scenes = [
                SyntheticScene(
                    nx=min(64, scene.nx), ns=min(4000, scene.ns),
                    dx=scene.dx, noise_rms=scene.noise_rms or 0.08,
                    seed=1000 + s,
                    # amplitude curriculum reaching into the low-SNR
                    # regime the sweep scores (0.12 ~ 8 dB here)
                    calls=[
                        SyntheticCall(t0=2.5 + 3.5 * k,
                                      x0_m=(0.15 + 0.18 * k) * min(64, scene.nx) * scene.dx,
                                      amplitude=0.12 + 0.22 * k + 0.04 * s)
                        for k in range(4)
                    ],
                )
                for s in range(3)
            ]
            params, _ = learned.fit(cfg, train_scenes, epochs=25, batch=512)
            detectors["learned"] = learned.LearnedDetector(params, cfg)
        if args.family != "all":
            detectors = {args.family: detectors[args.family]}
        amps = [float(a) for a in args.amplitudes.split(",")]
        seeds = [int(s) for s in args.seeds.split(",")]
        out = {
            fam: amplitude_sweep(det, scene, amps, seeds=seeds,
                                 time_tol_s=args.time_tol)
            for fam, det in detectors.items()
        }
        def _no_nan(v):
            # zero-pick sweep points carry precision=NaN; strict-JSON
            # consumers (jq, json.load) reject bare NaN tokens
            if isinstance(v, dict):
                return {k: _no_nan(x) for k, x in v.items()}
            if isinstance(v, list):
                return [_no_nan(x) for x in v]
            if isinstance(v, float) and v != v:
                return None
            return v

        payload = _no_nan(out if args.family == "all" else out[args.family])
        if args.out:
            from das4whales_tpu.utils.artifacts import atomic_json

            atomic_json(args.out, payload, indent=1)
            print("wrote", args.out, file=sys.stderr)
        if args.figure:
            import matplotlib

            matplotlib.use("Agg")
            from das4whales_tpu.viz.plot import plot_eval_curves

            stem, ext = os.path.splitext(args.figure)
            for fam, rows in out.items():
                fig = plot_eval_curves(rows, show=False)
                path = (args.figure if args.family != "all" else
                        f"{stem}_{fam}{ext or '.png'}")
                fig.savefig(path, dpi=90)
                print("wrote", path, file=sys.stderr)
        print(json.dumps(payload, indent=1))
        return 0
    if args.workflow == "serve":
        from das4whales_tpu.service import load_service_config
        from das4whales_tpu.service.runner import serve

        cfg = load_service_config(args.config)
        if args.port is not None:
            cfg.port = args.port
        if args.outdir is not None:
            cfg.outdir = args.outdir
        if args.no_resume:
            cfg.resume = False
        if args.trace:
            cfg.trace = True
        results = serve(cfg, until_idle=args.until_idle)
        n_failed = 0
        for name, res in results.items():
            n_failed += res.n_failed
            print(f"serve: tenant {name}: {res.n_done} done, "
                  f"{res.n_failed} failed, {res.n_skipped} skipped, "
                  f"{res.n_quarantined} quarantined, "
                  f"{res.n_timeout} timeout -> {res.outdir}")
        return 0 if n_failed == 0 else 3
    if args.workflow == "fleet":
        import signal as _signal
        import threading as _threading

        from das4whales_tpu.fleet import (FleetRouter, FleetSupervisor,
                                          load_fleet_config)

        fcfg = load_fleet_config(args.config)
        if args.root is not None:
            fcfg.root = args.root
        if args.workers is not None:
            fcfg.workers = args.workers
        if args.port is not None:
            fcfg.port = args.port
        sup = FleetSupervisor(fcfg)
        router = None
        stop_ev = _threading.Event()
        try:
            sup.start()
            router = FleetRouter(sup, host=fcfg.host,
                                 port=fcfg.port).start()
            print(f"fleet: router at {router.url} "
                  f"({fcfg.workers} workers)", file=sys.stderr)
            if args.until_settled:
                ok = sup.wait_until_settled(timeout_s=args.settle_timeout)
                if not ok:
                    print("fleet: settle timeout", file=sys.stderr)
                    return 3
                return 0
            for sig in (_signal.SIGTERM, _signal.SIGINT):
                _signal.signal(sig, lambda *_a: stop_ev.set())
            stop_ev.wait()
            return 0
        finally:
            if router is not None:
                router.stop()
            sup.stop()
    if args.workflow == "longrecord":
        import numpy as np

        from das4whales_tpu.io.interrogators import get_acquisition_parameters
        from das4whales_tpu.workflows.longrecord import detect_long_record

        meta = get_acquisition_parameters(args.files[0], args.interrogator)
        sel = ([int(v) for v in args.channels.split(",")]
               if args.channels else [0, meta.nx, 1])
        # pass --fused through unconditionally: the workflow itself rejects
        # it for non-mf families, and silently dropping the flag would let
        # a user believe the fused route ran when it did not
        fam_kw = None
        if args.family == "learned":
            if not args.model:
                print("longrecord: --family learned requires --model")
                return 2
            fam_kw = {"model": args.model}
        res = detect_long_record(
            args.files, sel, meta,
            family=args.family, halo=args.halo,
            fused_bandpass=args.fused,
            max_peaks_per_channel=args.max_peaks,
            interrogator=args.interrogator,
            family_kwargs=fam_kw,
        )
        from das4whales_tpu.utils.artifacts import atomic_file, atomic_json

        os.makedirs(args.outdir, exist_ok=True)
        with atomic_file(os.path.join(args.outdir, "picks.npz"),
                         "wb") as fh:
            np.savez(
                fh,
                **{f"picks_{k}": v for k, v in res.picks.items()},
                **{f"times_s_{k}": v for k, v in res.pick_times_s.items()},
            )
        summary = {
            "files": list(args.files), "family": args.family,
            "n_files": res.n_files, "n_samples": res.n_samples,
            "t0_utc": str(res.t0_utc),
            "thresholds": res.thresholds,
            "n_picks": {k: int(v.shape[1]) for k, v in res.picks.items()},
        }
        atomic_json(os.path.join(args.outdir, "summary.json"), summary,
                    indent=1)
        for name, pk in res.picks.items():
            span = (f" [{res.pick_times_s[name].min():.1f}, "
                    f"{res.pick_times_s[name].max():.1f}] s"
                    if pk.shape[1] else "")
            print(f"longrecord: {name}: {pk.shape[1]} picks{span}")
        print(f"longrecord: {res.n_files} files as one "
              f"{res.n_samples / meta.fs:.0f} s record -> {args.outdir}")
        return 0
    if args.workflow == "campaign":
        from das4whales_tpu.io.interrogators import get_acquisition_parameters
        from das4whales_tpu.workflows.campaign import CampaignAborted, run_campaign

        # ONE probe pass: the first probeable file supplies the default
        # channel selection and (for --family adapters) the design shape —
        # a corrupt head of the list must not crash the fault-tolerant
        # runner before it starts
        meta0 = None
        for path in args.files:
            try:
                meta0 = get_acquisition_parameters(path, args.interrogator)
                break
            except Exception:  # noqa: BLE001 — run_campaign records it
                continue
        if args.channels:
            sel = [int(v) for v in args.channels.split(",")]
        elif meta0 is not None:
            sel = [0, meta0.nx, 1]
        else:
            print("campaign: no file in the list is probeable; nothing to do")
            return 3
        if args.bank and (args.family != "mf" or args.sharded
                          or args.multihost):
            print("campaign: --bank applies to the single-chip/batched "
                  "mf family (the bank axis rides the one-program route)")
            return 2
        detector = None
        if args.family == "learned":
            if args.sharded:
                print("campaign: --family learned is single-chip only")
                return 2
            if not args.model:
                print("campaign: --family learned requires --model "
                      "(train with models.learned.fit + save_params)")
                return 2
            from das4whales_tpu.models import learned as _learned

            params, lcfg = _learned.load_params(args.model)
            detector = _learned.LearnedDetector(params, lcfg)
        elif args.family != "mf":
            if args.sharded:
                print("campaign: --family spectro/gabor is single-chip only")
                return 2
            if meta0 is None:
                print("campaign: no file in the list is probeable; nothing to do")
                return 3
            # the family builders wire the shared prefilter + adapter;
            # workflows.planner maps the result to its DetectorProgram so
            # the campaign applies the full resilience stack (ladder,
            # watchdog, health gate) to this family too
            if args.family == "spectro":
                from das4whales_tpu.workflows.spectrodetect import (
                    campaign_detector,
                )

                detector = campaign_detector(meta0, sel,
                                             fused_bandpass=args.fused)
            else:
                from das4whales_tpu.workflows.gabordetect import (
                    campaign_detector,
                )

                detector = campaign_detector(meta0, sel,
                                             fused_bandpass=args.fused)
        try:
            if args.trace and (args.multihost or args.sharded):
                # the flight recorder covers the single-chip runners
                # today — say so instead of silently dropping the flag
                print("campaign: --trace covers single-chip campaigns "
                      "only; proceeding WITHOUT a trace (use the "
                      "single-chip runner, or DAS_TRACE=1 for raw spans "
                      "without the trace.json export)")
            if args.multihost:
                if detector is not None:
                    print("campaign: --multihost supports the mf family only")
                    return 2
                from das4whales_tpu.workflows.campaign import (
                    run_campaign_multiprocess,
                )

                res = run_campaign_multiprocess(
                    args.files, sel, args.outdir,
                    resume=not args.no_resume, max_failures=args.max_failures,
                    interrogator=args.interrogator,
                    fused_bandpass=args.fused,
                )
            elif args.sharded:
                from das4whales_tpu.parallel.mesh import make_mesh
                from das4whales_tpu.workflows.campaign import run_campaign_sharded

                res = run_campaign_sharded(
                    args.files, sel, args.outdir, make_mesh(),
                    resume=not args.no_resume, max_failures=args.max_failures,
                    interrogator=args.interrogator,
                    fused_bandpass=args.fused,
                )
            else:
                kwargs = {} if detector is not None else {
                    "fused_bandpass": args.fused,
                    # campaigns consume picks only: the one-program route
                    # (single dispatch + single packed fetch per file)
                    "keep_correlograms": False,
                }
                if args.bank:
                    kwargs["templates"] = args.bank
                res = run_campaign(
                    args.files, sel, args.outdir, detector=detector,
                    resume=not args.no_resume, max_failures=args.max_failures,
                    interrogator=args.interrogator, trace=args.trace,
                    **kwargs,
                )
        except CampaignAborted as exc:
            print(f"campaign aborted: {exc} (progress kept in {args.outdir})")
            return 4
        print(f"campaign: {res.n_done} done, {res.n_failed} failed, "
              f"{res.n_skipped} skipped -> {res.outdir}")
        if args.multihost:
            # one report writer: every process prints its result, but
            # only process 0 regenerates summary.json/density.png
            import jax as _jax

            if _jax.process_index() != 0:
                return 0 if res.n_failed == 0 else 3
        if res.n_done:
            from das4whales_tpu.utils.artifacts import atomic_json
            from das4whales_tpu.workflows.campaign import (
                plot_campaign_density,
                summarize_campaign,
            )

            summary = summarize_campaign(args.outdir)
            fig = plot_campaign_density(summary)
            fig.savefig(os.path.join(args.outdir, "density.png"), dpi=120)
            slim = {k: v for k, v in summary.items() if k != "density"}
            atomic_json(os.path.join(args.outdir, "summary.json"), slim,
                        indent=1)
            print(f"campaign: report -> {args.outdir}/summary.json, density.png")
        return 0 if res.n_failed == 0 else 3
    mod = importlib.import_module(f"das4whales_tpu.workflows.{args.workflow}")
    kwargs = dict(url=args.url, outdir=args.outdir, show=args.show)
    if getattr(args, "no_snr", False):
        kwargs["with_snr"] = False
    result = mod.main(**kwargs)
    if isinstance(result, dict) and "picks" in result:
        for name, pk in result["picks"].items():
            print(f"{args.workflow}: template {name}: {pk.shape[1]} picks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
