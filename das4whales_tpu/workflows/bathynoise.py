"""Bathymetry/noise workflow (reference ``scripts/main_bathynoise.py``):
join cable geometry with strain data and compute per-channel noise
statistics — median/mean/std of the envelope, ``SNR_1d = 20 log10(std/med)``
(main_bathynoise.py:183-194), and the noise power profile vs distance over a
quiet time window (main_bathynoise.py:250-258). Stats run on device in one
jitted program over all channels."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..models.matched_filter import MatchedFilterDetector
from ..ops.spectral import envelope
from .common import acquire, maybe_savefig


@jax.jit
def channel_noise_stats(trf_fk: jnp.ndarray):
    """Per-channel envelope median/mean, trace std, and SNR_1d [dB]."""
    env = envelope(trf_fk)
    med = jnp.median(env, axis=-1)
    mean = jnp.mean(env, axis=-1)
    std = jnp.std(trf_fk, axis=-1)
    snr_1d = 20.0 * jnp.log10(std / med)
    return {"med": med, "mean": mean, "std": std, "snr_1d": snr_1d}


@functools.partial(jax.jit, static_argnames=("i0", "i1"))
def noise_power_profile(trf_fk: jnp.ndarray, i0: int, i1: int, ref: float = 1e-11):
    """Mean noise power per channel over samples [i0, i1), in dB re
    ``ref^2`` (main_bathynoise.py:255-257)."""
    noise = trf_fk[:, i0:i1]
    power = jnp.mean(noise * noise, axis=-1)
    power_db = 10.0 * jnp.log10(power / ref**2)
    noise_mean = jnp.mean(envelope(noise), axis=-1)
    return power_db, noise_mean


def main(url: str | None = None, outdir: str | None = None, show: bool = False,
         selected_channels_m=None, tnoise=(0.0, 5.0), cable_depth_csv: str | None = None):
    block, meta, sel = acquire(url, selected_channels_m=selected_channels_m)

    mf = MatchedFilterDetector(meta, sel, tuple(block.trace.shape))
    trf_fk = mf.filter_block(block.trace)

    stats = {k: np.asarray(v) for k, v in channel_noise_stats(trf_fk).items()}
    i0, i1 = (int(t * meta.fs) for t in tnoise)
    power_db, noise_mean = noise_power_profile(trf_fk, i0, i1)
    stats["noise_power_db"] = np.asarray(power_db)
    stats["noise_mean"] = np.asarray(noise_mean)

    depths = None
    if cable_depth_csv is not None:
        from ..viz.map import load_cable_coordinates

        df = load_cable_coordinates(cable_depth_csv, meta.dx)
        # nearest geometry sample for each selected channel (by distance)
        depths = np.interp(block.dist, df["chan_m"].to_numpy(), df["depth"].to_numpy())
        stats["depth"] = depths

    figures = {}
    if outdir is not None or show:
        import matplotlib.pyplot as plt

        fig, ax1 = plt.subplots(figsize=(12, 5))
        ax1.plot(block.dist / 1e3, stats["noise_power_db"], label="noise power")
        ax1.set_xlabel("Distance [km]")
        ax1.set_ylabel("Noise power [dB re 1e-22]")
        if depths is not None:
            ax2 = ax1.twinx()
            ax2.plot(block.dist / 1e3, depths, "tab:orange", alpha=0.6, label="depth")
            ax2.set_ylabel("Depth [m]")
        fig.tight_layout()
        figures["noise_profile"] = maybe_savefig(fig, outdir, "bathynoise_profile.png")

        fig, ax = plt.subplots(figsize=(12, 5))
        ax.plot(block.dist / 1e3, stats["snr_1d"])
        ax.set_xlabel("Distance [km]")
        ax.set_ylabel("SNR_1d [dB]")
        fig.tight_layout()
        figures["snr_1d"] = maybe_savefig(fig, outdir, "bathynoise_snr1d.png")

    return {"stats": stats, "trf_fk": trf_fk, "block": block, "figures": figures}


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None, outdir="out_bathynoise")
