"""Basic load/filter/visualize workflow (reference ``scripts/main_plots.py``
and the tutorial flow, SURVEY.md §3.4): load → bandpass → f-k filter →
t-x plot → best-channel spectrogram → template-design panel → optional
5x-rate audio export of the best channel."""

from __future__ import annotations

import os

import numpy as np

from ..models.matched_filter import MatchedFilterDetector
from ..models.templates import gen_template_fincall
from ..ops.spectral import spectrogram
from ..utils.audio import export_audio
from .common import acquire, maybe_savefig


def main(url: str | None = None, outdir: str | None = None, show: bool = False,
         selected_channels_m=None, audio: bool = True):
    block, meta, sel = acquire(url, selected_channels_m=selected_channels_m)

    mf = MatchedFilterDetector(meta, sel, tuple(block.trace.shape))
    trf_fk = mf.filter_block(block.trace)

    # best channel by peak envelope amplitude (main_mfdetect.py:61 idiom)
    tr_np = np.asarray(trf_fk)
    best = int(np.argmax(np.max(np.abs(tr_np), axis=1)))
    p, tt, ff = spectrogram(trf_fk[best], meta.fs)

    figures = {}
    if outdir is not None or show:
        from .. import viz

        fig = viz.plot_tx(tr_np, block.tx, block.dist,
                          file_begin_time_utc=block.t0_utc, show=show)
        figures["tx"] = maybe_savefig(fig, outdir, "plots_tx.png")
        fig = viz.plot_fx(tr_np[:: max(len(tr_np) // 64, 1)], block.dist[:: max(len(tr_np) // 64, 1)],
                          meta.fs, nfft=512, show=show)
        figures["fx"] = maybe_savefig(fig, outdir, "plots_fx.png")
        fig = viz.plot_spectrogram(np.asarray(p), np.asarray(tt), np.asarray(ff),
                                   f_min=10, f_max=35, show=show)
        figures["spectrogram"] = maybe_savefig(fig, outdir, "plots_spectrogram.png")

        time = block.tx
        hf = np.asarray(gen_template_fincall(time, meta.fs, 17.8, 28.8, 0.68))
        lf = np.asarray(gen_template_fincall(time, meta.fs, 14.7, 21.8, 0.78))
        t_peak = float(np.argmax(np.abs(tr_np[best])) / meta.fs)
        fig = viz.design_mf(tr_np[best], hf, lf, t_peak, t_peak, time, meta.fs, show=show)
        figures["design_mf"] = maybe_savefig(fig, outdir, "plots_design_mf.png")

    audio_path = None
    if audio and outdir is not None:
        os.makedirs(outdir, exist_ok=True)
        audio_path = export_audio(tr_np[best], meta.fs,
                                  os.path.join(outdir, f"channel_{best}_x5.wav"), speed=5.0)

    return {
        "trf_fk": trf_fk,
        "best_channel": best,
        "spectrogram": (p, tt, ff),
        "block": block,
        "figures": figures,
        "audio": audio_path,
    }


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None, outdir="out_plots")
