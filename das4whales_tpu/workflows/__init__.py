"""End-to-end workflows (the reference's ``scripts/main_*.py`` entry
points, SURVEY.md §2.2) — each is a ``main(url=None, outdir=None, ...)``
callable that runs offline on a synthetic OOI-like scene when no URL/file
is given."""

from . import bathynoise, common, fkcomp, gabordetect, longrecord, mfdetect, planner, plots, spectrodetect  # noqa: F401
from .common import acquire, default_scene  # noqa: F401
from .longrecord import detect_long_record  # noqa: F401
from .planner import DetectorProgram, RoutePlanner, program_for  # noqa: F401
