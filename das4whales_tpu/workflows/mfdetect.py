"""Flagship matched-filter detection workflow (reference
``scripts/main_mfdetect.py``, SURVEY.md §3.1): load → bandpass → hybrid_ninf
f-k filter → HF/LF matched-filter cross-correlograms → SNR → envelope peak
picking → detection overlay. The whole device path is two XLA programs via
:class:`~das4whales_tpu.models.matched_filter.MatchedFilterDetector`."""

from __future__ import annotations

import numpy as np

from ..models.matched_filter import MatchedFilterDetector
from ..utils.profiling import StageTimer
from .common import acquire, maybe_savefig


def main(url: str | None = None, outdir: str | None = None, show: bool = False,
         selected_channels_m=None, with_snr: bool = True):
    """Run the full pipeline; returns a result dict (picks are (2, n)
    [channel_idx, time_idx] arrays per template)."""
    timer = StageTimer()
    with timer.stage("acquire"):
        block, meta, sel = acquire(url, selected_channels_m=selected_channels_m)

    with timer.stage("design"):
        det = MatchedFilterDetector(meta, sel, tuple(block.trace.shape))
        det.design.sparsity_report(verbose=True)  # tools.disp_comprate parity

    with timer.stage("detect"):
        res = det(block.trace, with_snr=with_snr)

    figures = {}
    if outdir is not None or show:
        from .. import viz

        fig = viz.plot_tx(np.asarray(res.trf_fk), block.tx, block.dist,
                          file_begin_time_utc=block.t0_utc, show=show)
        figures["tx"] = maybe_savefig(fig, outdir, "mf_tx.png")
        for name, snr in res.snr.items():
            fig = viz.snr_matrix(np.asarray(snr), block.tx, block.dist, vmax=30,
                                 title=name, show=show)
            figures[f"snr_{name}"] = maybe_savefig(fig, outdir, f"mf_snr_{name}.png")
        names = list(res.picks)
        fig = viz.detection_mf(
            np.asarray(res.trf_fk), res.picks[names[0]], res.picks[names[-1]],
            block.tx, block.dist, meta.fs, meta.dx, sel,
            file_begin_time_utc=block.t0_utc, show=show)
        figures["detection"] = maybe_savefig(fig, outdir, "mf_detection.png")

    print(timer.report())
    return {
        "picks": res.picks,
        "thresholds": res.thresholds,
        "trf_fk": res.trf_fk,
        "correlograms": res.correlograms,
        "block": block,
        "figures": figures,
        "timings": timer.totals,
    }


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None, outdir="out_mfdetect")
