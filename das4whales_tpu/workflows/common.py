"""Shared workflow prologue (the identical header of every reference
``scripts/main_*.py``: download → metadata → channel selection in meters →
load, e.g. main_mfdetect.py:9-42), with an offline synthetic fallback so
every workflow runs without network access."""

from __future__ import annotations

import os
from typing import Sequence

from ..config import SELECTED_CHANNELS_M, as_metadata
from ..io import synth
from ..io.download import dl_file
from ..io.hdf5 import load_das_data
from ..io.interrogators import get_acquisition_parameters
from ..utils.log import get_logger, log_metadata

log = get_logger("das4whales_tpu.workflows")


def default_scene(nx: int = 512, ns: int = 12000) -> synth.SyntheticScene:
    """A 60 s OOI-like scene with HF+LF fin-call pairs at three sites."""
    calls = []
    for k, x0 in enumerate((800.0, 2000.0, 3400.0)):
        t0 = 8.0 + 14.0 * k
        calls.append(synth.SyntheticCall(t0=t0, x0_m=x0, fmin=17.8, fmax=28.8,
                                         duration=0.68, amplitude=4.0))
        calls.append(synth.SyntheticCall(t0=t0 + 12.0, x0_m=x0, fmin=14.7, fmax=21.8,
                                         duration=0.78, amplitude=4.0))
    return synth.SyntheticScene(nx=nx, ns=ns, calls=calls, seed=42)


def channels_m_to_idx(selected_channels_m: Sequence[float], dx: float) -> list:
    """Meters → channel indices, the caller-side convention of every
    reference script (main_mfdetect.py:25-34)."""
    return [int(m // dx) for m in selected_channels_m]


def acquire(
    url: str | None = None,
    *,
    datadir: str = "data",
    interrogator: str = "optasense",
    selected_channels_m: Sequence[float] | None = None,
    scene: synth.SyntheticScene | None = None,
    dtype=None,
):
    """Resolve ``url`` (remote URL, local path, or None → synthetic scene),
    read metadata, and load the strided channel selection as strain.

    Returns ``(block, metadata, selected_channels)`` where ``block`` is a
    :class:`~das4whales_tpu.io.hdf5.StrainBlock`.
    """
    if url is None:
        scene = scene or default_scene()
        os.makedirs(datadir, exist_ok=True)
        filepath = os.path.join(datadir, "synthetic_ooi.h5")
        synth.write_synthetic_file(filepath, scene)
        log.info("synthesized offline scene at %s (%d calls)", filepath, len(scene.calls))
    elif url.startswith(("http://", "https://")):
        filepath = dl_file(url, datadir=datadir)
    else:
        filepath = url

    metadata = get_acquisition_parameters(filepath, interrogator=interrogator)
    log_metadata(metadata.__dict__, logger=log)

    meta = as_metadata(metadata)
    if selected_channels_m is None:
        # canonical 20-65 km selection when it fits, else the whole array
        if meta.nx * meta.dx > SELECTED_CHANNELS_M[1]:
            selected_channels_m = SELECTED_CHANNELS_M
        else:
            selected_channels_m = (0.0, meta.nx * meta.dx, meta.dx)
    selected_channels = channels_m_to_idx(selected_channels_m, meta.dx)

    kwargs = {} if dtype is None else {"dtype": dtype}
    block = load_das_data(filepath, selected_channels, meta, **kwargs)
    return block, meta, selected_channels


def mf_prefilter(metadata, selected_channels, trace_shape=None, *,
                 fused_bandpass: bool = True):
    """The bandpass + f-k front end every signal-processing family
    shares (the identical head of main_mfdetect / main_spectrodetect /
    main_gabordetect): a :class:`MatchedFilterDetector` whose
    ``filter_block`` is the prefilter. One builder so the spectro and
    gabor campaign detectors (``spectrodetect.campaign_detector`` /
    ``gabordetect.campaign_detector``) cannot diverge from the flagship's
    filter design. ``trace_shape=None`` derives the post-selection shape
    from the metadata."""
    from ..config import ChannelSelection
    from ..models.matched_filter import MatchedFilterDetector

    meta = as_metadata(metadata)
    if trace_shape is None:
        sel = ChannelSelection.from_list(list(selected_channels))
        trace_shape = (sel.n_channels(meta.nx), meta.ns)
    return MatchedFilterDetector(meta, list(selected_channels),
                                 tuple(trace_shape),
                                 fused_bandpass=fused_bandpass)


def maybe_savefig(fig, outdir: str | None, name: str) -> str | None:
    if fig is None or outdir is None:
        return None
    os.makedirs(outdir, exist_ok=True)
    path = os.path.join(outdir, name)
    fig.savefig(path, dpi=80)
    import matplotlib.pyplot as plt

    plt.close(fig)
    return path
