"""f-k filter comparison workflow (reference ``scripts/main_fkcomp.py:64-125``):
design all four hybrid filter variants on the same block, apply each, and
compare the resulting SNR matrices."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..config import SCRIPT_FK
from ..ops import fk as fk_ops
from ..ops.spectral import snr_tr_array
from .common import acquire, maybe_savefig

_DESIGNERS = {
    "hybrid": lambda shape, sel, dx, fs, c: fk_ops.hybrid_filter_design(
        shape, sel, dx, fs, c.cs_min, c.cp_min, c.fmin, c.fmax),
    "hybrid_ninf": lambda shape, sel, dx, fs, c: fk_ops.hybrid_ninf_filter_design(
        shape, sel, dx, fs, c.cs_min, c.cp_min, c.cp_max, c.cs_max, c.fmin, c.fmax),
    "hybrid_gs": lambda shape, sel, dx, fs, c: fk_ops.hybrid_gs_filter_design(
        shape, sel, dx, fs, c.cs_min, c.cp_min, c.fmin, c.fmax),
    "hybrid_ninf_gs": lambda shape, sel, dx, fs, c: fk_ops.hybrid_ninf_gs_filter_design(
        shape, sel, dx, fs, c.cs_min, c.cp_min, c.cp_max, c.cs_max, c.fmin, c.fmax),
}


def main(url: str | None = None, outdir: str | None = None, show: bool = False,
         selected_channels_m=None, fk_config=SCRIPT_FK):
    block, meta, sel = acquire(url, selected_channels_m=selected_channels_m)
    shape = tuple(block.trace.shape)

    filtered, snr, reports, figures = {}, {}, {}, {}
    for name, designer in _DESIGNERS.items():
        mask = designer(shape, sel, meta.dx, meta.fs, fk_config)
        reports[name] = fk_ops.compression_report(mask, verbose=False)
        trf = fk_ops.fk_filter_apply_rfft(block.trace, jnp.asarray(mask))
        filtered[name] = trf
        snr[name] = snr_tr_array(trf, env=True)
        if outdir is not None or show:
            from .. import viz

            fig = viz.snr_matrix(np.asarray(snr[name]), block.tx, block.dist,
                                 vmax=30, title=name, show=show)
            figures[name] = maybe_savefig(fig, outdir, f"fkcomp_snr_{name}.png")

    return {
        "filtered": filtered,
        "snr": snr,
        "compression": reports,
        "block": block,
        "figures": figures,
    }


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None, outdir="out_fkcomp")
