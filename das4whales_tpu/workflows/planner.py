"""Family-agnostic resilient route planner (the executor every detector
family inherits).

PRs 4-6 grew a resilience stack for detection campaigns — classified
retry, on-device health quarantine, an elastic OOM downshift ladder, a
dispatch watchdog, pipelined dispatch — but it was gated on
``isinstance(detector, MatchedFilterDetector)`` inside
``workflows/campaign.py``: spectro, gabor and learned campaigns rode a
flat route where a single device OOM permanently failed a file that a
leaner route (or the host backend) would have processed. This module
extracts the route planner into a family-agnostic contract:

* :class:`DetectorProgram` — the per-family adapter: capability flags
  (supported ladder stages, fused vs host health stats, async dispatch)
  plus ``detect(rung, trace)``, the family's program at one ladder rung.
* :class:`DownshiftLadder` — the sticky per-bucket rung bookkeeping of
  the elastic resource ladder (moved from ``workflows.campaign``),
  now filtered to the family's declared stages.
* :class:`RoutePlanner` — the routed executor: resolves each file
  through the family program at the bucket's sticky rung, bounds every
  dispatch with the watchdog (``faults.call_with_deadline``), fires the
  chaos harness's ``on_dispatch(path, rung)`` hook INSIDE the deadline,
  and absorbs resource-class failures by descending the ladder.
* :func:`program_for` — the family registry: maps any campaign detector
  (``MatchedFilterDetector``, the spectro/gabor eval adapters,
  ``LearnedDetector``, or any callable returning ``.picks``) to its
  :class:`DetectorProgram`.

Every family's ladder starts at the per-file rung and ends at the host
rung, so a resource-class failure is always recoverable somewhere; the
family declares which intermediate rungs (tiled / time-sharded) its
math supports. Matched-filter campaigns ride the same planner with
picks pinned bit-identical to the pre-planner behavior (the chaos and
parity suites gate this). Coverage matrix: docs/ROBUSTNESS.md
"Family x guarantee coverage".
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import numpy as np

from .. import faults
from ..telemetry import trace as telemetry
from ..utils.log import get_logger

log = get_logger("planner")


def _append_event(outdir: str, event: Dict) -> None:
    from .campaign import _append_event as _ev

    _ev(outdir, event)


def thresholds_for(result, picks) -> Dict[str, float]:
    """Per-template thresholds for the picks artifact, from a detector
    result. Distinguishes an ABSENT ``thresholds`` attribute
    (missing/None — the family exposes no threshold metadata; every
    template records NaN) from a PRESENT mapping, which is trusted
    as-is even when empty or partial (missing names record NaN at save
    time). The old ``getattr(...) or {...}`` fallback conflated the
    two: an empty-but-present dict is falsy and was silently replaced,
    while a partial dict crashed the artifact writer."""
    thresholds = getattr(result, "thresholds", None)
    if thresholds is None:
        return {name: float("nan") for name in picks}
    return dict(thresholds)


class DetectorProgram:
    """One detector family's executor contract.

    Subclasses declare the capability flags and implement
    :meth:`detect`; the campaign runners never inspect the detector
    type again — the program IS the family:

    * ``family`` — the manifest/ledger label (``FileRecord.family``).
    * ``stages`` — the ladder stages this family's math supports, in
      ladder order. Must include ``"file"`` (the entry rung) and should
      include ``"host"`` (the rung of last resort — detection on the
      CPU backend completes where no device rung fits).
    * ``supports_fused_health`` — the family fuses ``ops.health`` stats
      into its detection program (stats ride the program's own fetch);
      otherwise the planner computes host-side stats on the
      already-host-resident block (same values, one numpy pass).
    * ``supports_dispatch`` — :meth:`dispatch` can launch the program
      asynchronously (the depth-D pipelined campaign dispatch).
    * ``supports_batched`` — a batched (B files per program) facade
      exists (``parallel.batch.batched_detector_for`` — the slab routes
      of ``run_campaign_batched`` and the service scheduler). Every
      campaign family has one: the matched filter's packed-pick program,
      and the spectro/gabor/learned heavy-stage facades (one mapped
      heavy program per slab, the family's own per-file finalize).
    """

    family = "generic"
    stages: Tuple[str, ...] = ("file", "host")
    supports_fused_health = False
    supports_dispatch = False
    supports_batched = False

    def __init__(self, detector):
        self.det = detector

    @property
    def engines(self) -> Dict[str, str]:
        """Resolved execution-engine labels the family's detector rides
        (``ops.mxu.engine_labels`` — ``mf_engine``/``fk_engine``/
        ``pick_engine``; empty for families without engine routing).
        Family-agnostic by construction: every family inherits engine
        attribution in the ladder's rung descriptions the moment its
        detector grows engine attributes. Eval adapters (spectro/gabor)
        carry their engine attributes on the wrapped detector — both
        levels are consulted."""
        from ..ops import mxu

        labels = mxu.engine_labels(self.det)
        inner = getattr(self.det, "det", None)
        if inner is not None:
            labels = {**mxu.engine_labels(inner), **labels}
        return labels

    # -- the per-rung program ---------------------------------------------

    def _det_at(self, stage: str):
        """The detector view serving ``stage`` — families with a
        memory-lean ``tiled`` view override this; the default serves
        the same detector at every stage."""
        return self.det

    def detect(self, rung, trace, *, n_real=None, with_health: bool = False,
               clip=None):
        """One HOST block's ``(picks, thresholds, stats)`` at ``rung``.
        Raises on failure — including resource exhaustion at this rung,
        which the caller's ladder absorbs. The default implementation
        runs the generic ``det(block) -> .picks`` contract through
        :meth:`_det_at`, with the ``host`` rung pinned to the CPU
        backend; families with their own per-rung programs (the matched
        filter) override the whole method."""
        import jax

        det = self._det_at(rung[0])
        if rung[0] == "host":
            with jax.default_device(jax.devices("cpu")[0]):
                return self._call_detector(det, trace,
                                           with_health=with_health, clip=clip)
        return self._call_detector(det, trace, with_health=with_health,
                                   clip=clip)

    def dispatch(self, trace, *, with_health: bool = False, clip=None):
        """Launch the per-file program asynchronously (an
        ``InFlightResult``-style handle whose ``resolve()`` is the one
        sync), or None when the family has no async route."""
        return None

    # -- shared helpers ----------------------------------------------------

    def _host_stats(self, trace, with_health: bool, clip) -> Dict[str, float]:
        if not with_health:
            return {}
        from ..ops import health as health_ops

        return health_ops.host_health_stats(np.asarray(trace), clip_abs=clip)

    def _call_detector(self, det, trace, *, with_health: bool, clip):
        """The generic per-file program: ``det(block)`` -> ``.picks``
        (+ optional ``.thresholds``), host-side health stats."""
        import jax.numpy as jnp

        result = det(jnp.asarray(trace))
        stats = self._host_stats(trace, with_health, clip)
        return result.picks, thresholds_for(result, result.picks), stats


class GenericProgram(DetectorProgram):
    """Any callable returning ``.picks`` — the flat route of PRs 4-6,
    now with the host rung (and therefore OOM recovery) for free."""


class MatchedFilterProgram(DetectorProgram):
    """The flagship family: every rung of the ladder, fused health on
    the sparse one-program route, async dispatch for the depth-D
    pipeline, and the batched slab route (``run_campaign_batched``)."""

    family = "mf"
    stages = ("file", "tiled", "timeshard", "host")
    supports_batched = True

    def __init__(self, detector):
        super().__init__(detector)
        self.supports_fused_health = bool(
            getattr(detector, "supports_fused_health", False)
        )
        self.supports_dispatch = getattr(detector, "pick_mode", "") == "sparse"
        if getattr(detector, "supports_bank_split", False):
            # splittable template bank (models/templates.py): the ladder
            # gains the bank-split rung — T/2 sub-bank dispatches before
            # the route itself is sacrificed (faults.BANK_STAGE)
            self.stages = ("file", "bank", "tiled", "timeshard", "host")

    def dispatch(self, trace, *, with_health=False, clip=None):
        if not self.supports_dispatch:
            return None
        return self.det.dispatch_picks(trace, with_health=with_health,
                                       health_clip=clip)

    def detect(self, rung, trace, *, n_real=None, with_health=False,
               clip=None):
        import jax
        import jax.numpy as jnp

        det = self.det
        stage = rung[0]
        if stage == "bank":
            # the bank-split rung: T/2 sub-bank views, two dispatches,
            # merged per-file picks — bit-identical to the one-dispatch
            # bank under the splittable per_template threshold scope
            # (models.matched_filter.bank_view documents the exactness).
            # Health stats describe the INPUT block — identical either
            # half; computed once on the first.
            picks, thresholds, stats = {}, {}, {}
            for i, d in enumerate(det.split_views()):
                res = d.detect_picks(
                    jnp.asarray(trace), n_real=n_real,
                    with_health=with_health and i == 0, health_clip=clip,
                )
                picks.update(res.picks)
                thresholds.update(res.thresholds)
                if i == 0:
                    stats = res.health
            return picks, thresholds, stats
        if stage == "timeshard":
            from ..parallel.timeshard import (
                detect_picks_time_sharded,
                ladder_time_mesh,
            )

            mesh = ladder_time_mesh(np.asarray(trace).shape)
            if mesh is None:
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: no viable time-shard mesh for "
                    f"shape {np.asarray(trace).shape}"  # -> next rung (host)
                )
            picks, thresholds = detect_picks_time_sharded(
                det, trace, mesh, n_real=n_real
            )
            return picks, thresholds, self._host_stats(trace, with_health,
                                                       clip)

        if stage == "tiled":
            det = det.tiled_view()
        elif stage == "host":
            det = det.host_view()

        def run(d):
            res = d.detect_picks(
                jnp.asarray(trace), n_real=n_real,
                with_health=with_health, health_clip=clip,
            )
            return res.picks, res.thresholds, res.health

        if stage == "host":
            with jax.default_device(det.host_device):
                return run(det)
        return run(det)


class SpectroProgram(DetectorProgram):
    """Spectrogram-correlation family (``eval.SpectroEvalAdapter``):
    per-file, channel-chunk-tiled (smaller spectrogram sweep chunks —
    ``models.spectro.SpectroCorrDetector.tiled_view``) and host rungs.
    Every stage is per-channel math, so the tiled rung's picks are
    bit-identical to the per-file rung's. The batched slab route
    (``parallel.batch.BatchedSpectroDetector``) maps the family's heavy
    stage over the B file axis — the STFT rides the A/B-routed rFFT or
    framed windowed-DFT MXU matmul engine (``ops.spectral``)."""

    family = "spectro"
    stages = ("file", "tiled", "host")
    supports_batched = True

    def _det_at(self, stage):
        if stage != "tiled":
            return self.det
        import copy

        adapter = copy.copy(self.det)
        adapter.det = self.det.det.tiled_view()
        return adapter


class GaborProgram(DetectorProgram):
    """Gabor/image family (``eval.GaborEvalAdapter``): per-file and host
    rungs only — the oriented Gabor pair couples ~1000 channels of the
    t-x image, so a channel-tiled rung would change the detection math
    at tile seams (``parallel/gabor.py`` documents the halo cost). The
    batched slab route (``parallel.batch.BatchedGaborDetector``)
    batches over FILES, so the halo seam problem never arises there —
    the oriented pair rides the A/B-routed FFT or f32-accumulated
    ``conv_general_dilated`` engine (``ops.image.filter2d_same``)."""

    family = "gabor"
    stages = ("file", "host")
    supports_batched = True


class LearnedProgram(DetectorProgram):
    """Learned CNN family (``models.learned.LearnedDetector``):
    per-file, window-row-chunked tiled
    (``LearnedDetector.tiled_view`` — caps the classifier's activation
    memory) and host rungs. The batched slab route
    (``parallel.batch.BatchedLearnedDetector``) scores B files' window
    batches in one program; host-side threshold + NMS per file."""

    family = "learned"
    stages = ("file", "tiled", "host")
    supports_batched = True

    def _det_at(self, stage):
        return self.det.tiled_view() if stage == "tiled" else self.det


#: family name -> the family's program class (the batched campaign and
#: the service scheduler resolve ladder stages and per-file-rung
#: programs through this table; ``program_for`` stays the
#: detector-instance registry)
FAMILY_PROGRAMS = {
    "mf": MatchedFilterProgram,
    "spectro": SpectroProgram,
    "gabor": GaborProgram,
    "learned": LearnedProgram,
}


def family_ladder_stages(family: str) -> Tuple[str, ...]:
    """The downshift-ladder stages a BATCHED route may visit for one
    family: ``"batched"`` plus whatever the family's per-file program
    declares. Spectro/gabor/learned do not support every MF rung (no
    timeshard math), so their ladders must skip straight to the rungs
    their planner program can actually serve — a downshift onto an
    undeclared rung would silently run the plain per-file program under
    the wrong label."""
    cls = FAMILY_PROGRAMS[family]
    return tuple(
        s for s in faults.DOWNSHIFT_STAGES
        if s == "batched" or s in cls.stages
    )


def program_for(detector) -> DetectorProgram:
    """The family registry: any campaign detector -> its
    :class:`DetectorProgram`. A detector already wrapped in a program
    passes through; unknown detector types get the
    :class:`GenericProgram` flat contract (per-file + host rungs, host
    health stats) — which is strictly MORE resilient than the
    pre-planner generic path (no ladder at all)."""
    if isinstance(detector, DetectorProgram):
        return detector
    from ..models.learned import LearnedDetector
    from ..models.matched_filter import MatchedFilterDetector

    if isinstance(detector, MatchedFilterDetector):
        return MatchedFilterProgram(detector)
    if isinstance(detector, LearnedDetector):
        return LearnedProgram(detector)
    from ..eval import GaborEvalAdapter, SpectroEvalAdapter

    if isinstance(detector, SpectroEvalAdapter):
        return SpectroProgram(detector)
    if isinstance(detector, GaborEvalAdapter):
        return GaborProgram(detector)
    return GenericProgram(detector)


class DownshiftLadder:
    """The elastic resource ladder's sticky bookkeeping
    (docs/ROBUSTNESS.md "Resource ladder").

    One campaign, one ladder: per bucket key it remembers the WINNING
    rung — ``("batched", B)`` at shrinking B, then ``("file", 1)`` (the
    per-file route), ``("tiled", 1)`` (the family's memory-lean view),
    ``("timeshard", 1)`` (time-sharded over a multi-device mesh, when
    the family supports it and the shape divides), ``("host", 1)`` (CPU
    backend). ``stages`` filters the ladder to the family's declared
    support (``DetectorProgram.stages``); ``family`` labels the
    manifest's ``downshift`` ledger events so downshifts are auditable
    per family. A resource-class failure advances the bucket's rung
    ONCE and the rung sticks for the rest of the campaign (no per-file
    thrash); every move lands in the manifest's ``downshift`` ledger.
    """

    def __init__(self, rz, outdir: str, batch: int = 1,
                 write: bool = True, timeshard: bool = True,
                 stages=faults.DOWNSHIFT_STAGES, family: str = "",
                 engines: Dict[str, str] | None = None):
        self.rz = rz
        self.outdir = outdir
        self.batch = int(batch)
        self.write = write
        self.allow_timeshard = timeshard
        self.stages = tuple(stages)
        self.family = family
        # resolved execution-engine labels the family's detector rides
        # (ops.mxu.engine_labels: mf/fk/pick engine) — stamped into every
        # ledger event's rung description so a downshift audit shows not
        # just WHERE a bucket ran but on WHICH routes. Campaign-wide
        # default; per-bucket resolutions (each bucket's shape A/Bs
        # independently) override via :meth:`set_engines`. The labels
        # describe the bucket's DEVICE-rung routing — the host rung
        # re-resolves auto engines for the CPU backend
        # (models.matched_filter.host_view).
        self.engines = dict(engines or {})
        self._engines_by_key: Dict = {}
        self.sticky: Dict[tuple, tuple] = {}
        # keys whose detector rides a SPLITTABLE template bank
        # (models.templates.TemplateBank.splittable): only they get the
        # interleaved bank-split rungs (faults.BANK_STAGE)
        self._bank_keys: set = set()
        self._bank_all = False

    def enable_bank_split(self, key=None) -> None:
        """Arm the bank-split rung for ``key`` (None: every key — the
        unbatched planner, whose one program serves the whole run). The
        campaign calls this per bucket once the bucket's detector proves
        ``supports_bank_split``."""
        if key is None:
            self._bank_all = True
        else:
            self._bank_keys.add(key)

    def bank_split_enabled(self, key=None) -> bool:
        return self._bank_all or key in self._bank_keys

    def set_engines(self, key, labels) -> None:
        """Record ``key``'s own resolved engine labels (per-bucket shapes
        route independently; the campaign default stays for keys that
        never registered)."""
        self._engines_by_key[key] = dict(labels or {})

    def engines_for(self, key) -> Dict[str, str]:
        return self._engines_by_key.get(key, self.engines)

    def rungs(self, trace_shape=None, key=None) -> list:
        bank = self.bank_split_enabled(key)
        out = []
        if "batched" in self.stages:
            b = self.batch
            while b > 1:
                out.append(("batched", b))
                if bank:
                    # sacrifice the T axis before B: the same batch as
                    # two T/2 sub-bank dispatches (faults.rung_rank
                    # interleaves bank:b between batched:b and b/2)
                    out.append(("bank", b))
                b //= 2
        out.append(("file", 1))
        if bank:
            out.append(("bank", 1))
        if "tiled" in self.stages:
            out.append(("tiled", 1))
        if ("timeshard" in self.stages and self.allow_timeshard
                and trace_shape is not None):
            import jax

            from ..parallel.timeshard import viable_time_mesh_size

            if viable_time_mesh_size(trace_shape, len(jax.devices())):
                out.append(("timeshard", 1))
        if "host" in self.stages:
            out.append(("host", 1))
        return out

    def current(self, key) -> tuple:
        return self.sticky.get(
            key, ("batched", self.batch) if self.batch > 1 else ("file", 1)
        )

    def rung_snapshot(self) -> Dict[tuple, tuple]:
        """A copy of the sticky map for cross-thread readers (the
        service's /tenants snapshot): ``dict(...)`` of a dict is a
        C-atomic copy, so an HTTP thread never iterates the live map
        while the scheduler thread downshifts it (daslint R8's
        torn-iteration clause — ISSUE 13)."""
        return dict(self.sticky)

    def _ledger(self, key, from_rung, to_rung, error: str,
                preflight: bool = False) -> None:
        """One downshift ledger move: a ``downshift`` SPAN paired with
        the manifest event, the span's id stamped into the event — so a
        trace-side downshift and its ledger line resolve one-to-one
        (the flight-recorder contract, docs/OBSERVABILITY.md). Spans
        (and events) only exist for writing ladders, keeping the
        pairing exact."""
        if not self.write:
            return
        with telemetry.span(
            "downshift", bucket=str(key), family=self.family,
            from_rung=faults.rung_label(from_rung),
            to_rung=faults.rung_label(to_rung), preflight=preflight,
        ) as sp:
            event = {
                "event": "downshift",
                "bucket": key if isinstance(key, str) else list(key),
                "family": self.family,
                "from": faults.rung_label(from_rung),
                "to": faults.rung_label(to_rung),
                **({"engines": eng} if (eng := self.engines_for(key))
                   else {}),
                "error": error, "sticky": True,
            }
            if preflight:
                event["preflight"] = True
            if sp.span_id is not None:
                event["span_id"] = sp.span_id
            _append_event(self.outdir, event)

    def pin(self, key, rung, reason: str) -> None:
        """Preflight placement: start ``key`` at ``rung`` (no failure
        occurred — ledgered as a preflight downshift when it moves the
        bucket off the top rung)."""
        top = ("batched", self.batch) if self.batch > 1 else ("file", 1)
        self.sticky[key] = rung
        if faults.rung_rank(rung) > faults.rung_rank(top):
            self.rz.tally("downshifts")
            self._ledger(key, top, rung, reason, preflight=True)
            log.info("preflight: bucket %s starts at rung %s (%s)",
                     key, faults.rung_label(rung), reason)

    def downshift(self, key, rung, exc, trace_shape=None):
        """Advance ``key``'s sticky rung past ``rung`` after a
        resource-class failure; returns the new rung, or None when the
        ladder is exhausted (the failure dispositions per-file)."""
        nxt = None
        for cand in self.rungs(trace_shape, key):
            if faults.rung_rank(cand) > faults.rung_rank(rung):
                nxt = cand
                break
        if nxt is None:
            return None
        self.sticky[key] = nxt
        self.rz.tally("downshifts")
        self._ledger(key, rung, nxt, f"{type(exc).__name__}: {exc}")
        log.warning(
            "resource exhaustion at rung %s (%s: %s); downshifting bucket "
            "%s to %s (sticky)", faults.rung_label(rung),
            type(exc).__name__, exc, key, faults.rung_label(nxt),
        )
        return nxt


class RoutePlanner:
    """One campaign's routed, degradable, watchdogged executor over a
    family :class:`DetectorProgram`.

    ``run_file`` resolves one file at the bucket's sticky rung: the
    family program (or a pre-dispatched in-flight handle at the top
    rung) runs inside the dispatch watchdog with the chaos harness's
    ``on_dispatch(path, rung)`` hook firing inside the deadline —
    exactly where a real wedged/OOMing launch surfaces. Resource-class
    failures descend the family's ladder (sticky, ledgered); everything
    else re-raises for the campaign's classified disposition.
    """

    def __init__(self, rz, outdir: str, program: DetectorProgram, *,
                 write: bool = True, timeshard: bool = True,
                 dispatch_deadline_s: float | None = None, fault_plan=None):
        self.rz = rz
        self.program = program
        self.fault_plan = fault_plan
        self.deadline_s = dispatch_deadline_s
        self.top = ("file", 1)
        self.ladder = DownshiftLadder(
            rz, outdir, batch=1, write=write, timeshard=timeshard,
            stages=program.stages, family=program.family,
            engines=program.engines,
        )
        if "bank" in program.stages:
            # one program serves the whole unbatched run: the splittable-
            # bank capability holds for every ladder key
            self.ladder.enable_bank_split()

    def current(self, key: str = "campaign") -> tuple:
        return self.ladder.current(key)

    def run_file(self, path: str, trace, *, n_real=None,
                 with_health: bool = False, clip=None, inflight=None,
                 key: str = "campaign"):
        """One file's ``(picks, thresholds, stats, rung)`` through the
        rung loop. ``inflight`` is the depth-D pipeline's pre-dispatched
        handle for this file: consumed only while the bucket still rides
        the top rung (a downshift between dispatch and resolve abandons
        it); any failure discards it — a handle is never resolved
        twice."""
        from ..parallel import dispatch as dispatch_mod

        recovered = False
        shape = np.asarray(trace).shape
        with telemetry.span("file", file=os.path.basename(path),
                            family=self.program.family):
            while True:   # rung loop: resource failures downshift, sticky
                rung = self.ladder.current(key)
                if inflight is not None and rung != self.top:
                    # the campaign downshifted between this file's dispatch
                    # and its resolve: the in-flight program ran at a rung
                    # now known to exhaust — abandon it
                    inflight = None

                def fn(inflight=inflight, rung=rung):
                    if inflight is not None:
                        # the pipeline's pre-dispatched program: this is its
                        # packed fetch (the one sync), inside the watchdog
                        res = inflight.resolve()
                        return res.picks, res.thresholds, res.health
                    return self.program.detect(
                        rung, trace, n_real=n_real,
                        with_health=with_health, clip=clip,
                    )

                try:
                    picks, thresholds, stats = \
                        dispatch_mod.resolve_watchdogged(
                            fn, [path], rung, self.deadline_s,
                            self.fault_plan, family=self.program.family,
                        )
                    break
                except Exception as exc:  # noqa: BLE001 — ladder absorbs resource
                    inflight = None   # spent/abandoned: never consume twice
                    if (faults.classify_failure(exc) == "resource"
                            and self.ladder.downshift(key, rung, exc, shape)):
                        recovered = True
                        continue
                    raise
        if recovered:
            self.rz.tally("oom_recoveries")
        return picks, thresholds, stats, rung
