"""Spectrogram-correlation detection workflow (reference
``scripts/main_spectrodetect.py``, SURVEY.md §3.2): same prologue and f-k
filtering as the matched-filter flow, then per-channel sliced spectrograms
cross-correlated with HF/LF hat kernels, picks at the spectrogram rate."""

from __future__ import annotations

import numpy as np

from ..models.matched_filter import MatchedFilterDetector
from ..models.spectro import SpectroCorrDetector
from .common import acquire, maybe_savefig, mf_prefilter


def campaign_detector(metadata, selected_channels, trace_shape=None, *,
                      threshold: float = 14.0, fused_bandpass: bool = True,
                      **spectro_kwargs):
    """The spectro family wired for the resilient campaign runner: the
    shared bandpass + f-k prefilter (``common.mf_prefilter``) feeding a
    :class:`SpectroCorrDetector`, wrapped in the eval adapter the route
    planner maps to the ``"spectro"`` :class:`DetectorProgram`
    (``workflows.planner``) — so a spectro campaign inherits the whole
    resilience stack: retry taxonomy, health quarantine, the downshift
    ladder (per-file -> channel-chunk-tiled -> host), the dispatch
    watchdog and chaos coverage."""
    from ..eval import SpectroEvalAdapter

    mf = mf_prefilter(metadata, selected_channels, trace_shape,
                      fused_bandpass=fused_bandpass)
    return SpectroEvalAdapter(
        mf, SpectroCorrDetector(mf.metadata, threshold=threshold,
                                **spectro_kwargs),
    )


def main(url: str | None = None, outdir: str | None = None, show: bool = False,
         selected_channels_m=None, threshold: float = 14.0):
    block, meta, sel = acquire(url, selected_channels_m=selected_channels_m)

    mf = MatchedFilterDetector(meta, sel, tuple(block.trace.shape))
    trf_fk = mf.filter_block(block.trace)

    det = SpectroCorrDetector(meta.with_shape(*block.trace.shape), threshold=threshold)
    correlograms, picks, spectro_fs = det(trf_fk)

    figures = {}
    if outdir is not None or show:
        from .. import viz

        names = list(picks)
        fig = viz.detection_spectcorr(
            np.asarray(trf_fk), picks[names[0]], picks[names[-1]],
            block.tx, block.dist, spectro_fs, meta.dx, sel,
            file_begin_time_utc=block.t0_utc, show=show)
        figures["detection"] = maybe_savefig(fig, outdir, "spectro_detection.png")

    return {
        "picks": picks,
        "correlograms": correlograms,
        "spectro_fs": spectro_fs,
        "trf_fk": trf_fk,
        "block": block,
        "figures": figures,
    }


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None, outdir="out_spectrodetect")
