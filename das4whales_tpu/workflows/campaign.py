"""Fault-tolerant, resumable detection campaigns over file collections.

The reference's only batch story is re-running a script per file by hand;
its only resume behavior is ``dl_file`` skipping already-downloaded files
(data_handle.py:248-250), and a single corrupt file kills the run
(SURVEY.md §5.3-4: no failure detection, no checkpoint/resume). This
runner processes an arbitrary file list with:

* **design-once / detect-many** — one jitted detector reused across the
  campaign (tutorial.md:93), fed by the double-buffered prefetch stream
  (``io.stream``);
* **per-file fault isolation** — a file that fails to probe, read, or
  detect is recorded and skipped; the stream is restarted after the
  failure and the campaign continues (``max_failures`` bounds the
  tolerance);
* **durable progress** — every file appends a JSON-lines manifest record
  (status, pick counts, wall, error, attempts) and picks land in
  per-file ``.npz`` artifacts; re-running with ``resume=True`` skips
  completed files, so a killed campaign continues where it stopped;
* **classified failure handling** (``das4whales_tpu.faults``,
  docs/ROBUSTNESS.md) — transient-class failures (I/O blips, transfer
  errors) retry with seeded exponential backoff; corrupt-class failures
  disposition ``failed`` immediately; data-class breaches of the fused
  on-device health stats (``ops.health``) disposition ``quarantined``
  instead of silently-``done`` garbage picks; a hung reader becomes
  ``status="timeout"`` via the per-file read deadline; only fatal-class
  failures abort the run. The whole contract is provable under the
  seeded chaos harness (``faults.FaultPlan``, tests/test_chaos.py).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Sequence

import numpy as np

from .. import faults
from .. import fsck
from ..config import as_health_config
from ..io.stream import stream_strain_blocks
from ..models.matched_filter import MatchedFilterDetector
from ..telemetry import costs as tcosts
from ..telemetry import metrics as tmetrics
from ..telemetry import probes as tprobes
from ..telemetry import quality as tquality
from ..telemetry import trace as telemetry
from ..utils import artifacts
from ..utils.log import get_logger

log = get_logger("campaign")

# flight-recorder metrics (ISSUE 11, docs/OBSERVABILITY.md): slab wall
# percentiles for the batched route and the AOT preflight's HBM
# high-water, next to the dispatch/queue metrics parallel.dispatch owns
_h_slab_wall = tmetrics.histogram(
    "das_slab_wall_seconds",
    "wall seconds per batched slab (dispatch through bookkeeping)",
)
_g_preflight_hwm = tmetrics.gauge(
    "das_preflight_hbm_peak_bytes",
    "largest AOT-priced program HBM peak seen by the memory preflight",
)

MANIFEST = "manifest.jsonl"

#: the quality observatory's tenant label for (single-stream) campaign
#: runs — the service uses real tenant names (service/scheduler.py)
QUALITY_TENANT = "campaign"

#: statuses that disposition a file for good — resume skips them (a
#: quarantined file is deterministically unhealthy; re-reading it every
#: resume would re-derive the same breach). "failed" and "timeout" are
#: retried by a resume: they may have been transient at campaign scale.
_SETTLED_STATUSES = ("done", "quarantined")


class CampaignAborted(RuntimeError):
    """Raised when failures exceed ``max_failures``."""

    fault_class = "fatal"


@dataclass
class FileRecord:
    path: str
    #: "done" | "failed" | "skipped" | "quarantined" | "timeout"
    status: str
    n_picks: Dict[str, int] = field(default_factory=dict)
    wall_s: float = 0.0
    error: str = ""
    picks_file: str = ""
    #: how many attempts this file consumed (retried transients > 1)
    attempts: int = 1
    #: data-health stats (ops.health) when the campaign computed them
    health: Dict[str, float] = field(default_factory=dict)
    #: detector family that processed the file (workflows.planner:
    #: "mf" | "spectro" | "gabor" | "learned" | "generic"; "" on
    #: records from pre-planner manifests)
    family: str = ""
    #: the route rung that actually executed (faults.rung_label —
    #: "batched:4" / "file" / "tiled" / "timeshard" / "host"; also
    #: "sharded" / "multihost" for the SPMD campaigns) — with
    #: ``family`` this makes the downshift ledger auditable per family
    rung: str = ""


@dataclass
class CampaignResult:
    outdir: str
    records: List[FileRecord]

    @property
    def n_done(self) -> int:
        return sum(r.status == "done" for r in self.records)

    @property
    def n_failed(self) -> int:
        return sum(r.status == "failed" for r in self.records)

    @property
    def n_skipped(self) -> int:
        return sum(r.status == "skipped" for r in self.records)

    @property
    def n_quarantined(self) -> int:
        return sum(r.status == "quarantined" for r in self.records)

    @property
    def n_timeout(self) -> int:
        return sum(r.status == "timeout" for r in self.records)


def _manifest_path(outdir: str) -> str:
    return os.path.join(outdir, MANIFEST)


def _load_settled(outdir: str) -> set:
    """Paths whose LAST manifest record settles them (done/quarantined —
    last-record-wins, so a file that failed then succeeded on a later
    attempt reads settled, and one whose artifact was superseded by a
    fresh failure record does not)."""
    last: Dict[str, str] = {}

    def _warn_bad(lineno: int, verdict: str, _line: str) -> None:
        # torn final line / CRC-failed record from an unclean death:
        # tolerate (the file re-runs) but never silently
        log.warning("manifest %s line %d: %s record skipped by resume",
                    _manifest_path(outdir), lineno, verdict)

    for rec in artifacts.read_records(_manifest_path(outdir),
                                      on_bad=_warn_bad):
        if "path" in rec:
            last[rec["path"]] = rec.get("status", "")
    return {p for p, status in last.items() if status in _SETTLED_STATUSES}


def _append_manifest(outdir: str, rec: FileRecord) -> None:
    artifacts.append_record(_manifest_path(outdir), rec.__dict__)


def _append_event(outdir: str, event: Dict) -> None:
    """Append a non-file EVENT record to the manifest (no ``path`` key,
    so resume bookkeeping and per-file consumers skip it): the downshift
    ledger (``event="downshift"``), elastic-mesh rebuilds
    (``event="mesh_downshift"``) and the end-of-run resilience counters
    (``event="counters"``) — ``summarize_campaign`` aggregates them."""
    artifacts.append_record(_manifest_path(outdir), dict(event))


def _picks_path(outdir: str, path: str) -> str:
    """Deterministic artifact path for one file's picks (every process of
    a multi-host campaign computes the same name; only process 0 writes)."""
    import hashlib

    stem = os.path.splitext(os.path.basename(path))[0]
    # disambiguate same-named files from different directories (a campaign
    # over day1/seg.h5 + day2/seg.h5 must not overwrite artifacts)
    digest = hashlib.sha1(os.path.abspath(path).encode()).hexdigest()[:8]
    return os.path.join(outdir, "picks", f"{stem}-{digest}.npz")


def _save_picks(outdir: str, path: str, picks: Dict[str, np.ndarray],
                thresholds: Dict[str, float]) -> str:
    """Write one file's picks artifact ATOMICALLY (tmp + ``os.replace``):
    the manifest's ``done`` record is appended only after this returns,
    so a crash mid-write can never pair a torn ``.npz`` with a ``done``
    record — resume re-runs the file instead of trusting the torn
    artifact."""
    out = _picks_path(outdir, path)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    arrays = {f"picks_{name}": np.asarray(pk) for name, pk in picks.items()}
    # a family may legitimately expose thresholds for only SOME templates
    # (or none: an empty-but-present dict) — record NaN for the missing
    # names instead of crashing the artifact writer (workflows.planner
    # ``thresholds_for`` documents the absent-vs-empty distinction)
    arrays["thresholds"] = np.asarray(
        [float(thresholds.get(name, float("nan"))) for name in picks]
    )
    arrays["template_names"] = np.asarray(list(picks), dtype="U")
    # tmp + fsync + replace + directory fsync, via the one durable-write
    # layer (utils.artifacts — this function's original body is where
    # that layer came from): the rename must be durable before the
    # manifest's done record is appended, or a power loss could keep the
    # manifest line while dropping the directory entry.
    with artifacts.atomic_file(out, "wb") as fh:
        np.savez(fh, **arrays)
    return out


def load_picks(picks_file: str) -> Dict[str, np.ndarray]:
    """Read one campaign picks artifact back into ``{name: (2, n)}``."""
    with np.load(picks_file) as z:
        return {str(n): z[f"picks_{n}"] for n in z["template_names"]}


def load_settled(outdir: str) -> set:
    """Public face of the resume bookkeeping: the paths whose last
    manifest record settles them (done/quarantined — the PR 4
    last-record-wins semantics). The campaigns' ``resume=True`` and the
    service's source-side skip (``das4whales_tpu.service``) both read
    this, so "settled" has exactly one definition."""
    return _load_settled(outdir)


def pending_files(files, outdir: str | None = None, *,
                  settled: set | None = None) -> list:
    """Resume-single-tenant (ISSUE 20): the work-list that REMAINS for
    one tenant/campaign outdir — ``files`` minus the manifest-settled
    set, in the original order. This is the primitive fleet migration
    composes: a worker adopting a tenant from a dead (or drained) peer
    replays exactly this list, so a file settles done exactly once
    fleet-wide no matter how many workers served the tenant. Pass a
    pre-loaded ``settled`` set to skip the manifest re-read."""
    if settled is None:
        if outdir is None:
            raise ValueError("pending_files needs outdir or settled")
        settled = _load_settled(outdir)
    return [f for f in files if f not in settled]


def _normalize_metas(metadata, files):
    """The stream's metadata convention (None / one-for-all / aligned
    sequence) as an explicit per-file list."""
    if metadata is None:
        return [None] * len(files)
    if isinstance(metadata, (list, tuple)):
        if len(metadata) != len(files):
            raise ValueError(
                f"got {len(metadata)} metadata entries for {len(files)} files"
            )
        return list(metadata)
    return [metadata] * len(files)


def _split_resume(files, outdir: str, resume: bool, records: List[FileRecord]):
    """Partition ``files`` into (pending, pending_indices), appending
    'skipped' records for manifest-settled (done/quarantined) files."""
    done = _load_settled(outdir) if resume else set()
    pending, idx = [], []
    for j, path in enumerate(files):
        if path in done:
            records.append(FileRecord(path=path, status="skipped"))
        else:
            pending.append(path)
            idx.append(j)
    if records and resume:
        log.info("resume: %d/%d files already settled", len(records), len(files))
    return pending, idx


def _failure_recorder(outdir: str, records: List[FileRecord], max_failures,
                      write: bool = True, family: str = ""):
    """Shared per-file failure bookkeeping: manifest record + warning +
    max_failures enforcement (every non-done disposition — failed,
    quarantined, timeout — counts toward the tolerance). ``write=False``
    keeps the bookkeeping but skips the manifest append (multi-host
    non-writer processes). ``family`` is the default family label
    stamped on failure records (per-call override wins)."""
    state = {"n": 0}

    def fail(path: str, exc: Exception, status: str = "failed",
             attempts: int = 1, health=None, family=family,
             rung: str = "") -> None:
        state["n"] += 1
        rec = FileRecord(path=path, status=status,
                         error=f"{type(exc).__name__}: {exc}",
                         attempts=max(int(attempts), 1),
                         health=dict(health or {}),
                         family=family, rung=rung)
        records.append(rec)
        if write:
            _append_manifest(outdir, rec)
        log.warning("file %s (%d non-done so far): %s — %s",
                    status, state["n"], path, rec.error)
        if max_failures is not None and state["n"] > max_failures:
            raise CampaignAborted(
                f"{state['n']} failures exceed max_failures={max_failures}"
            ) from exc

    return fail


class _Resilience:
    """One campaign run's classified-failure machinery: the retry state
    over a ``faults.RetryPolicy``, the data-health config, and the
    terminal-disposition recorder (docs/ROBUSTNESS.md)."""

    def __init__(self, outdir, records, max_failures, retry, health,
                 write: bool = True):
        self.policy = faults.as_retry_policy(retry)
        self.state = faults.RetryState(self.policy)
        self.health_cfg = as_health_config(health)
        #: family label stamped on this run's failure records — set once
        #: the campaign resolves its DetectorProgram (workflows.planner)
        self.family = ""
        self._fail = _failure_recorder(outdir, records, max_failures,
                                       write=write)
        self.outdir = outdir
        self.write = write
        # per-CAMPAIGN resource-resilience tallies (the process-wide
        # faults.counters() aggregate across campaigns; these feed this
        # run's manifest "counters" event and summarize_campaign)
        self.tallies: Dict[str, int] = {
            "downshifts": 0, "oom_recoveries": 0, "watchdog_timeouts": 0,
        }

    def fail(self, path: str, exc: Exception, status: str = "failed",
             attempts: int = 1, health=None, rung: str = "") -> None:
        self._fail(path, exc, status=status, attempts=attempts,
                   health=health, family=self.family, rung=rung)

    def tally(self, name: str, n: int = 1) -> None:
        self.tallies[name] = self.tallies.get(name, 0) + n
        faults.count(name, n)

    def flush_tallies(self) -> None:
        """Write the end-of-run counters event — only when nonzero, so a
        healthy campaign's manifest stays pure file records. Stamped
        with the enclosing span id (the campaign root) when the flight
        recorder is on."""
        if self.write and any(self.tallies.values()):
            event = {"event": "counters", **self.tallies}
            sid = telemetry.current_span_id()
            if sid is not None:
                event["span_id"] = sid
            _append_event(self.outdir, event)

    def attempt(self, path: str) -> int:
        return self.state.attempt(path)

    def check_health(self, path: str, stats, rung: str = "") -> None:
        """Raise ``faults.DataHealthError`` (data-class -> quarantine)
        when ``stats`` breach the configured thresholds. ``rung`` labels
        the route that computed the stats so the quarantine record can
        name it (``FileRecord.rung``)."""
        if self.health_cfg is None or not stats:
            return
        reason = self.health_cfg.breach(stats)
        if reason:
            exc = faults.DataHealthError(reason, stats)
            exc.campaign_rung = rung
            raise exc

    def dispose(self, path: str, exc: Exception) -> str:
        """Classify a file's failure and either schedule a retry
        (returns ``"retry"`` after the deterministic backoff sleep) or
        record its terminal status (returns ``"next"``). Fatal-class
        failures re-raise — only they abort the campaign. Terminal
        records carry the rung the failure surfaced at when the
        dispatch layer annotated it (``campaign_rung`` —
        ``parallel.dispatch.resolve_watchdogged``)."""
        n_att = self.state.n_attempts(path)
        rung = getattr(exc, "campaign_rung", "")
        if isinstance(exc, faults.DeadlineExceeded):
            faults.count("timeouts")
            if isinstance(exc, faults.DispatchDeadlineExceeded):
                # the dispatch watchdog fired (wedged XLA runtime), not
                # the reader deadline — attributed separately so an OOM
                # triage can tell a hung chip from a hung mount
                self.tally("watchdog_timeouts")
            self.fail(path, exc, status="timeout", attempts=n_att, rung=rung)
            return "next"
        fclass = faults.classify_failure(exc)
        if fclass == "fatal":
            raise exc
        if self.state.should_retry(path, fclass):
            delay = self.state.backoff(path, fclass)
            log.warning("%s failure on %s (attempt %d): retrying after "
                        "%.3fs — %s", fclass, path, n_att, delay, exc)
            return "retry"
        if fclass == "data":
            faults.count("quarantined")
            self.fail(path, exc, status="quarantined", attempts=n_att,
                      health=getattr(exc, "stats", None), rung=rung)
        else:
            self.fail(path, exc, attempts=n_att, rung=rung)
        return "next"


# The elastic downshift ladder, the per-family DetectorProgram contract
# and the routed executor now live in workflows/planner.py (family-
# agnostic: every detector family inherits the ladder, watchdog, health
# gate and chaos dispatch hook — not just the matched filter).
from .planner import (  # noqa: E402
    DetectorProgram,
    DownshiftLadder,
    MatchedFilterProgram,
    RoutePlanner,
    family_ladder_stages,
    program_for,
)

# The service scheduler (das4whales_tpu/service/scheduler.py) reuses
# this module's per-file bookkeeping machinery — _Resilience,
# _file_record, _append_event, _load_settled (via load_settled), the
# das_slab_wall_seconds histogram — so a service tenant's manifest,
# artifacts and failure taxonomy are the batch campaign's, by
# construction (that shared machinery is what makes service picks
# bit-identical to run_campaign_batched's; tests/test_service.py).


FAMILIES = ("mf", "spectro", "gabor", "learned")


def family_detector(family: str, metadata, selected_channels, trace_shape,
                    *, wire: str = "conditioned", **detector_kwargs):
    """One bucket's PER-FILE detector at the bucket shape — the shared
    family builder behind :func:`run_campaign_batched` and the service
    scheduler's ``TenantRuntime._detector_for``. The batched facade
    (``parallel.batch.batched_detector_for``) wraps the result; the
    planner program (``workflows.planner.program_for``) serves its
    per-file/tiled/host rungs.

    ``detector_kwargs`` are the family constructor's: the matched
    filter's ``MatchedFilterDetector`` kwargs, the spectro/gabor
    ``campaign_detector`` kwargs, or — for ``"learned"`` — either
    ``params=``/``cfg=`` or ``pretrained=`` (default ``"fin_cnn"``,
    ``models.learned.load_pretrained``) plus ``LearnedDetector``
    kwargs."""
    if family == "mf":
        return MatchedFilterDetector(
            metadata, selected_channels, trace_shape, wire=wire,
            pick_mode="sparse", keep_correlograms=False,
            **detector_kwargs,
        )
    if family == "spectro":
        from .spectrodetect import campaign_detector

        return campaign_detector(metadata, selected_channels, trace_shape,
                                 **detector_kwargs)
    if family == "gabor":
        from .gabordetect import campaign_detector

        return campaign_detector(metadata, selected_channels, trace_shape,
                                 **detector_kwargs)
    if family != "learned":
        raise ValueError(
            f"unknown detector family {family!r}; expected one of {FAMILIES}"
        )
    from ..models.learned import LearnedDetector, load_pretrained

    kw = dict(detector_kwargs)
    if "params" in kw and "cfg" in kw:
        params, cfg = kw.pop("params"), kw.pop("cfg")
    else:
        params, cfg = load_pretrained(kw.pop("pretrained", "fin_cnn"))
    return LearnedDetector(params, cfg, **kw)


def run_campaign(
    files: Sequence[str],
    selected_channels,
    outdir: str,
    metadata=None,
    detector: MatchedFilterDetector | None = None,
    resume: bool = True,
    max_failures: int | None = None,
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "h5py",
    wire: str = "conditioned",
    retry=None,
    health=True,
    read_deadline_s: float | None = None,
    dispatch_deadline_s: float | None = None,
    dispatch_depth: int | None = None,
    trace: bool | None = None,
    quality: bool | None = None,
    fault_plan=None,
    **detector_kwargs,
) -> CampaignResult:
    """Detect over ``files``, tolerating per-file failures and resuming
    past completed work.

    ``quality`` (None: the ``DAS_QUALITY`` env default) arms the
    science-quality observatory exactly like
    :func:`run_campaign_batched` — per-file quality records from the
    already-fetched payload, a manifest ``quality`` event and
    ``<outdir>/quality.json`` at campaign end; picks bit-identical and
    zero extra compiles/dispatches either way (``telemetry.quality``,
    docs/OBSERVABILITY.md).

    ``trace`` (None: the ``DAS_TRACE`` env default) arms the FLIGHT
    RECORDER (``das4whales_tpu.telemetry``): the campaign runs inside a
    root span, every read/h2d/resolve/downshift/retry is a span with
    file/rung/family attributes, the ledger's downshift events carry
    their span ids, and ``<outdir>/trace.json`` (Chrome-trace/Perfetto)
    is exported next to the manifest — picks are bit-identical with
    tracing on or off (docs/OBSERVABILITY.md).

    ``detector=None`` builds a ``MatchedFilterDetector`` from the first
    readable file's shape/metadata (extra ``detector_kwargs`` pass
    through). ``wire="raw"`` streams stored-dtype counts (narrow wire)
    and builds the detector with the matching on-device conditioning
    prologue — a caller-supplied ``detector`` must have been built with
    the same ``wire``. Returns a :class:`CampaignResult`; durable state
    lives in ``outdir/manifest.jsonl`` + ``outdir/picks/*.npz``.

    Resilience knobs (docs/ROBUSTNESS.md): ``retry`` — a
    ``faults.RetryPolicy`` (None/True: the env-driven default, 3
    attempts with seeded exponential backoff; False: off) applied to
    transient-class failures, with attempt counts recorded in the
    manifest; ``health`` — a ``config.DataHealthConfig`` (None/True: the
    default, which quarantines any non-finite sample; False: off)
    checked against the on-device health stats fused into the detection
    program (``ops.health``; host-computed for detector families without
    the fused route); ``read_deadline_s`` — per-file reader deadline
    (``status="timeout"`` instead of a stalled campaign);
    ``dispatch_deadline_s`` — the dispatch WATCHDOG (None: the
    ``DAS_DISPATCH_DEADLINE_S`` env default): bounds any one device
    dispatch+fetch, so a wedged XLA runtime becomes ``status="timeout"``
    too (``faults.call_with_deadline``); ``fault_plan`` — a
    ``faults.FaultPlan`` chaos schedule (testing).

    Resource exhaustion (``faults.classify_failure == "resource"``, e.g.
    an XLA ``RESOURCE_EXHAUSTED``): EVERY detector family downshifts the
    route through the family-agnostic planner (``workflows.planner``) —
    per-file -> the family's declared leaner rungs (channel-tiled /
    time-sharded where the math supports them) -> host — with the
    winning rung STICKY for the rest of the run and ledgered in the
    manifest with the family label (docs/ROBUSTNESS.md "Resource
    ladder" + "Family x guarantee coverage"). The executed family and
    rung land on every ``FileRecord``.

    ``dispatch_depth`` (None: the ``DAS_DISPATCH_DEPTH`` env default,
    2) arms DEPTH-D PIPELINED DISPATCH on the healthy per-file rung
    (``parallel.dispatch``, docs/PERF.md "Pipelined dispatch"): file
    k+1's one-program detection is dispatched before file k's packed
    fetch, so its compute overlaps file k's host-side bookkeeping.
    Applies to families whose program declares async dispatch + fused
    health (``DetectorProgram.supports_dispatch`` — the sparse-engine
    matched filter today); every other configuration — and any file
    whose resolve fails — takes the synchronous path with identical
    attribution and retries.
    """
    from ..config import dispatch_deadline_default

    if dispatch_deadline_s is None:
        dispatch_deadline_s = dispatch_deadline_default()
    use_quality = tquality.resolve_enabled(quality)
    if use_quality:
        tquality.OBSERVATORY.fresh(QUALITY_TENANT)

    det_wire = getattr(detector, "wire", "conditioned")
    if detector is not None and det_wire != wire:
        raise ValueError(
            f"detector was built with wire={det_wire!r} but the "
            f"campaign streams wire={wire!r}; a conditioned-wire detector "
            "fed raw counts would treat them as strain (no on-device "
            "demean/scale) and silently mis-detect"
        )
    os.makedirs(outdir, exist_ok=True)
    fsck.startup_check(outdir, label="campaign")
    metas = _normalize_metas(metadata, list(files))
    records: List[FileRecord] = []
    pending, pend_idx = _split_resume(list(files), outdir, resume, records)
    pend_metas = [metas[j] for j in pend_idx]
    rz = _Resilience(outdir, records, max_failures, retry, health)
    # resolve the family program up front when the detector is known, so
    # even a file that fails BEFORE the first successful detect carries
    # the right family in its record (the per-family audit must not
    # split a planner-era campaign across "" and the real family)
    route: RoutePlanner | None = None
    if detector is not None:
        route = RoutePlanner(
            rz, outdir, program_for(detector),
            dispatch_deadline_s=dispatch_deadline_s, fault_plan=fault_plan,
        )
        rz.family = route.program.family
    else:
        rz.family = "mf"   # detector=None builds a MatchedFilterDetector
    _BUCKET = "campaign"   # one unbatched campaign = one sticky ladder key

    def detect_one(path, block, t0, inflight=None):
        """One attempt at the transfer+detect+health half of a file
        (raises on failure; the caller dispositions). Resource-class
        dispatch failures downshift the family's route in place
        (sticky — ``workflows.planner``). ``inflight`` is the depth-D
        pipeline's pre-dispatched program for this file: the first
        healthy-rung attempt consumes its packed fetch instead of
        dispatching fresh; any failure discards it (retries re-dispatch
        synchronously)."""
        nonlocal detector, route
        if fault_plan is not None:
            fault_plan.on_transfer(path)
        if detector is None:
            detector = MatchedFilterDetector(
                block.metadata, selected_channels, block.trace.shape,
                wire=wire, **detector_kwargs,
            )
        if route is None:
            route = RoutePlanner(
                rz, outdir, program_for(detector),
                dispatch_deadline_s=dispatch_deadline_s,
                fault_plan=fault_plan,
            )
            rz.family = route.program.family
        det_meta = getattr(detector, "metadata", None)
        if (wire == "raw" and det_meta is not None
                and block.metadata is not None
                and block.metadata.scale_factor != det_meta.scale_factor):
            # the raw wire conditions on device with the DETECTOR's
            # scale; a file probed with a different factor would get
            # the wrong strain silently — fail it per-file instead
            raise ValueError(
                f"scale_factor {block.metadata.scale_factor!r} != "
                f"detector scale {det_meta.scale_factor!r}; wire='raw' "
                "conditions with one scale — use wire='conditioned' "
                "for heterogeneous file sets"
            )
        if fault_plan is not None:
            fault_plan.on_detect(path)
        clip = rz.health_cfg.clip_abs if rz.health_cfg is not None else None
        with_health = rz.health_cfg is not None
        # the family-agnostic rung loop: the planner resolves the file at
        # the sticky rung inside the watchdog (chaos on_dispatch fires
        # inside the deadline), downshifting on resource-class failures —
        # EVERY family, not just the matched filter
        picks, thresholds, stats, rung = route.run_file(
            path, block.trace, with_health=with_health, clip=clip,
            inflight=inflight, key=_BUCKET,
        )
        # -> quarantine on breach (record names the executing rung)
        rz.check_health(path, stats, rung=faults.rung_label(rung))
        if fault_plan is not None:
            fault_plan.detect_succeeded()
        rec = FileRecord(
            path=path, status="done",
            n_picks={k: int(v.shape[1]) for k, v in picks.items()},
            wall_s=round(time.perf_counter() - t0, 3),
            picks_file=_save_picks(outdir, path, picks, thresholds),
            attempts=rz.state.n_attempts(path), health=dict(stats or {}),
            family=route.program.family, rung=faults.rung_label(rung),
        )
        # manifest BEFORE the in-memory record: this block is retried,
        # and a transient manifest-append failure must not leave a
        # phantom record that a successful retry would duplicate
        _append_manifest(outdir, rec)
        records.append(rec)
        tprobes.note_file_ok()   # healthy file: readiness quarantine streak resets
        if use_quality:
            _observe_quality(QUALITY_TENANT, detector, path, picks,
                             thresholds, stats,
                             np.asarray(block.trace).shape[-1])

    from ..parallel.dispatch import PipelinedDispatch

    pipe = PipelinedDispatch(dispatch_depth)

    def try_dispatch_file(path, block):
        """The pipeline's dispatch phase: launch this file's program
        asynchronously when the family supports async dispatch + fused
        health and the campaign rides the healthy per-file rung. None ->
        the synchronous path (attribution-identical; also taken for the
        first file, which builds the detector and its program)."""
        if not pipe.enabled or route is None or rz.health_cfg is None:
            return None
        if not (route.program.supports_dispatch
                and route.program.supports_fused_health
                and route.current(_BUCKET) == ("file", 1)):
            return None
        det_meta = getattr(detector, "metadata", None)
        if (wire == "raw" and det_meta is not None
                and block.metadata is not None
                and block.metadata.scale_factor != det_meta.scale_factor):
            return None   # detect_one fails it per-file on the sync path
        try:
            return route.program.dispatch(
                block.trace, with_health=True, clip=rz.health_cfg.clip_abs,
            )
        except Exception:  # noqa: BLE001 — surfaces on the sync path
            return None

    def finalize_file(path, block, t0, infl) -> None:
        while True:  # transfer+detect attempts (block already read)
            rz.attempt(path)
            try:
                detect_one(path, block, t0, inflight=infl)
            except Exception as exc:  # noqa: BLE001
                infl = None   # retries re-dispatch synchronously
                if rz.dispose(path, exc) == "retry":
                    continue
            break

    def drain_pipe() -> None:
        for tok, queued in pipe.drain():
            finalize_file(*tok, queued)

    with telemetry.campaign_trace(outdir, trace, kind="per-file",
                                  n_files=len(files), family=rz.family):
        i = 0
        while i < len(pending):
            # one stream per contiguous run of healthy files; a failure
            # mid-stream kills the generator, so restart it after the
            # culprit — or AT it, when its failure class earned a retry
            stream = stream_strain_blocks(
                pending[i:], selected_channels, pend_metas[i:],
                interrogator=interrogator, prefetch=prefetch, engine=engine,
                as_numpy=True, wire=wire, read_deadline_s=read_deadline_s,
                fault_plan=fault_plan,
            )
            while True:
                path = pending[i] if i < len(pending) else None
                try:
                    block = next(stream)
                except StopIteration:
                    i = len(pending)
                    break
                except Exception as exc:  # noqa: BLE001 — per-file isolation
                    # queued in-flight files are earlier, healthy reads:
                    # finalize them first so their records precede the
                    # culprit's in the manifest
                    drain_pipe()
                    rz.attempt(path)
                    if rz.dispose(path, exc) == "next":
                        i += 1
                    break  # restart the stream either way
                t0 = time.perf_counter()
                infl = try_dispatch_file(path, block)
                if infl is None:
                    drain_pipe()
                    finalize_file(path, block, t0, None)
                else:
                    for tok, queued in pipe.submit((path, block, t0), infl):
                        finalize_file(*tok, queued)
                i += 1
            del stream
        drain_pipe()   # end of segment: the one remaining sync
        rz.flush_tallies()
        if use_quality:
            _flush_quality(outdir, [QUALITY_TENANT])
    return CampaignResult(outdir=outdir, records=records)


def run_campaign_batched(
    files: Sequence[str],
    selected_channels,
    outdir: str,
    metadata=None,
    batch: int = 4,
    bucket="pow2",
    resume: bool = True,
    max_failures: int | None = None,
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "h5py",
    wire: str = "conditioned",
    family: str = "mf",
    in_flight: int = 2,
    serial: bool | None = None,
    persistent_cache: bool | str = True,
    retry=None,
    health=True,
    read_deadline_s: float | None = None,
    dispatch_deadline_s: float | None = None,
    preflight: bool | None = None,
    dispatch_depth: int | None = None,
    trace: bool | None = None,
    cost_cards: bool | None = None,
    quality: bool | None = None,
    fault_plan=None,
    **detector_kwargs,
) -> CampaignResult:
    """Single-chip BATCHED campaign: ``batch`` files per program step.

    ``family`` selects the detector family riding the slab route —
    ``"mf"`` (default), ``"spectro"``, ``"gabor"``, or ``"learned"``.
    Every family runs the full one-program batched contract
    (``parallel.batch.batched_detector_for``): one heavy program per
    slab, AOT-priced admission, ``("batched", B)`` downshift rungs,
    pipelined dispatch, cost cards — with per-file picks pinned
    bit-identical to that family's per-file rung. Non-MF families
    require ``wire="conditioned"`` (their prefilter consumes strain);
    ``detector_kwargs`` go to the family's campaign builder
    (``spectrodetect/gabordetect.campaign_detector``; the learned
    family takes ``params``/``cfg`` or ``pretrained="fin_cnn"`` plus
    ``LearnedDetector`` knobs).

    ``quality`` (None: the ``DAS_QUALITY`` env default) arms the
    SCIENCE-QUALITY OBSERVATORY (``telemetry.quality``, ISSUE 15):
    every done file feeds the pick-stream counters/SNR histograms, the
    per-channel health gauges and the EWMA drift baselines — derived
    entirely from the packed fetch the campaign already pays — and the
    run ends with a manifest ``quality`` event plus
    ``<outdir>/quality.json`` next to the manifest
    (``scripts/trace_report.py --quality`` renders it). Picks are
    bit-identical either way and compile_guard pins zero extra
    compiles/dispatches: the observatory only READS fetched values;
    disabled, every hook is one attribute check.

    ``trace`` (None: the ``DAS_TRACE`` env default) arms the FLIGHT
    RECORDER exactly like :func:`run_campaign`: a root campaign span,
    read/h2d/slab/resolve/preflight/downshift spans, ledger events
    stamped with span ids, and ``<outdir>/trace.json`` exported next to
    the manifest — picks bit-identical either way
    (docs/OBSERVABILITY.md).

    ``cost_cards`` (None: the ``DAS_COST_CARDS`` env default) arms the
    COST OBSERVATORY (``telemetry.costs``, ISSUE 14): every priced or
    starting rung's program yields a per-``(bucket, rung, engine)``
    cost card at the preflight's own ``lower().compile()`` boundary
    (XLA FLOPs/bytes, memory peaks, ``das_compile_seconds``), every
    resolved slab feeds the live ``das_roofline_frac{stage,engine}``
    gauge, and the registry exports to ``<outdir>/cost_cards.json``
    next to the manifest (``scripts/trace_report.py --costs`` merges it
    with the span walls). Picks are bit-identical either way — the
    cards are AOT-priced, never dispatched; disabled, the hooks cost
    one attribute check (the PR 10 overhead budget).

    The throughput route for the "one file cannot saturate the chip"
    regime (BENCH_r05: every stage at ~1-2% of roofline): the slab
    assembler (``io.stream.stream_batched_slabs``) coalesces same-bucket
    files off the overlap executor into one ``[B, channel, time]`` stack,
    and the batched one-program route (``parallel.batch``) detects the
    whole slab in ONE dispatch + ONE packed fetch — per-file picks
    bit-identical to :func:`run_campaign`'s unbatched one-program route.
    Manifest/resume/picks-artifact contract, per-file fault isolation and
    ``max_failures`` are exactly :func:`run_campaign`'s.

    Heterogeneous record lengths ride shape buckets (``bucket``:
    ``config.BatchBucketConfig`` / ``"pow2"`` / ``"exact"`` / fixed
    lengths) so the campaign compiles O(#buckets) programs; those
    compiles persist across processes via the on-disk compilation cache
    (``persistent_cache``: True wires ``config.compilation_cache_dir()``,
    a str names the directory, False skips — docs/TPU_RUNBOOK.md).
    Slab donation is retired (the R12 contract audit —
    ``parallel.batch`` module docstring), so there is no ``donate``
    knob; ``in_flight`` bounds slabs resident on device; ``serial`` forces the in-program batch execution
    mode (``True``: ``lax.map``, ``False``: ``vmap``; ``None`` resolves
    per backend — see ``parallel.batch._batched_body``). ``wire="raw"`` streams stored-dtype counts and
    conditions on device per bucket (padded records demean over real
    samples only); like :func:`run_campaign`, a file whose probed
    ``scale_factor`` differs from its bucket detector's fails per-file.

    Resilience (``retry`` / ``health`` / ``read_deadline_s`` /
    ``fault_plan``): :func:`run_campaign`'s classified contract, plus
    the batched route's GRACEFUL-DEGRADATION ladder — a whole-slab
    device failure retries the slab's files through the unbatched
    one-program route (on the assembler's host blocks) before failing
    any of them, so one poisoned file costs one file, not a slab
    (docs/ROBUSTNESS.md). Health stats are fused per file into the
    batched program (``ops.health``) and breaching files are
    ``quarantined``.

    Resource exhaustion rides the ELASTIC DOWNSHIFT LADDER
    (docs/ROBUSTNESS.md "Resource ladder"): a resource-class device
    failure (XLA ``RESOURCE_EXHAUSTED``) retries the slab at
    B -> B/2 -> ... -> 1 (sub-slabs rebuilt from the assembler's host
    blocks — ``io.stream.subdivide_slab``), then the per-file
    one-program route, the channel-tiled route, the time-sharded route
    (multi-device meshes whose shape divides), and finally the host CPU
    backend. The winning rung is STICKY per bucket for the rest of the
    campaign (one ``downshift`` ledger event per move in the manifest,
    no per-file thrash) and per-file picks are bit-identical at every
    single-chip rung (the batched program's per-file math IS the
    unbatched program's). ``dispatch_deadline_s`` arms the dispatch
    WATCHDOG (None: the ``DAS_DISPATCH_DEADLINE_S`` env default): a
    wedged dispatch/fetch becomes ``status="timeout"``.
    ``preflight`` (None: the ``DAS_MEMORY_PREFLIGHT`` env default) runs
    the AOT memory preflight per bucket (``utils.memory``): each bucket
    starts at the largest batch whose program fits
    ``DAS_HBM_BUDGET_GB`` — and shapes that fit at no rung are skipped
    up front instead of dispatched into a certain OOM.

    ``dispatch_depth`` (None: the ``DAS_DISPATCH_DEPTH`` env default,
    2) arms DEPTH-D PIPELINED DISPATCH (``parallel.dispatch``,
    docs/PERF.md "Pipelined dispatch"): while a bucket rides its top
    (healthy) rung, slab k+1's K0 program is dispatched BEFORE slab k's
    packed fetch is taken, so H2D, compute and fetch of different slabs
    overlap and the campaign takes one sync per slab that itself
    overlaps the successors' compute — no idle dispatch wall between
    slabs. The adaptive-K escalation is decided from the already-fetched
    K0 payload (``sat_count`` rides the packed fetch). Every resilience
    contract is unchanged: an in-flight failure surfaces when ITS slab
    resolves — in file order, inside the same watchdog/ladder/degrade
    wrappers — so manifest attribution, the chaos oracle and the sticky
    downshift ledger are byte-identical to ``dispatch_depth=1``
    (synchronous, the pre-pipeline behavior; also the fallback whenever
    a bucket leaves its top rung). Device memory holds up to
    ``dispatch_depth`` slabs' programs in flight on top of the transfer
    pipeline's ``in_flight`` stacks.
    """
    from ..config import (
        dispatch_deadline_default,
        enable_persistent_compilation_cache,
        hbm_budget_bytes,
        memory_preflight_default,
    )
    from ..io.stream import SlabReadError, stream_batched_slabs, subdivide_slab
    from ..parallel.batch import (
        BatchedMatchedFilterDetector,
        batched_detector_for,
        trim_picks,
    )
    from ..parallel.dispatch import PipelinedDispatch, resolve_watchdogged

    if family not in FAMILIES:
        raise ValueError(
            f"unknown detector family {family!r}; batched campaigns serve "
            f"{', '.join(FAMILIES)}"
        )
    if family != "mf" and wire != "conditioned":
        raise ValueError(
            f"family={family!r} requires wire='conditioned': the family's "
            "prefilter consumes strain, not stored-dtype counts (got "
            f"wire={wire!r})"
        )
    if family != "mf" and bucket != "exact":
        # The non-MF families are NOT padding-invariant: spectro/gabor
        # derive thresholds from the record's own max and learned
        # windows the full time axis, so a pow2-padded record changes
        # picks. Exact-length buckets keep every rung's math (batched,
        # per-file fallback, host blocks) on the same samples — the
        # bit-identity guarantee. Same-length files still share one
        # bucket, so batching is intact for uniform acquisitions.
        log.info("family=%s campaigns bucket exactly (overriding "
                 "bucket=%r): padded records would change data-dependent "
                 "thresholds/windows", family, bucket)
        bucket = "exact"
    if dispatch_deadline_s is None:
        dispatch_deadline_s = dispatch_deadline_default()
    if preflight is None:
        preflight = memory_preflight_default()
    use_costs = tcosts.resolve_enabled(cost_cards)
    use_quality = tquality.resolve_enabled(quality)
    if use_quality:
        # one campaign run = one drift baseline: never inherit a
        # previous run's regime (telemetry.quality.fresh)
        tquality.OBSERVATORY.fresh(QUALITY_TENANT)
    if persistent_cache:
        enable_persistent_compilation_cache(
            persistent_cache if isinstance(persistent_cache, str) else None
        )
    os.makedirs(outdir, exist_ok=True)
    fsck.startup_check(outdir, label="campaign")
    metas = _normalize_metas(metadata, list(files))
    records: List[FileRecord] = []
    pending, pend_idx = _split_resume(list(files), outdir, resume, records)
    pend_metas = [metas[j] for j in pend_idx]
    rz = _Resilience(outdir, records, max_failures, retry, health)
    rz.family = family
    fail = rz.fail
    with_health = rz.health_cfg is not None
    clip = rz.health_cfg.clip_abs if with_health else None
    # Ladder stages: "batched" plus whatever the family's per-file
    # program declares — spectro/gabor/learned do not support every MF
    # rung (no timeshard math), so downshifts must skip straight to the
    # rungs their planner program can actually serve.
    ladder = DownshiftLadder(rz, outdir, batch=batch, family=family,
                             stages=family_ladder_stages(family))

    dets: Dict[tuple, object] = {}       # bucket -> batched facade
    progs: Dict[tuple, DetectorProgram] = {}   # per-file-rung programs
    skip_buckets: Dict[tuple, str] = {}   # preflight: nothing fits

    def build_family_detector(key, slab):
        return family_detector(
            family, slab.blocks[0].metadata, selected_channels,
            (key[0], slab.bucket_ns), wire=wire, **detector_kwargs,
        )

    def _bucket_key(slab) -> tuple:
        return (slab.stack.shape[1], slab.bucket_ns,
                np.dtype(np.asarray(slab.blocks[0].trace).dtype).name)

    def preflight_bucket(key, bdet, slab) -> None:
        """AOT memory preflight (utils.memory): start this bucket at the
        largest (bucket, B) whose program fits DAS_HBM_BUDGET_GB, before
        its first dispatch — and skip shapes no rung can fit."""
        from ..utils import memory as memutils

        budget = hbm_budget_bytes()
        cands, b = [], batch
        while b >= 1:
            cands.append(b)
            b //= 2
        dt = np.asarray(slab.blocks[0].trace).dtype

        def price(bd, b_, program):
            if use_costs:
                # the cost observatory captures at the SAME compile the
                # preflight pays: one lower().compile() serves both the
                # admission decision and the program's cost card
                st = tcosts.capture_batched(
                    bd, b_, dt, bucket=tcosts.bucket_label(key),
                    program=program, with_health=with_health,
                    health_clip=clip,
                )
            else:
                st = memutils.batched_program_memory(
                    bd, b_, dt, with_health=with_health, health_clip=clip
                )
            if st is not None:
                # preflight high-water: the hungriest program this
                # campaign ever priced (the Prometheus surface's HBM
                # headroom signal)
                _g_preflight_hwm.max(float(st.peak))
            return st

        # candidate rungs in LADDER order: the full bank at each B, then
        # — for splittable banks — the bank-split rung at the same B
        # (the T axis is priced before B is sacrificed); the fitting
        # policy itself (unpriceable-reads-as-fitting) lives in ONE
        # place, utils.memory.first_fitting
        split = getattr(bdet.det, "supports_bank_split", False)
        rung_cands = []
        for b_ in cands:
            rung_cands.append(("batched", b_))
            if split:
                rung_cands.append(("bank", b_))

        def price_rung(rung_):
            stage_, b_ = rung_
            # the LARGER (ceil) T/2 sub-bank certifies the split pair
            bd = bdet.split_views()[0] if stage_ == "bank" else bdet
            return price(bd, b_, faults.rung_label(rung_))

        best = memutils.first_fitting(price_rung, rung_cands, budget)
        if best is not None:
            stage_, b_ = best
            if stage_ == "bank":
                ladder.pin(key, ("bank", b_), (
                    f"preflight: full T={len(bdet.det.bank)} bank over "
                    f"budget at B={b_}; T/2 sub-banks fit "
                    f"{budget / 2**30:.2f} GiB"
                ))
            elif b_ < batch:
                ladder.pin(
                    key, ("batched", b_) if b_ > 1 else ("file", 1),
                    f"preflight: largest fitting batch B={b_} under "
                    f"{budget / 2**30:.2f} GiB",
                )
            return
        if family != "mf":
            # family facades have no batched-tiled program to price; the
            # per-file rung starts the family's own ladder (per-file ->
            # tiled/host), whose programs the ladder protects un-priced
            ladder.pin(key, ("file", 1), (
                f"preflight: no (bucket, B) {family} program fits "
                f"{budget / 2**30:.2f} GiB; per-file ladder takes over"
            ))
            return
        # not even B=1 fits the monolithic program: price the tiled one
        tiled = BatchedMatchedFilterDetector(
            bdet.det.tiled_view(), serial=bdet.serial
        )
        if use_costs:
            tstats = tcosts.capture_batched(
                tiled, 1, dt, bucket=tcosts.bucket_label(key),
                program="tiled", with_health=with_health, health_clip=clip,
            )
        else:
            tstats = memutils.batched_program_memory(
                tiled, 1, dt, with_health=with_health, health_clip=clip
            )
        if tstats is None or tstats.fits(budget):
            ladder.pin(key, ("tiled", 1),
                       "preflight: only the tiled per-file program fits "
                       f"{budget / 2**30:.2f} GiB")
            return
        reason = (
            f"preflight: no (bucket, B) program shape fits "
            f"DAS_HBM_BUDGET_GB ({budget / 2**30:.2f} GiB); smallest "
            f"candidate needs {tstats.peak / 2**30:.2f} GiB — skipped "
            "before dispatch"
        )
        skip_buckets[key] = reason
        event = {"event": "preflight_skip",
                 "bucket": key if isinstance(key, str) else list(key),
                 "reason": reason}
        sid = telemetry.current_span_id()   # the enclosing preflight span
        if sid is not None:
            event["span_id"] = sid
        _append_event(outdir, event)
        log.warning("bucket %s: %s", key, reason)

    def detector_for(slab):
        key = _bucket_key(slab)
        bdet = dets.get(key)
        if bdet is None:
            per_file_det = build_family_detector(key, slab)
            bdet = batched_detector_for(
                per_file_det, serial=serial,
                trace_shape=(key[0], slab.bucket_ns),
            )
            if hasattr(bdet, "_resolve_engines"):
                # family facades: resolve the per-shape engine decision
                # EAGERLY (the A/B router times candidates — never under
                # the preflight's trace)
                bdet._resolve_engines((batch, key[0], slab.bucket_ns))
            dets[key] = bdet
            progs[key] = program_for(per_file_det)
            # each bucket's detector resolved its own engines (per-shape
            # A/B, ops.mxu router) — register them so that bucket's
            # downshift events describe ITS routes, not the first
            # bucket's
            ladder.set_engines(key, progs[key].engines)
            if getattr(bdet.det, "supports_bank_split", False):
                # splittable template bank: this bucket's ladder gains
                # the bank-split rung (T/2 sub-banks before B shrinks)
                ladder.enable_bank_split(key)
            if preflight:
                with telemetry.span("preflight", bucket=str(key)):
                    preflight_bucket(key, bdet, slab)
            if use_costs and key not in skip_buckets:
                # the bucket's STARTING rung always has a card, preflight
                # or not (ensure: the preflight walk already captured the
                # rungs it priced — a pinned ("file", 1) bucket still
                # gains its own "file"-labeled card here so the resolve-
                # time lookup matches the executing rung's label)
                rung0 = ladder.current(key)
                stage0, b0 = rung0
                if stage0 in ("batched", "bank", "file"):
                    bd0 = (bdet.split_views()[0] if stage0 == "bank"
                           else bdet)
                    tcosts.ensure_batched_card(
                        bd0, max(1, int(b0)),
                        np.asarray(slab.blocks[0].trace).dtype,
                        bucket=tcosts.bucket_label(key),
                        program=faults.rung_label(rung0),
                        with_health=with_health, health_clip=clip,
                    )
        return bdet

    def dispatched(paths, rung, fn):
        """One watchdogged device dispatch: the chaos dispatch hook
        (``FaultPlan.on_dispatch``) fires INSIDE the deadline-bounded
        callable, exactly like a real wedged/OOMing launch
        (``parallel.dispatch.resolve_watchdogged`` — shared with the
        planner's per-file executor)."""
        return resolve_watchdogged(fn, paths, rung, dispatch_deadline_s,
                                   fault_plan, family=family)

    def per_file_fallback(slab, k, prog, rung=("file", 1)):
        """The unbatched per-file route on the assembler's host block
        (never the device slab — the host copy is the stable source):
        the packed-overflow exact path AND the degradation ladder's
        second rung. ``rung`` honors a stickier ladder placement (a
        bucket already downshifted to tiled/host retries there, not at
        a rung known to OOM)."""
        tr = np.asarray(slab.blocks[k].trace)
        padded = np.zeros((tr.shape[0], slab.bucket_ns), tr.dtype)
        padded[:, : tr.shape[1]] = tr

        def fn():
            return prog.detect(
                rung, padded, n_real=slab.n_real[k],
                with_health=with_health, clip=clip,
            )

        return dispatched([slab.paths[k]], rung, fn)

    def run_rung(slab, rung, bdet, ok, inflight=None):
        """The whole slab's entries at one ladder rung — aligned with
        ``range(slab.n_valid)``; raises on the rung's failure (resource
        -> the caller downshifts). ``inflight`` (an
        ``InFlightResult`` from the depth-D pipeline's dispatch phase)
        short-circuits the top batched rung: the program is already
        running — the watchdogged call here is its packed fetch, with
        the chaos dispatch hooks firing inside the deadline exactly
        like a fresh dispatch (an async launch's failure also surfaces
        at the fetch)."""
        prog = progs[_bucket_key(slab)]
        stage, b = rung
        if stage == "batched":
            if b >= batch:
                if inflight is not None:
                    return dispatched(list(slab.paths), rung,
                                      inflight.resolve)
                subs = [slab]
            else:
                # re-bucket from the assembler's HOST blocks: the device
                # stack may be donated/unfit, and sub-slabs at B' reuse
                # the existing per-(bucket, B') compiled programs
                subs = subdivide_slab(slab, b)
            entries = []
            for sub in subs:
                def fn(sub=sub):
                    return bdet.detect_batch(
                        sub.stack, n_real=sub.n_real, n_valid=sub.n_valid,
                        with_health=with_health, health_clip=clip,
                    )
                entries.extend(
                    dispatched(list(sub.paths), rung, fn)[: sub.n_valid]
                )
            return entries
        if stage == "bank":
            # the bank-split rung: the SAME batch as two T/2 sub-bank
            # dispatches (parallel.batch split_views — picks
            # bit-identical to the one-dispatch bank under the
            # splittable per_template scope), before B is sacrificed.
            # Any in-flight full-bank handle was discarded by the
            # caller when the bucket left its top rung.
            subs = [slab] if b >= batch else subdivide_slab(slab, b)
            half_a, half_b = bdet.split_views()
            entries = []
            for sub in subs:
                halves = []
                for j, hdet in enumerate((half_a, half_b)):
                    # health stats describe the INPUT block — identical
                    # either half, so only the FIRST dispatch computes
                    # them (the second would pay the on-device reduction
                    # twice and compile a with_health program variant
                    # for nothing — the planner's per-file bank rung
                    # plays the same trick)
                    def fn(sub=sub, hdet=hdet, j=j):
                        return hdet.detect_batch(
                            sub.stack, n_real=sub.n_real,
                            n_valid=sub.n_valid,
                            with_health=with_health and j == 0,
                            health_clip=clip,
                        )
                    halves.append(
                        dispatched(list(sub.paths), rung, fn)[: sub.n_valid]
                    )
                for ea, eb in zip(*halves):
                    if ea is None or eb is None:
                        entries.append(None)   # overflow: exact fallback
                        continue
                    merged_picks = {**ea[0], **eb[0]}
                    merged_thr = {**ea[1], **eb[1]}
                    entries.append(
                        (merged_picks, merged_thr, ea[2]) if with_health
                        else (merged_picks, merged_thr)
                    )
            return entries
        entries = []
        for k in range(slab.n_valid):
            if not ok[k]:
                entries.append(None)   # dispositioned by the scale guard
                continue
            tr = np.asarray(slab.blocks[k].trace)
            padded = np.zeros((tr.shape[0], slab.bucket_ns), tr.dtype)
            padded[:, : tr.shape[1]] = tr

            def fn(padded=padded, k=k):
                return prog.detect(
                    rung, padded, n_real=slab.n_real[k],
                    with_health=with_health, clip=clip,
                )
            entries.append(dispatched([slab.paths[k]], rung, fn))
        return entries

    def handle_slab(slab, inflight=None) -> None:
        bdet = detector_for(slab)
        det = bdet.det
        key = _bucket_key(slab)
        if key in skip_buckets:
            for k in range(slab.n_valid):
                fail(slab.paths[k], RuntimeError(skip_buckets[key]))
            return
        ok = []
        for k in range(slab.n_valid):
            meta_k = slab.blocks[k].metadata
            if (wire == "raw" and meta_k is not None
                    and meta_k.scale_factor != det.metadata.scale_factor):
                # the raw wire conditions with the BUCKET detector's scale
                # (same per-file guard as run_campaign)
                fail(slab.paths[k], ValueError(
                    f"scale_factor {meta_k.scale_factor!r} != detector "
                    f"scale {det.metadata.scale_factor!r}; wire='raw' "
                    "conditions with one scale — use wire='conditioned' "
                    "for heterogeneous file sets"
                ))
                ok.append(False)
            else:
                ok.append(True)
        t0 = time.perf_counter()
        degraded = False
        recovered = False
        results = None
        try:
            if fault_plan is not None:
                # the slab is one transfer and one program: a planned
                # transfer/detect fault against ANY of its files fails
                # the slab (and the ladder then isolates the culprit).
                # The culprit's slab-level firing IS one of its attempts
                # — count it, so the batched route's retry budget and
                # terminal disposition match the unbatched route and the
                # chaos oracle even at n_times == max_attempts
                for k in range(slab.n_valid):
                    if ok[k]:
                        try:
                            fault_plan.on_transfer(slab.paths[k])
                            fault_plan.on_detect(slab.paths[k])
                        except Exception:
                            rz.attempt(slab.paths[k])
                            raise
            rung = ladder.current(key)
            if inflight is not None and rung != ("batched", batch):
                # the bucket downshifted between this slab's dispatch and
                # its resolve (an earlier in-flight slab OOMed): the
                # pre-dispatched program ran at a rung now known to
                # exhaust — discard the handle (abandoning the in-flight
                # work) and run at the sticky rung instead
                inflight = None
            shape = (int(slab.stack.shape[1]), slab.bucket_ns)
            while True:   # the elastic ladder: downshift on resource
                try:
                    results = run_rung(slab, rung, bdet, ok,
                                       inflight=inflight)
                    break
                except Exception as exc:  # noqa: BLE001
                    # never reuse a handle past a failure: a timed-out
                    # resolve was abandoned mid-fetch on the watchdog
                    # worker, and a failed one is spent
                    inflight = None
                    fclass = faults.classify_failure(exc)
                    if fclass == "fatal":
                        raise
                    if fclass == "resource":
                        nxt = ladder.downshift(key, rung, exc, shape)
                        if nxt is not None:
                            rung = nxt
                            recovered = True
                            continue
                    raise   # non-resource / exhausted: degrade per-file
        except Exception as exc:  # noqa: BLE001 — degradation ladder
            if faults.classify_failure(exc) == "fatal":
                raise
            # the PR 4 rung: a whole-slab device failure retries the
            # slab's files through the unbatched one-program route
            # before failing ANY of them — one poisoned file costs one
            # file, not a slab
            faults.count("degradations")
            log.warning(
                "batched slab of %d files failed (%s: %s); degrading to "
                "the unbatched per-file route", slab.n_valid,
                type(exc).__name__, exc,
            )
            degraded = True
        wall = time.perf_counter() - t0
        _h_slab_wall.observe(wall)
        if use_costs and not degraded and results is not None:
            # live utilization: this slab's measured wall against its
            # rung's cost-card roofline prediction (no card priced for
            # the rung -> no-op; never touches picks)
            tcosts.note_slab_resolved(
                tcosts.bucket_label(key), faults.rung_label(rung),
                tcosts._program_engine(bdet), wall,
            )
        shape = (int(slab.stack.shape[1]), slab.bucket_ns)
        for k in range(slab.n_valid):
            if not ok[k]:
                continue  # its slot computed with the wrong scale: discard
            path = slab.paths[k]
            use_fallback = degraded or results[k] is None
            # the fallback honors the bucket's sticky ladder placement:
            # never below the per-file rung, never above a rung the
            # campaign already saw OOM
            pf_rung = max(("file", 1), ladder.current(key),
                          key=faults.rung_rank)
            file_recovered = recovered
            while True:
                rz.attempt(path)
                try:
                    if use_fallback:
                        if fault_plan is not None and degraded:
                            fault_plan.on_transfer(path)
                            fault_plan.on_detect(path)
                        picks, thresholds, stats = per_file_fallback(
                            slab, k, progs[key], rung=pf_rung
                        )
                        exec_rung = pf_rung
                    else:
                        entry = results[k]
                        picks, thresholds = entry[0], entry[1]
                        stats = (entry[2] if with_health
                                 and len(entry) > 2 else {})
                        exec_rung = rung
                    rz.check_health(path, stats,  # -> quarantine on breach
                                    rung=faults.rung_label(exec_rung))
                    picks = trim_picks(picks, slab.n_real[k])
                    if fault_plan is not None:
                        fault_plan.detect_succeeded()
                    _file_record(
                        outdir, path, picks, thresholds,
                        round(wall / max(slab.n_valid, 1), 3), records,
                        attempts=rz.state.n_attempts(path),
                        health=dict(stats or {}),
                        family=bdet.family,
                        rung=faults.rung_label(exec_rung),
                    )
                    if use_quality:
                        _observe_quality(QUALITY_TENANT, det, path, picks,
                                         thresholds, stats, slab.n_real[k])
                    if file_recovered:
                        rz.tally("oom_recoveries")
                except Exception as exc:  # noqa: BLE001 — per-file isolation
                    if (use_fallback
                            and faults.classify_failure(exc) == "resource"):
                        # resource exhaustion in the fallback too: keep
                        # descending the ladder (a route change, not a
                        # retry — refund the attempt)
                        nxt = ladder.downshift(key, pf_rung, exc, shape)
                        if nxt is not None:
                            rz.state.unattempt(path)
                            pf_rung = nxt
                            file_recovered = True
                            continue
                    if rz.dispose(path, exc) == "retry":
                        # rerunning the already-fetched batch entry would
                        # fail identically — retries go through the
                        # per-file route
                        use_fallback = True
                        continue
                break

    pipe = PipelinedDispatch(dispatch_depth)

    def try_dispatch(slab):
        """The pipeline's dispatch phase: launch the slab's K0 program
        asynchronously when the bucket rides its healthy top rung.
        Returns None (-> the synchronous path) for downshifted or
        skipped buckets, batch=1 campaigns (their top rung is the
        per-file route), and dispatch-time failures — which the sync
        path then re-raises at this slab's own turn, keeping
        attribution identical to the unpipelined campaign."""
        if not pipe.enabled or batch < 2:
            return None
        try:
            # everything here can fail (detector build, preflight,
            # tracing): any failure routes the slab to the synchronous
            # path, where handle_slab re-raises it under the same
            # per-file guards as the unpipelined campaign
            bdet = detector_for(slab)
            key = _bucket_key(slab)
            if (key in skip_buckets
                    or ladder.current(key) != ("batched", batch)):
                return None
            return bdet.dispatch_batch(
                slab.stack, n_real=slab.n_real, n_valid=slab.n_valid,
                with_health=with_health, health_clip=clip,
            )
        except CampaignAborted:
            raise
        except Exception:  # noqa: BLE001 — surfaces on the sync path
            return None

    def finalize(slab, inflight) -> None:
        try:
            with telemetry.span("slab", index0=slab.index0,
                                n_files=slab.n_valid,
                                bucket_ns=slab.bucket_ns,
                                pipelined=inflight is not None):
                handle_slab(slab, inflight)
        except CampaignAborted:
            raise
        except Exception as exc:  # noqa: BLE001 — slab-level guard
            # a whole-slab failure the ladder could not absorb
            # (detector build, fatal-class program error) fails
            # each of its files, preserving max_failures — except
            # files already dispositioned this run (a
            # scale-mismatched file was failed inside handle_slab
            # before the slab program ran; double-counting it
            # would fire max_failures one file early and write a
            # duplicate manifest record)
            if faults.classify_failure(exc) == "fatal":
                raise
            dispositioned = {r.path for r in records}
            for path in slab.paths:
                if path not in dispositioned:
                    fail(path, exc)

    def drain_pipe() -> None:
        for queued_slab, queued_infl in pipe.drain():
            finalize(queued_slab, queued_infl)

    # the transfer pipeline must keep at least `depth` slabs moving or
    # the dispatch pipeline starves waiting on H2D (io.stream documents
    # the combined residency bound: in_flight + depth + 1 slabs)
    stream_in_flight = max(in_flight, pipe.depth) if pipe.enabled else in_flight

    with telemetry.campaign_trace(outdir, trace, kind="batched",
                                  n_files=len(files), batch=batch,
                                  family=family):
        i = 0
        while i < len(pending):
            slabs = stream_batched_slabs(
                pending[i:], selected_channels, pend_metas[i:], batch=batch,
                bucket=bucket, interrogator=interrogator, prefetch=prefetch,
                engine=engine, wire=wire, in_flight=stream_in_flight,
                read_deadline_s=read_deadline_s, fault_plan=fault_plan,
            )
            try:
                for slab in slabs:
                    infl = try_dispatch(slab)
                    if infl is None:
                        # ineligible slab: flush the queue (FIFO — manifest
                        # order is file order) and run it synchronously
                        drain_pipe()
                        finalize(slab, None)
                    else:
                        for tok in pipe.submit(slab, infl):
                            finalize(*tok)
                # end of segment: resolving the queued tail is the
                # segment's one remaining sync — no per-slab
                # block_until_ready anywhere
                drain_pipe()
            except SlabReadError as exc:
                # the assembler attributes the culprit's index; classify
                # its cause — transient earns a retry AT the culprit,
                # timeout / corrupt / data disposition it and resume past.
                # Queued in-flight slabs hold earlier (healthy) files:
                # finalize them first so their records precede the
                # culprit's
                drain_pipe()
                path = pending[i + exc.index]
                rz.attempt(path)
                if rz.dispose(path, exc.cause) == "retry":
                    i = i + exc.index
                else:
                    i = i + exc.index + 1
                continue
            i = len(pending)
        rz.flush_tallies()
        if use_costs and tcosts.REGISTRY.cards():
            try:
                # the observatory's durable artifact, next to the
                # manifest (scripts/trace_report.py --costs merges it
                # with the span walls)
                tcosts.export_json(os.path.join(outdir, "cost_cards.json"))
            except OSError:
                pass   # the campaign outcome wins
        if use_quality:
            _flush_quality(outdir, [QUALITY_TENANT])
    return CampaignResult(outdir=outdir, records=records)


# per-(template, file) pack capacity for the sharded campaign's pick
# transfer; counts above it trigger the exact full-grid fallback
_PICK_PACK_CAP = 1 << 18


def _adaptive_sharded_steps(factory, design, mesh, pick_k0: int = 64,
                            max_peaks: int = 256, **kw):
    """Jitted ``(K0 pack, full-capacity topk)`` step pair: the adaptive-K
    policy of ``ops.peaks.picks_with_escalation`` expressed across SPMD
    programs (``escalation_method`` semantics — the sort-free pack kernel
    wherever a bigger-K rerun can correct truncation, top-k where it is
    final). The full-capacity program compiles lazily, only if a batch
    actually saturates."""
    import jax

    # daslint: allow[R2] one-shot factory: the campaign builds its step pair once per run
    step_k0 = jax.jit(factory(design, mesh, outputs="picks",
                              max_peaks=pick_k0, pick_method="pack", **kw))
    full: dict = {}

    def step_full(stack):
        if "fn" not in full:
            # daslint: allow[R2] lazy singleton: built at most once, kept in `full`
            full["fn"] = jax.jit(factory(design, mesh, outputs="picks",
                                         max_peaks=max_peaks,
                                         pick_method="topk", **kw))
        return full["fn"](stack)

    return step_k0, step_full


def _compact_batch_picks(positions, selected, n_samples: int, capacity: int):
    """Sharded-step ``SparsePicks`` ``[nT, B, C, K]`` -> per-(template,
    file) packed ``(chan [nT, B, cap], time [nT, B, cap], count [nT, B])``
    ON the mesh (``ops.peaks.compact_picks_rowmajor``; GSPMD inserts the
    gathers). Applies the same time-padding mask
    (``positions < n_samples``) as ``eval.sharded_picks_to_dict`` so the
    packed picks equal the full-transfer path's output exactly, in the
    same row-major order. Module-level jit: one trace per batch shape
    across the whole campaign (no-retrace discipline, docs/DESIGN.md)."""
    import functools

    import jax

    global _compact_batch_picks_jit
    if _compact_batch_picks_jit is None:
        from ..ops import peaks as peak_ops

        # daslint: allow[R2] module-level singleton: guarded by _compact_batch_picks_jit
        @functools.partial(jax.jit, static_argnames=("ns_", "cap"))
        def _run(pos, sel, ns_, cap):
            nT, B, C, K = pos.shape
            sel = sel & (pos < ns_)
            rows, times, cnt = peak_ops.compact_picks_rowmajor(
                pos.reshape(nT * B, C, K), sel.reshape(nT * B, C, K), cap
            )
            return (rows.reshape(nT, B, cap), times.reshape(nT, B, cap),
                    cnt.reshape(nT, B))

        _compact_batch_picks_jit = _run
    return _compact_batch_picks_jit(positions, selected, n_samples, capacity)


_compact_batch_picks_jit = None


def _probe_healthy(pairs, interrogator, fail, expect_shape=None, rz=None):
    """Probe (path, metadata) pairs; returns ``(healthy [(path, spec)],
    spec0)``. ``expect_shape=(nx, ns)`` routes shape mismatches to
    ``fail`` — in a multi-host campaign a wrong-shape file would
    otherwise raise on only the host that reads it while its peers sit
    in the step's collectives (DCN-timeout deadlock, not a per-file
    failure). ``rz`` (a :class:`_Resilience`) adds the classified
    contract at probe granularity: transient probe failures retry with
    backoff, the rest disposition per class."""
    from ..io.stream import _probe

    healthy, spec0 = [], None
    for path, meta_j in pairs:
        while True:
            if rz is not None:
                rz.attempt(path)
            try:
                spec = _probe(path, interrogator, meta_j)
                shape = (spec.meta.nx, spec.meta.ns)
                want = expect_shape or (
                    (spec0.meta.nx, spec0.meta.ns) if spec0 is not None
                    else shape
                )
                if shape != want:
                    raise ValueError(
                        f"file shape {shape} != campaign shape {want} "
                        "(one step serves one shape; run mismatched files "
                        "in their own campaign)"
                    )
                if spec0 is None:
                    spec0 = spec
                healthy.append((path, spec))
            except Exception as exc:  # noqa: BLE001 — per-file isolation
                if rz is not None:
                    if rz.dispose(path, exc) == "retry":
                        continue
                else:
                    fail(path, exc)
            break
    return healthy, spec0


def _observe_quality(tenant, det, path, picks, thresholds, stats,
                     n_time_samples) -> None:
    """Feed the science-quality observatory one done file
    (``telemetry.quality``, ISSUE 15): the record is derived entirely
    from the artifacts already in hand — pick counts, the fetched
    thresholds (whose base recovers the envelope peak), and the fused
    health stats. Shared by the batched/per-file campaigns and the
    service scheduler (one derivation, every route). Decorative by
    contract: a telemetry failure must never cost the file record."""
    try:
        design = getattr(det, "design", None)
        fs = float(getattr(design, "fs", 0.0) or 0.0) or float(
            getattr(getattr(det, "metadata", None), "fs", 0.0) or 0.0
        )
        tquality.OBSERVATORY.observe(tenant, tquality.file_quality(
            path=path, picks=picks, thresholds=thresholds, stats=stats,
            duration_s=(float(n_time_samples) / fs if fs else None),
            thr_factors=tquality.threshold_factor_map(design),
            thr_scope=str(getattr(det, "threshold_scope", "global")),
        ))
    except Exception:  # noqa: BLE001 — observability never costs a record
        log.debug("quality observe failed for %s", path, exc_info=True)


def _flush_quality(outdir: str, tenants) -> None:
    """End-of-run quality surfaces: one manifest ``quality`` event
    (summary rows — the ledger analog of the ``counters`` event) and
    the durable ``quality.json`` next to the manifest (the same records
    ``GET /quality`` and ``trace_report --quality`` render)."""
    try:
        snap = tquality.OBSERVATORY.snapshot(tenants=tenants)
        if not snap["tenants"]:
            return
        _append_event(outdir, {"event": "quality",
                               "tenants": snap["tenants"],
                               "drifting": snap["drifting"]})
        tquality.export_json(os.path.join(outdir, "quality.json"),
                             tenants=tenants)
    except OSError:
        pass   # the campaign outcome wins
    except Exception:  # noqa: BLE001 — decorative surfaces only
        log.debug("quality flush failed for %s", outdir, exc_info=True)


def _file_record(outdir, path, picks, thresholds, wall_s, records,
                 write: bool = True, attempts: int = 1,
                 health=None, family: str = "", rung: str = "") -> FileRecord:
    """One completed file's bookkeeping — artifact + manifest + record —
    shared by every campaign flavor (``write=False``: multi-host
    non-writer processes compute identical records, write nothing).
    ``family``/``rung`` stamp the detector family and the route rung
    that actually executed (the per-family audit trail)."""
    if write:
        picks_file = _save_picks(outdir, path, picks, thresholds)
    else:
        picks_file = _picks_path(outdir, path)
    rec = FileRecord(
        path=path, status="done",
        n_picks={n: int(p.shape[1]) for n, p in picks.items()},
        wall_s=wall_s, picks_file=picks_file,
        attempts=max(int(attempts), 1), health=dict(health or {}),
        family=family, rung=rung,
    )
    # manifest BEFORE the in-memory record: the batched route retries
    # this call, and a transient manifest-append failure must not leave
    # a phantom record that a successful retry would duplicate
    if write:
        _append_manifest(outdir, rec)
    records.append(rec)
    tprobes.note_file_ok()   # healthy file: readiness quarantine streak resets
    return rec


def run_campaign_sharded(
    files: Sequence[str],
    selected_channels,
    outdir: str,
    mesh,
    metadata=None,
    batch: int | None = None,
    resume: bool = True,
    max_failures: int | None = None,
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "h5py",
    relative_threshold: float = 0.5,
    hf_factor: float | None = None,
    fused_bandpass: bool = True,
    wire: str = "conditioned",
    retry=None,
    elastic: bool = True,
) -> CampaignResult:
    """Multi-chip campaign: file batches land pre-sharded on the mesh and
    the whole batch detects in ONE program (data-parallel over files,
    channel-parallel within each — ``parallel.pipeline``), with the same
    manifest/resume/picks-artifact contract as :func:`run_campaign`.

    ``wire="raw"`` is the narrow-wire mode: stored-dtype batches land
    pre-sharded (2× fewer H2D bytes for int16 sources) and the SPMD step
    conditions on the mesh (``make_sharded_mf_step(wire="raw")``) using
    the probed ``scale_factor``; picks are bit-identical.

    Fault isolation is at PROBE granularity: every pending file is probed
    up front (cheap attribute read for HDF5; full parse for TDMS) and
    unprobeable files are recorded failed before any batch forms — a
    read error after a clean probe (rare: truncated-after-header file)
    aborts the run, since a half-read batch cannot be attributed cleanly.
    Probed metadata feeds the stream, so no file is probed twice.
    ``batch`` defaults to the mesh's file-axis size; ``hf_factor`` is the
    first template's threshold factor, threaded to both the picking step
    and the recorded artifact thresholds (single source). ``retry``
    (``faults.RetryPolicy`` / None / False) applies the classified
    transient-retry contract at the probe boundary — the sharded step
    itself runs lockstep collectives, so per-file mid-step retry is
    structurally impossible here (docs/ROBUSTNESS.md).

    ``elastic=True`` adds ELASTIC SHARD RECOVERY: when a step fails
    non-fatally mid-campaign (a chip lost or wedged — XLA surfaces that
    as a runtime error on the next dispatch), the campaign probes the
    mesh's devices (:func:`_probe_healthy_devices`), rebuilds the mesh
    on the largest surviving device count that still divides the channel
    axis, recompiles the step pair there, and re-runs ONLY the in-flight
    batch — settled files are never re-processed. Each rebuild lands in
    the manifest as a ``mesh_downshift`` event (docs/ROBUSTNESS.md
    "Resource ladder").
    """
    import types

    import jax
    import jax.numpy as jnp

    from ..eval import sharded_picks_to_dict
    from ..io.stream import _probe, stream_file_batches
    from ..ops.peaks import compacted_to_host
    from ..parallel.pipeline import make_sharded_mf_step

    os.makedirs(outdir, exist_ok=True)
    fsck.startup_check(outdir, label="campaign")
    metas = _normalize_metas(metadata, list(files))
    records: List[FileRecord] = []
    pending, pend_idx = _split_resume(list(files), outdir, resume, records)
    pend_metas = [metas[j] for j in pend_idx]
    rz = _Resilience(outdir, records, max_failures, retry, health=False)
    rz.family = "mf"   # the sharded SPMD step is the MF family's
    fail = rz.fail

    healthy_specs, spec0 = _probe_healthy(
        zip(pending, pend_metas), interrogator, fail, rz=rz
    )
    if wire == "raw":
        # the raw wire conditions on the mesh with ONE scale (spec0's); a
        # file probed with a different factor cannot ride this step — fail
        # it at probe granularity, like any unprobeable file
        for p, sp in healthy_specs:
            if sp.meta.scale_factor != spec0.meta.scale_factor:
                fail(p, ValueError(
                    f"scale_factor {sp.meta.scale_factor!r} != campaign "
                    f"scale {spec0.meta.scale_factor!r}; wire='raw' "
                    "conditions with one scale — use wire='conditioned' "
                    "for heterogeneous file sets"
                ))
        healthy_specs = [(p, sp) for p, sp in healthy_specs
                         if sp.meta.scale_factor == spec0.meta.scale_factor]
    healthy = [p for p, _ in healthy_specs]
    healthy_metas = [sp.meta for _, sp in healthy_specs]
    if not healthy:
        return CampaignResult(outdir=outdir, records=records)

    from ..config import ChannelSelection
    from ..models.matched_filter import design_matched_filter

    sel = ChannelSelection.from_list(selected_channels)
    design = design_matched_filter(
        (sel.n_channels(spec0.meta.nx), spec0.meta.ns), selected_channels,
        spec0.meta,
    )
    if batch is None:
        batch = max(int(mesh.shape.get("file", 1)), 1)
    wire_kw = (
        {"wire": "raw", "scale_factor": spec0.meta.scale_factor}
        if wire == "raw" else {}
    )
    step_k0, step_full = _adaptive_sharded_steps(
        make_sharded_mf_step, design, mesh,
        relative_threshold=relative_threshold, hf_factor=hf_factor,
        fused_bandpass=fused_bandpass, **wire_kw,
    )

    # per-template factors — the SAME resolution the step factory ran
    # (MatchedFilterDesign.resolve_threshold_policy)
    fac_vec, _ = design.resolve_threshold_policy(hf_factor)
    factors = {name: float(f)
               for name, f in zip(design.template_names, fac_vec)}

    from ..parallel import dispatch as dispatch_mod

    def process_batch(stack, blocks, step_k0, step_full, consumed):
        t0 = time.perf_counter()
        # ASYNC dispatch (no block_until_ready wall): the one-scalar
        # saturation fetch below is the escalation decision's only sync,
        # and the packed pick fetch further down is the batch's data
        # sync — dropping the per-batch block_until_ready lets the next
        # batch's H2D (the stream's transfer thread) overlap this
        # batch's compute (ISSUE 6; docs/PERF.md "Pipelined dispatch")
        sp_picks, thres = dispatch_mod.launch(step_k0, stack)
        if int(dispatch_mod.fetch(jnp.sum(sp_picks.saturated))):
            # a row saturated at K0: rerun at full capacity (same
            # escalation contract as ops.peaks.picks_with_escalation)
            sp_picks, thres = dispatch_mod.launch(step_full, stack)
        # pack picks on the mesh before they cross to the host (same
        # boundary-crossing reduction as the single-chip detector's
        # device-side compaction, models/matched_filter.py): only
        # O(actual picks) ints transfer instead of the [nT, B, C, K]
        # slot grid. Overflow (count > cap) falls back to the exact
        # full-grid transfer — never silent truncation. The pack
        # dispatches BEFORE the thres fetch: fetching the scalar first
        # would serialize the pack behind a host round trip — the exact
        # gap this route exists to remove.
        nT, B, Cr, K = sp_picks.positions.shape
        cap = min(Cr * K, _PICK_PACK_CAP)
        rows_d, times_d, cnt_d = _compact_batch_picks(
            sp_picks.positions, sp_picks.selected, spec0.meta.ns, cap
        )
        thres_np = dispatch_mod.fetch(thres)
        host_picks = None
        faults.count("syncs")   # compacted_to_host's np.asarray fetch
        packed = compacted_to_host(rows_d, times_d, cnt_d, cap)
        if packed is not None:
            rows_np, times_np, cnt = packed
        else:
            # one device->host conversion per batch, not per file
            host_picks = types.SimpleNamespace(
                positions=np.asarray(sp_picks.positions),
                selected=np.asarray(sp_picks.selected),
            )
        # the packed fetch above was the batch's data sync: the wall now
        # covers dispatch+compute+fetch, like the old block_until_ready
        # placement, without having serialized the next batch behind it
        wall = time.perf_counter() - t0
        # an elastic re-run replays the whole in-flight batch: files the
        # aborted first pass already recorded must not gain a duplicate
        # done record (and artifact) here
        recorded = {r.path for r in records}
        for k, _block in enumerate(blocks):
            path = healthy[consumed + k]
            if path in recorded:
                continue
            if host_picks is None:
                picks = {
                    name: np.asarray([rows_np[i, k, : cnt[i, k]],
                                      times_np[i, k, : cnt[i, k]]])
                    for i, name in enumerate(design.template_names)
                }
            else:
                picks = sharded_picks_to_dict(
                    host_picks, design.template_names, file_index=k,
                    n_samples=spec0.meta.ns,
                )
            # thres base: [B] under the global scope, [nT, B] under a
            # bank's decoupled per_template scope (parallel.pipeline)
            base = np.asarray(thres_np)
            thresholds = {
                name: float(base[i, k] if base.ndim == 2 else base[k])
                * factors[name]
                for i, name in enumerate(design.template_names)
            }
            _file_record(outdir, path, picks, thresholds,
                         round(wall / max(len(blocks), 1), 3), records,
                         family="mf", rung="sharded")

    consumed = 0  # batches cover `healthy` strictly in order
    rebuilds = 0
    while consumed < len(healthy):
        # one stream per mesh incarnation: after an elastic rebuild the
        # remaining (unsettled) files re-stream placed for the NEW mesh
        stream = stream_file_batches(
            healthy[consumed:], selected_channels, healthy_metas[consumed:],
            batch=batch, mesh=mesh, interrogator=interrogator,
            prefetch=prefetch, engine=engine, tail="pad", wire=wire,
        )
        rebuilt = False
        for stack, blocks in stream:
            try:
                process_batch(stack, blocks, step_k0, step_full, consumed)
            except Exception as exc:  # noqa: BLE001 — elastic recovery
                if not elastic or faults.classify_failure(exc) == "fatal":
                    raise
                if rebuilds >= _MAX_MESH_REBUILDS:
                    log.error("elastic recovery exhausted after %d mesh "
                              "rebuilds", rebuilds)
                    raise
                rebuilds += 1
                mesh = _rebuild_mesh_after_device_loss(
                    mesh, design.trace_shape[0], exc, outdir
                )
                step_k0, step_full = _adaptive_sharded_steps(
                    make_sharded_mf_step, design, mesh,
                    relative_threshold=relative_threshold,
                    hf_factor=hf_factor, fused_bandpass=fused_bandpass,
                    **wire_kw,
                )
                rebuilt = True
                del stream  # only the in-flight batch re-runs
                break
            consumed += len(blocks)
        if not rebuilt:
            break
    rz.flush_tallies()
    return CampaignResult(outdir=outdir, records=records)


#: elastic shard recovery gives up after this many mesh rebuilds in one
#: campaign — a failure that survives repeated shrinking is not a lost
#: chip, and re-probing forever would mask it
_MAX_MESH_REBUILDS = 4


#: per-device wall bound on the survivor probe: a WEDGED chip often
#: neither fails nor answers — without a deadline the probe itself would
#: stall the recovery it exists to enable (the dispatch-watchdog lesson)
_DEVICE_PROBE_DEADLINE_S = 30.0


def _probe_healthy_devices(devices) -> list:
    """The devices in ``devices`` that still answer a trivial transfer +
    compute round trip within :data:`_DEVICE_PROBE_DEADLINE_S` — the
    elastic campaign's survivor probe. A lost chip raises and a wedged
    one times out here instead of inside the next lockstep step (the
    probe worker is abandoned, ``faults.call_with_deadline``).
    Module-level and deliberately simple so tests (and operators) can
    monkeypatch the survivor policy."""
    import jax

    def probe(d):
        x = jax.device_put(np.ones((8,), np.float32), d)
        return float(np.asarray(x.sum())) == 8.0

    ok = []
    for d in devices:
        try:
            if faults.call_with_deadline(
                lambda d=d: probe(d), _DEVICE_PROBE_DEADLINE_S, str(d)
            ):
                ok.append(d)
        except Exception:  # noqa: BLE001 — dead/wedged chip: excluded
            continue
    return ok


def _rebuild_mesh_after_device_loss(mesh, n_channels: int, exc, outdir):
    """Rebuild the campaign mesh on the surviving devices after a step
    failure: probe the old mesh's devices, keep the largest count that
    divides the channel axis (the sharded step's layout constraint), and
    ledger the move as a ``mesh_downshift`` manifest event. Raises the
    original ``exc`` when no survivor configuration exists."""
    from ..parallel.mesh import make_mesh

    old = list(np.asarray(mesh.devices).ravel())
    ok = _probe_healthy_devices(old)
    if len(ok) == len(old):
        # every device answers: the failure was NOT device loss (a
        # deterministic program/data error would fail identically on a
        # rebuilt same-size mesh — at the cost of recompiling both
        # steps, _MAX_MESH_REBUILDS times). Surface it instead.
        log.error("all %d mesh devices probe healthy; step failure is "
                  "not device loss — re-raising", len(old))
        raise exc
    n = 0
    for cand in range(len(ok), 0, -1):
        if n_channels % cand == 0:
            n = cand
            break
    if n < 1:
        log.error("no surviving device configuration divides the channel "
                  "axis (%d survivors of %d)", len(ok), len(old))
        raise exc
    new_mesh = make_mesh(shape=(1, n), axis_names=tuple(mesh.axis_names),
                         devices=ok[:n])
    _append_event(outdir, {
        "event": "mesh_downshift", "from_devices": len(old),
        "to_devices": n, "error": f"{type(exc).__name__}: {exc}",
    })
    log.warning("elastic recovery: mesh rebuilt on %d/%d devices after "
                "%s: %s", n, len(old), type(exc).__name__, exc)
    return new_mesh


def run_campaign_multiprocess(
    files: Sequence[str],
    selected_channels,
    outdir: str,
    metadata=None,
    resume: bool = True,
    max_failures: int | None = None,
    interrogator: str = "optasense",
    relative_threshold: float = 0.5,
    hf_factor: float | None = None,
    fused_bandpass: bool = True,
    wire: str = "conditioned",
) -> CampaignResult:
    """Multi-HOST campaign: one SPMD program per batch across all
    processes of the JAX runtime.

    ``wire="raw"`` is rejected here for now: the shard callback's zero
    fill for failed reads needs the stored dtype known identically on
    every process *before* any process has read a byte, which the
    metadata-only probe does not guarantee for irregular files. The
    single-host campaigns carry the narrow wire.

    Every process runs this same call with the same arguments after
    ``parallel.distributed.initialize_from_env()`` formed the runtime
    (single-process degenerates to a local mesh). The file list and
    ``outdir`` must be on storage every process can read — the probe
    runs everywhere so the healthy set is identical — and process 0
    alone writes the manifest/picks artifacts (every process returns the
    same ``CampaignResult``).

    Data placement is the DCN-friendly ``distributed.global_mesh()``
    layout: the file axis is process-major and
    ``jax.make_array_from_callback`` materializes only each process's
    addressable shards, so EVERY HOST READS JUST ITS OWN FILES and raw
    strain never crosses DCN — only the packed picks (kB) are
    allgathered for writing. The reference's only multi-machine story is
    a human running per-file scripts on several nodes (SURVEY.md §5.8).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from ..config import ChannelSelection
    from ..eval import sharded_picks_to_dict
    from ..io.stream import _probe, _read_host
    from ..models.matched_filter import design_matched_filter
    from ..parallel import distributed
    from ..parallel.pipeline import input_sharding, make_sharded_mf_step

    if wire != "conditioned":
        raise ValueError(
            "run_campaign_multiprocess supports wire='conditioned' only "
            "(raw dtype must be known identically on every process)"
        )
    is_writer = jax.process_index() == 0
    mesh = distributed.global_mesh()
    batch = int(mesh.shape["file"])

    os.makedirs(outdir, exist_ok=True)
    if is_writer:
        # only process 0 repairs (truncates a torn tail / sweeps tmps);
        # non-writer readers tolerate the torn state they might glimpse
        fsck.startup_check(outdir, label="campaign")
    metas = _normalize_metas(metadata, list(files))
    records: List[FileRecord] = []
    pending, pend_idx = _split_resume(list(files), outdir, resume, records)
    pend_metas = [metas[j] for j in pend_idx]
    fail = _failure_recorder(outdir, records, max_failures, write=is_writer,
                             family="mf")

    healthy_specs, spec0 = _probe_healthy(
        zip(pending, pend_metas), interrogator, fail
    )
    if not healthy_specs:
        return CampaignResult(outdir=outdir, records=records)

    sel = ChannelSelection.from_list(selected_channels)
    C = sel.n_channels(spec0.meta.nx)
    ns = spec0.meta.ns
    design = design_matched_filter((C, ns), selected_channels, spec0.meta)
    if design.resolve_threshold_policy(hf_factor)[1] == "per_template":
        # the multihost threshold allgather assumes the coupled
        # per-file scalar base; wiring the decoupled [nT, B] base
        # across processes is untested on this runtime — fail fast
        # instead of silently coupling a bank that promises decoupled
        # thresholds (single-chip/batched/sharded routes honor it)
        raise ValueError(
            "run_campaign_multiprocess does not support "
            "threshold_scope='per_template' banks yet; use the "
            "single-chip, batched or single-host sharded campaign, or "
            "a global-scope bank"
        )
    step_k0, step_full = _adaptive_sharded_steps(
        make_sharded_mf_step, design, mesh,
        relative_threshold=relative_threshold, hf_factor=hf_factor,
        fused_bandpass=fused_bandpass,
    )
    sharding = input_sharding(mesh)
    # per-template factors — the SAME resolution the step factory ran
    # (MatchedFilterDesign.resolve_threshold_policy)
    fac_vec, _ = design.resolve_threshold_policy(hf_factor)
    factors = {name: float(f)
               for name, f in zip(design.template_names, fac_vec)}

    for s in range(0, len(healthy_specs), batch):
        group = healthy_specs[s : s + batch]
        n_real = len(group)
        padded = group + [group[-1]] * (batch - n_real)

        # Pre-read this process's OWN files BEFORE entering the collective
        # region (ADVICE r4): a read failure inside the
        # make_array_from_callback shard callback (truncated bulk data, a
        # transient FS error past the metadata-only probe) would raise on
        # one process while its peers sit in the SPMD step's collectives
        # until DCN timeout. Reading first and allgathering a per-file ok
        # mask keeps every process in lockstep: a failed file becomes a
        # zero shard inside the step (its outputs are discarded) and a
        # deterministic per-file failure record on every process.
        t0 = time.perf_counter()
        cache: dict = {}
        read_errs: dict = {}
        idx_map = sharding.addressable_devices_indices_map((batch, C, ns))
        my_fis = sorted({
            fi
            for sl in idx_map.values()
            for fi in range(
                sl[0].start or 0,
                batch if sl[0].stop is None else sl[0].stop,
            )
        })
        ok_local = np.ones(batch, dtype=np.int32)
        for fi in my_fis:
            spec = padded[fi][1]
            try:
                cache[fi] = _read_host(spec, sel)          # [C, ns] float32
            except Exception as exc:  # noqa: BLE001 — per-file isolation
                ok_local[fi] = 0
                read_errs[fi] = f"{type(exc).__name__}: {exc}"
        ok = (
            np.asarray(multihost_utils.process_allgather(ok_local, tiled=True))
            .reshape(-1, batch).min(axis=0).astype(bool)
        )

        def _shard(idx, padded=padded, cache=cache):
            fsl, csl, tsl = idx
            rows = []
            for fi in range(fsl.start or 0, fsl.stop if fsl.stop is not None
                            else (fsl.start or 0) + 1):
                buf = cache.get(fi)
                if buf is None:
                    # failed read: zeros keep the SPMD program in lockstep;
                    # this slot's outputs are never recorded. Allocate at
                    # the SLICE shape — a full [C, ns] zeros temp would be
                    # ~1 GB per shard at canonical shape
                    rows.append(np.zeros(
                        (len(range(C)[csl]), len(range(ns)[tsl])), np.float32
                    ))
                else:
                    rows.append(buf[csl, tsl])
            return np.stack(rows)

        x = jax.make_array_from_callback((batch, C, ns), sharding, _shard)
        from ..parallel import dispatch as dispatch_mod

        # async dispatch: the replicated saturation scalar fetched below
        # is the escalation decision's only sync (same decision on every
        # process, no extra collective round); the pick allgathers are
        # the batch's data sync — no per-batch block_until_ready wall
        sp_picks, thres = dispatch_mod.launch(step_k0, x)
        if int(dispatch_mod.fetch(jnp.sum(sp_picks.saturated))):
            sp_picks, thres = dispatch_mod.launch(step_full, x)
        wall = time.perf_counter() - t0

        # the device-side pack dispatches BEFORE the thres allgather:
        # gathering the scalar first would serialize the pack behind a
        # full collective round trip on every process
        nT, _, Cr, K = sp_picks.positions.shape
        cap = min(Cr * K, _PICK_PACK_CAP)
        rows_d, times_d, cnt_d = _compact_batch_picks(
            sp_picks.positions, sp_picks.selected, ns, cap
        )
        faults.count("syncs")   # the allgather is this batch's sync point
        thres_np = np.asarray(
            multihost_utils.process_allgather(thres, tiled=True)
        ).reshape(batch)
        # counts first (nT*B ints), then DEVICE-slice to the pow2 max
        # before the cross-host gather — only actual picks ride DCN, the
        # same trick compacted_to_host plays for the device->host hop
        cnt = np.asarray(
            multihost_utils.process_allgather(cnt_d, tiled=True)
        ).reshape(nT, batch)
        kmax = int(cnt.max(initial=0))
        host_picks = None
        if kmax <= cap:
            kpad = min(cap, 1 << max(kmax - 1, 0).bit_length())
            rows_np = np.asarray(multihost_utils.process_allgather(
                rows_d[..., :kpad], tiled=True)
            ).reshape(nT, batch, kpad).astype(np.int64)
            times_np = np.asarray(multihost_utils.process_allgather(
                times_d[..., :kpad], tiled=True)
            ).reshape(nT, batch, kpad).astype(np.int64)
        else:  # pack overflow: exact full-grid fallback (allgathered)
            import types

            host_picks = types.SimpleNamespace(
                positions=np.asarray(multihost_utils.process_allgather(
                    sp_picks.positions, tiled=True)),
                selected=np.asarray(multihost_utils.process_allgather(
                    sp_picks.selected, tiled=True)),
            )

        for k, (path, _spec) in enumerate(group):
            if not ok[k]:
                # same mask on every process -> identical record streams
                # and a synchronized max_failures abort (the error TEXT is
                # only exact on the owning process; peers record a pointer)
                fail(path, RuntimeError(
                    read_errs.get(k, "read failed (see owning process log)")
                ))
                continue
            if host_picks is None:
                picks = {
                    name: np.asarray([rows_np[i, k, : cnt[i, k]],
                                      times_np[i, k, : cnt[i, k]]])
                    for i, name in enumerate(design.template_names)
                }
            else:
                picks = sharded_picks_to_dict(
                    host_picks, design.template_names, file_index=k,
                    n_samples=ns,
                )
            thresholds = {name: float(thres_np[k]) * factors[name]
                          for name in design.template_names}
            _file_record(outdir, path, picks, thresholds,
                         round(wall / max(n_real, 1), 3), records,
                         write=is_writer, family="mf", rung="multihost")
    # writer must finish artifacts before any process reads them
    multihost_utils.sync_global_devices("das4whales-campaign-end")
    return CampaignResult(outdir=outdir, records=records)


def summarize_campaign(outdir: str) -> dict:
    """Aggregate a campaign's manifest + picks artifacts into a report
    dict: per-file status/pick counts, totals per template, and a
    ``[file x channel]`` detection-count matrix (the campaign-scale
    analog of the reference's single-file detection scatter,
    plot.py:373-415)."""
    recs = artifacts.read_records(_manifest_path(outdir))
    # non-file EVENT records (no "path"): the downshift ledger, elastic
    # mesh rebuilds and the end-of-run resilience counters (_append_event)
    events = [r for r in recs if "path" not in r and "event" in r]
    downshift_events = [e for e in events if e["event"] == "downshift"]
    mesh_events = [e for e in events if e["event"] == "mesh_downshift"]
    counters = {"downshifts": 0, "oom_recoveries": 0, "watchdog_timeouts": 0}
    for e in events:
        if e["event"] == "counters":
            for k in counters:
                counters[k] += int(e.get(k, 0))
    # keep only each path's LAST record: resume runs and retried files
    # append fresh records (a file that failed, then succeeded on a
    # later attempt, counts ONCE — as done), so nothing is double-counted
    latest = {r["path"]: r for r in recs if "path" in r}
    # per-family / per-rung audit (workflows.planner): every record
    # carries the detector family and the route rung that executed it,
    # so a downshift ledger is attributable per family ("" groups
    # records from pre-planner manifests)
    by_family: Dict[str, Dict[str, int]] = {}
    for r in latest.values():
        fam = by_family.setdefault(r.get("family", ""), {})
        fam[r["status"]] = fam.get(r["status"], 0) + 1
    rungs: Dict[str, int] = {}
    for r in latest.values():
        if r["status"] == "done":
            label = r.get("rung", "") or "?"
            rungs[label] = rungs.get(label, 0) + 1
    done = [r for r in latest.values() if r["status"] == "done"]
    failed = [r for r in latest.values() if r["status"] == "failed"]
    quarantined = [r for r in latest.values() if r["status"] == "quarantined"]
    timeout = [r for r in latest.values() if r["status"] == "timeout"]

    totals: Dict[str, int] = {}
    density = {}                  # name -> [n_files x nx] counts
    nx = 0
    for fi, rec in enumerate(done):
        picks = load_picks(rec["picks_file"])
        for name, pk in picks.items():
            totals[name] = totals.get(name, 0) + pk.shape[1]
            if pk.shape[1]:
                nx = max(nx, int(pk[0].max()) + 1)
    for name in totals:
        density[name] = np.zeros((len(done), nx), dtype=np.int32)
    for fi, rec in enumerate(done):
        picks = load_picks(rec["picks_file"])
        for name, pk in picks.items():
            if pk.shape[1]:
                np.add.at(density[name][fi], pk[0].astype(int), 1)
    return {
        "n_done": len(done),
        "n_failed": len(failed),
        "n_quarantined": len(quarantined),
        "n_timeout": len(timeout),
        "total_attempts": sum(int(r.get("attempts", 1)) for r in latest.values()),
        # resource-resilience ledger (zeros / empty on a healthy run):
        # sticky downshift moves, files recovered by the elastic ladder,
        # dispatch-watchdog timeouts, elastic mesh rebuilds
        "downshifts": counters["downshifts"],
        "oom_recoveries": counters["oom_recoveries"],
        "watchdog_timeouts": counters["watchdog_timeouts"],
        "downshift_ledger": downshift_events,
        "mesh_downshifts": mesh_events,
        # status counts per detector family + done counts per executed
        # rung — the family-resilience audit (docs/ROBUSTNESS.md
        # "Family x guarantee coverage")
        "by_family": by_family,
        "rungs": rungs,
        "failed_paths": [r["path"] for r in failed],
        "quarantined_paths": [r["path"] for r in quarantined],
        "timeout_paths": [r["path"] for r in timeout],
        "total_picks": totals,
        "files": [{"path": r["path"], "n_picks": r["n_picks"],
                   "wall_s": r["wall_s"], "family": r.get("family", ""),
                   "rung": r.get("rung", "")} for r in done],
        "density": density,
    }


def plot_campaign_density(summary: dict, dx_km: float = 2.042e-3, show=None):
    """Detection-density heatmaps (file index x cable distance) from a
    :func:`summarize_campaign` dict — one panel per template. Returns the
    matplotlib Figure (headless-safe, like ``viz.plot``)."""
    import matplotlib

    if not show:
        matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    names = list(summary["density"])
    fig, axes = plt.subplots(
        1, max(len(names), 1), figsize=(7 * max(len(names), 1), 5),
        squeeze=False,
    )
    for ax, name in zip(axes[0], names):
        d = summary["density"][name]
        im = ax.imshow(
            d, aspect="auto", origin="lower", cmap="turbo",
            extent=[0, d.shape[1] * dx_km, -0.5, d.shape[0] - 0.5],
        )
        ax.set_xlabel("Distance [km]")
        ax.set_ylabel("File index")
        ax.set_title(f"{name}: {summary['total_picks'][name]} picks")
        fig.colorbar(im, ax=ax, label="picks per channel")
    fig.tight_layout()
    if show:
        plt.show()
    return fig
