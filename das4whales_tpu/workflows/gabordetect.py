"""Gabor/image detection workflow (reference ``scripts/main_gabordetect.py``,
SURVEY.md §3.3): prologue + f-k filter, then envelope→image, oriented Gabor
scoring at the sound-speed slope, binned mask, masked matched filter, picks."""

from __future__ import annotations

import numpy as np

from ..models.gabor import GaborDetector
from ..models.matched_filter import MatchedFilterDetector
from .common import acquire, maybe_savefig, mf_prefilter


def campaign_detector(metadata, selected_channels, trace_shape=None, *,
                      fused_bandpass: bool = True, **gabor_kwargs):
    """The Gabor/image family wired for the resilient campaign runner:
    the shared bandpass + f-k prefilter (``common.mf_prefilter``)
    feeding a :class:`GaborDetector`, wrapped in the eval adapter the
    route planner maps to the ``"gabor"`` :class:`DetectorProgram`
    (``workflows.planner``) — the family's ladder is per-file -> host
    (the oriented Gabor pair couples ~kilochannel image rows, so no
    tiled rung), with the same retry/health/watchdog/chaos coverage as
    every other family."""
    from ..eval import GaborEvalAdapter

    mf = mf_prefilter(metadata, selected_channels, trace_shape,
                      fused_bandpass=fused_bandpass)
    return GaborEvalAdapter(
        mf, GaborDetector(mf.metadata, list(selected_channels),
                          **gabor_kwargs),
    )


def main(url: str | None = None, outdir: str | None = None, show: bool = False,
         selected_channels_m=None):
    block, meta, sel = acquire(url, selected_channels_m=selected_channels_m)

    mf = MatchedFilterDetector(meta, sel, tuple(block.trace.shape))
    trf_fk = mf.filter_block(block.trace)

    det = GaborDetector(meta.with_shape(*block.trace.shape), sel)
    res = det(trf_fk)

    figures = {}
    if outdir is not None or show:
        from .. import viz

        names = list(res["picks"])
        fig = viz.detection_grad(
            np.asarray(trf_fk), res["picks"][names[0]], block.tx, block.dist,
            meta.fs, meta.dx, sel, file_begin_time_utc=block.t0_utc, show=show)
        figures["detection"] = maybe_savefig(fig, outdir, "gabor_detection.png")

    res["trf_fk"] = trf_fk
    res["block"] = block
    res["figures"] = figures
    return res


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None, outdir="out_gabordetect")
