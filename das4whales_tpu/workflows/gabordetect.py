"""Gabor/image detection workflow (reference ``scripts/main_gabordetect.py``,
SURVEY.md §3.3): prologue + f-k filter, then envelope→image, oriented Gabor
scoring at the sound-speed slope, binned mask, masked matched filter, picks."""

from __future__ import annotations

import numpy as np

from ..models.gabor import GaborDetector
from ..models.matched_filter import MatchedFilterDetector
from .common import acquire, maybe_savefig


def main(url: str | None = None, outdir: str | None = None, show: bool = False,
         selected_channels_m=None):
    block, meta, sel = acquire(url, selected_channels_m=selected_channels_m)

    mf = MatchedFilterDetector(meta, sel, tuple(block.trace.shape))
    trf_fk = mf.filter_block(block.trace)

    det = GaborDetector(meta.with_shape(*block.trace.shape), sel)
    res = det(trf_fk)

    figures = {}
    if outdir is not None or show:
        from .. import viz

        names = list(res["picks"])
        fig = viz.detection_grad(
            np.asarray(trf_fk), res["picks"][names[0]], block.tx, block.dist,
            meta.fs, meta.dx, sel, file_begin_time_utc=block.t0_utc, show=show)
        figures["detection"] = maybe_savefig(fig, outdir, "gabor_detection.png")

    res["trf_fk"] = trf_fk
    res["block"] = block
    res["figures"] = figures
    return res


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else None, outdir="out_gabordetect")
