"""Continuous long-record detection across file boundaries.

The reference (and its dask path) processes each 60 s file independently
(scripts/main_mfdetect.py per-file; dask_wrap.py:21-93 is still per-file),
so a call straddling two files is split across two windows and its
matched-filter response never fully accumulates — boundary calls are
systematically weakened or lost. This workflow treats a recording
campaign as what it physically is: one continuous ``[channel x time]``
record. Consecutive files are streamed (io/stream.py, native engine when
available), concatenated along time, and processed by the
sequence-parallel time-sharded step (parallel/timeshard.py) whose halo
exchange makes every interior sample — including every former file
boundary — exact.

Returns picks with absolute times from the first file's UTC start.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults
from ..config import as_metadata
from ..io.stream import stream_strain_blocks
from ..models.matched_filter import design_matched_filter
from ..ops import peaks as peak_ops
from ..parallel import dispatch as dispatch_mod
from ..parallel.mesh import make_mesh
from ..parallel.timeshard import make_sharded_mf_step_time, time_sharding
from ..telemetry import trace as telemetry
from ..utils.log import get_logger

log = get_logger("das4whales_tpu.workflows.longrecord")


@dataclass
class LongRecordResult:
    picks: Dict[str, np.ndarray]        # (2, n) [channel_idx, absolute_sample_idx]
    pick_times_s: Dict[str, np.ndarray]  # absolute seconds from record start
    thresholds: Dict[str, float]
    t0_utc: object
    n_samples: int
    n_files: int


def _pad_to_multiple(x: np.ndarray, mult: int) -> np.ndarray:
    pad = (-x.shape[-1]) % mult
    if pad:
        x = np.pad(x, ((0, 0), (0, pad)))
    return x


# record-level pack capacity; counts above it trigger the exact
# full-grid fallback (kept monkeypatchable for the parity test)
_PICK_PACK_CAP = 1 << 20


@functools.partial(jax.jit, static_argnames=("capacity",))
def _pack_record_picks(positions, selected, ns_eff, capacity: int):
    """Pack the record's ``[nT, C, K]`` pick grid on device (same
    boundary-crossing reduction as the campaign/single-chip paths;
    ``ops.peaks.compact_picks_rowmajor`` keeps the reference row-major
    order). ``ns_eff`` drops picks inside the divisibility padding —
    already divided by any frame→sample scale so the comparison runs on
    raw positions."""
    sel = selected & (positions < ns_eff)
    return peak_ops.compact_picks_rowmajor(positions, sel, capacity)


def detect_long_record(
    files: Sequence[str],
    selected_channels,
    metadata=None,
    *,
    mesh=None,
    time_axis: str = "time",
    halo: int = 512,
    engine: str = "auto",
    interrogator: str = "optasense",
    relative_threshold: float = 0.5,
    hf_factor: float | None = None,
    templates=None,
    bp_band=(14.0, 30.0),
    fk_config=None,
    max_peaks_per_channel: int = 512,
    family: str = "mf",
    fused_bandpass: bool | None = None,
    family_kwargs: dict | None = None,
    wire: str = "conditioned",
    mf_engine: str | None = None,
) -> LongRecordResult:
    """Detect calls over a continuous multi-file record.

    ``files`` must be consecutive segments of one recording (their
    concatenation is treated as gapless, the acquisition's native layout).
    The time axis is sharded over ``mesh`` (defaults to all devices on a
    1-D ``(time,)`` mesh); channels stay whole for the flagship family,
    so any channel count works.

    ``wire="raw"`` (flagship family only) streams and concatenates the
    STORED dtype — the multi-file record crosses host→device as raw
    counts (2× fewer bytes for int16 sources, and half the host RAM for
    the concatenated record) and the time-sharded step conditions on
    device by gather-subtracting the exact per-file host means
    (``ops.conditioning.condition_segmented`` — the conditioned wire
    demeans each file separately, so a whole-record demean would be the
    wrong map when files carry different DC count offsets).

    ``family`` selects the detector: ``"mf"`` (flagship matched filter),
    ``"spectro"`` (spectrogram correlation — picks are reported at frame
    resolution, converted to samples via the hop), or ``"gabor"`` (image
    pipeline). The non-flagship families run the shared bandpass+f-k
    front end first (their workflows' prologue), then their own
    time-sharded step; both need the channel count divisible by the mesh
    (their relabel scatters channels). ``family_kwargs`` passes through
    to the family's step factory (e.g. ``threshold`` for spectro,
    ``ksize``/``bin_factor``/``channel_halo`` for gabor).
    """
    if family not in ("mf", "spectro", "gabor", "learned"):
        raise ValueError(f"unknown family {family!r}")
    if wire not in ("conditioned", "raw"):
        raise ValueError(f"unknown wire {wire!r}; expected 'conditioned' or 'raw'")
    if wire == "raw" and family != "mf":
        raise ValueError(
            "wire='raw' is wired into the flagship family only; the "
            "spectro/gabor/learned front ends consume conditioned strain"
        )
    fam_kw = dict(family_kwargs or {})
    if family == "mf" and fam_kw:
        raise ValueError(
            "family_kwargs only apply to family='spectro'/'gabor'/"
            f"'learned' — got {sorted(fam_kw)} with family='mf' (did you "
            "forget family=?)"
        )
    if family == "learned" and not (
        "model" in fam_kw or ("params" in fam_kw and "cfg" in fam_kw)
    ):
        raise ValueError(
            "family='learned' needs family_kwargs={'model': <npz path>} "
            "(models.learned.save_params) or {'params': ..., 'cfg': ...}"
        )
    if fused_bandpass is None:
        # library default: fused for the flagship family (the on-chip
        # gate-3 decision, docs/PERF.md round-4); the spectro/gabor front
        # end designs its own bandpass, so "fused" has no meaning there
        fused_bandpass = family == "mf"
    if family != "mf" and fused_bandpass:
        raise ValueError(
            "fused_bandpass applies to the flagship family only; the "
            "spectro/gabor front end designs its own bandpass"
        )
    if family == "mf" and fused_bandpass and halo != 512:
        import warnings

        warnings.warn(
            f"halo={halo} has no effect on the fused mf route (no "
            "halo-exchange bandpass stage); pass fused_bandpass=False to "
            "tune staged-bandpass boundary exactness",
            stacklevel=2,
        )
    files = list(files)
    if not files:
        raise ValueError("need at least one file")
    if mesh is None:
        mesh = make_mesh(shape=(len(jax.devices()),), axis_names=(time_axis,))
    p = mesh.shape[time_axis]

    with telemetry.span("longrecord.read", n_files=len(files),
                        family=family):
        blocks = list(stream_strain_blocks(
            files, selected_channels, metadata,
            interrogator=interrogator, engine=engine, as_numpy=True,
            wire=wire,
        ))
    meta = as_metadata(blocks[0].metadata)
    record = np.concatenate([b.trace for b in blocks], axis=-1)
    n_samples = record.shape[-1]
    # spectro additionally needs each local shard to be a whole number of
    # STFT hops (frame grid aligned with shard boundaries) — derive the
    # hop from the SAME knobs the step factory will use (family_kwargs
    # may override win_size/overlap_pct)
    pad_mult = p
    nhop = None
    if family == "spectro":
        nperseg = int(float(fam_kw.get("win_size", 0.8)) * meta.fs)
        nhop = int(np.floor(nperseg * (1 - float(fam_kw.get("overlap_pct", 0.95)))))
        pad_mult = p * nhop
    record = _pad_to_multiple(record, pad_mult)
    nnx, nns = record.shape
    log.info("continuous record: %d files -> [%d x %d] (%.1f s)",
             len(files), nnx, nns, n_samples / meta.fs)

    if family == "learned":
        # no bandpass/f-k front end (the classifier consumes raw
        # spectrogram windows) and no time sharding: scoring is
        # per-channel independent, so the record CHANNEL-shards over the
        # same devices collective-free (models.learned
        # make_sharded_inference) and picks come from the detector's own
        # NMS with absolute window centers. Padding windows past the real
        # record end are dropped like every family's divisibility pad.
        from ..models import learned as _learned

        if "model" in fam_kw:
            params_l, cfg_l = _learned.load_params(fam_kw["model"])
        else:
            params_l, cfg_l = fam_kw["params"], fam_kw["cfg"]
        thr_l = float(fam_kw.get("threshold", 0.5))
        if nnx % p:
            raise ValueError(
                f"family='learned' channel-shards the record: channel "
                f"count {nnx} must be divisible by {p}"
            )
        cmesh = make_mesh(shape=(p,), axis_names=("channel",),
                          devices=np.asarray(mesh.devices).reshape(-1))
        score_fn, put = _learned.make_sharded_inference(params_l, cfg_l, cmesh)
        # pipelined-dispatch discipline (parallel.dispatch): launch the
        # step asynchronously; the counted fetch below IS the sync — no
        # block_until_ready double round trip
        scores = np.asarray(dispatch_mod.fetch(
            dispatch_mod.launch(score_fn, put(record))
        ))
        det = _learned.LearnedDetector(params_l, cfg_l, threshold=thr_l)
        res = det.picks_from_scores(scores)
        pk = res.picks[det.name]
        pk = pk[:, pk[1] < n_samples]      # drop divisibility-padding picks
        return LongRecordResult(
            picks={det.name: pk},
            pick_times_s={det.name: pk[1] / meta.fs},
            thresholds={det.name: thr_l},
            t0_utc=blocks[0].t0_utc, n_samples=n_samples, n_files=len(files),
        )

    from ..config import SCRIPT_FK

    fk_cfg = fk_config or SCRIPT_FK
    xd = jax.device_put(jnp.asarray(record), time_sharding(mesh, time_axis))

    if family == "mf":
        design = design_matched_filter(
            (nnx, nns), blocks[0].selection.to_list(), meta,
            fk_config=fk_cfg, bp_band=bp_band, templates=templates,
        )
        # campaign-mode outputs: the full-record trf/corr/env arrays never
        # become program outputs (this workflow only consumes picks)
        cond_kw = {}
        if wire == "raw":
            # per-FILE conditioning parameters, host-side: the conditioned
            # wire demeans each file before concatenation, so the on-device
            # prologue must subtract the same per-file means (and leave the
            # divisibility pad exactly 0) — one numpy pass per raw block,
            # the identical reduction the conditioned readers run, making
            # raw-wire conditioning bit-identical (ops.conditioning
            # .condition_segmented)
            scales = {as_metadata(b.metadata).scale_factor for b in blocks}
            if len(scales) > 1:
                raise ValueError(
                    f"wire='raw' conditions the record with one scale but "
                    f"the files probed {sorted(scales)}; use "
                    "wire='conditioned' for heterogeneous file sets"
                )
            # dtype=f32 reduces with the same pairwise float32 sum as the
            # conditioned readers' astype(f32).mean, WITHOUT materializing
            # a float32 copy of each raw block (that temp would transiently
            # re-inflate the host RAM the narrow wire halves)
            cond_kw = dict(
                scale_factor=meta.scale_factor,
                cond_segments=[b.trace.shape[-1] for b in blocks],
                cond_means=np.stack(
                    [b.trace.mean(axis=1, dtype=np.float32) for b in blocks],
                    axis=1,
                ),
            )
        # MXU correlate engine (ops/mxu.py): same per-shape router as the
        # campaign routes — None defers to DAS_MF_ENGINE/auto, so the
        # long-record path rides the matmul recast exactly when they do
        from ..ops import mxu as mxu_ops
        from ..ops.xcorr import padded_template_stats

        resolved_mf, _mf_why = mxu_ops.resolve_mf_engine(
            mf_engine, design.trace_shape,
            *padded_template_stats(design.templates),
        )
        step = make_sharded_mf_step_time(
            design, mesh, time_axis=time_axis, halo=halo,
            relative_threshold=relative_threshold, hf_factor=hf_factor,
            pick_mode="sparse", max_peaks=max_peaks_per_channel,
            fused_bandpass=fused_bandpass, outputs="picks",
            wire=wire, mf_engine=resolved_mf, **cond_kw,
        )
        # async dispatch (parallel.dispatch): the device-side pick pack
        # below is dispatched back-to-back with the step — the old
        # per-step block_until_ready serialized the pack behind a full
        # host round trip for nothing (ISSUE 6 sync-in-loop burn-down).
        # thr_map is DEFERRED: float(thres) blocks on the step, so
        # fetching it here would serialize the pack dispatch just as
        # block_until_ready did
        sp_picks, thres = dispatch_mod.launch(step, xd)
        names = design.template_names
        # per-template factors — the SAME resolution the step factory
        # ran (MatchedFilterDesign.resolve_threshold_policy); thres is
        # the scalar pre-factor base under the global scope, the [nT]
        # vector under the bank's per_template scope
        fac, _ = design.resolve_threshold_policy(hf_factor)

        def thr_map_fn():
            base = np.broadcast_to(np.asarray(thres, np.float32), fac.shape)
            return {
                name: float(base[i]) * float(fac[i])
                for i, name in enumerate(names)
            }
        pos_scale = 1
    else:
        # shared front end (the spectro/gabor workflows' prologue):
        # time-sharded zero-phase bandpass + pencil f-k. Only the mask is
        # needed here — skip design_matched_filter's (unused) full-record
        # templates and bandpass gain.
        from dataclasses import replace as _dc_replace

        from ..ops import fk as fk_ops
        from ..parallel.timeshard import (
            sharded_bp_filt_time,
            sharded_fk_apply_time,
        )

        if nnx % p:
            raise ValueError(
                f"family={family!r} relabels channels across the mesh: "
                f"channel count {nnx} must be divisible by {p}"
            )
        fk_mask = fk_ops.hybrid_ninf_filter_design(
            (nnx, nns), blocks[0].selection.to_list(), meta.dx, meta.fs,
            cs_min=fk_cfg.cs_min, cp_min=fk_cfg.cp_min,
            cp_max=fk_cfg.cp_max, cs_max=fk_cfg.cs_max,
            fmin=fk_cfg.fmin, fmax=fk_cfg.fmax,
        ).astype(np.float32)
        trf_dev = sharded_fk_apply_time(
            sharded_bp_filt_time(
                xd, mesh, meta.fs, bp_band[0], bp_band[1],
                halo=halo, time_axis=time_axis,
            ),
            fk_mask, mesh, time_axis=time_axis,
        )
        meta_rec = _dc_replace(meta, nx=nnx, ns=nns)
        if family == "spectro":
            from ..parallel.spectro import make_sharded_spectro_step_time

            step, names = make_sharded_spectro_step_time(
                meta_rec, mesh, outputs="picks",
                max_peaks=max_peaks_per_channel, time_axis=time_axis,
                **fam_kw,
            )
            sp_picks = dispatch_mod.launch(step, trf_dev)
            # echo the threshold the factory actually used (its own
            # signature default is the single source)
            import inspect

            factory_default = inspect.signature(
                make_sharded_spectro_step_time
            ).parameters["threshold"].default
            thr = float(fam_kw.get("threshold", factory_default))
            thr_map_fn = lambda: {name: thr for name in names}  # host value
            pos_scale = nhop                   # frame index -> sample index
        else:
            from ..parallel.gabor import make_sharded_gabor_step_time

            # the original selection sets the Gabor angle only; the record's
            # actual row count (meta_rec.nx is already post-selection) drives
            # the sharding validation. outputs='picks' keeps the full-record
            # correlograms out of the program outputs (campaign mode).
            # the gabor family keeps its HF/LF-named legacy factor pair
            hf_leg = 0.9 if hf_factor is None else float(hf_factor)
            step, names = make_sharded_gabor_step_time(
                meta_rec, blocks[0].selection.to_list(), mesh,
                relative_threshold=relative_threshold, hf_factor=hf_leg,
                max_peaks=max_peaks_per_channel, time_axis=time_axis,
                n_channels=nnx, outputs="picks",
                **fam_kw,
            )
            sp_picks, thres = dispatch_mod.launch(step, trf_dev)
            # deferred (fetched after the pick pack is dispatched)
            thr_map_fn = lambda: {
                name: float(thres) * (hf_leg if name == "HF" else 1.0)
                for name in names
            }
            pos_scale = 1

    picks, times_s, thr_out = {}, {}, {}
    # drop picks inside the divisibility padding (padded zeros cannot
    # raise the pmax threshold, but the envelope can ring there); the
    # mask runs on raw (pre-scale) positions inside the device pack.
    # The pack dispatches FIRST — before any fetch of the step's
    # outputs — so it runs back-to-back with the step; only then do the
    # saturated/threshold fetches block (on a step that the pack is
    # already queued behind)
    ns_eff = (n_samples - 1) // pos_scale + 1
    cap = min(int(np.prod(sp_picks.positions.shape[-2:])), _PICK_PACK_CAP)
    with telemetry.span("longrecord.resolve", family=family,
                        n_samples=n_samples):
        rows_d, times_d, cnt_d = dispatch_mod.launch(
            _pack_record_picks, sp_picks.positions, sp_picks.selected,
            ns_eff, cap
        )
        saturated = dispatch_mod.fetch(sp_picks.saturated)
        thr_map = thr_map_fn()   # scalar transfer; the step already finished
        faults.count("syncs")   # compacted_to_host's np.asarray is the sync
        packed = peak_ops.compacted_to_host(rows_d, times_d, cnt_d, cap)
    if packed is not None:
        rows_np, times_np, cnt = packed
        positions = selected = None
    else:  # pack overflow: exact full-grid fallback
        positions = np.asarray(sp_picks.positions)
        selected = np.asarray(sp_picks.selected)
    for i, name in enumerate(names):
        if saturated[i].any():
            log.warning(
                "%s: peak capacity saturated on %d/%d channels; picks beyond "
                "the %d tallest per channel were dropped — raise "
                "max_peaks_per_channel to keep them",
                name, int(saturated[i].sum()), nnx, max_peaks_per_channel,
            )
        if positions is None:
            k = int(cnt[i])
            pk = np.asarray([rows_np[i, :k], times_np[i, :k] * pos_scale])
        else:
            sel = selected[i] & (positions[i] < ns_eff)
            pk = peak_ops.sparse_to_pick_times(positions[i], sel)
            pk = np.asarray([pk[0], pk[1] * pos_scale])
        picks[name] = pk
        times_s[name] = pk[1] / meta.fs
        thr_out[name] = thr_map[name]
    return LongRecordResult(
        picks=picks, pick_times_s=times_s, thresholds=thr_out,
        t0_utc=blocks[0].t0_utc, n_samples=n_samples, n_files=len(files),
    )
