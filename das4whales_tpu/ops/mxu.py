"""MXU matmul execution routes for the FLOP-heavy matched-filter stages.

Bench r5 put every FFT-based stage at 0.7-2.3% of the chip's peak: the
rFFT correlate and the f-k apply run on the TPU's VPU and never touch the
MXU — the systolic matmul unit that holds ~98 TFLOP/s f32 (~197 bf16) of
the chip's advertised peak. Two recasts fix that, following TINA
(arxiv 2408.16551: non-NN DSP as NN-accelerator matmuls) and Large-Scale
DFT on TPUs (arxiv 2002.03260: the DFT itself as a matmul):

* **Correlation as a banded-Toeplitz matmul** — the whale-call templates
  are ~140-160 taps against 12k-sample records, so the positive-lag raw
  correlation ``raw[t, c, k] = sum_j xn[c, k+j] y[t, j]`` is a
  ``[channel, frames, tap] @ [tap, template]`` contraction. It is
  expressed here as ``lax.conv_general_dilated`` (XLA's im2col matmul —
  on TPU it lowers straight onto the MXU) with f32 accumulation
  (``preferred_element_type``), optionally with bf16 inputs behind the
  precision gate. The normalization prologue and padded-template
  correction epilogue are the SAME code the FFT engine runs
  (``ops.xcorr.normalized_block_and_suffix`` / ``corrected_from_raw``),
  so the engines can only differ in the raw correlation's rounding.

* **f-k apply as a DFT-matrix matmul** — the channel-axis FFT pair of the
  banded applier (``ops.fk.fk_filter_apply_rfft_banded``) becomes two
  complex matmuls against the precomputed ``[C, C]`` DFT matrix, fused
  with the mask multiply between them. O(C^2) matmul beats O(C log C)
  FFT on the MXU below a channel-count threshold
  (``config.fk_matmul_max_channels``); the time-axis rFFT/irFFT stays an
  FFT (12k samples is far past the crossover).

The **engine router** (:func:`resolve_mf_engine` /
:func:`resolve_fk_engine`; ``DAS_MF_ENGINE`` / ``DAS_FK_ENGINE`` =
``fft`` / ``matmul`` / ``auto``) decides per shape. ``auto`` consults a
per-shape A/B **calibration table** — measured once on the live backend,
persisted to disk like the compilation cache
(``config.calibration_cache_path``) — and the bf16 **precision gate**:
the bf16 route is eligible ONLY when its picks are bit-identical to the
f32 FFT route on a fixed-seed calibration record; otherwise the gate
records why in the table and the router falls back to f32
(docs/PRECISION.md "bf16 eligibility").
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import time
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from . import fk as fk_ops
from . import peaks as peak_ops
from . import spectral, xcorr

#: Matched-filter correlate engines (resolved static values; the router's
#: external vocabulary adds "auto"). ``matmul-fused`` is the tap-folded
#: variant: the zero-phase bandpass rides INSIDE the correlate taps
#: (:func:`fused_template_taps`), eliminating the per-channel filter pass
#: — precision-gated like bf16, falling back to the plain f32 matmul.
MF_ENGINES = ("fft", "matmul", "matmul-bf16", "matmul-fused")

#: f-k apply engines. The DFT-matmul stays f32: the mask multiply sits
#: between two C-length transforms whose bf16 rounding would compound,
#: and the stage is HBM-bound long before the MXU is (docs/PRECISION.md).
FK_ENGINES = ("fft", "matmul")


# ---------------------------------------------------------------------------
# Correlation as a banded-Toeplitz (im2col) matmul
# ---------------------------------------------------------------------------


def correlate_taps(xn: jnp.ndarray, templates_true: jnp.ndarray,
                   bf16: bool = False,
                   pad: Tuple[int, int] | None = None) -> jnp.ndarray:
    """Positive-lag raw correlation ``sum_j xn[..., k+j] * y[t, j]`` as an
    MXU contraction: ``conv_general_dilated`` in the ML (no-flip)
    convention IS the ``[frames, tap] @ [tap, template]`` im2col matmul,
    right-padded ``m - 1`` so every lag ``k in [0, n)`` is produced
    exactly as the FFT route's truncated linear correlation. ``xn`` is
    ``[..., n]`` with arbitrary leading axes; returns ``[nT, ..., n]``
    in f32 accumulation (bf16 inputs only when ``bf16`` — the precision
    gate's domain). ``pad`` overrides the ``(0, m - 1)`` edge padding —
    the tap-folded engine correlates against ``m + 2L``-tap rows whose
    lag origin sits ``L`` taps in (:func:`fused_template_taps`), so it
    pads ``(L, m - 1 + L)`` to keep lag ``k == 0`` aligned with the
    staged route's."""
    n = xn.shape[-1]
    nT, m = templates_true.shape
    lead = xn.shape[:-1]
    lhs = xn.reshape((-1, 1, n))                    # [batch, feat=1, time]
    rhs = templates_true[:, None, :]                # [out=nT, in=1, tap]
    if bf16:
        lhs = lhs.astype(jnp.bfloat16)
        rhs = rhs.astype(jnp.bfloat16)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,),
        padding=[(0, m - 1) if pad is None else (int(pad[0]), int(pad[1]))],
        dimension_numbers=("NCH", "OIH", "NCH"),
        preferred_element_type=jnp.float32,
    )                                               # [batch, nT, lags]
    return jnp.moveaxis(out, 1, 0).reshape((nT,) + lead + (out.shape[-1],))


def _matmul_correlograms_body(data, templates_true, mu, scale, bf16: bool):
    """The corrected-correlogram math of
    ``xcorr.compute_cross_correlograms_corrected`` with the raw
    correlation on the MXU: identical normalization prologue and
    padded-template correction epilogue (shared ``ops.xcorr`` helpers),
    only the transform differs."""
    xn, suffix = xcorr.normalized_block_and_suffix(data)
    raw = correlate_taps(xn, templates_true, bf16=bf16)
    return xcorr.corrected_from_raw(raw, suffix, mu, scale, data.dtype)


@functools.partial(jax.jit, static_argnames=("bf16",))
def compute_cross_correlograms_matmul(
    data: jnp.ndarray, templates_true: jnp.ndarray, mu: jnp.ndarray,
    scale: jnp.ndarray, bf16: bool = False,
) -> jnp.ndarray:
    """MXU engine twin of ``xcorr.compute_cross_correlograms_corrected``
    (same signature, same ``[nT, ..., n]`` output, same template triple
    from ``padded_template_stats``): the raw correlation runs as a
    banded-Toeplitz matmul instead of an rFFT product. f32 everywhere;
    ``bf16=True`` rounds the matmul INPUTS to bf16 with f32 accumulation
    — only the precision-gated router may select that."""
    return _matmul_correlograms_body(data, templates_true, mu, scale, bf16)


# ---------------------------------------------------------------------------
# Tap folding: the bandpass INSIDE the correlate contraction (TINA-style)
# ---------------------------------------------------------------------------


def fused_template_taps(templates_true, fir) -> Tuple[np.ndarray, np.ndarray,
                                                      int]:
    """Fold the zero-phase bandpass FIR ``h`` (half-length ``L``,
    ``ops.filters.butter_zero_phase_fir``) into each template's correlate
    taps: because the staged route correlates the FILTERED block against
    the raw template, ``sum_j (h * x)[k+j] y[t, j] ==
    sum_u x[k+u] (h conv y_t)[u]`` with ``u in [-L, m-1+L]`` — so the
    per-channel filter pass folds into ``2L`` extra taps per template.

    Returns ``(folded [nT+1, m+2L] f32, tcum [nT, m+1] f32, L)``. The
    EXTRA last row is ``h`` itself (centered on the lag origin), so the
    same contraction also emits the bandpassed block ``g = h * x`` — the
    normalization prologue (``mean``/``max|.|``/suffix of ``g``) is then
    derived in-graph from row ``nT`` instead of a separate filter program
    (:func:`fused_correlograms_body`). ``tcum[t, r] = sum_{j < r}
    y[t, j]`` (template tap prefix sums) feeds the demean term of the
    fold's closed form — the PREFIX vector, not just the total, because
    at partial-overlap lags ``k > n - m`` the staged route's zero-padded
    ``xn`` truncates the sum at ``j < n - k`` taps. Host design in
    float64 (the ``dft_matrices`` precedent), cast to f32 on return."""
    tt = np.atleast_2d(np.asarray(templates_true, dtype=np.float64))  # daslint: allow[R3] f64 design fold, cast to f32 below
    h = np.asarray(fir, dtype=np.float64)  # daslint: allow[R3] f64 design fold, cast to f32 below
    L = (int(h.shape[0]) - 1) // 2
    nT, m = tt.shape
    P = m + 2 * L
    folded = np.zeros((nT + 1, P))
    for i in range(nT):
        folded[i] = np.convolve(h, tt[i])           # length m + 2L
    folded[nT, : 2 * L + 1] = h                     # the IR row: recovers g
    tcum = np.concatenate(
        [np.zeros((nT, 1)), np.cumsum(tt, axis=-1)], axis=-1
    )
    return folded.astype(np.float32), tcum.astype(np.float32), L


def fused_correlograms_body(data, templates_true, folded_taps, tcum, mu,
                            scale, fir_half: int):
    """Corrected correlograms from the RAW (unfiltered) block with the
    bandpass folded into the taps — the whole
    ``_fft_zero_phase_jit -> normalized_block_and_suffix ->
    correlate_taps -> corrected_from_raw`` chain as ONE ``m + 2L``-tap
    MXU contraction plus an elementwise epilogue.

    Let ``g = h * x`` (row ``nT`` of the contraction, extended ``m - 1``
    lags past the record so its ring-down tail is available),
    ``mg = mean(g[:n])``, ``Mg = max|g[:n]|`` (tiny-guarded like
    ``_demean_peak_normalize``), ``suffix_g[k] = sum_{n > i >= k} g[i]``.
    The staged route zero-pads its normalized block past the record, so
    at lag ``k`` only the first ``w(k) = min(m, n - k)`` template taps
    contribute; its corrected correlogram is then exactly::

        corr[t, c, k] = (raw[t, c, k] - tail[t, c, k]
                         - mg tcum[t, w(k)]
                         - mu_t (suffix_g[k] - (n - k) mg)) / (Mg s_t)

    where ``raw`` is rows ``0..nT-1`` of the same contraction (which
    integrate the FULL overlap, including ``j >= n - k``) and ``tail``
    re-correlates the ``m - 1`` ring-down samples ``g[n:]`` against the
    template tails — a second, tiny ``[.., m-1] x [nT, m]`` contraction
    — to subtract exactly the terms the staged truncation never sees.
    Matches the staged route on a LINEARLY-filtered block to f32
    rounding at every lag; the remaining deviation vs the shipping
    routes is the bandpass edge spelling (circular/odd-extension vs
    zero-padded) plus the FIR truncation tail, which is why this engine
    is precision-gated (:func:`fused_correlate_gate`), never assumed
    bit-identical. f32 throughout; cast to ``data.dtype`` on return."""
    L = int(fir_half)
    P = int(folded_taps.shape[-1])
    nT = int(folded_taps.shape[0]) - 1
    m = int(tcum.shape[-1]) - 1
    n = data.shape[-1]
    x32 = data.astype(jnp.float32)
    # one contraction, extended m-1 lags right: rows 0..nT-1 are the raw
    # full-overlap correlations, row nT is g with its ring-down tail
    out = correlate_taps(
        x32, folded_taps.astype(jnp.float32),
        pad=(L, P - 1 - L + m - 1),
    )                                               # [nT+1, ..., n+m-1]
    g_ext = out[-1]
    g = g_ext[..., :n]                              # bandpassed block
    raw = out[:-1][..., :n]
    mg = jnp.mean(g, axis=-1, keepdims=True)
    tiny = jnp.asarray(np.finfo(np.float32).tiny, jnp.float32)
    big = jnp.maximum(jnp.max(jnp.abs(g), axis=-1, keepdims=True), tiny)
    suffix_g = jnp.flip(jnp.cumsum(jnp.flip(g, -1), axis=-1), -1)
    nd = raw.ndim - 1
    mu_b = mu.astype(jnp.float32).reshape((nT,) + (1,) * nd)
    sc_b = scale.astype(jnp.float32).reshape((nT,) + (1,) * nd)
    # tail correction: T[t, c, n - r] = sum_i g[c, n + i] y[t, r + i]
    # (the template LEADS the ring-down by r = 1..m-1 taps) — exactly
    # the j >= n - k terms `raw` integrated but the staged route never
    # sees. Left-padding m-1 puts that negative-lag family at output
    # index m - 1 - r, so the slice assigns in increasing-k order.
    tail_corr = correlate_taps(g_ext[..., n:],
                               templates_true.astype(jnp.float32),
                               pad=(m - 1, 0))      # [nT, ..., m-1]
    tail = jnp.zeros(raw.shape, jnp.float32).at[..., n - m + 1:].set(
        tail_corr
    )
    # staged truncation of the demean term: w(k) = min(m, n - k) taps
    w = jnp.clip(n - jnp.arange(n), 0, m)
    coeff = jnp.take_along_axis(
        tcum.astype(jnp.float32), w[None, :].astype(jnp.int32), axis=-1
    ).reshape((nT,) + (1,) * (nd - 1) + (n,))
    remaining = jnp.arange(n, 0, -1, dtype=jnp.float32)   # n - k
    corr = (raw - tail - mg[None] * coeff
            - mu_b * (suffix_g[None] - remaining * mg[None]))
    return (corr / (big[None] * sc_b)).astype(data.dtype)


@functools.partial(jax.jit, static_argnames=("fir_half",))
def compute_cross_correlograms_fused(
    data: jnp.ndarray, templates_true: jnp.ndarray,
    folded_taps: jnp.ndarray, tcum: jnp.ndarray,
    mu: jnp.ndarray, scale: jnp.ndarray, fir_half: int,
) -> jnp.ndarray:
    """Standalone jitted entry for the tap-folded engine (gate, A/B
    calibration, tests); the detection programs inline
    :func:`fused_correlograms_body` under their own jit."""
    return fused_correlograms_body(data, templates_true, folded_taps, tcum,
                                   mu, scale, fir_half)


def correlograms_body(data, templates_true, mu, scale, engine: str,
                      fused=None, fir_half: int = 0):
    """Engine dispatch for the correlate stage, usable INSIDE a caller's
    jit (the detection programs thread ``mf_engine`` as a static and
    call this; compilation belongs to the outer program). ``fused`` is
    the ``(folded_taps, tcum)`` device pair for the ``matmul-fused``
    engine (None elsewhere — the ``fk_dft`` operand pattern); on that
    engine ``data`` must be the UNFILTERED block (the bandpass rides the
    taps)."""
    if engine == "fft":
        return xcorr.compute_cross_correlograms_corrected(
            data, templates_true, mu, scale
        )
    if engine == "matmul-fused":
        if fused is None:
            raise ValueError(
                "matmul-fused engine needs the (folded_taps, tcum) pair "
                "from fused_template_taps"
            )
        folded_taps, tcum = fused
        return fused_correlograms_body(data, templates_true, folded_taps,
                                       tcum, mu, scale, fir_half)
    if engine not in ("matmul", "matmul-bf16"):
        raise ValueError(
            f"unknown mf_engine {engine!r}; expected one of {MF_ENGINES}"
        )
    return _matmul_correlograms_body(
        data, templates_true, mu, scale, engine == "matmul-bf16"
    )


# ---------------------------------------------------------------------------
# f-k apply as a channel-axis DFT-matrix matmul (arxiv 2002.03260)
# ---------------------------------------------------------------------------


def dft_matrices(n: int, dtype=np.float32) -> Tuple[np.ndarray, np.ndarray]:
    """``(cos, sin)`` parts of the forward DFT matrix
    ``W[j, k] = exp(-2 pi i j k / n)``, designed in float64 (phase from
    ``(j k) mod n`` so the angle never leaves ``[-2 pi, 0]`` — exact for
    ``n^2`` within float64) and cast to ``dtype``. The inverse transform
    reuses the pair: ``W^-1 = (cos - i sin) / n``."""
    # deliberate float64 DESIGN precision (host, once per shape): the
    # phase grid must be exact before the f32 cast — the ops/image.py
    # design-constant precedent
    k = np.arange(n, dtype=np.float64)  # daslint: allow[R3] f64 design grid, cast to f32 below
    ang = (-2.0 * np.pi / n) * (np.outer(k, k) % n)
    return np.cos(ang).astype(dtype), np.sin(ang).astype(dtype)


def _mm(a, b):
    """``[M, K] @ [K, N]`` with f32 accumulation on the MXU."""
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def fk_apply_dft_matmul(
    trace: jnp.ndarray, mask_band: jnp.ndarray, lo: int, hi: int,
    wr: jnp.ndarray, wi: jnp.ndarray,
) -> jnp.ndarray:
    """``fk_filter_apply_rfft_banded`` with the channel-axis FFT pair as
    DFT-matrix matmuls fused with the mask: ``Z = W^-1 (M . (W X))``
    runs as eight real ``[C, C] @ [C, band]`` MXU contractions on the
    in-band rfft columns only. The time-axis rFFT/irFFT stays an FFT.
    ``(wr, wi)`` is :func:`dft_matrices` at the trace's channel count.

    Output equals the banded FFT applier up to matmul-vs-FFT rounding
    (~1e-6 relative at f32); picks downstream are pinned bit-identical
    by the router's tests wherever it selects this route."""
    nnx, nns = trace.shape
    Xf = jnp.fft.rfft(trace, axis=1)                  # [C, F]
    xr = Xf.real[:, lo:hi]
    xi = Xf.imag[:, lo:hi]
    # forward channel DFT: Y = W X
    yr = _mm(wr, xr) - _mm(wi, xi)
    yi = _mm(wr, xi) + _mm(wi, xr)
    m = mask_band.astype(yr.dtype)
    yr = yr * m
    yi = yi * m
    # inverse channel DFT: Z = conj(W) Y / C
    inv = jnp.asarray(1.0 / nnx, yr.dtype)
    zr = (_mm(wr, yr) + _mm(wi, yi)) * inv
    zi = (_mm(wr, yi) - _mm(wi, yr)) * inv
    Z = jnp.zeros_like(Xf).at[:, lo:hi].set(jax.lax.complex(zr, zi))
    return jnp.fft.irfft(Z, n=nns, axis=1).astype(trace.dtype)


#: Standalone jitted entry for A/B timing and tests (the detection
#: programs inline :func:`fk_apply_dft_matmul` under their own jit).
fk_apply_dft_matmul_jit = jax.jit(
    fk_apply_dft_matmul, static_argnames=("lo", "hi")
)


def fk_apply_body(trace, mask_band, lo, hi, engine: str, fk_dft):
    """Engine dispatch for the f-k apply, usable inside a caller's jit
    (``fk_engine`` static). ``fk_dft`` is the ``(wr, wi)`` device pair
    for the matmul engine (None on the FFT route)."""
    if engine == "matmul":
        wr, wi = fk_dft
        return fk_apply_dft_matmul(trace, mask_band, lo, hi, wr, wi)
    if engine != "fft":
        raise ValueError(
            f"unknown fk_engine {engine!r}; expected one of {FK_ENGINES}"
        )
    return fk_ops.fk_filter_apply_rfft_banded(trace, mask_band, lo, hi)


# ---------------------------------------------------------------------------
# Per-shape A/B calibration table (persisted like the compile cache)
# ---------------------------------------------------------------------------


class CalibrationTable:
    """Tiny on-disk key -> record store for the engine router: per-shape
    A/B walls and bf16 precision-gate verdicts, measured once per
    (backend, shape) and persisted so later processes route without
    re-measuring (the compile-cache pattern, config.calibration_cache_path).
    Best-effort durable: a missing/corrupt file reads as empty, writes
    are atomic (tmp + replace) and a write failure never breaks routing.
    """

    def __init__(self, path: str | None = None):
        self.path = path or config.calibration_cache_path()
        self._mem: Dict[str, dict] = {}
        self._loaded = False

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        self._mem.update(self._read_disk())

    def _read_disk(self) -> Dict[str, dict]:
        try:
            with open(self.path) as fh:
                data = json.load(fh)
            if isinstance(data, dict):
                return {k: v for k, v in data.items()
                        if isinstance(v, dict)}
        except (OSError, json.JSONDecodeError, ValueError):
            pass
        return {}

    def get(self, key: str) -> dict | None:
        self._load()
        return self._mem.get(key)

    def put(self, key: str, value: dict) -> None:
        self._load()
        self._mem[key] = dict(value)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            # merge the CURRENT on-disk entries under ours before the
            # atomic replace: another process (a multiprocess campaign
            # worker, a concurrent bench rung) may have persisted shapes
            # this instance never loaded — dumping a stale snapshot
            # would discard their multi-second measurements and make the
            # fleet re-calibrate forever. Last-writer-wins per key;
            # whole-file loss never.
            merged = self._read_disk()
            merged.update(self._mem)
            self._mem = merged
            with open(tmp, "w") as fh:
                json.dump(merged, fh, indent=0, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass


_default_table_cache: Dict[str, CalibrationTable] = {}


def default_table() -> CalibrationTable:
    """The process's shared calibration table at the configured path
    (re-resolved per path so tests pointing ``DAS_CALIBRATION_CACHE``
    at a tmpdir get their own)."""
    path = config.calibration_cache_path()
    tab = _default_table_cache.get(path)
    if tab is None:
        tab = _default_table_cache[path] = CalibrationTable(path)
    return tab


def _best_wall(fn, repeats: int = 2) -> float:
    """Best-of-N wall of ``fn`` after a compile+warm call — the A/B
    measurement unit (design-time, once per shape, cached)."""
    jax.block_until_ready(fn())
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


#: A/B measurement channel cap: both correlate engines are linear in
#: channels, so the per-channel comparison at <=2048 rows decides the
#: full-shape winner without materializing canonical-scale temps.
_CAL_MAX_CHANNELS = 2048


def calibrate_correlate(C: int, n: int, m: int, nT: int, *,
                        table: CalibrationTable | None = None,
                        backend: str | None = None,
                        repeats: int = 2) -> dict:
    """A/B the correlate engines (fft / matmul / matmul-bf16) at the
    given shape on the live backend; measured ONCE and cached in the
    calibration table. Both engines are linear in channels, so the
    measurement runs at ``min(C, 2048)`` rows (recorded as
    ``cal_channels``)."""
    table = table or default_table()
    backend = backend or jax.default_backend()
    key = f"correlate|{backend}|C{C}xN{n}|m{m}T{nT}"
    hit = table.get(key)
    if hit is not None:
        return hit
    Cc = min(int(C), _CAL_MAX_CHANNELS)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(Cc, n)).astype(np.float32))
    tt = jnp.asarray(rng.normal(size=(nT, m)).astype(np.float32))
    mu = jnp.zeros((nT,), jnp.float32)
    sc = jnp.ones((nT,), jnp.float32)
    entry = {"cal_channels": Cc}
    entry["fft_s"] = _best_wall(
        lambda: xcorr.compute_cross_correlograms_corrected(x, tt, mu, sc),
        repeats,
    )
    entry["matmul_s"] = _best_wall(
        lambda: compute_cross_correlograms_matmul(x, tt, mu, sc, bf16=False),
        repeats,
    )
    entry["matmul_bf16_s"] = _best_wall(
        lambda: compute_cross_correlograms_matmul(x, tt, mu, sc, bf16=True),
        repeats,
    )
    entry["winner"] = (
        "fft" if entry["fft_s"] <= entry["matmul_s"] else "matmul"
    )
    table.put(key, entry)
    return entry


def calibrate_fk(C: int, n: int, lo: int, hi: int, *,
                 table: CalibrationTable | None = None,
                 backend: str | None = None, repeats: int = 2) -> dict:
    """A/B the banded f-k appliers (channel FFT pair vs DFT matmul) at
    the given shape; measured once, cached. The DFT matrix pair is built
    fresh for the measurement and dropped."""
    table = table or default_table()
    backend = backend or jax.default_backend()
    key = f"fk|{backend}|C{C}xN{n}|band{hi - lo}"
    hit = table.get(key)
    if hit is not None:
        return hit
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(int(C), int(n))).astype(np.float32))
    mb = jnp.asarray(
        rng.uniform(size=(int(C), int(hi - lo))).astype(np.float32)
    )
    wr_np, wi_np = dft_matrices(int(C))
    wr, wi = jnp.asarray(wr_np), jnp.asarray(wi_np)
    entry = {
        "fft_s": _best_wall(
            lambda: fk_ops.fk_filter_apply_rfft_banded(x, mb, int(lo), int(hi)),
            repeats,
        ),
        "matmul_s": _best_wall(
            lambda: fk_apply_dft_matmul_jit(x, mb, int(lo), int(hi), wr, wi),
            repeats,
        ),
    }
    entry["winner"] = (
        "fft" if entry["fft_s"] <= entry["matmul_s"] else "matmul"
    )
    table.put(key, entry)
    return entry


#: Jitted A/B entry for the STFT engines (the detection programs inline
#: :func:`spectral.stft_magnitude` under their own jit).
_stft_magnitude_timed = jax.jit(
    spectral.stft_magnitude, static_argnames=("nfft", "hop", "engine")
)


def calibrate_stft(C: int, n: int, nfft: int, hop: int, *,
                   table: CalibrationTable | None = None,
                   backend: str | None = None, repeats: int = 2) -> dict:
    """A/B the STFT-magnitude engines (batched rFFT vs framed windowed-DFT
    matmul, plus the Pallas kernel on TPU where it runs) at the given
    shape; measured once, cached. Linear in channels like the correlate,
    so the measurement runs at ``min(C, 2048)`` rows."""
    table = table or default_table()
    backend = backend or jax.default_backend()
    key = f"stft|{backend}|C{C}xN{n}|nfft{nfft}h{hop}"
    hit = table.get(key)
    if hit is not None:
        return hit
    Cc = min(int(C), _CAL_MAX_CHANNELS)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(Cc, int(n))).astype(np.float32))
    entry = {"cal_channels": Cc}
    candidates = ("rfft", "matmul") + (("pallas",) if backend == "tpu" else ())
    for eng in candidates:
        entry[f"{eng}_s"] = _best_wall(
            lambda e=eng: _stft_magnitude_timed(
                x, nfft=int(nfft), hop=int(hop), engine=e
            ),
            repeats,
        )
    entry["winner"] = min(candidates, key=lambda e: entry[f"{e}_s"])
    table.put(key, entry)
    return entry


def calibrate_gabor(H: int, W: int, m1: int, m2: int, *,
                    table: CalibrationTable | None = None,
                    backend: str | None = None, repeats: int = 2) -> dict:
    """A/B the 2-D same-correlation engines (batched FFT product vs
    ``conv_general_dilated`` im2col matmul) at the given binned-image and
    kernel shape; measured once, cached."""
    from . import image as image_ops

    table = table or default_table()
    backend = backend or jax.default_backend()
    key = f"gabor|{backend}|H{H}xW{W}|k{m1}x{m2}"
    hit = table.get(key)
    if hit is not None:
        return hit
    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.normal(size=(int(H), int(W))).astype(np.float32))
    ker = jnp.asarray(rng.normal(size=(int(m1), int(m2))).astype(np.float32))
    entry = {
        "fft_s": _best_wall(
            lambda: image_ops.filter2d_same(img, ker, engine="fft"), repeats
        ),
        "conv_s": _best_wall(
            lambda: image_ops.filter2d_same(img, ker, engine="conv"), repeats
        ),
    }
    entry["winner"] = "fft" if entry["fft_s"] <= entry["conv_s"] else "conv"
    table.put(key, entry)
    return entry


# ---------------------------------------------------------------------------
# bf16 precision gate
# ---------------------------------------------------------------------------


def calibration_record(shape, templates_true, seed: int = 2408,
                       noise_rms: float = 0.02) -> np.ndarray:
    """The deterministic gate record: fixed-seed noise with the ACTUAL
    templates injected at staggered channels/onsets and graded
    amplitudes (strong and near-threshold copies), so the gate scores
    the pick decisions this template set really makes."""
    C, n = int(shape[0]), int(shape[1])
    rng = np.random.default_rng(seed)
    x = rng.normal(0.0, noise_rms, size=(C, n)).astype(np.float32)
    tt = np.atleast_2d(np.asarray(templates_true, np.float32))
    nT, m = tt.shape
    k = 0
    for amp in (0.6, 0.25, 0.1):
        for i in range(nT):
            ch = (k * 7 + 3) % C
            onset = (k * (n // 7) + n // 11) % max(1, n - m)
            x[ch, onset : onset + m] += amp * tt[i]
            k += 1
    return x


def _gate_picks(corr, max_peaks: int = 64):
    """The engine-independent downstream of the gate: reference threshold
    policy -> envelope -> fixed-capacity sparse peaks (the one-program
    route's pick math at quick scale)."""
    from ..models.matched_filter import (
        REL_THRESHOLD,
        reference_threshold_factors,
    )

    env = spectral.envelope_sqrt(corr, axis=-1)
    thr = (REL_THRESHOLD * jnp.max(corr)) * reference_threshold_factors(
        corr.shape[0], corr.dtype
    )
    return peak_ops.find_peaks_sparse_batched(
        env, thr[:, None], max_peaks=max_peaks, method="topk"
    )


#: Gate-record channel cap (the gate is per-channel math; 512 rows of
#: the real record length decide eligibility without canonical temps).
_GATE_MAX_CHANNELS = 512


def gate_key(backend, trace_shape, templates_true, mu, scale) -> str:
    """The bf16 gate's calibration-table key. Includes a CONTENT digest
    of the template triple, not just its shape: the gate record is built
    from the actual templates, so two banks with equal (C, n, m, nT)
    can have different eligibility — a shape-only key would let one
    bank's verdict silently route another bank onto bf16."""
    tt = np.ascontiguousarray(np.atleast_2d(np.asarray(templates_true)),
                              dtype=np.float32)
    digest = hashlib.sha1(
        tt.tobytes()
        + np.ascontiguousarray(mu, np.float32).tobytes()
        + np.ascontiguousarray(scale, np.float32).tobytes()
    ).hexdigest()[:10]
    nT, m = tt.shape
    C, n = int(trace_shape[0]), int(trace_shape[1])
    return f"bf16gate|{backend}|C{C}xN{n}|m{m}T{nT}|t{digest}"


def bf16_correlate_gate(trace_shape, templates_true, mu, scale, *,
                        table: CalibrationTable | None = None,
                        backend: str | None = None,
                        record=None) -> Tuple[bool, str]:
    """Eligibility of the bf16 matmul correlate at ``trace_shape``: picks
    from the bf16 route must be BIT-IDENTICAL to the f32 FFT route on
    the calibration record. Returns ``(eligible, reason)``; the verdict
    (and its reason) is cached in the calibration table per
    (backend, shape, template set) — the rejection path is an auditable
    record, not a silent fallback. ``record`` overrides the built-in
    fixed-seed record (tests pin both gate outcomes with it; an
    explicit record bypasses the cache)."""
    table = table or default_table()
    backend = backend or jax.default_backend()
    tt = np.atleast_2d(np.asarray(templates_true))
    C, n = int(trace_shape[0]), int(trace_shape[1])
    key = gate_key(backend, trace_shape, tt, mu, scale)
    cached = record is None
    if cached:
        hit = table.get(key)
        if hit is not None:
            return bool(hit["eligible"]), str(hit["reason"])
        record = calibration_record((min(C, _GATE_MAX_CHANNELS), n), tt)
    x = jnp.asarray(np.asarray(record, np.float32))
    tt_d = jnp.asarray(tt.astype(np.float32))
    mu_d = jnp.asarray(np.asarray(mu, np.float32))
    sc_d = jnp.asarray(np.asarray(scale, np.float32))
    ref = _gate_picks(
        xcorr.compute_cross_correlograms_corrected(x, tt_d, mu_d, sc_d)
    )
    got = _gate_picks(
        compute_cross_correlograms_matmul(x, tt_d, mu_d, sc_d, bf16=True)
    )
    ref_sel = np.asarray(ref.selected, bool)
    got_sel = np.asarray(got.selected, bool)
    ref_pos = np.asarray(ref.positions)
    got_pos = np.asarray(got.positions)
    sel_same = bool(np.array_equal(ref_sel, got_sel))
    pos_same = bool(np.array_equal(ref_pos[ref_sel], got_pos[ref_sel])) \
        if sel_same else False
    if sel_same and pos_same:
        eligible, reason = True, (
            f"picks bit-identical to the f32 FFT route on the "
            f"[{x.shape[0]}x{n}] calibration record ({int(ref_sel.sum())} "
            f"picks)"
        )
    else:
        n_diff = (
            int((ref_sel != got_sel).sum()) if not sel_same
            else int((ref_pos[ref_sel] != got_pos[ref_sel]).sum())
        )
        what = "pick slots" if not sel_same else "pick positions"
        eligible, reason = False, (
            f"{n_diff} {what} differ from the f32 FFT route on the "
            f"[{x.shape[0]}x{n}] calibration record "
            f"({int(ref_sel.sum())} f32 picks)"
        )
    if cached:
        table.put(key, {"eligible": eligible, "reason": reason})
    return eligible, reason


def fused_gate_key(backend, trace_shape, templates_true, mu, scale,
                   fir) -> str:
    """The fused-tap gate's calibration-table key: the bf16 key's
    content-digest discipline (two banks with equal shapes can gate
    differently) PLUS the FIR in the digest and its half-length in the
    key — a re-designed bandpass re-gates even at identical shapes."""
    tt = np.ascontiguousarray(np.atleast_2d(np.asarray(templates_true)),
                              dtype=np.float32)
    h = np.ascontiguousarray(np.asarray(fir), dtype=np.float32)
    digest = hashlib.sha1(
        tt.tobytes()
        + np.ascontiguousarray(mu, np.float32).tobytes()
        + np.ascontiguousarray(scale, np.float32).tobytes()
        + h.tobytes()
    ).hexdigest()[:10]
    nT, m = tt.shape
    C, n = int(trace_shape[0]), int(trace_shape[1])
    L = (int(h.shape[0]) - 1) // 2
    return f"fusedgate|{backend}|C{C}xN{n}|m{m}T{nT}|L{L}|t{digest}"


def fused_correlate_gate(trace_shape, templates_true, mu, scale, fir,
                         gain_n, *,
                         table: CalibrationTable | None = None,
                         backend: str | None = None,
                         record=None) -> Tuple[bool, str]:
    """Eligibility of the tap-folded correlate at ``trace_shape``: picks
    from the fused route (raw record -> folded-tap contraction) must be
    BIT-IDENTICAL on the calibration record to the staged route's
    (circular ``|H|^2`` gain ``gain_n`` at the record length — the
    fused-mask program's own bandpass spelling — then the f32 FFT
    correlate). The two differ by the FIR truncation tail and by
    linear-vs-circular edge handling within ~``L`` samples of the record
    ends (docs/PRECISION.md), so eligibility is a measured verdict per
    (backend, shape, template set, FIR), cached with its reason exactly
    like :func:`bf16_correlate_gate`; ``record`` pins both outcomes in
    tests and bypasses the cache."""
    from . import filters as filt_ops

    table = table or default_table()
    backend = backend or jax.default_backend()
    tt = np.atleast_2d(np.asarray(templates_true))
    C, n = int(trace_shape[0]), int(trace_shape[1])
    key = fused_gate_key(backend, trace_shape, tt, mu, scale, fir)
    cached = record is None
    if cached:
        hit = table.get(key)
        if hit is not None:
            return bool(hit["eligible"]), str(hit["reason"])
        record = calibration_record((min(C, _GATE_MAX_CHANNELS), n), tt)
    x = jnp.asarray(np.asarray(record, np.float32))
    tt_d = jnp.asarray(tt.astype(np.float32))
    mu_d = jnp.asarray(np.asarray(mu, np.float32))
    sc_d = jnp.asarray(np.asarray(scale, np.float32))
    gain_d = jnp.asarray(np.asarray(gain_n, np.float32))
    folded, tcum, L = fused_template_taps(tt, fir)
    g_ref = filt_ops._fft_zero_phase_jit(x, gain_d, 0)
    ref = _gate_picks(
        xcorr.compute_cross_correlograms_corrected(g_ref, tt_d, mu_d, sc_d)
    )
    got = _gate_picks(
        compute_cross_correlograms_fused(
            x, tt_d, jnp.asarray(folded), jnp.asarray(tcum), mu_d, sc_d, L
        )
    )
    ref_sel = np.asarray(ref.selected, bool)
    got_sel = np.asarray(got.selected, bool)
    ref_pos = np.asarray(ref.positions)
    got_pos = np.asarray(got.positions)
    sel_same = bool(np.array_equal(ref_sel, got_sel))
    pos_same = bool(np.array_equal(ref_pos[ref_sel], got_pos[ref_sel])) \
        if sel_same else False
    if sel_same and pos_same:
        eligible, reason = True, (
            f"picks bit-identical to the staged f32 route on the "
            f"[{x.shape[0]}x{n}] calibration record ({int(ref_sel.sum())} "
            f"picks; L={L})"
        )
    else:
        n_diff = (
            int((ref_sel != got_sel).sum()) if not sel_same
            else int((ref_pos[ref_sel] != got_pos[ref_sel]).sum())
        )
        what = "pick slots" if not sel_same else "pick positions"
        eligible, reason = False, (
            f"{n_diff} {what} differ from the staged f32 route on the "
            f"[{x.shape[0]}x{n}] calibration record "
            f"({int(ref_sel.sum())} staged picks; L={L})"
        )
    if cached:
        table.put(key, {"eligible": eligible, "reason": reason})
    return eligible, reason


def calibrate_correlate_fused(C: int, n: int, m: int, nT: int, L: int, *,
                              table: CalibrationTable | None = None,
                              backend: str | None = None,
                              repeats: int = 2) -> dict:
    """A/B the STAGED chain (circular-gain bandpass program + f32 FFT
    correlate) against the tap-folded single contraction at the given
    shape; measured once on the live backend, cached. Synthetic taps at
    the real (m, L) — the verdict is a wall comparison, eligibility is
    the gate's job."""
    from . import filters as filt_ops

    table = table or default_table()
    backend = backend or jax.default_backend()
    key = f"correlate-fused|{backend}|C{C}xN{n}|m{m}T{nT}|L{L}"
    hit = table.get(key)
    if hit is not None:
        return hit
    Cc = min(int(C), _CAL_MAX_CHANNELS)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(Cc, n)).astype(np.float32))
    tt = jnp.asarray(rng.normal(size=(nT, m)).astype(np.float32))
    mu = jnp.zeros((nT,), jnp.float32)
    sc = jnp.ones((nT,), jnp.float32)
    gain = jnp.asarray(
        rng.uniform(size=(n // 2 + 1,)).astype(np.float32)
    )
    h = rng.normal(size=(2 * int(L) + 1,)).astype(np.float32)
    folded, tcum, _ = fused_template_taps(np.asarray(tt), h)
    folded_d, tcum_d = jnp.asarray(folded), jnp.asarray(tcum)

    def staged():
        g = filt_ops._fft_zero_phase_jit(x, gain, 0)
        return xcorr.compute_cross_correlograms_corrected(g, tt, mu, sc)

    entry = {"cal_channels": Cc}
    entry["staged_s"] = _best_wall(staged, repeats)
    entry["fused_s"] = _best_wall(
        lambda: compute_cross_correlograms_fused(
            x, tt, folded_d, tcum_d, mu, sc, int(L)
        ),
        repeats,
    )
    entry["winner"] = (
        "matmul-fused" if entry["fused_s"] < entry["staged_s"] else "staged"
    )
    table.put(key, entry)
    return entry


# ---------------------------------------------------------------------------
# Engine router
# ---------------------------------------------------------------------------


def resolve_mf_engine(requested, trace_shape, templates_true, mu, scale, *,
                      table: CalibrationTable | None = None,
                      backend: str | None = None,
                      fused_design=None) -> Tuple[str, str]:
    """Resolve the correlate engine for a detector at ``trace_shape``.

    ``requested`` is ``"fft"`` / ``"matmul"`` (forced) /
    ``"matmul-bf16"`` / ``"matmul-fused"`` (forced but still
    precision-gated — an ineligible shape falls back to the f32 matmul
    with the gate's recorded reason) / ``"auto"`` / None (defer to
    ``DAS_MF_ENGINE``, default auto). Auto: the FFT route off-TPU (no
    MXU to win); on TPU the per-shape A/B calibration (measured once,
    cached) picks the faster of fft/matmul, bf16 additionally requires
    the precision gate AND a faster calibrated wall than f32 matmul, and
    the tap-folded engine (considered only when the caller supplies
    ``fused_design``) requires its gate AND a staged-vs-fused A/B win.
    ``fused_design`` is the ``(fir, gain_n)`` pair from the detector's
    bandpass design — the FIR to fold and the record-length circular
    gain the gate references; without it ``matmul-fused`` cannot gate
    and falls back. Returns ``(engine, reason)`` — the reason is
    stamped into bench payloads and planner ledgers."""
    req = requested or config.mf_engine_default()
    if req in ("fft", "matmul"):
        return req, "forced"
    tt = np.atleast_2d(np.asarray(templates_true))
    nT, m = tt.shape
    if req == "matmul-bf16":
        ok, why = bf16_correlate_gate(
            trace_shape, tt, mu, scale, table=table, backend=backend
        )
        if ok:
            return "matmul-bf16", f"forced; precision gate passed: {why}"
        return "matmul", f"bf16 ineligible, f32 matmul fallback: {why}"
    if req == "matmul-fused":
        if fused_design is None:
            return "matmul", (
                "matmul-fused unavailable without the bandpass FIR "
                "(fused_design); f32 matmul fallback"
            )
        fir, gain_n = fused_design
        ok, why = fused_correlate_gate(
            trace_shape, tt, mu, scale, fir, gain_n,
            table=table, backend=backend,
        )
        if ok:
            return "matmul-fused", f"forced; precision gate passed: {why}"
        return "matmul", f"fused-taps ineligible, f32 matmul fallback: {why}"
    if req != "auto":
        raise ValueError(
            f"unknown mf_engine {req!r}; expected one of "
            f"{MF_ENGINES + ('auto',)}"
        )
    backend = backend or jax.default_backend()
    if backend != "tpu":
        return "fft", f"auto: backend {backend!r} has no MXU; FFT route"
    C, n = int(trace_shape[0]), int(trace_shape[1])
    ab = calibrate_correlate(C, n, m, nT, table=table, backend=backend)
    bf16_s = ab.get("matmul_bf16_s", float("inf"))
    best_f32 = min(ab["fft_s"], ab["matmul_s"])
    if bf16_s < best_f32:
        # bf16 outruns BOTH f32 engines (it can win even where fft beats
        # the f32 matmul — the calibration measured it, so consult it):
        # eligible only behind the gate, else fall through to the f32 A/B
        ok, why = bf16_correlate_gate(
            trace_shape, tt, mu, scale, table=table, backend=backend
        )
        if ok:
            return "matmul-bf16", (
                f"auto: A/B matmul-bf16 {bf16_s:.4g}s < best f32 "
                f"{best_f32:.4g}s; precision gate passed: {why}"
            )
        return ab["winner"], (
            f"auto: A/B {ab['winner']} wins at f32 (fft {ab['fft_s']:.4g}s,"
            f" matmul {ab['matmul_s']:.4g}s); bf16 ineligible: {why}"
        )
    if fused_design is not None:
        # the fused A/B compares whole CHAINS (bandpass+correlate vs the
        # single folded contraction), not correlate-only walls — its own
        # calibration entry decides, gated exactly like a forced request
        fir, gain_n = fused_design
        L = (int(np.asarray(fir).shape[0]) - 1) // 2
        abf = calibrate_correlate_fused(
            C, n, m, nT, L, table=table, backend=backend
        )
        if abf["winner"] == "matmul-fused":
            ok, why = fused_correlate_gate(
                trace_shape, tt, mu, scale, fir, gain_n,
                table=table, backend=backend,
            )
            if ok:
                return "matmul-fused", (
                    f"auto: A/B fused {abf['fused_s']:.4g}s < staged "
                    f"{abf['staged_s']:.4g}s; precision gate passed: {why}"
                )
    if ab["winner"] == "fft":
        return "fft", (
            f"auto: A/B fft {ab['fft_s']:.4g}s <= matmul "
            f"{ab['matmul_s']:.4g}s"
        )
    return "matmul", (
        f"auto: A/B matmul {ab['matmul_s']:.4g}s < fft {ab['fft_s']:.4g}s"
    )


def resolve_fk_engine(requested, n_channels, time_samples, band, *,
                      table: CalibrationTable | None = None,
                      backend: str | None = None) -> Tuple[str, str]:
    """Resolve the f-k apply engine at ``n_channels`` (the f-k
    transform's channel count — the padded count for channel-padded
    designs). ``requested``: ``"fft"`` / ``"matmul"`` (forced — the
    caller owns the O(C^2) DFT-matrix memory) / ``"auto"`` / None
    (defer to ``DAS_FK_ENGINE``). Auto: FFT off-TPU; on TPU the matmul
    route only below ``config.fk_matmul_max_channels()`` AND where the
    per-shape A/B calibration says it wins. Returns
    ``(engine, reason)``."""
    req = requested or config.fk_engine_default()
    if req in FK_ENGINES:
        return req, "forced"
    if req != "auto":
        raise ValueError(
            f"unknown fk_engine {req!r}; expected one of "
            f"{FK_ENGINES + ('auto',)}"
        )
    backend = backend or jax.default_backend()
    if backend != "tpu":
        return "fft", f"auto: backend {backend!r} has no MXU; FFT route"
    C = int(n_channels)
    cap = config.fk_matmul_max_channels()
    if C > cap:
        return "fft", (
            f"auto: C={C} above DAS_FK_MATMUL_MAX_CHANNELS={cap} "
            f"(O(C^2) DFT matrix; FFT route)"
        )
    ab = calibrate_fk(C, int(time_samples), 0, int(band), table=table,
                      backend=backend)
    if ab["winner"] == "matmul":
        return "matmul", (
            f"auto: A/B matmul {ab['matmul_s']:.4g}s < fft "
            f"{ab['fft_s']:.4g}s"
        )
    return "fft", (
        f"auto: A/B fft {ab['fft_s']:.4g}s <= matmul {ab['matmul_s']:.4g}s"
    )


def resolve_stft_engine_ab(requested, n_channels, time_samples, nfft, hop, *,
                           table: CalibrationTable | None = None,
                           backend: str | None = None) -> Tuple[str, str]:
    """Resolve the STFT-magnitude engine at the spectro family's sweep
    shape. ``requested``: ``"rfft"`` / ``"matmul"`` / ``"pallas"``
    (forced) / ``"auto"`` / None (defer to ``DAS4WHALES_STFT_ENGINE``,
    default auto). Auto: the rFFT route off-TPU (no MXU to win); on TPU
    the per-shape A/B calibration (measured once, cached) picks the
    fastest of rfft/matmul/pallas. Returns ``(engine, reason)`` — the
    reason is stamped into bench payloads and planner ledgers, exactly
    the :func:`resolve_mf_engine` contract."""
    req = requested or "auto"
    if req == "auto":
        req = os.environ.get("DAS4WHALES_STFT_ENGINE", "auto")
    if req in spectral.STFT_ENGINES:
        return req, "forced"
    if req != "auto":
        raise ValueError(
            f"unknown stft engine {req!r}; expected one of "
            f"{spectral.STFT_ENGINES + ('auto',)}"
        )
    backend = backend or jax.default_backend()
    if backend != "tpu":
        return "rfft", f"auto: backend {backend!r} has no MXU; rFFT route"
    ab = calibrate_stft(int(n_channels), int(time_samples), int(nfft),
                        int(hop), table=table, backend=backend)
    win = ab["winner"]
    detail = ", ".join(
        f"{e} {ab[f'{e}_s']:.4g}s"
        for e in ("rfft", "matmul", "pallas") if f"{e}_s" in ab
    )
    return win, f"auto: A/B {win} wins ({detail})"


def resolve_gabor_engine(requested, image_shape, kernel_shape, *,
                         table: CalibrationTable | None = None,
                         backend: str | None = None) -> Tuple[str, str]:
    """Resolve the gabor family's 2-D same-correlation engine at the
    binned-image shape its oriented-kernel pair actually sweeps.
    ``requested``: ``"fft"`` / ``"conv"`` (forced) / ``"auto"`` / None
    (defer to ``DAS_GABOR_ENGINE``, default auto). Auto: FFT off-TPU;
    on TPU the per-shape A/B calibration decides. Returns
    ``(engine, reason)``."""
    from . import image as image_ops

    req = requested or os.environ.get("DAS_GABOR_ENGINE", "auto")
    if req in image_ops.FILTER2D_ENGINES:
        return req, "forced"
    if req != "auto":
        raise ValueError(
            f"unknown gabor engine {req!r}; expected one of "
            f"{image_ops.FILTER2D_ENGINES + ('auto',)}"
        )
    backend = backend or jax.default_backend()
    if backend != "tpu":
        return "fft", f"auto: backend {backend!r} has no MXU; FFT route"
    H, W = int(image_shape[0]), int(image_shape[1])
    m1, m2 = int(kernel_shape[0]), int(kernel_shape[1])
    ab = calibrate_gabor(H, W, m1, m2, table=table, backend=backend)
    if ab["winner"] == "conv":
        return "conv", (
            f"auto: A/B conv {ab['conv_s']:.4g}s < fft {ab['fft_s']:.4g}s"
        )
    return "fft", (
        f"auto: A/B fft {ab['fft_s']:.4g}s <= conv {ab['conv_s']:.4g}s"
    )


def engine_labels(detector) -> Dict[str, str]:
    """The resolved engine labels a detector rides (empty for families
    without engine routing) — stamped into bench payloads and the
    planner's downshift-ledger rung descriptions so every rung's route
    is auditable."""
    out = {}
    for attr in ("mf_engine", "fk_engine", "pick_engine", "stft_engine",
                 "gabor_engine"):
        val = getattr(detector, attr, None)
        if val:
            out[attr] = str(val)
    return out
