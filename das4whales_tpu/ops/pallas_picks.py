"""Pallas fused envelope → threshold → prominence → peak-pack kernel.

BENCH_r05 stage attribution: envelope+peaks runs at ``roofline_frac <=
0.023`` — the pick stage never saturates the VPU because the jnp route
materializes the ``[nT, C, T]`` envelope, the candidate block tables and
the top-k sort as separate HBM-resident HLO stages (each a full
HBM round trip at the canonical shape). TINA (arXiv:2408.16551) makes
the general point: non-NN DSP reaches accelerator peak only when a
stage chain is fused into one resident program instead of staged passes.

This kernel runs the WHOLE post-correlation pick chain per row block in
one VMEM-resident pass:

* envelope — ``sqrt(re² + im²)`` of the analytic signal
  (``ops.spectral.envelope_sqrt``; the FFT-based Hilbert transform
  itself stays outside — it is a global transform and already
  MXU/FFT-efficient). The ``[rows, T]`` envelope never exists in HBM.
* threshold + plateau-exact local maxima (``ops.peaks.local_maxima``),
* exact scipy prominences via the sqrt-decomposition block tables,
* fixed-capacity slot pack (``"pack"``) or tallest-K (``"topk"``).

The pick math is ``ops.peaks._find_peaks_rows`` — the SAME function the
jnp route executes — applied to the kernel's VMEM block, so the PICK
outputs (``positions``/``selected``/``saturated`` — the only fields the
detection programs consume) are bit-identical to the jnp route; the
parity matrix in tests/test_pallas_picks.py pins them bitwise and the
jnp route remains the fallback and the oracle. The internal
``heights``/``prominences`` floats may differ from the jitted jnp
route in the final ulp (the surrounding jit may fuse the envelope
multiply-adds into FMAs; the kernel rounds each op) — they never leave
the program.

Capability: compiled Mosaic lowering of this kernel needs in-kernel
gathers (``take_along_axis`` over the block axis), scatter-pack, cummax
and (for ``"topk"``) ``lax.top_k`` — newer Mosaic toolchains only.
:func:`lowering_gap` probes the ACTUAL kernel via ``jax.export`` (the
``test_pallas_tpu_lowering`` pattern) and :func:`resolve_engine` only
selects the kernel route on a TPU backend whose toolchain lowers it;
everywhere else the jnp route runs and tier-1 stays green. Off-TPU the
kernel executes in Pallas interpret mode, so CPU tests exercise the
identical kernel code path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import peaks as peak_ops
from . import spectral

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

#: rows per kernel instance — the Mosaic sublane granule for float32
#: (pallas_stft's block-shape lesson: keep the second-to-minor dim a
#: multiple of 8 and never size-1)
ROWS_PER_BLOCK = 8


def _picks_kernel(re_ref, im_ref, thr_ref, pos_ref, h_ref, prom_ref,
                  sel_ref, sat_ref, *, max_peaks: int, nb: int, method: str):
    """One ``[rb, T]`` row block: fused envelope → threshold → prominence
    → slot pack, entirely in VMEM. The pick chain is
    ``ops.peaks._find_peaks_rows`` verbatim — shared with the jnp route."""
    re = re_ref[...]
    im = im_ref[...]
    env = jnp.sqrt(re * re + im * im)       # == spectral.envelope_sqrt
    sp = peak_ops._find_peaks_rows(
        env, thr_ref[...][:, 0], max_peaks, nb, True, method
    )
    pos_ref[...] = sp.positions.astype(jnp.int32)
    h_ref[...] = sp.heights
    prom_ref[...] = sp.prominences
    sel_ref[...] = sp.selected
    sat_ref[...] = sp.saturated[:, None]


@functools.partial(
    jax.jit,
    static_argnames=("max_peaks", "nb", "method", "rows_per_block",
                     "interpret"),
)
def _envelope_peaks_impl(re, im, thr, max_peaks, nb, method, rows_per_block,
                         interpret):
    rows, T = re.shape
    rb = rows_per_block
    r_pad = -(-rows // rb) * rb
    if r_pad != rows:
        pad = [(0, r_pad - rows), (0, 0)]
        re = jnp.pad(re, pad)
        im = jnp.pad(im, pad)
        # +inf threshold: the height prefilter admits no candidate on a
        # padding row (selected all-False, saturated False)
        thr = jnp.pad(thr, pad, constant_values=jnp.inf)
    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    kernel = functools.partial(_picks_kernel, max_peaks=max_peaks, nb=nb,
                               method=method)
    K = max_peaks
    pos, h, prom, sel, sat = pl.pallas_call(
        kernel,
        grid=(r_pad // rb,),
        in_specs=[
            pl.BlockSpec((rb, T), lambda i: (i, 0), **vmem),
            pl.BlockSpec((rb, T), lambda i: (i, 0), **vmem),
            pl.BlockSpec((rb, 1), lambda i: (i, 0), **vmem),
        ],
        out_specs=[
            pl.BlockSpec((rb, K), lambda i: (i, 0), **vmem),
            pl.BlockSpec((rb, K), lambda i: (i, 0), **vmem),
            pl.BlockSpec((rb, K), lambda i: (i, 0), **vmem),
            pl.BlockSpec((rb, K), lambda i: (i, 0), **vmem),
            pl.BlockSpec((rb, 1), lambda i: (i, 0), **vmem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, K), jnp.int32),
            jax.ShapeDtypeStruct((r_pad, K), jnp.float32),
            jax.ShapeDtypeStruct((r_pad, K), jnp.float32),
            jax.ShapeDtypeStruct((r_pad, K), jnp.bool_),
            jax.ShapeDtypeStruct((r_pad, 1), jnp.bool_),
        ],
        interpret=interpret,
    )(re, im, thr)
    return (pos[:rows], h[:rows], prom[:rows], sel[:rows], sat[:rows, 0])


def envelope_peaks_sparse(
    re: jnp.ndarray,
    im: jnp.ndarray,
    threshold,
    max_peaks: int = 256,
    nb: int = 128,
    method: str = "topk",
    interpret: bool | None = None,
) -> peak_ops.SparsePicks:
    """Fused envelope+pick over the analytic signal's (re, im) parts.

    ``re``/``im`` are ``[..., T]`` float32 (leading axes flatten into
    the kernel's row axis and are restored on output); ``threshold``
    broadcasts to ``re.shape[:-1]``. Returns an
    ``ops.peaks.SparsePicks`` identical — bitwise, same ops — to
    ``find_peaks_sparse_batched(sqrt(re²+im²), threshold, ...)``, with
    the envelope, candidate tables and slot pack never leaving VMEM.

    ``interpret=None`` auto-selects: compiled on TPU, interpret mode
    elsewhere (CPU tests run the identical kernel).
    """
    if re.shape != im.shape:
        raise ValueError(f"re/im shape mismatch: {re.shape} vs {im.shape}")
    lead = re.shape[:-1]
    T = re.shape[-1]
    rows = int(np.prod(lead)) if lead else 1
    max_peaks = min(int(max_peaks), T)
    thr = jnp.broadcast_to(jnp.asarray(threshold, jnp.float32), lead)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    pos, h, prom, sel, sat = _envelope_peaks_impl(
        re.reshape(rows, T).astype(jnp.float32),
        im.reshape(rows, T).astype(jnp.float32),
        thr.reshape(rows, 1),
        max_peaks, nb, method, ROWS_PER_BLOCK, bool(interpret),
    )
    K = pos.shape[-1]
    return peak_ops.SparsePicks(
        pos.reshape(lead + (K,)), h.reshape(lead + (K,)),
        prom.reshape(lead + (K,)), sel.reshape(lead + (K,)),
        sat.reshape(lead),
    )


def analytic_envelope_peaks(
    corr: jnp.ndarray,
    threshold,
    max_peaks: int = 256,
    nb: int = 128,
    method: str = "topk",
    interpret: bool | None = None,
) -> peak_ops.SparsePicks:
    """The detection routes' drop-in for ``envelope_sqrt`` +
    ``find_peaks_sparse_batched``: Hilbert transform (batched FFT —
    outside the kernel, it is a global transform) followed by the fused
    envelope→threshold→prominence→pack kernel. ``corr`` is ``[..., T]``
    real correlograms; ``threshold`` broadcasts to ``corr.shape[:-1]``."""
    X = spectral.analytic_signal(corr, axis=-1)
    return envelope_peaks_sparse(
        X.real, X.imag, threshold, max_peaks=max_peaks, nb=nb,
        method=method, interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Capability probe + engine resolution
# ---------------------------------------------------------------------------

_PICK_ENGINES = ("jnp", "pallas")
_gap_cache: dict = {}


def lowering_gap(method: str = "pack") -> str | None:
    """Probe whether THIS toolchain's Mosaic lowers the actual fused
    pick kernel for a TPU target (the ``test_pallas_tpu_lowering``
    pattern: ``jax.export`` runs the real lowering pipeline without a
    chip). Returns the first-line error string naming the gap, or None
    when the kernel lowers. Cached per method for the process."""
    if method in _gap_cache:
        return _gap_cache[method]
    try:
        from jax import export as jax_export
    except ImportError:  # pragma: no cover
        _gap_cache[method] = "jax.export unavailable"
        return _gap_cache[method]

    def f(re, im, thr):
        return _envelope_peaks_impl(re, im, thr, 8, 64, method,
                                    ROWS_PER_BLOCK, False)

    try:
        # daslint: allow[R2] one-shot probe: built at most once per method, memoized in _gap_cache
        jax_export.export(jax.jit(f), platforms=["tpu"])(
            jnp.zeros((8, 256), jnp.float32), jnp.zeros((8, 256), jnp.float32),
            jnp.zeros((8, 1), jnp.float32),
        )
        gap = None
    except Exception as exc:  # noqa: BLE001 — any lowering failure gates
        gap = f"{type(exc).__name__}: {str(exc).splitlines()[0][:160]}"
    _gap_cache[method] = gap
    return gap


def resolve_engine(requested: str | None = None) -> str:
    """Resolve the pick engine for the sparse detection routes.

    ``requested`` is ``"jnp"`` / ``"pallas"`` (forced — ``"pallas"``
    off-TPU runs interpret mode, the tests' parity configuration) /
    ``"auto"`` / None. ``None`` defers to ``DAS_PICK_ENGINE`` (same
    values), defaulting to ``"auto"``: the fused Pallas kernel on a TPU
    backend whose Mosaic lowers it (both pack and topk — the adaptive-K
    policy needs the pair), the jnp route everywhere else.
    """
    req = requested or os.environ.get("DAS_PICK_ENGINE", "") or "auto"
    if req in _PICK_ENGINES:
        return req
    if req != "auto":
        raise ValueError(
            f"unknown pick engine {req!r}; expected one of "
            f"{_PICK_ENGINES + ('auto',)}"
        )
    if jax.default_backend() != "tpu":
        return "jnp"
    if lowering_gap("pack") is None and lowering_gap("topk") is None:
        return "pallas"
    return "jnp"
