"""Device-side DSP kernels (jit/vmap-first)."""

from . import chunked, conditioning, fk, filters, image, peaks, spectral, xcorr  # noqa: F401
