"""Device-side DSP kernels (jit/vmap-first)."""

from . import chunked, conditioning, fk, filters, health, image, mxu, peaks, spectral, xcorr  # noqa: F401
