"""Device-side DSP kernels (jit/vmap-first)."""

from . import chunked, conditioning, fk, filters, health, image, peaks, spectral, xcorr  # noqa: F401
