"""Device-side DSP kernels (jit/vmap-first)."""

from . import chunked, fk, filters, peaks, spectral, xcorr  # noqa: F401
