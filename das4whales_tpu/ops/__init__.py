"""Device-side DSP kernels (jit/vmap-first)."""

from . import chunked, fk, filters, image, peaks, spectral, xcorr  # noqa: F401
