"""Device-side DSP kernels (jit/vmap-first)."""

from . import fk, filters, peaks, spectral, xcorr  # noqa: F401
