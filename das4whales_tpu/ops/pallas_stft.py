"""Pallas MXU short-time Fourier transform (power spectrogram).

TPU-first redesign of the detectors' STFT stage (the reference loops
librosa STFT channel-by-channel, detect.py:382, detect.py:705-707; our
baseline jnp path gathers overlapping frames into HBM, a ``nfft/hop``-fold
materialization — 4-10x for the 75-95 % overlaps the detectors use).

On TPU a small-length FFT is VPU work, while the MXU sits idle; a DFT of
length 128-512 is *cheaper* as a matmul. This kernel therefore:

* folds the periodic Hann window into a real DFT matrix ``[nfft, 2F]``
  (cos | sin halves) once on the host,
* tiles the signal into lightly-overlapping span blocks (~1.2x HBM
  traffic instead of nfft/hop-fold),
* builds the overlapping frames **in VMEM** with static slices,
* runs one ``[frames*channels, nfft] @ [nfft, 2F]`` MXU matmul per grid
  step, and fuses the power ``re^2 + im^2`` before writing back.

Numerics: float32 in/out; the matmul accumulates in float32
(``preferred_element_type``), giving ~1e-6 relative agreement with the
rFFT path. Off-TPU the kernel runs in Pallas interpret mode, so CPU tests
exercise the exact same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _dft_matrix(nfft: int, window: np.ndarray) -> np.ndarray:
    """Windowed real-DFT matrix ``[nfft, 2F]`` with cos|sin halves,
    ``F = nfft//2 + 1``. ``x @ M`` gives (re | -im) of ``rfft(x * win)`` —
    the sign of im cancels in the power."""
    k = np.arange(nfft)[:, None]
    f = np.arange(nfft // 2 + 1)[None, :]
    ang = 2.0 * np.pi * k * f / nfft
    cos = np.cos(ang) * window[:, None]
    sin = np.sin(ang) * window[:, None]
    return np.concatenate([cos, sin], axis=1).astype(np.float32)


def _span_blocks(xp: jnp.ndarray, nb: int, stride: int, span: int) -> jnp.ndarray:
    """[C, T] -> [C, nb, span] overlapping span blocks via shifted reshapes
    (no gather): block b covers ``xp[:, b*stride : b*stride + span]``."""
    c = xp.shape[0]
    n_shift = -(-span // stride)  # ceil
    need = (nb + n_shift - 1) * stride
    if xp.shape[1] < need:
        xp = jnp.pad(xp, ((0, 0), (0, need - xp.shape[1])))
    parts = []
    for s in range(n_shift):
        width = min(stride, span - s * stride)
        seg = xp[:, s * stride : s * stride + nb * stride].reshape(c, nb, stride)
        parts.append(seg[:, :, :width])
    return jnp.concatenate(parts, axis=2)


def _stft_kernel(spans_ref, dft_ref, out_ref, frames_ref, *, fpb, cb, nfft, hop, nfreq):
    # spans_ref [1, cb, span]; frames_ref scratch [fpb, cb, nfft].
    # The block's LAST TWO dims are (cb, span) — cb a multiple of 8, span
    # the full array dim — which is what the Mosaic TPU lowering requires
    # of block shapes; a (cb, 1, span) layout put a size-1 dim second-to-
    # minor and failed to lower on the chip (round-4 on-chip session).
    for i in range(fpb):  # static unroll, static slices
        frames_ref[i, :, :] = spans_ref[0, :, i * hop : i * hop + nfft]
    flat = frames_ref[...].reshape(fpb * cb, nfft)
    prod = jnp.dot(flat, dft_ref[...], preferred_element_type=jnp.float32)
    re = prod[:, :nfreq]
    im = prod[:, nfreq:]
    power = (re * re + im * im).reshape(fpb, cb, nfreq)
    out_ref[...] = jnp.swapaxes(power, 0, 1)  # [cb, fpb, F]


@functools.partial(
    jax.jit,
    static_argnames=("nfft", "hop", "center", "frames_per_block", "channel_block", "interpret"),
)
def _stft_power_impl(x, dftm, nfft, hop, center, frames_per_block, channel_block, interpret):
    c, n = x.shape
    fpb, cb = frames_per_block, channel_block
    nfreq = nfft // 2 + 1

    if center:
        x = jnp.pad(x, ((0, 0), (nfft // 2, nfft // 2)))
        n_frames = 1 + n // hop
    else:
        n_frames = 1 + (n - nfft) // hop

    nf_pad = -(-n_frames // fpb) * fpb
    c_pad = -(-c // cb) * cb
    need = (nf_pad - 1) * hop + nfft
    x = jnp.pad(x, ((0, c_pad - c), (0, max(0, need - x.shape[1]))))

    nb = nf_pad // fpb
    stride = fpb * hop
    span = (fpb - 1) * hop + nfft
    # [nb, c_pad, span]: block-index-major layout so each grid step's block
    # keeps (channels, span) as its last two dims (see _stft_kernel note)
    spans = jnp.swapaxes(_span_blocks(x, nb, stride, span), 0, 1)

    kernel = functools.partial(_stft_kernel, fpb=fpb, cb=cb, nfft=nfft, hop=hop, nfreq=nfreq)
    vmem = {} if _VMEM is None else {"memory_space": _VMEM}
    scratch = (
        [pltpu.VMEM((fpb, cb, nfft), jnp.float32)]
        if pltpu is not None
        else [jax.ShapeDtypeStruct((fpb, cb, nfft), jnp.float32)]
    )
    out = pl.pallas_call(
        kernel,
        grid=(c_pad // cb, nb),
        in_specs=[
            pl.BlockSpec((1, cb, span), lambda ci, bi: (bi, ci, 0), **vmem),
            pl.BlockSpec((nfft, 2 * nfreq), lambda ci, bi: (0, 0), **vmem),
        ],
        out_specs=pl.BlockSpec((cb, fpb, nfreq), lambda ci, bi: (ci, bi, 0), **vmem),
        out_shape=jax.ShapeDtypeStruct((c_pad, nf_pad, nfreq), jnp.float32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(spans, dftm)
    return jnp.swapaxes(out[:c, :n_frames, :], 1, 2)  # [C, F, n_frames]


def stft_power(
    x: jnp.ndarray,
    nfft: int,
    hop: int,
    *,
    window: str = "hann",
    center: bool = True,
    frames_per_block: int = 16,
    channel_block: int = 8,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """``|STFT|^2`` of a ``[channel x time]`` float32 block on the MXU.

    Librosa conventions match :func:`das4whales_tpu.ops.spectral.stft`
    (periodic Hann, centered zero-padding, ``n_frames = 1 + n//hop``).
    Returns ``[channel, nfft//2 + 1, n_frames]`` float32 power.

    ``interpret=None`` auto-selects: compiled on TPU, interpret mode
    elsewhere (so tests on the CPU mesh run the identical kernel).
    """
    if x.ndim != 2:
        raise ValueError(f"expected [channel x time], got shape {x.shape}")
    if hop < 1 or hop > nfft:
        raise ValueError(f"need 1 <= hop <= nfft, got hop={hop}, nfft={nfft}")
    if not center and x.shape[-1] < nfft:
        # matches the rfft path (ops/spectral.py): without centering there is
        # no full frame to take, and silently returning zero frames hides it
        raise ValueError(
            f"center=False needs at least nfft={nfft} samples, got {x.shape[-1]}"
        )
    if window == "hann":
        # periodic Hann, librosa/stft parity
        win = 0.5 * (1.0 - np.cos(2.0 * np.pi * np.arange(nfft) / nfft))
    elif window == "ones":
        win = np.ones(nfft)
    else:
        raise ValueError(f"unknown window {window!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not interpret:
        # compiled Mosaic lowering requires the sublane-position block dims
        # (cb for the spans block, fpb for the output block) to be
        # multiples of 8; interpret mode has no such constraint
        frames_per_block = -(-frames_per_block // 8) * 8
        channel_block = -(-channel_block // 8) * 8
    dftm = jnp.asarray(_dft_matrix(nfft, win))
    return _stft_power_impl(
        jnp.asarray(x, jnp.float32), dftm, nfft, hop, center,
        frames_per_block, channel_block, interpret,
    )
