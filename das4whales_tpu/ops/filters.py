"""Time-domain filtering: Butterworth design, zero-phase IIR, and FFT fast paths.

The reference bandpass-filters the whole ``[channel x time]`` strain block
with ``scipy.signal.filtfilt`` / ``sosfiltfilt`` (dsp.py:859-880,
dsp.py:789-827, tutorial.md:101-124). Zero-phase IIR filtering is inherently
sequential, which is hostile to the MXU, so this module provides two TPU
paths with documented equivalence:

* **exact** — ``lfilter``/``sosfilt`` as a ``lax.scan`` over time (transposed
  direct-form II), wrapped in scipy's odd-extension + ``zi`` initialization
  so ``filtfilt``/``sosfiltfilt`` match scipy to float tolerance. The scan
  is vectorized across all channels, so each sequential step processes the
  full channel axis at once.
* **fft** — one batched rFFT round trip applying the squared Butterworth
  magnitude ``|H(f)|^2`` with zero phase. ``filtfilt``'s steady-state
  response *is* ``|H(f)|^2`` with zero phase; the only difference is edge
  handling, which the FFT path controls with the same odd extension. This is
  the default production path: a single fused FFT over the time axis.

Filter *design* stays on the host (scipy ``butter``), mirroring the
design-once / apply-many split the reference tutorial motivates
(tutorial.md:93).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import scipy.signal as sp


# ---------------------------------------------------------------------------
# Host-side design (coefficients are tiny; scipy is the right tool)
# ---------------------------------------------------------------------------

def butterworth_filter(filterspec, fs: float) -> np.ndarray:
    """Design a Butterworth filter in SOS form.

    Parity with reference ``dsp.butterworth_filter`` (dsp.py:789-827):
    ``filterspec`` is ``(order, critical_freq, btype)`` with critical
    frequencies in Hz.
    """
    order, critical_freq, btype = filterspec
    wn = np.asarray(critical_freq) / (fs / 2)
    return sp.butter(order, wn, btype=btype, output="sos")


def butter_bandpass_ba(order: int, fmin: float, fmax: float, fs: float) -> Tuple[np.ndarray, np.ndarray]:
    """(b, a) coefficients of the reference's bandpass (dsp.py:878)."""
    return sp.butter(order, [fmin / (fs / 2), fmax / (fs / 2)], "bp")


def butter_zero_phase_gain(
    nfft: int, fs: float, band: Tuple[float, float], order: int = 8
) -> np.ndarray:
    """Zero-phase ``|H(f)|^2`` rFFT gain of a Butterworth bandpass for an
    ``nfft``-sample window — the ONE construction shared by the filter
    design (models/matched_filter.py) and every sharded rebuild of it at a
    different window length (parallel/timeshard.py), so the convention
    cannot silently diverge."""
    sos = sp.butter(order, [band[0] / (fs / 2), band[1] / (fs / 2)], "bp", output="sos")
    return zero_phase_gain(np.fft.rfftfreq(nfft), sos).astype(np.float32)


def butter_zero_phase_gain_full(
    nns: int, fs: float, band, order: int = 8
) -> np.ndarray:
    """Zero-phase ``|H(f)|^2`` Butterworth gain on the FFTSHIFTED
    full-frequency grid of an ``nns``-sample window (symmetric in f, so
    folding it into an fftshifted f-k mask BEFORE the Hermitian
    symmetrization is exact) — the one construction behind every
    ``fused_bandpass`` route (models/matched_filter.py,
    parallel/pipeline.py, parallel/timeshard.py)."""
    sos = sp.butter(order, [band[0] / (fs / 2), band[1] / (fs / 2)], "bp", output="sos")
    freqs_cps = np.abs(np.fft.fftshift(np.fft.fftfreq(nns)))
    return zero_phase_gain(freqs_cps, sos).astype(np.float32)


def butter_zero_phase_fir(
    fs: float, band: Tuple[float, float], order: int = 8, *,
    tol: float = 1e-7, max_half: int = 512, design_n: int = 8192,
) -> Tuple[np.ndarray, int]:
    """Memoized front door for ``_butter_zero_phase_fir_design`` — every
    detector construction asks for the same few (fs, band, order)
    designs, so the ~3 ms f64 design grid is paid once per design, not
    per detector. The cached taps are returned read-only (callers only
    convolve against them)."""
    return _butter_zero_phase_fir_design(
        float(fs), (float(band[0]), float(band[1])), int(order),
        tol=float(tol), max_half=int(max_half), design_n=int(design_n),
    )


@functools.lru_cache(maxsize=32)
def _butter_zero_phase_fir_design(
    fs: float, band: Tuple[float, float], order: int = 8, *,
    tol: float = 1e-7, max_half: int = 512, design_n: int = 8192,
) -> Tuple[np.ndarray, int]:
    """Symmetric zero-phase FIR truncation of the Butterworth ``|H(f)|^2``
    impulse response — the TAP-FOLDING half of the one-program slab
    (ops/mxu.py ``fused_template_taps``): convolving a template with this
    kernel folds the bandpass INTO the correlate contraction, so the
    per-channel filter pass over ``[C, time]`` data disappears and its
    cost moves into ``2L`` extra taps inside the existing MXU matmul
    (TINA, arxiv 2408.16551).

    Designed on the host in float64 (the ``dft_matrices`` precedent): the
    gain is sampled on a ``design_n``-point grid (>=40 s at fs=200 —
    far past the Butterworth-8 ring-down), inverse-transformed, and
    truncated to the smallest half-length ``L`` whose discarded tail
    holds ``<= tol`` of the impulse energy (capped at ``max_half``).
    Exact symmetry is enforced (zero phase is the contract the fold's
    correlation-vs-convolution identity rests on). Returns
    ``(h [2L+1] float32, L)``.

    The truncation and the linear (zero-padded) edge handling are WHY
    the folded route is precision-gated (ops/mxu.py
    ``fused_correlate_gate``) rather than declared bit-identical: away
    from the record edges it matches the circular ``|H|^2`` gain to
    ~``sqrt(tol)`` relative; within ~``L`` samples of either edge the
    two differ by the wrap-vs-zero-pad transient (docs/PRECISION.md).
    """
    sos = sp.butter(order, [band[0] / (fs / 2), band[1] / (fs / 2)], "bp",
                    output="sos")
    n = int(design_n)
    # f64 design grid (host, once per design), cast to f32 on return
    gain = zero_phase_gain(np.fft.rfftfreq(n), sos)
    h = np.fft.fftshift(np.fft.irfft(gain, n=n))
    c = n // 2
    total = float(np.sum(h * h))
    L = int(max_half)
    for cand in range(1, int(max_half) + 1):
        seg = h[c - cand: c + cand + 1]
        if total - float(np.sum(seg * seg)) <= tol * total:
            L = cand
            break
    out = h[c - L: c + L + 1]
    out = 0.5 * (out + out[::-1])  # exact evenness: h[-k] == h[k]
    out = out.astype(np.float32)
    out.flags.writeable = False    # lru_cache shares this array
    return out, int(L)


def zero_phase_gain(freqs: np.ndarray, sos: np.ndarray) -> np.ndarray:
    """``|H(f)|^2`` of an SOS filter evaluated at ``freqs`` (cycles/sample
    units handled by the caller). Computed per-section for stability."""
    w = np.asarray(freqs) * 2 * np.pi
    z = np.exp(-1j * w)
    h = np.ones_like(z, dtype=complex)
    for sec in np.atleast_2d(sos):
        b0, b1, b2, a0, a1, a2 = sec
        h *= (b0 + b1 * z + b2 * z**2) / (a0 + a1 * z + a2 * z**2)
    return np.abs(h) ** 2


# ---------------------------------------------------------------------------
# Device-side sequential IIR (exact parity path)
# ---------------------------------------------------------------------------

def lfilter(b, a, x: jnp.ndarray, zi: jnp.ndarray | None = None):
    """Direct-form-II-transposed IIR filter along the last axis.

    Matches ``scipy.signal.lfilter``. The recurrence runs as a single
    ``lax.scan`` over time; every step updates all leading (channel) axes at
    once, so on TPU the per-step work is a wide vector op, not a scalar loop.
    """
    b = jnp.asarray(b, dtype=x.dtype)
    a = jnp.asarray(a, dtype=x.dtype)
    b = b / a[0]
    a = a / a[0]
    order = max(b.shape[0], a.shape[0]) - 1
    bp = jnp.zeros((order + 1,), x.dtype).at[: b.shape[0]].set(b)
    ap = jnp.zeros((order + 1,), x.dtype).at[: a.shape[0]].set(a)

    batch_shape = x.shape[:-1]
    if zi is None:
        z0 = jnp.zeros(batch_shape + (order,), x.dtype)
    else:
        z0 = jnp.broadcast_to(zi, batch_shape + (order,)).astype(x.dtype)

    xt = jnp.moveaxis(x, -1, 0)  # [time, ...batch]

    def step(z, xn):
        yn = bp[0] * xn + z[..., 0]
        # z_i <- b_{i+1} x + z_{i+1} - a_{i+1} y   (transposed DF-II)
        znext = bp[1:] * xn[..., None] - ap[1:] * yn[..., None]
        znext = znext.at[..., :-1].add(z[..., 1:])
        return znext, yn

    zf, yt = jax.lax.scan(step, z0, xt)
    return jnp.moveaxis(yt, 0, -1), zf


def _lfilter_zi(b, a) -> np.ndarray:
    """Steady-state ``zi`` for unit step input (scipy ``lfilter_zi``)."""
    return sp.lfilter_zi(np.asarray(b, float), np.asarray(a, float))


def _odd_ext(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """Odd extension at both ends along the last axis (scipy ``odd_ext``)."""
    left = 2 * x[..., :1] - x[..., n:0:-1]
    right = 2 * x[..., -1:] - x[..., -2 : -(n + 2) : -1]
    return jnp.concatenate([left, x, right], axis=-1)


@functools.partial(jax.jit, static_argnames=("padlen",))
def _filtfilt_jit(b, a, zi, x, padlen: int):
    ext = _odd_ext(x, padlen)
    zi = jnp.asarray(zi, x.dtype)
    y, _ = lfilter(b, a, ext, zi=zi * ext[..., :1])
    y = jnp.flip(y, axis=-1)
    y, _ = lfilter(b, a, y, zi=zi * y[..., :1])
    y = jnp.flip(y, axis=-1)
    return y[..., padlen:-padlen]


def filtfilt(b, a, x: jnp.ndarray, padlen: int | None = None) -> jnp.ndarray:
    """Zero-phase forward-backward IIR filter, scipy-``filtfilt`` parity
    (odd extension, ``lfilter_zi`` edge initialization, default
    ``padlen = 3 * max(len(a), len(b))``)."""
    b = np.asarray(b)
    a = np.asarray(a)
    if padlen is None:
        padlen = 3 * max(len(a), len(b))
    if padlen >= x.shape[-1]:
        raise ValueError("padlen must be less than the signal length")
    zi = _lfilter_zi(b, a)
    return _filtfilt_jit(jnp.asarray(b), jnp.asarray(a), zi, x, padlen)


def sosfilt(sos, x: jnp.ndarray, zi: jnp.ndarray | None = None):
    """Cascaded second-order-section filter along the last axis
    (scipy ``sosfilt``). One ``lax.scan`` runs all sections in sequence per
    time step, vectorized over channels."""
    sos = jnp.asarray(sos, dtype=x.dtype)
    n_sections = sos.shape[0]
    batch_shape = x.shape[:-1]
    if zi is None:
        z0 = jnp.zeros(batch_shape + (n_sections, 2), x.dtype)
    else:
        z0 = jnp.broadcast_to(zi, batch_shape + (n_sections, 2)).astype(x.dtype)

    xt = jnp.moveaxis(x, -1, 0)

    def step(z, xn):
        def section(carry, inputs):
            xcur, z_all = carry
            k = inputs
            b0, b1, b2, _, a1, a2 = sos[k]
            zk = z_all[..., k, :]
            yn = b0 * xcur + zk[..., 0]
            z0n = b1 * xcur - a1 * yn + zk[..., 1]
            z1n = b2 * xcur - a2 * yn
            z_all = z_all.at[..., k, :].set(jnp.stack([z0n, z1n], axis=-1))
            return (yn, z_all), None

        (yn, znew), _ = jax.lax.scan(section, (xn, z), jnp.arange(n_sections))
        return znew, yn

    zf, yt = jax.lax.scan(step, z0, xt)
    return jnp.moveaxis(yt, 0, -1), zf


@functools.partial(jax.jit, static_argnames=("padlen",))
def _sosfiltfilt_jit(sos, zi, x, padlen: int):
    ext = _odd_ext(x, padlen)
    zi = jnp.asarray(zi, x.dtype)
    y, _ = sosfilt(sos, ext, zi=zi * ext[..., 0][..., None, None])
    y = jnp.flip(y, axis=-1)
    y, _ = sosfilt(sos, y, zi=zi * y[..., 0][..., None, None])
    y = jnp.flip(y, axis=-1)
    return y[..., padlen:-padlen]


def sosfiltfilt(sos, x: jnp.ndarray, padlen: int | None = None) -> jnp.ndarray:
    """Zero-phase SOS filter, scipy-``sosfiltfilt`` parity."""
    sos_np = np.atleast_2d(np.asarray(sos))
    if padlen is None:
        ntaps = 2 * sos_np.shape[0] + 1
        padlen = 3 * (ntaps - min((sos_np[:, 2] == 0).sum(), (sos_np[:, 5] == 0).sum()))
    if padlen >= x.shape[-1]:
        raise ValueError("padlen must be less than the signal length")
    zi = sp.sosfilt_zi(sos_np)  # [n_sections, 2]
    return _sosfiltfilt_jit(jnp.asarray(sos_np), jnp.asarray(zi), x, int(padlen))


# ---------------------------------------------------------------------------
# FFT zero-phase fast path (default on TPU)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("padlen",))
def _fft_zero_phase_jit(x, gain, padlen: int):
    ext = _odd_ext(x, padlen) if padlen > 0 else x
    n = ext.shape[-1]
    X = jnp.fft.rfft(ext, axis=-1)
    y = jnp.fft.irfft(X * gain.astype(X.real.dtype), n=n, axis=-1)
    if padlen > 0:
        y = y[..., padlen:-padlen]
    return y.astype(x.dtype)


def fft_zero_phase(x: jnp.ndarray, sos: np.ndarray, padlen: int = 0) -> jnp.ndarray:
    """Apply ``|H(f)|^2`` of an SOS filter with zero phase via one rFFT
    round trip. Spectrally identical to ``filtfilt`` away from the edges;
    ``padlen > 0`` adds the same odd extension to control edge transients."""
    n = x.shape[-1] + 2 * padlen
    freqs = np.fft.rfftfreq(n)
    gain = jnp.asarray(zero_phase_gain(freqs, sos))
    return _fft_zero_phase_jit(x, gain, padlen)


def bp_filt(
    data: jnp.ndarray,
    fs: float,
    fmin: float,
    fmax: float,
    *,
    mode: str = "fft",
) -> jnp.ndarray:
    """Butterworth-8 zero-phase bandpass along time.

    Parity target: reference ``dsp.bp_filt`` (dsp.py:859-880), which runs
    ``filtfilt(butter(8, [fmin, fmax]))`` over every channel.

    ``mode='exact'`` reproduces scipy ``filtfilt`` bit-for-bit-ish via the
    scan path (order-8 direct form; use float64 for stability, as scipy
    does). ``mode='fft'`` (default) applies the identical ``|H(f)|^2``
    response in one batched FFT — the TPU production path.
    """
    if mode == "exact":
        b, a = butter_bandpass_ba(8, fmin, fmax, fs)
        return filtfilt(b, a, data)
    sos = sp.butter(8, [fmin / (fs / 2), fmax / (fs / 2)], "bp", output="sos")
    padlen = 3 * (2 * len(sos) + 1)
    return fft_zero_phase(data, sos, padlen=padlen)
