"""On-device data-health statistics (the quarantine gate).

A campaign file can read cleanly and still be garbage: a NaN-poisoned
slab (failed interrogator write), an ADC-saturated recording, a dead
span of fiber. Pre-taxonomy campaigns marked those ``done`` with
meaningless picks. The health stats here are computed IN THE SAME XLA
program as detection (``models.matched_filter.mf_detect_picks_program
(with_health=True)`` and the batched route) over data the filter stage
was about to read anyway, and ride the program's one packed fetch — no
extra dispatch, no extra device->host round trip. The campaign compares
them against :class:`das4whales_tpu.config.DataHealthConfig` thresholds
and dispositions breaching files ``quarantined`` (``workflows.campaign``,
``das4whales_tpu.faults.DataHealthError``).

Counts, not fractions, cross the wire: at the canonical block size
(2.6e8 samples) a single NaN yields ``1 - 4e-9``, which float32 rounds
back to exactly 1.0 — a fraction-typed stat would silently pass the
default ``max_nonfinite=0`` gate. int32 counts are exact up to 2**31
samples; the host converts to fractions in float64 for reporting.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: Number of scalar slots in the packed health-count vector.
N_COUNTS = 2


def health_stats(x, clip_abs, n_real=None):
    """Per-block health statistics, pure jnp (inline under any jit).

    ``x`` is the detection program's input block ``[..., C, T]`` — raw
    stored-dtype counts on the narrow wire, float strain on the
    conditioned wire; the stats see exactly what detection consumes.
    ``clip_abs`` (traced scalar) is the saturation magnitude: samples
    with ``|x| >= clip_abs`` count as clipped (pass ``inf`` to disable —
    no recompile, it is a traced operand). ``n_real`` (traced scalar or
    None) restricts the stats to the real samples of a bucket-padded
    record, so bucket padding can never dilute a breach below threshold.

    Returns ``(counts int32 [..., 2], rms float32 [...])`` with
    ``counts[..., 0]`` the non-finite sample count, ``counts[..., 1]``
    the clipped sample count, and ``rms`` the root-mean-square over the
    real samples (NaN when the block holds a NaN — itself a breach
    signal, since any rms threshold comparison with NaN reads unhealthy).
    """
    xf = x.astype(jnp.float32)
    finite = jnp.isfinite(xf)
    # clipping is FINITE saturation (ADC rails); non-finite samples are
    # already counted by the first slot and must not double-report
    clipped = (jnp.abs(xf) >= jnp.asarray(clip_abs, jnp.float32)) & finite
    if n_real is not None:
        valid = jnp.arange(x.shape[-1]) < n_real
        n = jnp.asarray(n_real, jnp.float32) * x.shape[-2]
        finite = finite | ~valid
        clipped = clipped & valid
        sq = jnp.where(valid, xf * xf, jnp.zeros((), jnp.float32))
    else:
        n = jnp.float32(x.shape[-1] * x.shape[-2])
        sq = xf * xf
    counts = jnp.stack(
        [
            jnp.sum((~finite).astype(jnp.int32), axis=(-2, -1)),
            jnp.sum(clipped.astype(jnp.int32), axis=(-2, -1)),
        ],
        axis=-1,
    )
    rms = jnp.sqrt(jnp.sum(sq, axis=(-2, -1)) / n)
    return counts, rms


def stats_to_dict(counts, rms, n_samples: int) -> dict:
    """One file's fetched health outputs -> the host-side stats dict the
    quarantine gate (:meth:`DataHealthConfig.breach`) and the manifest
    consume. Fractions are derived in float64 from the exact counts."""
    counts = np.asarray(counts)
    n = max(int(n_samples), 1)
    return {
        "nonfinite": int(counts[0]),
        "clipped": int(counts[1]),
        "nonfinite_frac": float(counts[0]) / n,
        "clip_frac": float(counts[1]) / n,
        "rms": float(rms),
        "n_samples": int(n_samples),
    }


def host_health_stats(arr: np.ndarray, clip_abs: float | None = None) -> dict:
    """Host-side fallback for detector families without the fused
    program (the campaign's generic-adapter path): same stats, numpy,
    one pass over the already-host-resident block."""
    x = np.asarray(arr)
    xf = x.astype(np.float64, copy=False)
    nonfinite = int(np.size(x) - np.count_nonzero(np.isfinite(xf)))
    clipped = (
        int(np.count_nonzero(np.isfinite(xf) & (np.abs(xf) >= float(clip_abs))))
        if clip_abs is not None else 0
    )
    rms = float(np.sqrt(np.mean(np.square(xf))))
    return {
        "nonfinite": nonfinite,
        "clipped": clipped,
        "nonfinite_frac": nonfinite / max(x.size, 1),
        "clip_frac": clipped / max(x.size, 1),
        "rms": rms,
        "n_samples": int(x.size),
    }
