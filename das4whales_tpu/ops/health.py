"""On-device data-health statistics (the quarantine gate + triage profile).

A campaign file can read cleanly and still be garbage: a NaN-poisoned
slab (failed interrogator write), an ADC-saturated recording, a dead
span of fiber. Pre-taxonomy campaigns marked those ``done`` with
meaningless picks. The health stats here are computed IN THE SAME XLA
program as detection (``models.matched_filter.mf_detect_picks_program
(with_health=True)`` and the batched route) over data the filter stage
was about to read anyway, and ride the program's one packed fetch — no
extra dispatch, no extra device->host round trip. The campaign compares
them against :class:`das4whales_tpu.config.DataHealthConfig` thresholds
and dispositions breaching files ``quarantined`` (``workflows.campaign``,
``das4whales_tpu.faults.DataHealthError``).

Counts, not fractions, cross the wire: at the canonical block size
(2.6e8 samples) a single NaN yields ``1 - 4e-9``, which float32 rounds
back to exactly 1.0 — a fraction-typed stat would silently pass the
default ``max_nonfinite=0`` gate. int32 counts are exact up to 2**31
samples; the host converts to fractions in float64 for reporting.

Besides the whole-block scalars, :func:`health_profile` computes a
BOUNDED per-channel-bin profile (ISSUE 15): RMS, clipped/non-finite
counts and dead-channel counts over ~:data:`N_BINS` channel bins, so a
dying fiber span or a clipping ADC bank is *locatable* (the quarantine
verdict names the offending channel range, and the science-quality
observatory — ``telemetry.quality`` — watches the dead fraction and
noise floor drift live). The host transfer stays O(bins), never
O(22k channels): the reduction happens in the detection program and the
bins ride the same packed fetch as the scalars.

The element-level clip/RMS/validity math exists ONCE
(:func:`_element_stats`, parameterized over the array namespace), so
the device path (:func:`health_stats` / :func:`health_profile`, jnp)
and the host fallback (:func:`host_health_stats`, numpy — detector
families without a fused program) can never drift apart.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: Number of scalar slots in the packed health-count vector.
N_COUNTS = 2

#: Per-bin slots in the packed profile count matrix: non-finite,
#: clipped, dead-channel counts (int32, exact — the fraction conversion
#: happens on the host, like the scalar counts).
N_BIN_COUNTS = 3

#: Default channel-bin budget for :func:`health_profile`. ~256 bins
#: keeps the host transfer and the manifest's per-record profile O(100)
#: numbers at the canonical 22050-channel shape (~87 channels/bin)
#: while still localizing a fault to a ~180 m fiber span.
N_BINS = 256


def channel_bins(n_channels: int, n_bins: int | None = None) -> tuple[int, int]:
    """Resolve the per-bin layout for ``n_channels``: ``(bins, per)``
    with ``per = ceil(C / min(n_bins, C))`` channels per bin and
    ``bins = ceil(C / per)`` bins actually needed (the last bin may be
    partial — its real channel count is ``C - (bins - 1) * per``).
    Deterministic per shape, so the profile's program shape is static."""
    c = int(n_channels)
    nb = N_BINS if n_bins is None else int(n_bins)
    nb = max(1, min(nb, max(c, 1)))
    # per >= 1 even for an empty selection: channel_bins(0) resolves to
    # the sensible (0 bins, 1 channel/bin) instead of dividing by zero
    per = max(1, -(-c // nb))
    return -(-c // per), per


def _element_stats(xp, xf, clip_abs, n_real):
    """THE per-element health definition, shared by the device (jnp)
    and host (numpy) paths: ``(finite, clipped, sq)`` masks/values over
    ``xf`` (already float). ``clipped`` is FINITE saturation only (ADC
    rails) — non-finite samples are counted by the first slot and must
    not double-report. ``n_real`` (None or a scalar) restricts the
    stats to the real time samples of a bucket-padded record: pad
    samples read finite, unclipped, and contribute 0 to the sum of
    squares, so padding can never dilute a breach below threshold."""
    finite = xp.isfinite(xf)
    clipped = (xp.abs(xf) >= clip_abs) & finite
    if n_real is not None:
        valid = xp.arange(xf.shape[-1]) < n_real
        finite = finite | ~valid
        clipped = clipped & valid
        sq = xp.where(valid, xf * xf, xp.zeros((), xf.dtype))
    else:
        sq = xf * xf
    return finite, clipped, sq


def health_stats(x, clip_abs, n_real=None):
    """Per-block health statistics, pure jnp (inline under any jit).

    ``x`` is the detection program's input block ``[..., C, T]`` — raw
    stored-dtype counts on the narrow wire, float strain on the
    conditioned wire; the stats see exactly what detection consumes.
    ``clip_abs`` (traced scalar) is the saturation magnitude: samples
    with ``|x| >= clip_abs`` count as clipped (pass ``inf`` to disable —
    no recompile, it is a traced operand). ``n_real`` (traced scalar or
    None) restricts the stats to the real samples of a bucket-padded
    record, so bucket padding can never dilute a breach below threshold.

    Returns ``(counts int32 [..., 2], rms float32 [...])`` with
    ``counts[..., 0]`` the non-finite sample count, ``counts[..., 1]``
    the clipped sample count, and ``rms`` the root-mean-square over the
    real samples (NaN when the block holds a NaN — itself a breach
    signal, since any rms threshold comparison with NaN reads unhealthy).
    """
    xf = x.astype(jnp.float32)
    finite, clipped, sq = _element_stats(
        jnp, xf, jnp.asarray(clip_abs, jnp.float32), n_real
    )
    if n_real is not None:
        n = jnp.asarray(n_real, jnp.float32) * x.shape[-2]
    else:
        n = jnp.float32(x.shape[-1] * x.shape[-2])
    counts = jnp.stack(
        [
            jnp.sum((~finite).astype(jnp.int32), axis=(-2, -1)),
            jnp.sum(clipped.astype(jnp.int32), axis=(-2, -1)),
        ],
        axis=-1,
    )
    rms = jnp.sqrt(jnp.sum(sq, axis=(-2, -1)) / n)
    return counts, rms


def health_profile(x, clip_abs, n_real=None, n_bins: int | None = None,
                   xp=jnp):
    """Per-channel-bin health profile (inline under any jit with the
    default ``xp=jnp``; the host fallback passes ``xp=np`` — like
    :func:`_element_stats`, the binning math exists ONCE so the two
    paths cannot drift).

    Same inputs as :func:`health_stats`; channels are grouped into
    :func:`channel_bins` bins of ``per`` consecutive channels. Returns
    ``(bin_counts int32 [..., bins, 3], bin_rms float32 [..., bins])``
    with slots non-finite / clipped / dead per bin — a channel is DEAD
    when its real samples are all exactly zero (the interrogator wrote
    nothing for that span of fiber; a NaN-poisoned channel is counted
    non-finite, not dead). Pad channels of the last partial bin
    contribute nothing; ``bin_rms`` divides by each bin's REAL channel
    count, so the partial bin's rms is not diluted."""
    c = x.shape[-2]
    nb, per = channel_bins(c, n_bins)
    xf = x.astype(xp.float32)
    finite, clipped, sq = _element_stats(
        xp, xf, xp.asarray(clip_abs, xp.float32), n_real
    )
    nonfinite_ch = xp.sum((~finite).astype(xp.int32), axis=-1)
    clipped_ch = xp.sum(clipped.astype(xp.int32), axis=-1)
    sumsq_ch = xp.sum(sq, axis=-1)
    dead_ch = (sumsq_ch == 0).astype(xp.int32)

    def binned(a):
        pad = nb * per - c
        if pad:
            a = xp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, pad)])
        return xp.sum(a.reshape(a.shape[:-1] + (nb, per)), axis=-1)

    bin_counts = xp.stack(
        [binned(nonfinite_ch), binned(clipped_ch), binned(dead_ch)], axis=-1
    )
    nt = (xp.asarray(n_real, xp.float32) if n_real is not None
          else xp.float32(x.shape[-1]))
    # real channels per bin (only the last bin may be partial) — a
    # static vector, so the rms denominator never counts pad channels
    ch_in_bin = xp.clip(c - per * xp.arange(nb), 0, per).astype(xp.float32)
    bin_rms = xp.sqrt(binned(sumsq_ch) / (ch_in_bin * nt))
    return bin_counts, bin_rms


def health_stats_profiled(x, clip_abs, n_real=None, n_bins: int | None = None):
    """Scalars + per-bin profile for the fused ``with_health`` programs:
    ``(counts, rms, bin_counts, bin_rms)``. The scalar half reduces
    exactly like :func:`health_stats` always did (bitwise-stable
    against pre-profile manifests); the shared element masks are CSE'd
    by XLA under the one jit."""
    counts, rms = health_stats(x, clip_abs, n_real=n_real)
    bin_counts, bin_rms = health_profile(x, clip_abs, n_real=n_real,
                                         n_bins=n_bins)
    return counts, rms, bin_counts, bin_rms


def stats_to_dict(counts, rms, n_samples: int, bin_counts=None, bin_rms=None,
                  n_channels: int | None = None) -> dict:
    """One file's fetched health outputs -> the host-side stats dict the
    quarantine gate (:meth:`DataHealthConfig.breach`) and the manifest
    consume. Fractions are derived in float64 from the exact counts.

    ``bin_counts``/``bin_rms`` (the :func:`health_profile` outputs, with
    ``n_channels`` naming the real channel count) extend the dict with
    the per-bin fields — ``bin_nonfinite`` / ``bin_clipped`` /
    ``bin_dead`` / ``bin_rms`` lists plus ``n_bins`` / ``bin_channels``
    / ``dead_channels`` / ``dead_frac`` — while every pre-profile key
    keeps its exact meaning (back-compat: consumers of the scalar keys
    never see a difference)."""
    counts = np.asarray(counts)
    n = max(int(n_samples), 1)
    out = {
        "nonfinite": int(counts[0]),
        "clipped": int(counts[1]),
        "nonfinite_frac": float(counts[0]) / n,
        "clip_frac": float(counts[1]) / n,
        "rms": float(rms),
        "n_samples": int(n_samples),
    }
    if bin_counts is not None and bin_rms is not None and n_channels:
        bc = np.asarray(bin_counts)
        nb = int(bc.shape[0])
        _, per = channel_bins(int(n_channels),
                              n_bins=nb if nb else None)
        dead = int(bc[:, 2].sum())
        out.update({
            "n_channels": int(n_channels),
            "n_bins": nb,
            "bin_channels": per,
            "bin_nonfinite": [int(v) for v in bc[:, 0]],
            "bin_clipped": [int(v) for v in bc[:, 1]],
            "bin_dead": [int(v) for v in bc[:, 2]],
            "bin_rms": [float(v) for v in np.asarray(bin_rms)],
            "dead_channels": dead,
            "dead_frac": dead / max(int(n_channels), 1),
        })
    return out


def host_health_stats(arr: np.ndarray, clip_abs: float | None = None) -> dict:
    """Host-side fallback for detector families without the fused
    program (the campaign's generic-adapter path): the same element
    definition (:func:`_element_stats`, numpy/float64), one pass over
    the already-host-resident block — including the per-channel-bin
    profile when ``arr`` is a ``[C, T]`` block, so host-stats
    done-records carry the same triage fields as fused ones."""
    x = np.asarray(arr)
    xf = x.astype(np.float64, copy=False)
    clip = float("inf") if clip_abs is None else float(clip_abs)
    finite, clipped, sq = _element_stats(np, xf, clip, None)
    counts = (int(x.size - np.count_nonzero(finite)),
              int(np.count_nonzero(clipped)))
    # empty input keeps the historical NaN rms (mean of nothing): NaN
    # reads UNHEALTHY against any configured rms bound — an empty block
    # must never pass a max_rms gate that a zero would slip through
    rms = (float(np.sqrt(sq.sum() / x.size)) if x.size
           else float("nan"))
    bin_counts = bin_rms = n_channels = None
    if x.ndim == 2 and x.size:
        # the SHARED profile definition at xp=np (one extra numpy pass
        # over the block — host stats accompany host-rung detection, so
        # the pass is noise next to the detect; the device definition's
        # float32 cast applies here too, which is what makes the
        # device==host bin parity exact-by-construction)
        bin_counts, bin_rms = health_profile(x, clip, xp=np)
        n_channels = x.shape[0]
    return stats_to_dict(np.asarray(counts), rms, x.size,
                         bin_counts=bin_counts, bin_rms=bin_rms,
                         n_channels=n_channels)
