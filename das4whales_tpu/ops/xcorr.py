"""Cross-correlation kernels for matched-filter detection.

The reference computes its cross-correlogram with a per-channel Python loop
over ``scipy.signal.correlate`` (detect.py:140-166, the hottest loop in the
flagship pipeline per SURVEY.md §3.1). Here the whole ``[channel x time]``
block correlates against the template in one batched rFFT product: the
template spectrum is computed once and broadcast against all channels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def next_fast_len(n: int) -> int:
    """Smallest 5-smooth (2^a 3^b 5^c) integer >= n.

    Mixed-radix FFTs degrade badly on large prime factors; padding the
    linear-correlation length to a 5-smooth size keeps every rFFT in the
    fast path on both TPU and CPU (same contract as
    ``scipy.fft.next_fast_len``).
    """
    if n <= 6:
        return max(n, 1)
    best = 1 << (n - 1).bit_length()  # upper bound: next power of two
    p5 = 1
    while p5 < best:
        p35 = p5
        while p35 < best:
            # smallest power of two lifting p35 over n
            q = -(-n // p35)  # ceil
            p2 = 1 << max(q - 1, 0).bit_length()
            cand = p2 * p35
            if cand == n:
                return n
            if cand < best:
                best = cand
            p35 *= 3
        p5 *= 5
    return best


def _xcorr_full_len(n: int, m: int) -> int:
    """FFT length for a linear (non-circular) correlation of n and m."""
    return next_fast_len(n + m - 1)


@jax.jit
def shift_xcorr(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Positive-lag full cross-correlation of two equal-length 1-D signals.

    Parity: reference ``detect.shift_xcorr`` (detect.py:96-112) —
    ``correlate(x, y, 'full')[len(x)-1:]``.
    """
    n, m = x.shape[-1], y.shape[-1]
    nfft = _xcorr_full_len(n, m)
    X = jnp.fft.rfft(x, nfft)
    Y = jnp.fft.rfft(y, nfft)
    corr = jnp.fft.irfft(X * jnp.conj(Y), nfft)
    return corr[..., :n]


@jax.jit
def shift_nxcorr(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Std-normalized positive-lag cross-correlation.

    Parity: reference ``detect.shift_nxcorr`` (detect.py:115-137).
    """
    corr = shift_xcorr(x, y)
    return corr / (jnp.std(x) * jnp.std(y) * x.shape[-1])


def _demean_peak_normalize(x: jnp.ndarray, guard_zero: bool = False) -> jnp.ndarray:
    """The reference's per-row normalization (detect.py:140-166): demean
    along the last axis, then divide by the peak magnitude of the RAW row.
    ONE definition shared by every correlogram builder — FFT and matmul
    engines (``ops.mxu``) normalize through this same code, so their
    inputs cannot drift apart. ``guard_zero`` replaces an all-zero row's
    peak with ``tiny`` so padding rows correlate to 0 instead of NaN."""
    mx = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    if guard_zero:
        mx = jnp.maximum(mx, jnp.asarray(jnp.finfo(x.dtype).tiny, x.dtype))
    return (x - jnp.mean(x, axis=-1, keepdims=True)) / mx


def normalized_block_and_suffix(data: jnp.ndarray):
    """Normalized data block + its suffix sums — the engine-independent
    prologue of the true-length-template corrected correlation (see
    ``padded_template_stats`` for the algebra). Returns ``(xn, suffix)``
    with ``suffix[..., k] = sum_{i>=k} xn[..., i]``."""
    xn = _demean_peak_normalize(data, guard_zero=True)
    suffix = jnp.flip(jnp.cumsum(jnp.flip(xn, -1), axis=-1), -1)
    return xn, suffix


def corrected_from_raw(raw, suffix, mu, scale, dtype):
    """Engine-independent epilogue of the corrected correlation: subtract
    the padded-template mean term and rescale (``padded_template_stats``).
    ``raw [nT, ..., n]`` is the positive-lag correlation of the normalized
    block against the TRUE-length templates — from the FFT engine
    (``compute_cross_correlograms_corrected``) or the MXU matmul engine
    (``ops.mxu.compute_cross_correlograms_matmul``)."""
    nd = raw.ndim - 1
    mu_b = mu.reshape((mu.shape[0],) + (1,) * nd)
    scale_b = jnp.asarray(scale).reshape((scale.shape[0],) + (1,) * nd)
    return ((raw - mu_b * suffix[None, ...]) / scale_b).astype(dtype)


@jax.jit
def compute_cross_correlogram(data: jnp.ndarray, template: jnp.ndarray) -> jnp.ndarray:
    """Matched-filter cross-correlogram over all channels.

    Parity: reference ``detect.compute_cross_correlogram``
    (detect.py:140-166): per-channel demean + peak normalization, template
    demean + peak normalization, then positive-lag full correlation. The
    reference's tqdm channel loop (detect.py:163-164) becomes a single
    batched FFT over the channel axis.
    """
    norm_data = _demean_peak_normalize(data)
    t = _demean_peak_normalize(template)

    n, m = data.shape[-1], t.shape[-1]
    nfft = _xcorr_full_len(n, m)
    X = jnp.fft.rfft(norm_data, nfft, axis=-1)
    Y = jnp.fft.rfft(t, nfft)
    corr = jnp.fft.irfft(X * jnp.conj(Y), nfft, axis=-1)
    return corr[..., :n].astype(data.dtype)


@jax.jit
def compute_cross_correlograms_multi(data: jnp.ndarray, templates: jnp.ndarray) -> jnp.ndarray:
    """Matched-filter correlograms for SEVERAL templates with ONE forward
    FFT of the data.

    ``vmap(compute_cross_correlogram)`` over templates recomputes
    ``rfft(norm_data)`` — the most expensive transform in the detection
    step — once per template; here the normalized data spectrum is shared
    and only the (tiny) template spectra and the inverse transforms repeat.
    Returns ``[n_templates, channel, time]``, identical numerics.
    """
    norm_data = _demean_peak_normalize(data)
    t = _demean_peak_normalize(templates)

    n, m = data.shape[-1], t.shape[-1]
    nfft = _xcorr_full_len(n, m)
    X = jnp.fft.rfft(norm_data, nfft, axis=-1)          # once, shared
    Y = jnp.fft.rfft(t, nfft, axis=-1)                  # [nT, F]
    # align [nT, F] against X's arbitrary leading (batch/channel) axes
    Yb = jnp.conj(Y).reshape((Y.shape[0],) + (1,) * (X.ndim - 1) + (Y.shape[-1],))
    corr = jnp.fft.irfft(X[None, ...] * Yb, nfft, axis=-1)
    return corr[..., :n].astype(data.dtype)


def padded_template_stats(templates_padded, device: bool = False):
    """Decompose a trace-length zero-padded template stack into the
    true-length form used by the memory-lean correlate route.

    The reference pads templates to the full trace length before
    correlating (detect.py:68-93 + detect.py:140-166), which forces
    ``nfft = next_fast_len(2n-1)`` — double the FFT length (and, at the
    canonical 22050x12000 OOI shape, >12 GB of one-program temps; the
    round-2 HBM OOM). But the padded-template correlogram is exactly
    recoverable from a true-length correlation: with ``mu`` the mean of the
    padded template and ``s`` its peak magnitude, the reference's
    demeaned/normalized template is ``(y_pad - mu)/s``, so

        corr[k] = (sum_j x[k+j] y_true[j] - mu * suffix_sum(x)[k]) / s

    where ``suffix_sum(x)[k] = sum_{i>=k} x[i]`` (the zero tail of the
    padded template contributes ``-mu`` against every remaining sample).
    Verified exact to machine precision against the padded route.

    Returns ``(templates_true [nT, m], mu [nT], scale [nT])`` as host
    numpy — or as device arrays with ``device=True`` (the form every
    consumer of the triple wants: single-chip detector, batch-sharded and
    time-sharded steps). ONE implementation for both entries, so the host
    and device template numerics cannot drift apart; ``scale`` is each
    template's OWN peak magnitude, matching the reference's
    template-by-template normalization (detect.py:140-166).
    """
    t = np.asarray(templates_padded)
    t = np.atleast_2d(t)
    nz = np.abs(t) > 0
    m = 1
    for row in nz:
        idx = np.nonzero(row)[0]
        if idx.size:
            m = max(m, int(idx[-1]) + 1)
    mu = t.mean(axis=-1)
    scale = np.max(np.abs(t), axis=-1)
    triple = t[:, :m].copy(), mu.astype(t.dtype), scale.astype(t.dtype)
    if device:
        return tuple(jnp.asarray(a) for a in triple)
    return triple


def padded_template_stats_device(templates_padded):
    """The device entry of :func:`padded_template_stats` (same single
    implementation, triple placed on the default device)."""
    return padded_template_stats(templates_padded, device=True)


@jax.jit
def compute_cross_correlograms_corrected(
    data: jnp.ndarray, templates_true: jnp.ndarray, mu: jnp.ndarray, scale: jnp.ndarray
) -> jnp.ndarray:
    """Numerics of ``compute_cross_correlograms_multi(data, padded)`` with
    TRUE-length template FFTs: ``nfft = next_fast_len(n + m - 1)`` instead
    of ``next_fast_len(2n - 1)`` — half the FFT length and half the
    correlate-stage temps at the canonical shape (see
    ``padded_template_stats`` for the exact algebra).

    ``data`` is ``[..., n]`` with arbitrary leading (batch/channel) axes;
    returns ``[nT, ..., n]``.
    """
    n, m = data.shape[-1], templates_true.shape[-1]
    nfft = _xcorr_full_len(n, m)
    xn, suffix = normalized_block_and_suffix(data)
    X = jnp.fft.rfft(xn, nfft, axis=-1)
    Y = jnp.fft.rfft(templates_true, nfft, axis=-1)
    Yb = jnp.conj(Y).reshape((Y.shape[0],) + (1,) * (xn.ndim - 1) + (Y.shape[-1],))
    raw = jnp.fft.irfft(X[None, ...] * Yb, nfft, axis=-1)[..., :n]
    return corrected_from_raw(raw, suffix, mu, scale, data.dtype)


@jax.jit
def fftconvolve_same_time(x: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """FFT convolution along the last (time) axis, ``mode='same'``, batched
    over leading axes. Replaces the reference's
    ``scipy.signal.fftconvolve(..., mode='same', axes=1)`` calls
    (detect.py:597, improcess.py:219)."""
    n, m = x.shape[-1], kernel.shape[-1]
    nfft = _xcorr_full_len(n, m)
    X = jnp.fft.rfft(x, nfft, axis=-1)
    K = jnp.fft.rfft(kernel, nfft, axis=-1)
    full = jnp.fft.irfft(X * K, nfft, axis=-1)[..., : n + m - 1]
    start = (m - 1) // 2
    return full[..., start : start + n]


@jax.jit
def fftconvolve2d_same(x: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """2-D FFT convolution, ``mode='same'``, batched over leading axes.

    Replaces ``scipy.signal.fftconvolve(image, kernel, mode='same')``
    (improcess.py:219) and ``cv2.filter2D``-style correlations when the
    kernel is flipped by the caller.
    """
    n1, n2 = x.shape[-2], x.shape[-1]
    m1, m2 = kernel.shape[-2], kernel.shape[-1]
    s1, s2 = n1 + m1 - 1, n2 + m2 - 1
    X = jnp.fft.rfft2(x, (s1, s2))
    K = jnp.fft.rfft2(kernel, (s1, s2))
    full = jnp.fft.irfft2(X * K, (s1, s2))
    a1, a2 = (m1 - 1) // 2, (m2 - 1) // 2
    return full[..., a1 : a1 + n1, a2 : a2 + n2]
