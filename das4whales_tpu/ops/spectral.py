"""Spectral transforms: analytic signal, STFT, f-x transform, SNR, instantaneous frequency.

TPU-native replacements for the reference's scipy/librosa spectral stack:
``scipy.signal.hilbert`` (used at dsp.py:974, detect.py:192, improcess.py:61),
``librosa.stft`` (dsp.py:66, detect.py:382), ``dsp.get_fx`` (dsp.py:18-38),
``dsp.snr_tr_array`` (dsp.py:956-976) and ``dsp.instant_freq``
(dsp.py:830-856). Everything here is a pure function of jnp arrays, traced
once under ``jit``, and batched over channels with a leading axis instead of
per-channel Python loops.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def hann_window(n: int, *, periodic: bool = False, dtype=jnp.float32) -> jnp.ndarray:
    """Hann window.

    ``periodic=False`` matches ``numpy.hanning`` (the reference's template
    window, detect.py:90,474); ``periodic=True`` matches librosa's STFT
    window convention.
    """
    if n == 1:
        return jnp.ones((1,), dtype=dtype)
    denom = n if periodic else n - 1
    k = jnp.arange(n, dtype=dtype)
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * k / denom)


def tukey_window(n: int, alpha: float = 0.03, dtype=jnp.float32) -> jnp.ndarray:
    """Tukey (tapered cosine) window, matching ``scipy.signal.windows.tukey``
    (the reference's data taper, dsp.py:721)."""
    if alpha <= 0:
        return jnp.ones((n,), dtype=dtype)
    if alpha >= 1:
        return hann_window(n, dtype=dtype)
    k = jnp.arange(n, dtype=dtype)
    width = alpha * (n - 1) / 2.0
    # Rising taper, flat middle, falling taper; expressed branch-free.
    rising = 0.5 * (1 + jnp.cos(jnp.pi * (k / width - 1.0)))
    falling = 0.5 * (1 + jnp.cos(jnp.pi * ((k - (n - 1)) / width + 1.0)))
    w = jnp.where(k < width, rising, jnp.where(k > (n - 1) - width, falling, 1.0))
    return w.astype(dtype)


def analytic_signal(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Analytic signal via the frequency-domain Hilbert multiplier.

    Equivalent to ``scipy.signal.hilbert``: zero out negative frequencies,
    double positive ones. One batched FFT replaces the reference's
    per-channel scipy calls (detect.py:192, dsp.py:974).

    For real input the forward transform is an rFFT and the one-sided
    spectrum (interior bins doubled, Nyquist/DC kept) is zero-extended to
    the full length before the complex inverse — the negative-frequency
    half of ``H * FFT(x)`` is zero anyway, so this is exact while halving
    the forward transform (the envelope stage is FFT-bound at detection
    shapes).
    """
    n = x.shape[axis]
    if jnp.iscomplexobj(x):
        X = jnp.fft.fft(x, axis=axis)
        h = np.zeros(n)
        if n % 2 == 0:
            h[0] = h[n // 2] = 1.0
            h[1 : n // 2] = 2.0
        else:
            h[0] = 1.0
            h[1 : (n + 1) // 2] = 2.0
        shape = [1] * x.ndim
        shape[axis] = n
        H = jnp.asarray(h, dtype=X.real.dtype).reshape(shape)
        return jnp.fft.ifft(X * H, axis=axis)

    spec = jnp.fft.rfft(x, axis=axis)
    nf = spec.shape[axis]
    h = np.ones(nf)
    # double strictly-interior positive bins; DC and (even-n) Nyquist stay
    h[1 : (n + 1) // 2] = 2.0
    shape = [1] * x.ndim
    shape[axis] = nf
    spec = spec * jnp.asarray(h, dtype=spec.real.dtype).reshape(shape)
    pad_shape = list(x.shape)
    pad_shape[axis] = n - nf
    full = jnp.concatenate(
        [spec, jnp.zeros(pad_shape, dtype=spec.dtype)], axis=axis
    )
    return jnp.fft.ifft(full, axis=axis)


def envelope(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Magnitude of the analytic signal (Hilbert envelope)."""
    return jnp.abs(analytic_signal(x, axis=axis))


def envelope_sqrt(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Hilbert envelope as the explicit ``sqrt(re² + im²)`` magnitude.

    Within ~1 ulp of :func:`envelope` (XLA lowers complex ``abs`` to a
    scaled hypot whose final rounding can differ per element), but
    expressed with real elementwise ops only — which is what lets the
    Pallas fused pick kernel (``ops.pallas_picks``) compute THE SAME
    envelope inside the kernel, where complex abs does not lower. Every
    matched-filter detection route uses this form, so per-pick parity
    across routes (jnp fallback ↔ Pallas kernel, staged ↔ one-program,
    single-chip ↔ sharded/time-sharded) stays bitwise instead of
    ulp-close."""
    X = analytic_signal(x, axis=axis)
    return jnp.sqrt(X.real * X.real + X.imag * X.imag)


@functools.partial(jax.jit, static_argnames=("nfft",))
def fx_transform(trace: jnp.ndarray, nfft: int) -> jnp.ndarray:
    """Per-channel FFT magnitude in the f-x domain.

    Parity with reference ``dsp.get_fx`` (dsp.py:18-38): two-sided fftshifted
    magnitude, scaled by ``2/nfft`` and expressed in nanostrain (x1e9).
    """
    fx = 2.0 * jnp.abs(jnp.fft.fftshift(jnp.fft.fft(trace, nfft, axis=-1), axes=-1))
    return fx / nfft * 1e9


def stft(
    x: jnp.ndarray,
    n_fft: int,
    hop: int,
    *,
    window: str = "hann",
    center: bool = True,
) -> jnp.ndarray:
    """Short-time Fourier transform magnitude-ready complex frames.

    Librosa-convention STFT (the reference's spectrogram engine, dsp.py:66,
    detect.py:382): periodic Hann window, centered frames with zero padding,
    output shape ``[..., n_fft//2 + 1, n_frames]`` with
    ``n_frames = 1 + len(x)//hop``. Implemented as a strided gather + batched
    rFFT so a whole ``[channel x time]`` block transforms in one XLA op
    instead of a per-channel loop (detect.py:705-707).
    """
    if window == "hann":
        win = hann_window(n_fft, periodic=True, dtype=x.dtype)
    elif window == "ones":
        win = jnp.ones((n_fft,), dtype=x.dtype)
    else:
        raise ValueError(f"unknown window {window!r}")

    n = x.shape[-1]
    if not center and n < n_fft:
        raise ValueError(
            f"center=False needs at least n_fft={n_fft} samples, got {n}"
        )
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad)
    n_frames = 1 + (n // hop if center else (n - n_fft) // hop)
    idx = np.arange(n_frames)[:, None] * hop + np.arange(n_fft)[None, :]
    frames = x[..., idx] * win  # [..., n_frames, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1)
    return jnp.swapaxes(spec, -1, -2)  # [..., freq, frame]


#: STFT-magnitude engine vocabulary (resolved static values; the routers'
#: external vocabulary adds "auto"). ``rfft`` is the batched-FFT path,
#: ``matmul`` the framed ``[frames, tap] @ [tap, 2F]`` MXU contraction
#: (arxiv 2002.03260), ``pallas`` the fused VMEM-framing TPU kernel.
STFT_ENGINES = ("rfft", "matmul", "pallas")


@functools.lru_cache(maxsize=8)
def _stft_matmul_matrix(nfft: int) -> np.ndarray:
    """Windowed real-DFT matrix ``[nfft, 2F]`` with cos|sin halves,
    ``F = nfft//2 + 1``, periodic Hann folded in: ``frames @ M`` gives
    (re | -im) of ``rfft(frames * win)`` — the sign of im cancels in the
    magnitude. Same design math as ``pallas_stft._dft_matrix`` (host,
    float64 angle grid, cast to f32 once per nfft)."""
    k = np.arange(nfft)[:, None]
    f = np.arange(nfft / 2 + 1)[None, :]
    ang = 2.0 * np.pi * k * f / nfft
    win = 0.5 - 0.5 * np.cos(2.0 * np.pi * np.arange(nfft) / nfft)
    cos = np.cos(ang) * win[:, None]
    sin = np.sin(ang) * win[:, None]
    return np.concatenate([cos, sin], axis=1).astype(np.float32)


def stft_magnitude_matmul(x: jnp.ndarray, nfft: int, hop: int) -> jnp.ndarray:
    """``|STFT|`` as the framed ``[frames, tap] @ [tap, 2F]`` MXU matmul:
    librosa framing identical to :func:`stft` (centered, zero-padded),
    but the window multiply and the DFT fuse into ONE precomputed
    windowed-DFT matrix so the whole transform is a single f32-accumulated
    ``dot_general`` per block (the TINA/2002.03260 recast — on TPU it
    lowers straight onto the MXU). Shapes/conventions identical to
    ``abs(stft(...))``; values agree to matmul-vs-FFT rounding (~1e-6
    relative at f32), so the router only selects it where the decision
    pins hold."""
    n = x.shape[-1]
    pad = [(0, 0)] * (x.ndim - 1) + [(nfft // 2, nfft // 2)]
    xp = jnp.pad(x, pad)
    n_frames = 1 + n // hop
    idx = np.arange(n_frames)[:, None] * hop + np.arange(nfft)[None, :]
    frames = xp[..., idx]  # [..., n_frames, nfft]
    mat = jnp.asarray(_stft_matmul_matrix(nfft))
    proj = jax.lax.dot_general(
        frames, mat, (((frames.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [..., n_frames, 2F]
    nf = nfft // 2 + 1
    re, im = proj[..., :nf], proj[..., nf:]
    mag = jnp.sqrt(re * re + im * im).astype(x.dtype)
    return jnp.swapaxes(mag, -1, -2)  # [..., freq, frame]


def resolve_stft_engine(engine: str = "auto") -> str:
    """Resolve the STFT engine exactly as ``stft_magnitude`` will:
    explicit arg > ``DAS4WHALES_STFT_ENGINE`` env > backend default
    (TPU→pallas, else rfft). Exposed so batch-size heuristics upstream
    (e.g. the spectro detector's channel chunking) can agree with the
    engine that actually runs. The per-shape A/B router (PR 8 pattern)
    is ``ops.mxu.resolve_stft_engine_ab``; forced engines and the env
    override resolve identically through both."""
    import os

    if engine == "auto":
        engine = os.environ.get("DAS4WHALES_STFT_ENGINE", "auto")
    if engine == "auto":
        engine = "pallas" if jax.default_backend() == "tpu" else "rfft"
    if engine not in STFT_ENGINES:
        raise ValueError(f"unknown stft engine {engine!r}")
    return engine


def stft_magnitude(
    x: jnp.ndarray, nfft: int, hop: int, *, engine: str = "auto"
) -> jnp.ndarray:
    """``|STFT|`` with an engine switch: the Pallas MXU-DFT kernel
    (ops/pallas_stft.py) on TPU — framing stays in VMEM instead of a
    ``nfft/hop``-fold HBM materialization — the framed windowed-DFT
    matmul (:func:`stft_magnitude_matmul`), or the batched-rFFT path.
    Shapes/conventions identical to ``abs(stft(...))``.

    ``engine``: ``"auto"`` (env ``DAS4WHALES_STFT_ENGINE`` overrides, then
    TPU→pallas, else rfft), ``"pallas"``, ``"matmul"``, or ``"rfft"``.
    """
    engine = resolve_stft_engine(engine)
    if engine == "rfft":
        return jnp.abs(stft(x, nfft, hop))
    if engine == "matmul":
        return stft_magnitude_matmul(x, nfft, hop)

    from .pallas_stft import stft_power

    lead = x.shape[:-1]
    power = stft_power(x.reshape(-1, x.shape[-1]), nfft, hop)
    return jnp.sqrt(power).reshape(lead + power.shape[1:])


@functools.partial(jax.jit, static_argnames=("nfft", "hop"))
def _spectrogram_db(waveform: jnp.ndarray, nfft: int, hop: int) -> jnp.ndarray:
    mag = jnp.abs(stft(waveform, nfft, hop))
    return 20.0 * jnp.log10(mag / jnp.max(mag))


def spectrogram(
    waveform: jnp.ndarray,
    fs: float,
    nfft: int = 128,
    overlap_pct: float = 0.8,
) -> Tuple[jnp.ndarray, np.ndarray, np.ndarray]:
    """Single-channel spectrogram in dB re max, with time/frequency axes.

    Parity with reference ``dsp.get_spectrogram`` (dsp.py:41-78): hop is
    ``floor(nfft * (1 - overlap_pct))``, output normalized by the global
    maximum, and the axes are linspace ramps over the full duration and
    Nyquist band.
    """
    hop = int(np.floor(nfft * (1 - overlap_pct)))
    p = _spectrogram_db(waveform, nfft, hop)
    height, width = p.shape[-2], p.shape[-1]
    tt = np.linspace(0, waveform.shape[-1] / fs, num=width)
    ff = np.linspace(0, fs / 2, num=height)
    return p, tt, ff


@functools.partial(jax.jit, static_argnames=("env",))
def snr_tr_array(trace: jnp.ndarray, env: bool = False) -> jnp.ndarray:
    """Per-sample SNR in dB against the per-channel standard deviation.

    Parity with reference ``dsp.snr_tr_array`` (dsp.py:956-976); the ``env``
    variant measures the Hilbert envelope instead of the raw samples.
    """
    std = jnp.std(trace, axis=-1, keepdims=True)
    if env:
        num = jnp.abs(analytic_signal(trace, axis=-1)) ** 2
    else:
        num = trace**2
    return 10.0 * jnp.log10(num / std**2)


@jax.jit
def instant_freq(channel: jnp.ndarray, fs: float) -> jnp.ndarray:
    """Instantaneous frequency from the unwrapped analytic phase.

    Parity with reference ``dsp.instant_freq`` (dsp.py:830-856); batched over
    any leading axes.
    """
    phase = jnp.unwrap(jnp.angle(analytic_signal(channel, axis=-1)), axis=-1)
    return jnp.diff(phase, axis=-1) / (2.0 * jnp.pi) * fs


@jax.jit
def taper_data(trace: jnp.ndarray, alpha: float = 0.03) -> jnp.ndarray:
    """Apply a Tukey taper along time (reference ``dsp.taper_data``,
    dsp.py:705-722)."""
    return trace * tukey_window(trace.shape[-1], alpha, dtype=trace.dtype)
