"""Spectral transforms: analytic signal, STFT, f-x transform, SNR, instantaneous frequency.

TPU-native replacements for the reference's scipy/librosa spectral stack:
``scipy.signal.hilbert`` (used at dsp.py:974, detect.py:192, improcess.py:61),
``librosa.stft`` (dsp.py:66, detect.py:382), ``dsp.get_fx`` (dsp.py:18-38),
``dsp.snr_tr_array`` (dsp.py:956-976) and ``dsp.instant_freq``
(dsp.py:830-856). Everything here is a pure function of jnp arrays, traced
once under ``jit``, and batched over channels with a leading axis instead of
per-channel Python loops.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def hann_window(n: int, *, periodic: bool = False, dtype=jnp.float32) -> jnp.ndarray:
    """Hann window.

    ``periodic=False`` matches ``numpy.hanning`` (the reference's template
    window, detect.py:90,474); ``periodic=True`` matches librosa's STFT
    window convention.
    """
    if n == 1:
        return jnp.ones((1,), dtype=dtype)
    denom = n if periodic else n - 1
    k = jnp.arange(n, dtype=dtype)
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * k / denom)


def tukey_window(n: int, alpha: float = 0.03, dtype=jnp.float32) -> jnp.ndarray:
    """Tukey (tapered cosine) window, matching ``scipy.signal.windows.tukey``
    (the reference's data taper, dsp.py:721)."""
    if alpha <= 0:
        return jnp.ones((n,), dtype=dtype)
    if alpha >= 1:
        return hann_window(n, dtype=dtype)
    k = jnp.arange(n, dtype=dtype)
    width = alpha * (n - 1) / 2.0
    # Rising taper, flat middle, falling taper; expressed branch-free.
    rising = 0.5 * (1 + jnp.cos(jnp.pi * (k / width - 1.0)))
    falling = 0.5 * (1 + jnp.cos(jnp.pi * ((k - (n - 1)) / width + 1.0)))
    w = jnp.where(k < width, rising, jnp.where(k > (n - 1) - width, falling, 1.0))
    return w.astype(dtype)


def analytic_signal(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Analytic signal via the frequency-domain Hilbert multiplier.

    Equivalent to ``scipy.signal.hilbert``: zero out negative frequencies,
    double positive ones. One batched FFT replaces the reference's
    per-channel scipy calls (detect.py:192, dsp.py:974).

    For real input the forward transform is an rFFT and the one-sided
    spectrum (interior bins doubled, Nyquist/DC kept) is zero-extended to
    the full length before the complex inverse — the negative-frequency
    half of ``H * FFT(x)`` is zero anyway, so this is exact while halving
    the forward transform (the envelope stage is FFT-bound at detection
    shapes).
    """
    n = x.shape[axis]
    if jnp.iscomplexobj(x):
        X = jnp.fft.fft(x, axis=axis)
        h = np.zeros(n)
        if n % 2 == 0:
            h[0] = h[n // 2] = 1.0
            h[1 : n // 2] = 2.0
        else:
            h[0] = 1.0
            h[1 : (n + 1) // 2] = 2.0
        shape = [1] * x.ndim
        shape[axis] = n
        H = jnp.asarray(h, dtype=X.real.dtype).reshape(shape)
        return jnp.fft.ifft(X * H, axis=axis)

    spec = jnp.fft.rfft(x, axis=axis)
    nf = spec.shape[axis]
    h = np.ones(nf)
    # double strictly-interior positive bins; DC and (even-n) Nyquist stay
    h[1 : (n + 1) // 2] = 2.0
    shape = [1] * x.ndim
    shape[axis] = nf
    spec = spec * jnp.asarray(h, dtype=spec.real.dtype).reshape(shape)
    pad_shape = list(x.shape)
    pad_shape[axis] = n - nf
    full = jnp.concatenate(
        [spec, jnp.zeros(pad_shape, dtype=spec.dtype)], axis=axis
    )
    return jnp.fft.ifft(full, axis=axis)


def envelope(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Magnitude of the analytic signal (Hilbert envelope)."""
    return jnp.abs(analytic_signal(x, axis=axis))


def envelope_sqrt(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Hilbert envelope as the explicit ``sqrt(re² + im²)`` magnitude.

    Within ~1 ulp of :func:`envelope` (XLA lowers complex ``abs`` to a
    scaled hypot whose final rounding can differ per element), but
    expressed with real elementwise ops only — which is what lets the
    Pallas fused pick kernel (``ops.pallas_picks``) compute THE SAME
    envelope inside the kernel, where complex abs does not lower. Every
    matched-filter detection route uses this form, so per-pick parity
    across routes (jnp fallback ↔ Pallas kernel, staged ↔ one-program,
    single-chip ↔ sharded/time-sharded) stays bitwise instead of
    ulp-close."""
    X = analytic_signal(x, axis=axis)
    return jnp.sqrt(X.real * X.real + X.imag * X.imag)


@functools.partial(jax.jit, static_argnames=("nfft",))
def fx_transform(trace: jnp.ndarray, nfft: int) -> jnp.ndarray:
    """Per-channel FFT magnitude in the f-x domain.

    Parity with reference ``dsp.get_fx`` (dsp.py:18-38): two-sided fftshifted
    magnitude, scaled by ``2/nfft`` and expressed in nanostrain (x1e9).
    """
    fx = 2.0 * jnp.abs(jnp.fft.fftshift(jnp.fft.fft(trace, nfft, axis=-1), axes=-1))
    return fx / nfft * 1e9


def stft(
    x: jnp.ndarray,
    n_fft: int,
    hop: int,
    *,
    window: str = "hann",
    center: bool = True,
) -> jnp.ndarray:
    """Short-time Fourier transform magnitude-ready complex frames.

    Librosa-convention STFT (the reference's spectrogram engine, dsp.py:66,
    detect.py:382): periodic Hann window, centered frames with zero padding,
    output shape ``[..., n_fft//2 + 1, n_frames]`` with
    ``n_frames = 1 + len(x)//hop``. Implemented as a strided gather + batched
    rFFT so a whole ``[channel x time]`` block transforms in one XLA op
    instead of a per-channel loop (detect.py:705-707).
    """
    if window == "hann":
        win = hann_window(n_fft, periodic=True, dtype=x.dtype)
    elif window == "ones":
        win = jnp.ones((n_fft,), dtype=x.dtype)
    else:
        raise ValueError(f"unknown window {window!r}")

    n = x.shape[-1]
    if not center and n < n_fft:
        raise ValueError(
            f"center=False needs at least n_fft={n_fft} samples, got {n}"
        )
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad)
    n_frames = 1 + (n // hop if center else (n - n_fft) // hop)
    idx = np.arange(n_frames)[:, None] * hop + np.arange(n_fft)[None, :]
    frames = x[..., idx] * win  # [..., n_frames, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1)
    return jnp.swapaxes(spec, -1, -2)  # [..., freq, frame]


def resolve_stft_engine(engine: str = "auto") -> str:
    """Resolve the STFT engine exactly as ``stft_magnitude`` will:
    explicit arg > ``DAS4WHALES_STFT_ENGINE`` env > backend default
    (TPU→pallas, else rfft). Exposed so batch-size heuristics upstream
    (e.g. the spectro detector's channel chunking) can agree with the
    engine that actually runs."""
    import os

    if engine == "auto":
        engine = os.environ.get("DAS4WHALES_STFT_ENGINE", "auto")
    if engine == "auto":
        engine = "pallas" if jax.default_backend() == "tpu" else "rfft"
    if engine not in ("pallas", "rfft"):
        raise ValueError(f"unknown stft engine {engine!r}")
    return engine


def stft_magnitude(
    x: jnp.ndarray, nfft: int, hop: int, *, engine: str = "auto"
) -> jnp.ndarray:
    """``|STFT|`` with an engine switch: the Pallas MXU-DFT kernel
    (ops/pallas_stft.py) on TPU — framing stays in VMEM instead of a
    ``nfft/hop``-fold HBM materialization — or the batched-rFFT path
    elsewhere. Shapes/conventions identical to ``abs(stft(...))``.

    ``engine``: ``"auto"`` (env ``DAS4WHALES_STFT_ENGINE`` overrides, then
    TPU→pallas, else rfft), ``"pallas"``, or ``"rfft"``.
    """
    engine = resolve_stft_engine(engine)
    if engine == "rfft":
        return jnp.abs(stft(x, nfft, hop))

    from .pallas_stft import stft_power

    lead = x.shape[:-1]
    power = stft_power(x.reshape(-1, x.shape[-1]), nfft, hop)
    return jnp.sqrt(power).reshape(lead + power.shape[1:])


@functools.partial(jax.jit, static_argnames=("nfft", "hop"))
def _spectrogram_db(waveform: jnp.ndarray, nfft: int, hop: int) -> jnp.ndarray:
    mag = jnp.abs(stft(waveform, nfft, hop))
    return 20.0 * jnp.log10(mag / jnp.max(mag))


def spectrogram(
    waveform: jnp.ndarray,
    fs: float,
    nfft: int = 128,
    overlap_pct: float = 0.8,
) -> Tuple[jnp.ndarray, np.ndarray, np.ndarray]:
    """Single-channel spectrogram in dB re max, with time/frequency axes.

    Parity with reference ``dsp.get_spectrogram`` (dsp.py:41-78): hop is
    ``floor(nfft * (1 - overlap_pct))``, output normalized by the global
    maximum, and the axes are linspace ramps over the full duration and
    Nyquist band.
    """
    hop = int(np.floor(nfft * (1 - overlap_pct)))
    p = _spectrogram_db(waveform, nfft, hop)
    height, width = p.shape[-2], p.shape[-1]
    tt = np.linspace(0, waveform.shape[-1] / fs, num=width)
    ff = np.linspace(0, fs / 2, num=height)
    return p, tt, ff


@functools.partial(jax.jit, static_argnames=("env",))
def snr_tr_array(trace: jnp.ndarray, env: bool = False) -> jnp.ndarray:
    """Per-sample SNR in dB against the per-channel standard deviation.

    Parity with reference ``dsp.snr_tr_array`` (dsp.py:956-976); the ``env``
    variant measures the Hilbert envelope instead of the raw samples.
    """
    std = jnp.std(trace, axis=-1, keepdims=True)
    if env:
        num = jnp.abs(analytic_signal(trace, axis=-1)) ** 2
    else:
        num = trace**2
    return 10.0 * jnp.log10(num / std**2)


@jax.jit
def instant_freq(channel: jnp.ndarray, fs: float) -> jnp.ndarray:
    """Instantaneous frequency from the unwrapped analytic phase.

    Parity with reference ``dsp.instant_freq`` (dsp.py:830-856); batched over
    any leading axes.
    """
    phase = jnp.unwrap(jnp.angle(analytic_signal(channel, axis=-1)), axis=-1)
    return jnp.diff(phase, axis=-1) / (2.0 * jnp.pi) * fs


@jax.jit
def taper_data(trace: jnp.ndarray, alpha: float = 0.03) -> jnp.ndarray:
    """Apply a Tukey taper along time (reference ``dsp.taper_data``,
    dsp.py:705-722)."""
    return trace * tukey_window(trace.shape[-1], alpha, dtype=trace.dtype)
