"""On-device conditioning of raw interrogator counts (the narrow wire).

The reference conditions on the host — ``raw2strain`` (data_handle.py:
157-177) runs numpy demean+scale on the Python thread, so the block that
crosses host→device is already float32 strain. That makes the wire wide:
an int16 TDMS file inflates 2× (int32 stays 1×) before it ever reaches
HBM, and at the canonical OOI shape the conditioned block is ~1 GB of
host→device traffic per 60 s file — the dominant *unattributed* share of
the measured on-chip wall (docs/PERF.md stage table). Large-Scale DFT on
TPUs (arXiv:2002.03260) makes the general argument: keep data
device-resident and move the minimum over the wire.

This module is the other half of ``io``'s ``wire="raw"`` mode: the
stored-dtype counts cross the wire untouched and the SAME affine map the
host readers apply — ``(x.astype(f32) - mean(x, time)) * scale_factor``
— runs on device, fused into the head of whichever detection program
consumes the block (``models/matched_filter.py:mf_detect_picks_program``,
``parallel/pipeline.py:_mf_body``, ``parallel/timeshard.py``). Fused,
the conditioning costs one extra pass over data the filter stage was
about to read anyway; the wire shrinks 2× (int16) with bit-identical
pick output (same map, same order, device reduction).

Functions here are pure jnp and safe to inline under jit/shard_map; the
jitted wrappers at the bottom serve callers that condition as a
standalone step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def condition(trace: jnp.ndarray, scale, *, demean: bool = True,
              dtype=jnp.float32) -> jnp.ndarray:
    """Raw stored-dtype counts -> strain, on device.

    The exact affine map of the host conditioning path
    (``io/stream.py:_read_h5py_host``; reference data_handle.py:157-177):
    cast to ``dtype``, demean each channel along time, multiply by the
    interrogator scale factor. Pure function — inline it under any jit or
    shard_map body whose TIME axis is local (per-channel means are then
    shard-local; a time-sharded layout needs
    :func:`condition_time_sharded`).
    """
    x = trace.astype(dtype)
    if demean:
        x = x - jnp.mean(x, axis=-1, keepdims=True)
    return x * jnp.asarray(scale, dtype)


def condition_time_sharded(trace: jnp.ndarray, scale, axis_name: str,
                           n_time_global: int, *, demean: bool = True,
                           dtype=jnp.float32) -> jnp.ndarray:
    """:func:`condition` for a shard_map body whose TIME axis is sharded.

    The per-channel mean spans shards, so it is computed as a ``psum`` of
    local sums over ``axis_name`` divided by the GLOBAL time length —
    one scalar-per-channel collective, not a data transpose. Reduction
    order differs from the single-device mean by float roundoff only.

    ``n_time_global`` smaller than the sharded record length means the
    tail is divisibility zero-padding: the pad contributes nothing to
    the sum (raw zeros), and its samples are masked back to exactly 0
    after the demean — the conditioned wire pads AFTER conditioning, so
    leaving ``-mean*scale`` in the pad would break raw/conditioned
    parity through the record-length FFT.
    """
    x = trace.astype(dtype)
    if demean:
        m = jax.lax.psum(jnp.sum(x, axis=-1, keepdims=True), axis_name)
        x = x - m / n_time_global
        local = x.shape[-1]
        pos = jax.lax.axis_index(axis_name) * local + jnp.arange(local)
        x = jnp.where(pos < n_time_global, x, jnp.zeros((), dtype))
    return x * jnp.asarray(scale, dtype)


def condition_segmented(trace: jnp.ndarray, scale, seg_ids: jnp.ndarray,
                        seg_means: jnp.ndarray, *,
                        dtype=jnp.float32) -> jnp.ndarray:
    """:func:`condition` for a CONCATENATED multi-file record (the
    long-record workflow): the conditioned wire demeans each FILE
    separately before concatenation, so the raw wire must subtract
    per-file means, not one whole-record mean — files carry different DC
    count offsets (routine interrogator drift) and a global demean leaves
    a step at every file boundary whose filtered transient shifts picks.

    ``seg_ids`` maps each (local) time sample to its file's column in
    ``seg_means`` (``[channel x n_segments]``, float32). The means are
    computed on the HOST from the raw block with the same numpy
    reduction the conditioned readers use — element-wise subtract and
    scale are then the only device ops, so conditioned values are
    bit-identical to the host route (no reduction-order roundoff at
    all). Divisibility padding maps to a trailing all-zero mean column:
    pad samples condition to exactly 0, matching the conditioned wire's
    pad-after-conditioning zeros. Layout-agnostic along time (slice
    ``seg_ids`` with the local shard window under shard_map).
    """
    x = trace.astype(dtype)
    x = x - seg_means.astype(dtype)[:, seg_ids]
    return x * jnp.asarray(scale, dtype)


def condition_padded(trace: jnp.ndarray, scale, n_real, *,
                     demean: bool = True, dtype=jnp.float32) -> jnp.ndarray:
    """:func:`condition` for a time-PADDED record: ``trace`` is
    ``[..., T_bucket]`` raw counts whose REAL samples are
    ``[..., :n_real]`` and whose tail is bucket-padding zeros (the batched
    campaign's shape buckets, ``io.stream.stream_batched_slabs``).

    The per-channel mean spans only the real samples (masked sum divided
    by ``n_real`` — the pad contributes nothing, it is raw zeros) and pad
    samples are masked back to exactly 0 after the demean: the
    conditioned wire pads AFTER conditioning, so leaving ``-mean*scale``
    in the pad would break raw/conditioned parity through the
    bucket-length FFT. ``n_real`` may be a traced scalar, so ONE compiled
    program serves every real length inside a bucket. Reduction order
    over the padded axis differs from the exact-length ``jnp.mean`` by
    float roundoff only (same caveat as :func:`condition_time_sharded`);
    picks are unaffected — a per-channel constant offset is annihilated
    by the DC-killing bandpass/f-k filters and peak prominence is
    offset-invariant.
    """
    x = trace.astype(dtype)
    valid = jnp.arange(x.shape[-1]) < n_real
    if demean:
        s = jnp.sum(jnp.where(valid, x, jnp.zeros((), dtype)),
                    axis=-1, keepdims=True)
        x = x - s / jnp.asarray(n_real, dtype)
    x = jnp.where(valid, x, jnp.zeros((), dtype))
    return x * jnp.asarray(scale, dtype)


@functools.partial(jax.jit, static_argnames=("demean",))
def condition_jit(trace: jnp.ndarray, scale, demean: bool = True) -> jnp.ndarray:
    """Standalone jitted prologue for callers that must KEEP the raw
    buffer alive (the adaptive-K routes rerun the program on the same
    input, so the detector cannot donate it)."""
    return condition(trace, scale, demean=demean)


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("demean",))
def condition_donated(trace: jnp.ndarray, scale, demean: bool = True) -> jnp.ndarray:
    """:func:`condition_jit` with the raw input buffer DONATED — the
    narrow-wire block is dead the moment strain exists, so callers that
    own their buffer (fresh from the ingest stream, no rerun planned)
    should hand it back to XLA instead of holding both copies in HBM.
    Donation is a no-op on backends that do not implement it (CPU)."""
    return condition(trace, scale, demean=demean)
