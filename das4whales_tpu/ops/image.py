"""Image-processing kernels for the t-x-plane detector family.

TPU-native replacements for the reference's OpenCV / torch / skimage stack
(improcess.py): Gabor kernels (cv2.getGaborKernel, improcess.py:123),
Gaussian blur (cv2.GaussianBlur, improcess.py:391; scipy.ndimage
gaussian_filter, improcess.py:446), bilateral filtering (improcess.py:284),
Canny edges + Hough lines (improcess.py:291-307), the Radon transform
(improcess.py:366), image binning (torchvision Resize, improcess.py:418-420)
and the small convolution-based edge detectors (improcess.py:172-266).
Everything is jnp: convolutions lower to XLA ``conv_general_dilated`` /
batched FFTs, resampling to ``jax.image.resize`` and gathers to
``map_coordinates``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .spectral import analytic_signal
from .xcorr import fftconvolve2d_same


# ---------------------------------------------------------------------------
# Intensity scaling (improcess.py:23-63)
# ---------------------------------------------------------------------------

@jax.jit
def scale_pixels(img: jnp.ndarray) -> jnp.ndarray:
    """Min-max scale to [0, 1] (improcess.py:23-41)."""
    return (img - jnp.min(img)) / (jnp.max(img) - jnp.min(img))


@jax.jit
def trace2image(trace: jnp.ndarray) -> jnp.ndarray:
    """Per-channel std-normalized Hilbert envelope scaled to [0, 255]
    (improcess.py:44-63)."""
    env = jnp.abs(analytic_signal(trace, axis=-1))
    img = env / jnp.std(trace, axis=-1, keepdims=True)
    return scale_pixels(img) * 255.0


def angle_fromspeed(c0: float, fs: float, dx: float, selected_channels, verbose: bool = False) -> float:
    """Orientation (degrees) of a c0-speed wavefront in the decimated t-x
    image (improcess.py:66-95)."""
    step = selected_channels[2] if not np.isscalar(selected_channels) else selected_channels
    ratio = c0 / (fs * dx * step)
    theta = float(np.arctan(ratio) * 180 / np.pi)
    if verbose:
        print("Detection speed ratio: ", ratio)
        print("Angle: ", theta)
    return theta


# ---------------------------------------------------------------------------
# Kernels and convolutions
# ---------------------------------------------------------------------------

def gabor_kernel(
    ksize: int, sigma: float, theta: float, lambd: float, gamma: float, psi: float = 0.0
) -> np.ndarray:
    """Gabor kernel with OpenCV ``getGaborKernel`` conventions (including
    its index flip), so the designed filters match the reference's
    (improcess.py:116-124) to float precision."""
    # cv2 evaluates f(x, y) for x, y in [-ksize//2, ksize//2] inclusive and
    # stores it at kernel[ymax - y, xmax - x] — note the resulting kernel is
    # (2*(ksize//2)+1) square, i.e. 101x101 for the reference's ksize=100
    xmax = ksize // 2
    n = 2 * xmax + 1
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    y = xmax - ii
    x = xmax - jj
    xr = x * np.cos(theta) + y * np.sin(theta)
    yr = -x * np.sin(theta) + y * np.cos(theta)
    return np.exp(-(xr**2 + (gamma * yr) ** 2) / (2 * sigma**2)) * np.cos(2 * np.pi * xr / lambd + psi)


def gabor_filt_design(theta_c0: float, ksize: int = 100, sigma: float = 4.0,
                      lambd: float = 20.0, gamma: float = 0.15) -> Tuple[np.ndarray, np.ndarray]:
    """Up/down Gabor pair oriented along the sound-speed slope
    (improcess.py:98-140: theta = pi/2 + theta_c0, down = flipud(up))."""
    theta = np.pi / 2 + np.deg2rad(theta_c0)
    up = gabor_kernel(ksize, sigma, theta, lambd, gamma)
    return up, np.flipud(up)


#: 2-D same-correlation engines (resolved static values; the router's
#: external vocabulary adds "auto"): ``fft`` is the batched-FFT product,
#: ``conv`` the ``lax.conv_general_dilated`` im2col matmul with f32
#: accumulation — on TPU it lowers straight onto the MXU (the TINA
#: recast, arxiv 2408.16551).
FILTER2D_ENGINES = ("fft", "conv")


def _conv2d_corr(img: jnp.ndarray, kernel: jnp.ndarray, pad) -> jnp.ndarray:
    """Cross-correlation of ``img``'s trailing [H, W] plane with one
    [m1, m2] kernel via ``conv_general_dilated`` (XLA's im2col matmul;
    the ML convention does NOT flip — exactly cv2.filter2D), f32
    accumulation, leading axes folded into the conv batch."""
    lead = img.shape[:-2]
    lhs = img.reshape((-1, 1) + img.shape[-2:])      # [batch, feat=1, H, W]
    rhs = kernel[None, None, :, :]                   # [out=1, in=1, m1, m2]
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1, 1), padding=pad,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(lead + out.shape[-2:]).astype(img.dtype)


@functools.partial(jax.jit, static_argnames=("border", "engine"))
def filter2d_same(img: jnp.ndarray, kernel: jnp.ndarray, border: str = "reflect",
                  engine: str = "fft") -> jnp.ndarray:
    """Correlation (cv2.filter2D semantics: the kernel is NOT flipped) in
    'same' geometry, batched over leading axes.

    ``border='reflect'`` (numpy reflect == cv2's default BORDER_REFLECT_101)
    matches ``cv2.filter2D``'s edge handling; ``border='constant'``
    zero-pads like scipy's fftconvolve.

    ``engine='fft'`` runs the batched-FFT product; ``engine='conv'`` runs
    the SAME geometry as a ``conv_general_dilated`` im2col matmul with f32
    accumulation (MXU on TPU). Outputs agree to matmul-vs-FFT rounding;
    the router (``ops.mxu.resolve_gabor_engine``) decides per shape."""
    m1, m2 = kernel.shape[-2], kernel.shape[-1]
    a1, a2 = (m1 - 1) // 2, (m2 - 1) // 2
    b1, b2 = m1 - 1 - a1, m2 - 1 - a2
    if engine == "conv":
        kernel = jnp.asarray(kernel, dtype=img.dtype)
        if border == "constant":
            # zero-pad low by b (the FFT path's same-crop anchor for
            # even kernels) so both engines share one alignment
            return _conv2d_corr(img, kernel, [(b1, a1), (b2, a2)])
        pad = [(0, 0)] * (img.ndim - 2) + [(a1, b1), (a2, b2)]
        return _conv2d_corr(jnp.pad(img, pad, mode=border), kernel,
                            [(0, 0), (0, 0)])
    if engine != "fft":
        raise ValueError(
            f"unknown filter2d engine {engine!r}; expected one of "
            f"{FILTER2D_ENGINES}"
        )
    flipped = jnp.flip(jnp.flip(kernel, axis=-1), axis=-2)
    if border == "constant":
        return fftconvolve2d_same(img, flipped)
    pad = [(0, 0)] * (img.ndim - 2) + [(a1, b1), (a2, b2)]
    x = jnp.pad(img, pad, mode=border)
    out = fftconvolve2d_same(x, flipped)
    return out[..., b1 : b1 + img.shape[-2], b2 : b2 + img.shape[-1]]


def _gaussian_1d(sigma: float, radius: int) -> np.ndarray:
    x = np.arange(-radius, radius + 1)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return k / k.sum()


@functools.partial(jax.jit, static_argnames=("sigma", "truncate", "mode"))
def gaussian_filter2d(img: jnp.ndarray, sigma: float, truncate: float = 4.0, mode: str = "symmetric") -> jnp.ndarray:
    """Separable Gaussian smoothing matching ``scipy.ndimage.gaussian_filter``
    (default reflect mode, radius = int(truncate*sigma + 0.5)) — the smoother
    the reference applies to f-k masks (dsp.py:540) and image masks
    (improcess.py:446)."""
    radius = int(truncate * float(sigma) + 0.5)
    k = jnp.asarray(_gaussian_1d(float(sigma), radius), dtype=img.dtype)
    pad = [(0, 0)] * (img.ndim - 2) + [(radius, radius), (radius, radius)]
    x = jnp.pad(img, pad, mode=mode)
    # two separable valid-mode passes over the padded block
    x = _conv1d_last(x, k)
    x = jnp.swapaxes(_conv1d_last(jnp.swapaxes(x, -1, -2), k), -1, -2)
    return x


def _conv1d_last(x: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Valid-mode 1-D convolution along the last axis (symmetric kernel)."""
    n = k.shape[0]
    out = jnp.zeros(x.shape[:-1] + (x.shape[-1] - n + 1,), x.dtype)
    for i in range(n):
        out = out + k[i] * x[..., i : x.shape[-1] - n + 1 + i]
    return out


def gaussian_blur_cv(img: jnp.ndarray, size: int, sigma: float) -> jnp.ndarray:
    """``cv2.GaussianBlur`` semantics: odd ``size`` x ``size`` kernel,
    BORDER_REFLECT_101 (improcess.py:370-392)."""
    if sigma <= 0:
        sigma = 0.3 * ((size - 1) * 0.5 - 1) + 0.8
    radius = size // 2
    k = jnp.asarray(_gaussian_1d(float(sigma), radius), dtype=img.dtype)
    pad = [(0, 0)] * (img.ndim - 2) + [(radius, radius), (radius, radius)]
    x = jnp.pad(img, pad, mode="reflect")
    x = _conv1d_last(x, k)
    x = jnp.swapaxes(_conv1d_last(jnp.swapaxes(x, -1, -2), k), -1, -2)
    return x


# ---------------------------------------------------------------------------
# Edge detectors (improcess.py:143-266)
# ---------------------------------------------------------------------------

def gradient_oriented(image: jnp.ndarray, direction: Tuple[int, int]) -> jnp.ndarray:
    """Directional finite-difference gradient (improcess.py:143-169)."""
    dft, dfx = direction
    if dfx == 0:
        return -(image[:, :-dft] - image[:, dft:])
    if dft == 0:
        return -(image[dfx:, :] - image[:-dfx, :])
    return -(
        image[dfx:-dfx, :-dft]
        - 0.5 * image[2 * dfx :, dft:]
        - 0.5 * image[: -2 * dfx, dft:]
    )


_DIAG5 = np.array(
    [
        [0, 1, 1, 1, 1],
        [-1, 0, 1, 1, 1],
        [-1, -1, 0, 1, 1],
        [-1, -1, -1, 0, 1],
        [-1, -1, -1, -1, 0],
    ],
    # host-side design constant, cast to the image dtype at use; float64
    # keeps float64 scipy/golden references exact (a float32 constant makes
    # the reference's kernel FFT run at complex64)
    dtype=np.float64,  # daslint: allow[R3] deliberate float64 design constant
)


@jax.jit
def detect_diagonal_edges(matrix: jnp.ndarray, threshold: float = 0.0) -> jnp.ndarray:
    """Sum of both-orientation 5x5 anti/diagonal convolution responses
    (improcess.py:172-226; the reference's threshold argument is likewise
    unused in its active code path)."""
    k = jnp.asarray(_DIAG5, dtype=matrix.dtype)
    return fftconvolve2d_same(matrix, k) + fftconvolve2d_same(matrix, jnp.fliplr(k))


@jax.jit
def diagonal_edge_detection(img: jnp.ndarray, threshold: float = 0.0) -> jnp.ndarray:
    """3x3 diagonal-enhance convolution pair (the reference runs this
    through torch ``F.conv2d`` with zero padding, improcess.py:229-266;
    note torch conv2d cross-correlates, i.e. does not flip the kernel).
    Returns the combined response like the reference."""
    w = jnp.asarray([[2.0, -1.0, -1.0], [-1.0, 2.0, -1.0], [-1.0, -1.0, 2.0]], dtype=img.dtype)
    w_right = jnp.flipud(w)
    # same-mode convolution with the flipped kernel == torch's zero-padded
    # cross-correlation for an odd kernel
    out_l = fftconvolve2d_same(img, jnp.flip(jnp.flip(w, -1), -2))
    out_r = fftconvolve2d_same(img, jnp.flip(jnp.flip(w_right, -1), -2))
    return out_l + out_r


# ---------------------------------------------------------------------------
# Bilateral filter (improcess.py:319-344)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("diameter", "sigma_color", "sigma_space"))
def bilateral_filter(img: jnp.ndarray, diameter: int, sigma_color: float, sigma_space: float) -> jnp.ndarray:
    """Edge-preserving bilateral smoothing (cv2.bilateralFilter capability,
    improcess.py:319-344): Gaussian weights in space x intensity, evaluated
    over a (diameter x diameter) window via shifted adds — no gathers."""
    r = diameter // 2
    pad = [(0, 0)] * (img.ndim - 2) + [(r, r), (r, r)]
    xp = jnp.pad(img, pad, mode="edge")
    h, w = img.shape[-2], img.shape[-1]
    num = jnp.zeros_like(img)
    den = jnp.zeros_like(img)
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            if dy * dy + dx * dx > r * r:
                continue  # circular window like OpenCV
            shifted = xp[..., r + dy : r + dy + h, r + dx : r + dx + w]
            ws = np.exp(-(dy * dy + dx * dx) / (2.0 * sigma_space**2))
            wc = jnp.exp(-((shifted - img) ** 2) / (2.0 * sigma_color**2))
            wgt = ws * wc
            num = num + wgt * shifted
            den = den + wgt
    return num / den


# ---------------------------------------------------------------------------
# Canny + Hough (improcess.py:269-316)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("hysteresis_iters",))
def canny_edges(
    img: jnp.ndarray,
    low: float,
    high: float,
    hysteresis_iters: int = 32,
) -> jnp.ndarray:
    """Canny edge map: 3x3 Sobel gradients, 4-direction non-maximum
    suppression, double threshold, and hysteresis as an iterated dilation of
    strong edges through weak ones (a fixed-iteration fixpoint — XLA
    friendly). Capability parity with cv2.Canny(improcess.py:291)."""
    sob_x = jnp.asarray([[-1.0, 0, 1], [-2, 0, 2], [-1, 0, 1]], dtype=img.dtype)
    sob_y = jnp.asarray([[-1.0, -2, -1], [0, 0, 0], [1, 2, 1]], dtype=img.dtype)
    # replicate borders (cv2 semantics) so the image frame doesn't turn
    # into a spurious gradient wall
    imgp = jnp.pad(img, 1, mode="edge")
    gx = fftconvolve2d_same(imgp, jnp.flip(jnp.flip(sob_x, -1), -2))[1:-1, 1:-1]
    gy = fftconvolve2d_same(imgp, jnp.flip(jnp.flip(sob_y, -1), -2))[1:-1, 1:-1]
    mag = jnp.abs(gx) + jnp.abs(gy)  # L1, cv2 default

    # quantize gradient direction into 4 bins
    ang = jnp.arctan2(gy, gx)
    ang = jnp.where(ang < 0, ang + jnp.pi, ang)
    bins = jnp.floor((ang + jnp.pi / 8) / (jnp.pi / 4)).astype(jnp.int32) % 4

    mp = jnp.pad(mag, 1, constant_values=0)
    h, w = img.shape

    def shift(dy, dx):
        return mp[1 + dy : 1 + dy + h, 1 + dx : 1 + dx + w]

    n0a, n0b = shift(0, 1), shift(0, -1)      # horizontal gradient
    n1a, n1b = shift(1, 1), shift(-1, -1)     # 45 deg
    n2a, n2b = shift(1, 0), shift(-1, 0)      # vertical
    n3a, n3b = shift(1, -1), shift(-1, 1)     # 135 deg
    na = jnp.select([bins == 0, bins == 1, bins == 2, bins == 3], [n0a, n1a, n2a, n3a])
    nb = jnp.select([bins == 0, bins == 1, bins == 2, bins == 3], [n0b, n1b, n2b, n3b])
    nms = jnp.where((mag >= na) & (mag >= nb), mag, 0.0)

    strong = nms >= high
    weak = nms >= low

    def body(_, s):
        sp = jnp.pad(s, 1)
        grown = jnp.zeros_like(s)
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                grown = grown | sp[1 + dy : 1 + dy + h, 1 + dx : 1 + dx + w]
        return grown & weak | s

    edges = jax.lax.fori_loop(0, hysteresis_iters, body, strong)
    return edges


def hough_lines(
    edges,
    rho_res: float = 1.0,
    theta_res: float = np.pi / 180,
    threshold: int = 100,
    min_line_length: int = 10,
    max_line_gap: int = 10,
):
    """Deterministic line-segment extraction via a full Hough accumulator.

    Capability parity with cv2.HoughLinesP (improcess.py:300-307) without
    its randomized sampling: (1) vote all edge pixels into the (rho, theta)
    accumulator with one one-hot matmul per angle bin batch, (2) take
    accumulator peaks over threshold, (3) walk each peak's line through the
    edge map and emit runs >= min_line_length, merging gaps <= max_line_gap.
    Steps 1-2 run on device; segment extraction is host-side numpy on the
    few surviving lines.
    """
    edges = np.asarray(edges).astype(bool)
    h, w = edges.shape
    ys, xs = np.nonzero(edges)
    if len(xs) == 0:
        return []
    thetas = np.arange(0, np.pi, theta_res)
    diag = int(np.ceil(np.hypot(h, w)))
    rhos = np.arange(-diag, diag + rho_res, rho_res)

    pts = jnp.asarray(np.stack([xs, ys]).astype(np.float32))
    cs = jnp.asarray(np.stack([np.cos(thetas), np.sin(thetas)]).astype(np.float32))
    rho_v = pts.T @ cs  # [n_points, n_thetas]
    rho_idx = jnp.round((rho_v + diag) / rho_res).astype(jnp.int32)
    # accumulate votes: one-hot over rho bins summed over points
    acc = jax.vmap(
        lambda col: jnp.zeros(len(rhos), jnp.int32).at[col].add(1), in_axes=1
    )(rho_idx)  # [n_thetas, n_rhos]
    acc = np.asarray(acc)

    lines = []
    for ti, ri in zip(*np.nonzero(acc >= threshold)):
        theta, rho = thetas[ti], rhos[ri]
        c, s = np.cos(theta), np.sin(theta)
        # walk the line across the image
        if abs(s) > abs(c):  # mostly horizontal in x
            xs_l = np.arange(w)
            ys_l = np.round((rho - xs_l * c) / s).astype(int)
            valid = (ys_l >= 0) & (ys_l < h)
            on = np.zeros(w, bool)
            on[valid] = edges[ys_l[valid], xs_l[valid]]
            coords = np.stack([xs_l, ys_l], 1)
        else:
            ys_l = np.arange(h)
            xs_l = np.round((rho - ys_l * s) / c).astype(int)
            valid = (xs_l >= 0) & (xs_l < w)
            on = np.zeros(h, bool)
            on[valid] = edges[ys_l[valid], xs_l[valid]]
            coords = np.stack([xs_l, ys_l], 1)
        # merge runs separated by <= max_line_gap
        idx = np.nonzero(on)[0]
        if len(idx) == 0:
            continue
        splits = np.nonzero(np.diff(idx) > max_line_gap)[0]
        for seg in np.split(idx, splits + 1):
            if len(seg) and seg[-1] - seg[0] + 1 >= min_line_length:
                x1, y1 = coords[seg[0]]
                x2, y2 = coords[seg[-1]]
                lines.append((int(x1), int(y1), int(x2), int(y2)))
    return lines


def detect_long_lines(
    img,
    canny_low: float = 50.0,
    canny_high: float = 150.0,
    threshold: int = 100,
    min_line_length: int = 50,
    max_line_gap: int = 10,
    bilateral_diameter: int = 9,
    sigma_color: float = 75.0,
    sigma_space: float = 75.0,
):
    """Long-line extraction: bilateral smoothing -> Canny -> Hough segment
    walk. The reference composes cv2.bilateralFilter + cv2.Canny +
    cv2.HoughLinesP (improcess.py:269-316); here the smoothing and edge map
    run as jitted device kernels and only the per-line segment walk is host
    numpy. Returns ``(lines, edges)`` with lines as (x1, y1, x2, y2)."""
    img = jnp.asarray(img, dtype=jnp.float32)
    smooth = bilateral_filter(img, bilateral_diameter, sigma_color, sigma_space)
    edges = canny_edges(smooth, canny_low, canny_high)
    lines = hough_lines(
        edges, threshold=threshold,
        min_line_length=min_line_length, max_line_gap=max_line_gap,
    )
    return lines, edges


# ---------------------------------------------------------------------------
# Radon transform (improcess.py:347-367)
# ---------------------------------------------------------------------------

def radon_transform(image: jnp.ndarray, theta: np.ndarray | None = None) -> jnp.ndarray:
    """Radon transform (circle=False): pad to the diagonal, rotate by each
    angle with bilinear interpolation, sum along rows. Capability parity
    with ``skimage.transform.radon`` (improcess.py:347-367)."""
    if theta is None:
        theta = np.arange(180.0)
    img = jnp.asarray(image)
    h, w = img.shape
    diag = int(np.ceil(np.sqrt(h * h + w * w)))
    pad_h, pad_w = diag - h, diag - w
    img_p = jnp.pad(img, ((pad_h // 2, pad_h - pad_h // 2), (pad_w // 2, pad_w - pad_w // 2)))
    n = img_p.shape[0]
    center = (n - 1) / 2.0

    yy, xx = jnp.meshgrid(jnp.arange(n) - center, jnp.arange(n) - center, indexing="ij")
    coords = jnp.stack([yy.ravel(), xx.ravel()])

    def one_angle(deg):
        a = jnp.deg2rad(deg)
        rot = jnp.asarray([[jnp.cos(a), jnp.sin(a)], [-jnp.sin(a), jnp.cos(a)]])
        src = rot @ coords + center
        vals = jax.scipy.ndimage.map_coordinates(img_p, [src[0].reshape(n, n), src[1].reshape(n, n)], order=1)
        return vals.sum(axis=0)

    out = jax.lax.map(one_angle, jnp.asarray(theta, dtype=img_p.dtype))
    return out.T  # [projection position, angle] like skimage


def compute_radon_transform(image, theta=None):
    """Reference-named alias of :func:`radon_transform`
    (improcess.py:347-367)."""
    return radon_transform(image, theta)


# ---------------------------------------------------------------------------
# Binning / resize + masking (improcess.py:395-454)
# ---------------------------------------------------------------------------

def binning(image: jnp.ndarray, ft: float, fx: float) -> jnp.ndarray:
    """Resize by factors (ft along time, fx along channels) with bilinear
    antialiased interpolation (capability parity with torchvision
    ``Resize``, improcess.py:395-421)."""
    h = int(image.shape[-2] * fx)
    w = int(image.shape[-1] * ft)
    return jax.image.resize(image, image.shape[:-2] + (h, w), method="linear", antialias=True)


def apply_smooth_mask(array: jnp.ndarray, mask: jnp.ndarray, sigma: float = 1.5,
                      compat: bool = False) -> jnp.ndarray:
    """Multiply by a Gaussian-smoothed, renormalized mask.

    The reference computes the smoothed mask but then multiplies by the RAW
    mask (improcess.py:452 — a documented bug, SURVEY.md §7). Default
    behavior here applies the smoothed mask as documented;
    ``compat=True`` reproduces the reference's raw-mask multiply.
    """
    smoothed = gaussian_filter2d(mask.astype(array.dtype), sigma)
    # Uniform mask (e.g. no detections -> all zeros): min == max, so the
    # renormalization would be 0/0; pass the mask through unscaled instead.
    lo, hi = jnp.min(smoothed), jnp.max(smoothed)
    span = hi - lo
    smoothed = jnp.where(span > 0, (smoothed - lo) / jnp.where(span > 0, span, 1.0), smoothed)
    return array * (mask if compat else smoothed)
