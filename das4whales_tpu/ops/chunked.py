"""Chunked/out-of-core ops (the reference's dask layer, tools.py:8-257).

The reference scales past memory with dask/xarray ``map_blocks`` over time
chunks, accepting chunk-boundary error for time-domain filters
(tools.py:166 "will therefore have error at the end of chunks"). Here the
chunk axis is just another batch axis for XLA — every per-chunk kernel is
one jitted program vmapped over chunks — and time-domain filtering uses
halo overlap so boundaries are exact to within the IIR's exponential decay
(error ~ |pole|^halo, below float32 epsilon for the default halo).

All kernels are dtype-polymorphic, operate on the last (time) axis, and
broadcast over arbitrary leading axes, so they compose with
``shard_map``/pjit channel sharding from ``das4whales_tpu.parallel``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import fk as fk_ops
from .filters import filtfilt, sosfiltfilt
from .spectral import hann_window

# re-exported for tools.disp_comprate parity (tools.py:239-257)
disp_comprate = fk_ops.compression_report


@jax.jit
def detrend_linear(x: jnp.ndarray) -> jnp.ndarray:
    """Remove the least-squares line along the last axis (scipy
    ``signal.detrend`` default, used by the reference per chunk,
    tools.py:27)."""
    n = x.shape[-1]
    t = jnp.arange(n, dtype=x.dtype) - (n - 1) / 2.0
    denom = jnp.sum(t * t)
    slope = jnp.sum(x * t, axis=-1, keepdims=True) / denom
    mean = jnp.mean(x, axis=-1, keepdims=True)
    return x - mean - slope * t


@functools.partial(jax.jit, static_argnames=("nperseg", "noverlap", "scaling"))
def welch_psd(
    x: jnp.ndarray,
    fs: float,
    nperseg: int = 1024,
    noverlap: int | None = None,
    scaling: str = "density",
) -> jnp.ndarray:
    """One-sided Welch PSD along the last axis, scipy ``signal.welch``
    parity (hann window, 50% overlap, per-segment constant detrend,
    density scaling). Replaces the reference's per-chunk
    ``signal.welch`` (tools.py:228-237) with one batched rFFT.
    """
    n = x.shape[-1]
    if nperseg > n:
        # scipy parity: reduce nperseg to the signal length rather than
        # letting the gather below clamp out-of-bounds indices silently;
        # an explicit caller noverlap is kept (scipy keeps it too)
        nperseg = n
    if noverlap is None:
        noverlap = nperseg // 2
    elif noverlap >= nperseg:
        raise ValueError(f"noverlap ({noverlap}) must be < nperseg ({nperseg})")
    step = nperseg - noverlap
    n_seg = max((n - noverlap) // step, 1)

    idx = jnp.arange(n_seg)[:, None] * step + jnp.arange(nperseg)[None, :]
    segs = x[..., idx]  # [..., n_seg, nperseg]
    segs = segs - jnp.mean(segs, axis=-1, keepdims=True)
    win = hann_window(nperseg, periodic=True, dtype=x.dtype)
    spec = jnp.fft.rfft(segs * win, axis=-1)
    pxx = (spec.real**2 + spec.imag**2)
    if scaling == "density":
        pxx = pxx / (fs * jnp.sum(win**2))
    else:  # spectrum
        pxx = pxx / jnp.sum(win) ** 2
    # one-sided doubling except DC (and Nyquist when nperseg is even)
    last = pxx.shape[-1] - 1 if nperseg % 2 == 0 else pxx.shape[-1]
    pxx = pxx.at[..., 1:last].multiply(2.0)
    return jnp.mean(pxx, axis=-2)


def welch_freqs(fs: float, nperseg: int = 1024) -> np.ndarray:
    """Frequency axis matching :func:`welch_psd`."""
    return np.fft.rfftfreq(nperseg, d=1.0 / fs)


@functools.partial(jax.jit, static_argnames=("chunk", "nperseg"))
def spec(x: jnp.ndarray, fs: float, chunk: int = 3000, nperseg: int = 1024) -> jnp.ndarray:
    """Per-time-chunk Welch PSD -> [..., n_chunks, nfreq].

    Capability parity with reference ``tools.spec`` (tools.py:212-237),
    generalized: the reference hardcodes chunk=3000, fs=200, and 1-D input;
    here chunk/fs are parameters and leading axes broadcast.
    """
    n = x.shape[-1]
    n_chunks = n // chunk
    xc = x[..., : n_chunks * chunk].reshape(x.shape[:-1] + (n_chunks, chunk))
    return welch_psd(xc, fs, nperseg=min(nperseg, chunk))


@functools.partial(jax.jit, static_argnames=("chunk",))
def energy_time_domain(x: jnp.ndarray, chunk: int) -> jnp.ndarray:
    """Per-chunk time-domain energy sum(x^2) -> [..., n_chunks].

    Parity with reference ``tools.energy_TimeDomain`` (tools.py:84-157):
    Parseval energy per time chunk. A trailing partial chunk is dropped,
    matching dask's chunk layout.
    """
    n_chunks = x.shape[-1] // chunk
    xc = x[..., : n_chunks * chunk].reshape(x.shape[:-1] + (n_chunks, chunk))
    return jnp.sum(xc * xc, axis=-1)


def _chunked_zero_phase(filter_fn, x: jnp.ndarray, chunk: int, halo: int) -> jnp.ndarray:
    """Apply a zero-phase filter in overlapping time windows.

    Windows are ``chunk + 2*halo`` long and clamped inside the array, so
    every halo sample is real neighbor data and the first/last window edge
    coincides with the true array edge — there the filter's own scipy-
    parity odd extension applies, making array ends bit-comparable to the
    unchunked call. Interior chunk boundaries match to within the IIR
    impulse-response decay over ``halo`` samples.
    """
    n = x.shape[-1]
    width = chunk + 2 * halo
    if width >= n:
        return filter_fn(x)
    n_chunks = -(-n // chunk)
    starts = np.clip(np.arange(n_chunks) * chunk - halo, 0, n - width)
    win = x[..., starts[:, None] + np.arange(width)[None, :]]
    y = filter_fn(win)  # [..., n_chunks, width]
    offsets = np.arange(n_chunks) * chunk - starts
    crop = np.minimum(offsets[:, None] + np.arange(chunk)[None, :], width - 1)
    crop = jnp.asarray(crop.reshape((1,) * (y.ndim - 2) + crop.shape))
    y = jnp.take_along_axis(y, jnp.broadcast_to(crop, y.shape[:-1] + (chunk,)), axis=-1)
    return y.reshape(x.shape[:-1] + (n_chunks * chunk,))[..., :n]


def filtfilt_chunked(b, a, x: jnp.ndarray, chunk: int, halo: int | None = None) -> jnp.ndarray:
    """Zero-phase IIR filtering in time chunks with exact halo overlap.

    The reference's chunked filtfilt acknowledges boundary error
    (tools.py:161-187, docstring at :166). Here chunk boundaries are exact
    to within the filter's impulse-response decay over ``halo`` samples
    (default ``16 * 3 * max(len(a), len(b))``) and array ends match scipy's
    ``filtfilt`` edge handling exactly.
    """
    if halo is None:
        halo = 16 * 3 * max(len(np.asarray(a)), len(np.asarray(b)))
    return _chunked_zero_phase(lambda w: filtfilt(b, a, w), x, chunk, halo)


def sosfiltfilt_chunked(sos, x: jnp.ndarray, chunk: int, halo: int | None = None) -> jnp.ndarray:
    """SOS variant of :func:`filtfilt_chunked`."""
    sos = np.asarray(sos)
    if halo is None:
        halo = 16 * 3 * (2 * sos.shape[0] + 1)
    return _chunked_zero_phase(lambda w: sosfiltfilt(sos, w), x, chunk, halo)


def fk_filt_chunked(
    data: jnp.ndarray,
    chunk: int,
    tint,
    fs,
    xint,
    dx,
    c_min,
    c_max,
    sigma: float = 40.0,
) -> jnp.ndarray:
    """Per-time-chunk f-k speed-fan filtering.

    Parity with reference ``tools.fk_filt`` / ``fk_filt_chunk``
    (tools.py:8-81): linear detrend per chunk, Gaussian-smoothed
    (sigma=40) min-max-normalized speed fan, 2-D FFT filter per chunk.
    The mask is designed once for the chunk shape and the apply is
    vmapped over chunks — one XLA program instead of a dask graph.
    """
    nx, ns = data.shape
    n_chunks = ns // chunk
    mask = jnp.asarray(
        fk_ops.speed_fan_mask((nx, chunk), fs, dx, c_min, c_max, tint=tint, xint=xint, sigma=sigma)
    )
    xc = data[:, : n_chunks * chunk].reshape(nx, n_chunks, chunk).transpose(1, 0, 2)
    xc = detrend_linear(xc)
    out = jax.vmap(lambda blk: fk_ops.fk_filter_apply(blk, mask))(xc)
    return out.transpose(1, 0, 2).reshape(nx, n_chunks * chunk)
