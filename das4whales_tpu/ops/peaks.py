"""Vectorized peak picking with exact scipy prominence semantics.

The reference picks detection times with ``scipy.signal.find_peaks(...,
prominence=threshold)`` inside per-channel Python loops (detect.py:169-274),
parallelized at best with a ThreadPoolExecutor that loses channel order
(detect.py:242-246). Prominence is an inherently sequential-looking
definition (walk away from each peak until a higher sample), which SURVEY.md
§7 flags as a hard part of the TPU port.

This module computes *exact* scipy ``find_peaks`` + prominence results for
every sample of every channel simultaneously:

* plateau-aware local maxima via a packed-key native ``lax.cummax`` (run
  start index and entry-rise flag in one int32) — O(N), elementwise + one
  cumulative max, no generic scan (TPU-compiler friendly next to sorts);
* prominences via binary-lifting over precomputed sliding window max/min
  tables (sparse tables): for each sample, a greedy high-to-low descent
  skips power-of-two blocks whose max does not exceed the peak, folding in
  their mins — exactly scipy's walk-until-higher with min tracking, in
  O(N log N) fully-batched gathers instead of a per-peak walk.

Outputs are dense boolean masks + per-sample prominences (fixed shapes, jit
friendly); host-side helpers convert to the reference's ragged
list-of-index-arrays and (channel, time) tuple formats.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class SparsePicks(NamedTuple):
    """Fixed-capacity peak-pick result (one row per channel/correlogram).

    ``positions`` [..., K] sample indices ascending per row (invalid = N),
    ``selected`` the validity mask, ``saturated`` [...] per-row flag set
    when more than K local maxima passed the height prefilter (only then
    can picks be missed).
    """

    positions: jnp.ndarray
    heights: jnp.ndarray
    prominences: jnp.ndarray
    selected: jnp.ndarray
    saturated: jnp.ndarray


def _run_info(x: jnp.ndarray):
    """(run_start, rising) per sample: the start index of the sample's
    equal-value run and whether the run was entered by a strict rise
    (``x[start-1] < x[start]``; False for the run touching the left edge).

    Implemented with ONE native ``lax.cummax`` over a packed int32 key
    ``2*start + rising`` — the index part is monotone, so cummax carries the
    latest run start forward and the LSB smuggles the boolean along with no
    gather and no generic ``associative_scan``. (The earlier tuple
    associative-scan formulation wedged the TPU compiler for minutes when it
    shared an XLA module with ``top_k``/``sort`` — measured on v5e during
    round 3 — and was slower everywhere.)
    """
    n = x.shape[-1]
    chg = x[..., 1:] != x[..., :-1]
    rising = x[..., 1:] > x[..., :-1]
    # lax.iota, not jnp.arange: arange materializes a literal constant,
    # which a Pallas kernel body (ops/pallas_picks.py shares this code)
    # cannot capture on this jax version; iota is an op, same values
    idx1 = jax.lax.iota(jnp.int32, n - 1) + 1
    # i=0 starts a run with rising=False (left-edge run: never a peak)
    key_tail = jnp.where(chg, 2 * idx1 + rising.astype(jnp.int32), -1)
    zeros = jnp.zeros(x.shape[:-1] + (1,), jnp.int32)
    key = jnp.concatenate([zeros, key_tail], axis=-1)
    carried = jax.lax.cummax(key, axis=x.ndim - 1)
    return carried >> 1, (carried & 1).astype(bool)


def local_maxima(x: jnp.ndarray) -> jnp.ndarray:
    """Boolean mask of local maxima with scipy plateau semantics.

    Matches ``scipy.signal._peak_finding_utils._local_maxima_1d``: a maximum
    is a run of equal samples strictly greater than the samples on both
    sides; the reported index is the floor-midpoint of the run. Runs touching
    either signal edge are not maxima.
    """
    n = x.shape[-1]
    idx = jax.lax.iota(jnp.int32, n)

    run_start, rising = _run_info(x)
    run_start_r, falling_r = _run_info(jnp.flip(x, axis=-1))
    run_end = (n - 1) - jnp.flip(run_start_r, axis=-1)
    falling = jnp.flip(falling_r, axis=-1)  # run exited by a strict fall

    is_peak_run = rising & falling
    mid = (run_start + run_end) // 2
    return is_peak_run & (idx == mid)


def _window_tables(x: jnp.ndarray, levels: int):
    """Sparse tables of sliding-window max and min: level k holds the
    max/min over the window of length 2^k ending at each index."""
    tmax = [x]
    tmin = [x]
    for k in range(1, levels + 1):
        half = 1 << (k - 1)
        prev_max, prev_min = tmax[-1], tmin[-1]
        pad_max = jnp.pad(
            prev_max, [(0, 0)] * (x.ndim - 1) + [(half, 0)], constant_values=-jnp.inf
        )[..., : x.shape[-1]]
        pad_min = jnp.pad(
            prev_min, [(0, 0)] * (x.ndim - 1) + [(half, 0)], constant_values=jnp.inf
        )[..., : x.shape[-1]]
        tmax.append(jnp.maximum(prev_max, pad_max))
        tmin.append(jnp.minimum(prev_min, pad_min))
    return tmax, tmin


def _one_sided_base_min(x: jnp.ndarray, levels: int) -> jnp.ndarray:
    """For each index i: min(x[j+1..i]) where j is the nearest index < i with
    x[j] > x[i] (or the signal start if none) — scipy's left-base minimum.

    Greedy binary-lifting descent over the window tables; each level is one
    batched gather + compare, so the whole signal resolves in
    O(levels) = O(log N) vectorized steps.
    """
    n = x.shape[-1]
    tmax, tmin = _window_tables(x, levels)
    tmax_s = jnp.stack(tmax)  # [levels+1, ..., n]
    tmin_s = jnp.stack(tmin)

    pos = jnp.broadcast_to(jnp.arange(n), x.shape)
    base_min = jnp.full_like(x, jnp.inf)

    for k in range(levels, -1, -1):
        width = 1 << k
        can = pos >= (width - 1)  # block fully inside the signal
        gpos = jnp.clip(pos, 0, n - 1)
        blk_max = jnp.take_along_axis(tmax_s[k], gpos, axis=-1)
        blk_min = jnp.take_along_axis(tmin_s[k], gpos, axis=-1)
        skip = can & (blk_max <= x)
        base_min = jnp.where(skip, jnp.minimum(base_min, blk_min), base_min)
        pos = jnp.where(skip, pos - width, pos)

    return base_min


@jax.jit
def peak_prominences_dense(x: jnp.ndarray) -> jnp.ndarray:
    """Prominence of every sample, treating it as a peak.

    At indices where ``local_maxima`` is True this equals
    ``scipy.signal.peak_prominences`` exactly (wlen=None).
    """
    n = x.shape[-1]
    levels = max(1, int(np.ceil(np.log2(n))))
    left_min = _one_sided_base_min(x, levels)
    right_min = jnp.flip(_one_sided_base_min(jnp.flip(x, axis=-1), levels), axis=-1)
    return x - jnp.maximum(left_min, right_min)


@jax.jit
def find_peaks_prominence(x: jnp.ndarray, threshold) -> jnp.ndarray:
    """Boolean mask of peaks with prominence >= threshold.

    Exact-parity vectorized equivalent of
    ``scipy.signal.find_peaks(x, prominence=threshold)[0]`` applied along the
    last axis of a batched array.
    """
    mask = local_maxima(x)
    prom = peak_prominences_dense(x)
    return mask & (prom >= threshold)


# ---------------------------------------------------------------------------
# Sparse candidate path (TPU production route)
# ---------------------------------------------------------------------------
#
# The dense binary-lifting descent above is exact for every sample but leans
# on per-element gathers along the time axis, which TPUs execute serially
# (~40 ms per gather on a v5e for a 3M-element block — measured). The
# detection pipelines only ever need peaks above a threshold, so the
# production route is: (1) plateau-aware local maxima (cheap, elementwise),
# (2) top-k tallest candidates per channel, (3) *exact* scipy prominences
# for those candidates via a sqrt-decomposition of the time axis — block
# max/min tables plus per-candidate elementwise scans over the block axis
# and within-block offsets. The only gathers are contiguous block-row
# fetches over the ~sqrt(N) block axis, which the TPU handles well.
#
# For nonnegative signals (Hilbert envelopes — what the reference picks on,
# detect.py:192) a peak's prominence never exceeds its height, so
# prefiltering candidates by height >= threshold is exact: the result
# equals scipy.find_peaks(x, prominence=threshold) whenever the number of
# candidates above threshold fits in max_peaks (saturation is reported).


def _block_stats(x: jnp.ndarray, nb: int):
    """Reshape [..., N] -> [..., B, nb] with per-block max/min."""
    n = x.shape[-1]
    b = -(-n // nb)
    pad = b * nb - n
    if pad:
        xpad = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)], constant_values=-jnp.inf)
    else:
        xpad = x
    xb = xpad.reshape(x.shape[:-1] + (b, nb))
    return xb, jnp.max(xb, axis=-1), jnp.where(jnp.isneginf(xb), jnp.inf, xb).min(axis=-1)


def _one_sided_base_min_sparse(xb, block_max, block_min, pos, h, nb: int):
    """Exact scipy left-base minimum for candidate positions.

    ``xb``: [C, B, nb] blocked signal; ``pos``: [C, K] candidate sample
    indices; ``h``: [C, K] candidate heights. Returns [C, K] minima of
    x over (j, pos] where j is the last index < pos with x[j] > h.
    """
    C, B, _ = xb.shape
    bp = pos // nb                      # [C, K] block of the candidate
    tp = pos % nb
    offs = jax.lax.iota(jnp.int32, nb)  # [nb]
    blocks = jax.lax.iota(jnp.int32, B)  # [B]

    def block_gather(idx):
        # [C, 1, B, nb] gathered at [C, K, 1, 1] along the block axis
        return jnp.take_along_axis(xb[:, None], idx[:, :, None, None], axis=2)[:, :, 0, :]

    # own-block values: contiguous row gather over the (small) block axis
    ob = block_gather(bp)  # [C, K, nb]

    inf = jnp.asarray(jnp.inf, xb.dtype)
    big = jnp.asarray(jnp.finfo(xb.dtype).max, xb.dtype)

    # 1) previous-greater inside the candidate's own block (offsets < tp)
    m_own_mask = (offs < tp[..., None]) & (ob > h[..., None])
    has_own = m_own_mask.any(axis=-1)
    j_own = jnp.max(jnp.where(m_own_mask, offs, -1), axis=-1)              # [C,K]
    seg_own = (offs > j_own[..., None]) & (offs <= tp[..., None])
    min_own = jnp.min(jnp.where(seg_own, ob, inf), axis=-1)

    # 2) previous-greater in an earlier block
    bmask = (blocks < bp[..., None]) & (block_max[:, None, :] > h[..., None])  # [C,K,B]
    has_blk = bmask.any(axis=-1)
    bprev = jnp.max(jnp.where(bmask, blocks, 0), axis=-1)                  # [C,K]
    pb = block_gather(bprev)
    pb_mask = pb > h[..., None]
    j_pb = jnp.max(jnp.where(pb_mask, offs, -1), axis=-1)
    min_pb_suffix = jnp.min(jnp.where(offs > j_pb[..., None], pb, inf), axis=-1)

    # full blocks strictly between bprev and bp (or all blocks < bp if no
    # previous-greater exists)
    lo = jnp.where(has_blk, bprev, -1)
    mid_mask = (blocks > lo[..., None]) & (blocks < bp[..., None])
    min_mid = jnp.min(jnp.where(mid_mask, block_min[:, None, :], inf), axis=-1)

    # own-block prefix up to and including the candidate
    min_own_prefix = jnp.min(jnp.where(offs <= tp[..., None], ob, inf), axis=-1)

    other = jnp.minimum(jnp.where(has_blk, min_pb_suffix, big), jnp.minimum(min_mid, min_own_prefix))
    return jnp.where(has_own, min_own, other)


def _find_peaks_rows(
    x: jnp.ndarray,
    thr_bc: jnp.ndarray,
    max_peaks: int,
    nb: int,
    prefilter_height: bool,
    method: str,
) -> SparsePicks:
    """The per-row core of :func:`find_peaks_sparse`, unjitted.

    ``x`` is ``[C, N]``, ``thr_bc`` a ``[C]`` per-row threshold.
    Factored out so the Pallas fused pick kernel
    (``ops.pallas_picks``) can run EXACTLY these operations on its
    VMEM-resident row block — pick parity between the jnp route and the
    kernel route is then by construction, not by test luck."""
    C, N = x.shape
    thr_bc = jnp.asarray(thr_bc)

    mask = local_maxima(x)
    if prefilter_height:
        mask = mask & (x >= thr_bc[:, None])
    n_cand = jnp.sum(mask, axis=-1)
    saturated = n_cand > max_peaks

    if method == "pack":
        idx = jax.lax.iota(jnp.int32, N)
        cnt = jnp.cumsum(mask, axis=-1)
        dest = jnp.where(mask, cnt - 1, max_peaks)    # >= K -> dropped
        rows = jax.lax.iota(jnp.int32, C)[:, None]
        pos = jnp.full((C, max_peaks), N, jnp.int32).at[
            rows, dest
        ].set(jnp.broadcast_to(idx, (C, N)), mode="drop")
        slot_valid = (
            jax.lax.iota(jnp.int32, max_peaks)[None, :]
            < jnp.minimum(n_cand, max_peaks)[:, None]
        )
        gpos = jnp.where(slot_valid, pos, 0)
        heights = jnp.take_along_axis(x, gpos, axis=-1)
        heights = jnp.where(slot_valid, heights, -jnp.inf)
        valid = slot_valid
    elif method == "topk":
        cand_scores = jnp.where(mask, x, -jnp.inf)
        heights, pos = jax.lax.top_k(cand_scores, max_peaks)      # [C, K]
        valid = jnp.isfinite(heights)
        gpos = pos
    else:
        raise ValueError(f"unknown method {method!r}")

    xb, bmax, bmin = _block_stats(x, nb)
    left_min = _one_sided_base_min_sparse(xb, bmax, bmin, gpos, heights, nb)
    xf = jnp.flip(x, axis=-1)
    xbf, bmaxf, bminf = _block_stats(xf, nb)
    right_min = _one_sided_base_min_sparse(
        xbf, bmaxf, bminf, (N - 1) - gpos, heights, nb
    )

    prom = heights - jnp.maximum(left_min, right_min)
    selected = valid & (prom >= thr_bc[:, None])

    if method == "pack":
        # slots are position-ascending by construction; every slot NOT in
        # `selected` reports position N — the topk path's promise (a
        # valid-but-unselected candidate, i.e. one that failed the
        # prominence test, must not leak its position; ADVICE round 5)
        return SparsePicks(
            jnp.where(selected, pos, N), heights, prom, selected, saturated
        )
    # order by position per channel for reference-compatible pick lists
    pos_sorted_key = jnp.where(selected, pos, N)
    order = jnp.argsort(pos_sorted_key, axis=-1)
    take = lambda a: jnp.take_along_axis(a, order, axis=-1)
    return SparsePicks(
        take(pos_sorted_key), take(heights), take(prom), take(selected), saturated
    )


@functools.partial(jax.jit, static_argnames=("max_peaks", "nb", "method"))
def find_peaks_sparse(
    x: jnp.ndarray,
    threshold,
    max_peaks: int = 256,
    nb: int = 128,
    prefilter_height: bool = True,
    method: str = "topk",
):
    """Threshold-prominence peak picking via the sparse candidate route.

    Returns ``(positions, heights, prominences, selected, saturated)``:
    ``positions`` [C, max_peaks] sample indices sorted ascending per channel
    (invalid slots hold N), ``selected`` the boolean validity mask, and
    ``saturated`` a per-channel flag set when more than ``max_peaks`` local
    maxima passed the height prefilter (only then can picks be missed).

    For nonnegative inputs this matches
    ``scipy.signal.find_peaks(x, prominence=threshold)`` exactly whenever
    ``saturated`` is False.

    ``method`` selects the candidate-slotting kernel — the RESULT is
    identical whenever ``saturated`` is False; they differ only in which
    candidates a saturated row drops:

    * ``"topk"`` keeps the ``max_peaks`` TALLEST candidates
      (``lax.top_k``). On TPU, top-k lowers to a full per-row sort of
      the time axis — at the canonical detection shape that sort is the
      dominant pick-stage cost (docs/PERF.md).
    * ``"pack"`` keeps the FIRST ``max_peaks`` candidates in time order
      via a cumsum + scatter pack: no sort anywhere (slots come out
      position-ascending by construction, so the topk path's final
      argsort disappears too). This is the adaptive-K fast path: the K0
      attempt packs, and ``picks_with_escalation`` reruns a saturated
      row set at full capacity with ``"topk"``, preserving the
      documented tallest-K semantics wherever truncation CAN happen.
    """
    C, N = x.shape
    max_peaks = min(max_peaks, N)  # slot count cannot exceed the time axis
    thr = jnp.asarray(threshold)
    thr_bc = jnp.broadcast_to(thr, (C,)) if thr.ndim <= 1 else thr
    return _find_peaks_rows(x, thr_bc, max_peaks, nb, prefilter_height, method)


def find_peaks_sparse_batched(
    x: jnp.ndarray,
    threshold,
    max_peaks: int = 256,
    nb: int = 128,
    method: str = "topk",
) -> SparsePicks:
    """``find_peaks_sparse`` over arbitrary leading axes.

    ``x`` is ``[..., T]``; ``threshold`` must broadcast to ``x.shape[:-1]``
    (e.g. per-template/per-file thresholds in the sharded detection steps).
    Leading axes are flattened into the channel axis for the kernel and
    restored on output.
    """
    lead = x.shape[:-1]
    rows = int(np.prod(lead)) if lead else 1
    thr = jnp.broadcast_to(jnp.asarray(threshold), lead).reshape(rows)
    res = find_peaks_sparse(
        x.reshape(rows, x.shape[-1]), thr, max_peaks=max_peaks, nb=nb,
        method=method,
    )
    return SparsePicks(*(a.reshape(lead + a.shape[1:]) for a in res))


def find_peaks_sparse_tiled(
    x: jnp.ndarray,
    threshold,
    max_peaks: int = 256,
    tile: int = 512,
    nb: int = 128,
    method: str = "topk",
) -> SparsePicks:
    """``find_peaks_sparse_batched`` with the row (second-to-last) axis
    walked in ``tile``-sized chunks via ``lax.map``.

    The kernel's per-candidate block tables are ``[rows, K, T/nb]`` — at
    a canonical 22k-channel shard with K=256 the untiled intermediates
    accessed ~17x the HBM bytes of the tiled single-chip route (XLA cost
    model, scripts/derive_multichip.py). Tiling bounds the working set
    at tile size exactly like ``models.matched_filter.mf_pick_tiled``;
    results are identical (the kernel is per-row). Rows are zero-padded
    up to a tile multiple with an +inf threshold (no candidates) and
    cropped on output.

    ``x`` is ``[..., C, T]``; ``threshold`` broadcasts to ``x.shape[:-1]``.
    """
    lead = x.shape[:-2]
    C, T = x.shape[-2], x.shape[-1]
    thr_rows = jnp.broadcast_to(jnp.asarray(threshold), x.shape[:-1])
    tile = min(tile, C)
    n_t = -(-C // tile)
    pad = n_t * tile - C
    if pad:
        zeros = [(0, 0)] * len(lead)
        x = jnp.pad(x, zeros + [(0, pad), (0, 0)])
        thr_rows = jnp.pad(thr_rows, zeros + [(0, pad)],
                           constant_values=jnp.inf)
    xt = jnp.moveaxis(x.reshape(lead + (n_t, tile, T)), -3, 0)
    tt = jnp.moveaxis(thr_rows.reshape(lead + (n_t, tile)), -2, 0)
    sp = jax.lax.map(
        lambda a: find_peaks_sparse_batched(
            a[0], a[1], max_peaks=max_peaks, nb=nb, method=method
        ),
        (xt, tt),
    )

    def untile(f):
        f = jnp.moveaxis(f, 0, len(lead))          # [*lead, n_t, tile, ...]
        f = f.reshape(lead + (n_t * tile,) + f.shape[len(lead) + 2:])
        return jax.lax.slice_in_dim(f, 0, C, axis=len(lead))

    return SparsePicks(*(untile(f) for f in sp))


def sparse_to_pick_times(positions, selected) -> np.ndarray:
    """Sparse picks -> stacked (channel_idx[], time_idx[]) array in the
    reference's row-major order (detect.py:277-303)."""
    positions = np.asarray(positions)
    selected = np.asarray(selected)
    chan, slot = np.nonzero(selected)
    return np.asarray([chan, positions[chan, slot]])


@functools.partial(jax.jit, static_argnames=("capacity",))
def compact_picks_rowmajor(positions, selected, capacity: int):
    """Stable on-device compaction of fixed-capacity picks.

    ``positions``/``selected`` are ``[B, R, K]`` (batch, row, slot). For
    each batch entry the selected picks are packed — in the same
    row-major (row, slot) order ``np.nonzero`` walks — into fixed
    ``capacity``-length buffers, so only ``O(capacity)`` ints cross the
    device→host boundary instead of the full ``R*K`` slot grid. At the
    canonical detection shape that grid is hundreds of MB per call and
    dominated the measured on-chip wall (round-4 session, docs/PERF.md);
    real pick counts are 3-4 orders smaller.

    Returns ``(rows [B, capacity] int32, times [B, capacity] int32,
    count [B] int32)``. Entries past ``count`` are undefined padding; a
    ``count > capacity`` signals overflow — the caller must fall back to
    the full-transfer path (picks are NOT truncated silently).
    """
    B, R, K = positions.shape
    sel = selected.reshape(B, R * K)
    pos = positions.reshape(B, R * K)
    row_of = (jnp.arange(R * K, dtype=jnp.int32) // K)[None, :]
    # stable pack: cumsum gives each selected slot its output index
    idx = jnp.cumsum(sel.astype(jnp.int32), axis=-1) - 1
    dest = jnp.where(sel, idx, capacity)  # unselected -> dropped
    rows_out = jnp.zeros((B, capacity), jnp.int32).at[
        jnp.arange(B, dtype=jnp.int32)[:, None], dest
    ].set(jnp.broadcast_to(row_of, (B, R * K)), mode="drop")
    times_out = jnp.zeros((B, capacity), jnp.int32).at[
        jnp.arange(B, dtype=jnp.int32)[:, None], dest
    ].set(pos.astype(jnp.int32), mode="drop")
    count = jnp.sum(sel, axis=-1).astype(jnp.int32)
    return rows_out, times_out, count


def escalation_method(k: int, k_full: int) -> str:
    """THE method policy for adaptive-K picking: any attempt that a
    larger-capacity rerun can correct uses the sort-free ``"pack"``
    kernel; the full-capacity run (where truncation is final) uses
    ``"topk"`` so the documented tallest-K drop semantics hold wherever
    they can matter. Results are identical whenever no row saturates."""
    return "pack" if k < k_full else "topk"


def picks_with_escalation(run, k0: int, k_full: int):
    """Adaptive-K sparse picking: ``run(k)`` must return a result with a
    ``.saturated`` row mask. Runs at ``k0`` and reruns at ``k_full``
    only when a row saturated — bit-identical to running at ``k_full``
    directly, because ``saturated`` is precisely "more candidates than K
    passed the height prefilter" and a non-saturated row's picks are
    exact at any K. The kernel's slot tables scale with K, so the
    saturation-free common case is several times cheaper (docs/PERF.md
    knob A/B); pair with :func:`escalation_method` so the K0 attempt
    also skips the top-k sort. THE escalation policy: the detector
    routes and the bench's stage mirror all call this one function."""
    res = run(k0)
    if k0 < k_full and bool(np.asarray(res.saturated).any()):
        res = run(k_full)
    return res


def compacted_to_host(rows_d, times_d, cnt_d, capacity: int):
    """Bring ``compact_picks_rowmajor`` outputs to the host, or report
    overflow.

    Returns ``(rows int64 [..., kpad], times int64 [..., kpad],
    count np [...])`` with the slot axis sliced to the pow2-rounded max
    count (at most log2(capacity) distinct transfer shapes — no
    per-call retrace), or ``None`` when any count exceeds ``capacity``
    (caller must fall back to its exact full-grid path). int64 matches
    the ``np.nonzero`` dtype of the full-transfer paths so the public
    picks dtype never varies by route."""
    cnt = np.asarray(cnt_d)
    kmax = int(cnt.max(initial=0))
    if kmax > capacity:
        return None
    kpad = min(capacity, 1 << max(kmax - 1, 0).bit_length())
    return (
        np.asarray(rows_d[..., :kpad]).astype(np.int64),
        np.asarray(times_d[..., :kpad]).astype(np.int64),
        cnt,
    )


def pick_times_compacted(positions, selected, capacity: int = 1 << 18) -> np.ndarray:
    """``[C, K]`` sparse picks -> the reference's ``(2, n)``
    [channel_idx, time_idx] array with only O(capacity) ints crossing the
    device→host boundary (``compact_picks_rowmajor`` on device, padded
    transfer via ``compacted_to_host``) — the same boundary-crossing
    reduction the flagship detector ships; output order and dtype are
    identical to :func:`sparse_to_pick_times`, which remains the exact
    fallback on capacity overflow."""
    C, K = positions.shape
    cap = int(min(C * K, capacity))
    rows_d, times_d, cnt_d = compact_picks_rowmajor(
        positions[None], selected[None], cap
    )
    packed = compacted_to_host(rows_d, times_d, cnt_d, cap)
    if packed is None:
        return sparse_to_pick_times(positions, selected)
    rows, times, cnt = packed
    k = int(cnt[0])
    return np.asarray([rows[0, :k], times[0, :k]])


@functools.partial(jax.jit, static_argnames=("block_size",))
def find_peaks_prominence_blocked(x: jnp.ndarray, threshold, block_size: int = 1024) -> jnp.ndarray:
    """Channel-blocked variant of ``find_peaks_prominence`` for large
    ``[channel x time]`` inputs.

    The prominence descent holds O(log N) window tables per channel; at the
    full 22k-channel OOI selection that transient would exceed HBM, so
    channels are processed in blocks via ``lax.map`` (sequential over
    blocks, fully vectorized within a block).
    """
    c, n = x.shape
    nblocks = -(-c // block_size)
    pad = nblocks * block_size - c
    xp = jnp.pad(x, ((0, pad), (0, 0))).reshape(nblocks, block_size, n)
    out = jax.lax.map(lambda blk: find_peaks_prominence(blk, threshold), xp)
    return out.reshape(nblocks * block_size, n)[:c]


def find_peaks_scipy_host(env, threshold) -> np.ndarray:
    """Host-side exact picking: per-channel ``scipy.signal.find_peaks``.

    Returns the stacked ``(2, n)`` [channel_idx, time_idx] pick array. Same
    semantics as ``find_peaks_sparse`` (without the capacity limit) and as
    the reference's per-channel loop (detect.py:169-274). This is the right
    engine when the arrays live on a CPU host anyway: scipy's sequential
    walk beats the TPU-shaped block-table kernels on a scalar core by an
    order of magnitude (see docs/PERF.md), while on accelerator backends it
    would force a device->host round trip per block — use ``sparse`` there.
    """
    import scipy.signal as sp

    env = np.asarray(env)
    thr = np.broadcast_to(np.asarray(threshold), (env.shape[0],))
    chan: list = []
    time: list = []
    for i in range(env.shape[0]):
        pk = sp.find_peaks(env[i], prominence=thr[i])[0]
        chan.extend([i] * len(pk))
        time.extend(pk.tolist())
    return np.asarray([chan, time], dtype=np.int64).reshape(2, -1)


# ---------------------------------------------------------------------------
# Reference-shaped outputs (host side)
# ---------------------------------------------------------------------------

def mask_to_pick_lists(mask) -> List[np.ndarray]:
    """Dense peak mask -> ragged list of per-channel index arrays
    (the reference's ``pick_times``/``pick_times_env`` output shape,
    detect.py:169-274 — with channel order preserved, unlike
    ``pick_times_par``'s as_completed ordering bug at detect.py:244-245)."""
    mask = np.asarray(mask)
    return [np.nonzero(row)[0] for row in np.atleast_2d(mask)]


def convert_pick_times(peaks_indexes_m) -> np.ndarray:
    """Ragged pick lists -> stacked (channel_idx[], time_idx[]) array.

    Parity: reference ``detect.convert_pick_times`` (detect.py:277-303).
    Also accepts a dense boolean mask directly.
    """
    if isinstance(peaks_indexes_m, (np.ndarray, jnp.ndarray)) and np.asarray(peaks_indexes_m).dtype == bool:
        chan, time = np.nonzero(np.asarray(peaks_indexes_m))
        return np.asarray([chan, time])
    chan: list = []
    time: list = []
    for i, picks in enumerate(peaks_indexes_m):
        chan.extend([i] * len(picks))
        time.extend(list(picks))
    return np.asarray([chan, time])


def select_picked_times(idx_tp, tstart: float, tend: float, fs: float):
    """Restrict picks to a time window (reference ``detect.select_picked_times``,
    detect.py:306-330)."""
    sel = (idx_tp[1] >= tstart * fs) & (idx_tp[1] <= tend * fs)
    return idx_tp[0][sel], idx_tp[1][sel]


def warn_saturated(saturated, label: str, max_peaks: int) -> bool:
    """Surface pick-capacity saturation; returns True iff any slot saturated.

    Shared by every detector family (a truncated pick list must
    never pass silently). Fires BOTH ways on purpose: a logger warning,
    which repeats on every saturated call (``warnings`` dedups by source
    location, so in a detect-many campaign only the first file would
    warn), and a ``warnings.warn``, which callers can catch or escalate
    (the full-scale validators turn it into an error).
    """
    import warnings

    n = int(np.asarray(saturated).sum())
    if not n:
        return False
    from ..utils.log import get_logger

    msg = (f"peak capacity saturated for {label} on {n} channel slots; "
           f"picks beyond the {max_peaks} tallest were dropped — raise "
           f"max_peaks to keep them")
    get_logger("das4whales_tpu.ops.peaks").warning(msg)
    warnings.warn(msg)
    return True
