"""Frequency-wavenumber (f-k) filter design and application.

TPU-native rebuild of the reference's f-k stack (dsp.py:85-702,725-786,
883-953). The reference designs each mask with a Python loop over the 12k
frequency (or 22k wavenumber) bins and compresses the result with
``sparse.COO``; here every designer is a broadcasted closed-form evaluation
on the full ``[k x f]`` grid — one vectorized expression, no loops — and the
mask stays dense (on TPU a dense bf16/f32 mask is a cheap elementwise
multiply and regenerating it is microseconds, cf. SURVEY.md §2.3).

Design happens host-side in float64 numpy (design-once / apply-many, like
the Butterworth coefficients); application is a jitted 2-D FFT -> mask ->
inverse round trip on device.

Mask-value parity with the reference loops is exact: the same transition
expressions are evaluated on the same fftshifted axes, with later-assignment
-wins semantics reproduced by nested ``where``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import scipy.signal as sp
from scipy import ndimage

from ..config import ChannelSelection


def fk_axes(trace_shape: Tuple[int, int], selected_channels, dx: float, fs: float):
    """fftshifted frequency [Hz] and wavenumber [1/m] axes for a
    ``[channel x time]`` block (reference convention, dsp.py:129-130)."""
    sel = ChannelSelection.from_list(selected_channels)
    nnx, nns = trace_shape
    freq = np.fft.fftshift(np.fft.fftfreq(nns, d=1 / fs))
    knum = np.fft.fftshift(np.fft.fftfreq(nnx, d=sel.step * dx))
    return freq, knum


def _sine_ramp(x, lo, hi):
    """sin(pi/2 * (x - lo) / (hi - lo)) with safe division."""
    denom = np.where(hi == lo, 1.0, hi - lo)
    return np.sin(0.5 * np.pi * (x - lo) / denom)


def fk_filter_design(
    trace_shape, selected_channels, dx, fs,
    cs_min=1400.0, cp_min=1450.0, cp_max=3400.0, cs_max=3500.0,
) -> np.ndarray:
    """Speed-fan f-k filter with sine transition bands.

    Parity: reference ``dsp.fk_filter_design`` (dsp.py:85-171) — passband for
    apparent speeds in ``[cp_min, cp_max]``, sine ramps over
    ``[cs_min, cp_min]`` and ``[cp_max, cs_max]``, and rows with
    ``|k| < 0.005`` zeroed. The reference's per-wavenumber loop becomes one
    broadcast over the ``[k x f]`` grid.
    """
    freq, knum = fk_axes(trace_shape, selected_channels, dx, fs)
    K = knum[:, None]
    with np.errstate(divide="ignore", invalid="ignore"):
        speed = np.abs(freq[None, :] / K)

    m = np.ones_like(speed)
    up = (speed >= cs_min) & (speed <= cp_min)
    down = (speed >= cp_max) & (speed <= cs_max)
    with np.errstate(invalid="ignore"):
        m = np.where(up, _sine_ramp(np.where(up, speed, 0.0), cs_min, cp_min), m)
        m = np.where(down, 1.0 - _sine_ramp(np.where(down, speed, 0.0), cp_max, cs_max), m)
    m = np.where(speed >= cs_max, 0.0, m)
    m = np.where(speed < cs_min, 0.0, m)
    m = np.where(np.abs(K) < 0.005, 0.0, m)
    return m


def _bandpass_H_sine(freq, fmin, fmax, df_taper=4.0) -> np.ndarray:
    """Sine-tapered bandpass frequency response (dsp.py:214-231)."""
    fpmin, fpmax = fmin - df_taper, fmax + df_taper
    H = np.zeros_like(freq)
    rup = (freq >= fpmin) & (freq <= fmin)
    H[rup] = np.sin(0.5 * np.pi * (freq[rup] - fpmin) / (fmin - fpmin))
    H[(freq >= fmin) & (freq <= fmax)] = 1.0
    rdo = (freq >= fmax) & (freq <= fpmax)
    H[rdo] = np.cos(0.5 * np.pi * (freq[rdo] - fmax) / (fmax - fpmax))
    return H


def _col_range_mask(freq, fpmin, fpmax) -> np.ndarray:
    """Boolean over frequency bins replicating the reference's
    ``range(argmax(freq>=fpmin), argmax(freq>=fpmax))`` column loop bounds."""
    ns = len(freq)
    fmin_idx = int(np.argmax(freq >= fpmin))
    fmax_idx = int(np.argmax(freq >= fpmax))
    idx = np.arange(ns)
    return (idx >= fmin_idx) & (idx < fmax_idx)


def hybrid_filter_design(
    trace_shape, selected_channels, dx, fs,
    cs_min=1400.0, cp_min=1450.0, fmin=15.0, fmax=25.0,
) -> np.ndarray:
    """Infinite-wave-speed bandpass f-k hybrid filter, sine tapers.

    Parity: reference ``dsp.hybrid_filter_design`` (dsp.py:174-305):
    sine-tapered bandpass H(f) replicated along k, multiplied per frequency
    column by a highpass-in-speed fan with sine ramps between ``cs_min`` and
    ``cp_min``, then symmetrized with ``M += fliplr(M)``.
    """
    freq, knum = fk_axes(trace_shape, selected_channels, dx, fs)
    H = _bandpass_H_sine(freq, fmin, fmax, df_taper=4.0)
    M = np.tile(H, (len(knum), 1))

    in_cols = _col_range_mask(freq, fmin - 4.0, fmax + 4.0)
    K = knum[:, None]
    ks = freq / cs_min  # [f]
    kp = freq / cp_min
    valid = ks != kp

    m1 = (K >= -ks) & (K <= -kp)  # f+ k- ramp
    m2 = (K <= ks) & (K >= kp)    # f+ k+ ramp (reference's -knum form)
    pb = (K < kp) & (K > -kp)
    with np.errstate(divide="ignore", invalid="ignore"):
        v1 = -_sine_ramp(K, -ks, -ks + (kp - ks))  # -sin(pi/2 (K+ks)/(kp-ks))
        v2 = _sine_ramp(K, ks, ks + (kp - ks))     # sin(pi/2 (K-ks)/(kp-ks))
    col = np.where(pb, 1.0, np.where(m2 & valid, v2, np.where(m1 & valid, v1, 0.0)))
    M = np.where(in_cols[None, :], M * col, M)
    M += np.fliplr(M)
    return M


def butterworth_bandpass_H(freq, fs, fmin, fmax, order=8) -> np.ndarray:
    """One-sided squared Butterworth magnitude over the fftshifted frequency
    axis: zeros on the negative half, ``|freqz|^2`` on the positive half
    (reference construction, dsp.py:348-349)."""
    ns = len(freq)
    b, a = sp.butter(order, [fmin / (fs / 2), fmax / (fs / 2)], "bp")
    H_pos = np.abs(sp.freqz(b, a, worN=ns // 2)[1]) ** 2
    return np.concatenate((np.zeros(ns - ns // 2), H_pos))


def hybrid_ninf_filter_design(
    trace_shape, selected_channels, dx, fs,
    cs_min=1400.0, cp_min=1450.0, cp_max=3400.0, cs_max=3500.0,
    fmin=15.0, fmax=25.0,
) -> np.ndarray:
    """Band-limited (non-infinite speed) bandpass f-k hybrid filter.

    Parity: reference ``dsp.hybrid_ninf_filter_design`` (dsp.py:308-454) —
    the flagship filter of ``main_mfdetect.py:46``. Butterworth-8 squared
    magnitude along f (positive half only), speed fan with sine ramps from
    ``cs_max -> cp_max`` (low-k edge) and ``cp_min -> cs_min`` (high-k
    edge), then two symmetrizations ``M += fliplr(M); M += flipud(M)``.
    """
    freq, knum = fk_axes(trace_shape, selected_channels, dx, fs)
    H = butterworth_bandpass_H(freq, fs, fmin, fmax, order=8)
    M = np.tile(H, (len(knum), 1))

    in_cols = _col_range_mask(freq, fmin - 14.0, fmax + 14.0)
    K = knum[:, None]
    ks_min = freq / cs_max
    kp_min = freq / cp_max
    ks_max = freq / cs_min
    kp_max = freq / cp_min
    v_up_valid = ks_min != kp_min
    v_do_valid = ks_max != kp_max

    m_up = (K >= ks_min) & (K <= kp_min)
    m_do = (K >= kp_max) & (K <= ks_max)
    pb = (K > kp_min) & (K < kp_max)
    with np.errstate(divide="ignore", invalid="ignore"):
        v_up = _sine_ramp(K, ks_min, ks_min + (kp_min - ks_min))
        # reference: -sin(pi/2 (K - ks_max)/(ks_max - kp_max))
        v_do = -_sine_ramp(K, ks_max, ks_max + (ks_max - kp_max))
    col = np.where(pb, 1.0, np.where(m_do & v_do_valid, v_do, np.where(m_up & v_up_valid, v_up, 0.0)))
    M = np.where(in_cols[None, :], M * col, M)
    M += np.fliplr(M)
    M += np.flipud(M)
    return M


def hybrid_gs_filter_design(
    trace_shape, selected_channels, dx, fs,
    cs_min=1400.0, cp_min=1450.0, fmin=15.0, fmax=25.0, sigma=20.0,
) -> np.ndarray:
    """Infinite-wave-speed filter with Gaussian-smoothed edges.

    Parity: reference ``dsp.hybrid_gs_filter_design`` (dsp.py:457-579):
    binary passband H(f) on [fmin, fmax], per-column binary speed passband
    ``|k| < f/cp_min``, symmetrize with fliplr, then a sigma=20 Gaussian
    smooth. (The reference's dangling taper-mask assignments at
    dsp.py:524-529 are dead code and intentionally not reproduced.)
    """
    freq, knum = fk_axes(trace_shape, selected_channels, dx, fs)
    H = ((freq >= fmin) & (freq <= fmax)).astype(float)
    M = np.tile(H, (len(knum), 1))

    in_cols = _col_range_mask(freq, fmin - 4.0, fmax + 4.0)
    K = knum[:, None]
    kp = freq / cp_min
    col = ((K < kp) & (K > -kp)).astype(float)
    M = np.where(in_cols[None, :], M * col, M)
    M += np.fliplr(M)
    M = ndimage.gaussian_filter(M, sigma)
    return M


def hybrid_ninf_gs_filter_design(
    trace_shape, selected_channels, dx, fs,
    cs_min=1400.0, cp_min=1450.0, cp_max=3400.0, cs_max=3500.0,
    fmin=15.0, fmax=25.0, sigma=20.0,
) -> np.ndarray:
    """Band-limited filter with Gaussian-smoothed edges.

    Parity: reference ``dsp.hybrid_ninf_gs_filter_design`` (dsp.py:582-702):
    binary passband in f, per-column binary annulus
    ``-f/cp_min < k < -f/cp_max``, Gaussian smooth (sigma=20) *before* the
    fliplr/flipud symmetrizations — order preserved from the reference.
    """
    freq, knum = fk_axes(trace_shape, selected_channels, dx, fs)
    H = ((freq >= fmin) & (freq <= fmax)).astype(float)
    M = np.tile(H, (len(knum), 1))

    in_cols = _col_range_mask(freq, fmin - 4.0, fmax + 4.0)
    K = knum[:, None]
    kp_min = freq / cp_min
    kp_max = freq / cp_max
    col = ((K > -kp_min) & (K < -kp_max)).astype(float)
    M = np.where(in_cols[None, :], M * col, M)
    M = ndimage.gaussian_filter(M, sigma)
    M += np.fliplr(M)
    M += np.flipud(M)
    return M


def speed_fan_mask(
    trace_shape, fs, dx, c_min, c_max, tint=1.0, xint=1.0, sigma=20.0,
) -> np.ndarray:
    """Gaussian-smoothed binary speed-fan mask, min-max normalized.

    Parity: the mask inside reference ``dsp.fk_filt`` (dsp.py:883-953) and
    its dask chunk variant (tools.py:27-52, which uses sigma=40): keep
    ``c_min < |f/k| < c_max``, smooth, normalize to [0, 1].
    """
    nx, ns = trace_shape
    f = np.fft.fftshift(np.fft.fftfreq(ns, d=tint / fs))
    k = np.fft.fftshift(np.fft.fftfreq(nx, d=xint * dx))
    ff, kk = np.meshgrid(f, k)
    g = 1.0 * ((ff < kk * c_min) & (ff < -kk * c_min))
    g2 = 1.0 * ((ff < kk * c_max) & (ff < -kk * c_max))
    g = g + np.fliplr(g)
    g = g - (g2 + np.fliplr(g2))
    g = ndimage.gaussian_filter(g, sigma)
    g = (g - g.min()) / (g.max() - g.min())
    return g


# ---------------------------------------------------------------------------
# Application (device, jitted)
# ---------------------------------------------------------------------------

@jax.jit
def fk_filter_apply(trace: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Apply a pre-designed f-k mask: ``real(ifft2(ifftshift(fftshift(fft2(x)) * M)))``.

    Parity: reference ``dsp.fk_filter_filt`` / ``fk_filter_sparsefilt``
    (dsp.py:725-786) minus the sparse round trip. One fused XLA program on
    TPU; no host transfers.
    """
    fk = jnp.fft.fftshift(jnp.fft.fft2(trace))
    filtered = jnp.fft.ifft2(jnp.fft.ifftshift(fk * mask.astype(fk.real.dtype)))
    return filtered.real.astype(trace.dtype)


def _point_reflect(m: jnp.ndarray) -> jnp.ndarray:
    """``m[(-i) % N, (-j) % M]`` — spectral point reflection in fft order."""
    for ax in (0, 1):
        m = jnp.roll(jnp.flip(m, axis=ax), 1, axis=ax)
    return m


@jax.jit
def fk_filter_apply_rfft(trace: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Half-spectrum fast path: rFFT along time + full FFT along channels.

    Mathematically *identical* to ``fk_filter_apply``: taking ``.real`` of
    the full complex pipeline is equivalent to applying the Hermitian part
    of the mask, ``(M(k,f) + M(-k,-f)) / 2``. This path symmetrizes the mask
    explicitly, keeps only the non-negative-frequency half of the spectrum
    (rfft2 layout), and reconstructs with irfft — halving FFT flops and
    spectrum memory.
    """
    nnx, nns = trace.shape
    mu = jnp.fft.ifftshift(mask).astype(trace.dtype)  # [k x f], fft order
    msym = 0.5 * (mu + _point_reflect(mu))
    mask_half = msym[:, : nns // 2 + 1]
    spec = jnp.fft.fft(jnp.fft.rfft(trace, axis=1), axis=0)  # k x f_half
    spec = spec * mask_half.astype(spec.real.dtype)
    out = jnp.fft.irfft(jnp.fft.ifft(spec, axis=0), n=nns, axis=1)
    return out.real.astype(trace.dtype)


def fk_filt(
    data: jnp.ndarray, tint, fs, xint, dx, c_min, c_max, sigma: float = 20.0,
) -> jnp.ndarray:
    """Design-and-apply Gaussian speed-fan filter in one call.

    Parity: reference ``dsp.fk_filt`` (dsp.py:883-953).
    """
    mask = speed_fan_mask(data.shape, fs, dx, c_min, c_max, tint=tint, xint=xint, sigma=sigma)
    return fk_filter_apply(data, jnp.asarray(mask))


def symmetrize_mask_fftorder(mask: np.ndarray) -> np.ndarray:
    """fftshifted ``[k x f]`` design mask -> point-reflect-symmetrized full
    mask in fft order on both axes (guarantees a real filter output; the
    device-side analogue is ``_point_reflect``). Single source of truth for
    the mask convention shared by the single-device banded applier and the
    sharded f-k paths (``parallel.fft`` re-exports it)."""
    mu = np.fft.ifftshift(np.asarray(mask))
    pr = mu
    for ax in (0, 1):
        pr = np.roll(np.flip(pr, axis=ax), 1, axis=ax)
    return 0.5 * (mu + pr)


def banded_mask_half(mask, tol: float = 1e-6) -> tuple:
    """Host-side prep for the band-limited applier: symmetrize the
    fftshifted mask exactly as ``fk_filter_apply_rfft`` does, keep the
    non-negative-frequency half, and crop to the contiguous rfft-bin band
    outside which every column peaks below ``tol * max(mask)``.

    Every f-k mask this framework designs is band-limited in frequency
    (the speed fan lives inside [fmin, fmax] — 14-30 Hz of a 100 Hz
    Nyquist), but the designers' Gaussian frequency tapers have long
    tails; at the default ``tol=1e-6`` the kept band is ~35% of the bins.
    The channel-axis FFT/IFFT then runs only on in-band columns (~3x
    fewer channel-FFT FLOPs). The cropped tail's contribution is bounded
    by ``tol`` times the in-band gain AND multiplies data the upstream
    Butterworth-8 bandpass has already crushed out of band — far below
    float32 roundoff of the result. ``tol=0`` keeps strictly-nonzero
    support (exact).

    This is the TPU-native analog of the reference's ``sparse.COO`` f-k
    filter (dsp.py:725-786, tools.py:255-257: 25.4x compression at the
    canonical shape) — the same sparsity, exploited for FLOPs and HBM
    instead of host RAM.

    Returns ``(mask_band [C, hi-lo] float32 numpy, lo, hi)``.
    """
    m = np.asarray(mask)
    nns = m.shape[1]
    half = symmetrize_mask_fftorder(m)[:, : nns // 2 + 1]
    col = np.abs(half).max(axis=0)
    thr = tol * float(col.max()) if col.max() > 0 else 0.0
    nz = np.nonzero(col > thr)[0]
    if nz.size == 0:
        lo, hi = 0, 1
    else:
        lo, hi = int(nz[0]), int(nz[-1]) + 1
    return half[:, lo:hi].astype(np.float32), lo, hi


@functools.partial(jax.jit, static_argnames=("lo", "hi"))
def fk_filter_apply_rfft_banded(
    trace: jnp.ndarray, mask_band: jnp.ndarray, lo: int, hi: int
) -> jnp.ndarray:
    """Band-limited half-spectrum f-k apply.

    Output equals ``fk_filter_apply_rfft`` exactly when the mask is zero
    outside rfft bins ``[lo, hi)`` (``banded_mask_half(tol=0)``); at the
    default ``tol=1e-6`` crop the difference is bounded by the cropped
    taper tail (<= tol relative, further attenuated by the upstream
    bandpass — below float32 roundoff in the pipeline). The channel-axis
    FFT/IFFT pair runs only on the in-band columns: ~3x fewer channel-FFT
    FLOPs and a ~3x smaller mask at the canonical 14-30 Hz band with the
    default tolerance."""
    nnx, nns = trace.shape
    Xf = jnp.fft.rfft(trace, axis=1)                       # [C, F]
    Ys = jnp.fft.fft(Xf[:, lo:hi], axis=0) * mask_band.astype(Xf.real.dtype)
    Zs = jnp.fft.ifft(Ys, axis=0)
    Z = jnp.zeros_like(Xf).at[:, lo:hi].set(Zs)
    return jnp.fft.irfft(Z, n=nns, axis=1).astype(trace.dtype)


def compression_report(mask: np.ndarray, itemsize: int = 8, verbose: bool = True):
    """Report dense vs sparse storage of an f-k mask.

    Capability parity with reference ``tools.disp_comprate`` (tools.py:239-257),
    which reports the ``sparse.COO`` savings. On TPU the mask is kept dense
    (elementwise multiply is HBM-bandwidth-trivial), but the report remains
    for cost observability.
    """
    mask = np.asarray(mask)
    nnz = int(np.count_nonzero(mask))
    sparse_gib = nnz * itemsize / 1024**3
    dense_gib = mask.size * itemsize / 1024**3
    ratio = dense_gib / sparse_gib if sparse_gib > 0 else float("inf")
    pct = abs(dense_gib - sparse_gib) * 100 / dense_gib if dense_gib else 0.0
    if verbose:
        print(f"The size of the sparse filter is {sparse_gib:.4f} Gib")
        print(f"The size of the dense filter is {dense_gib:.2f} Gib")
        print(f"The compression ratio is {ratio:.2f} ({pct:.1f} %)")
    return {"sparse_gib": sparse_gib, "dense_gib": dense_gib, "ratio": ratio, "pct": pct}
