"""Distributed 2-D FFT and f-k filtering over a channel-sharded mesh.

The reference's single biggest array op is the monolithic
``fft2``/``ifft2`` of the 22k x 12k strain block (dsp.py:748-786). To scale
that across chips the channel axis is sharded and the transform runs as a
pencil decomposition (cf. "Large-Scale Discrete Fourier Transform on TPUs",
PAPERS.md):

1. rFFT along time — fully local (time axis unsharded);
2. ``all_to_all`` transpose over the ``channel`` mesh axis: the local
   frequency axis is scattered, the channel axis gathered;
3. FFT along channels — now fully local;
4. multiply the (frequency-sharded) f-k mask;
5. inverse channel FFT, ``all_to_all`` back, inverse rFFT.

The only communication is the two all_to_alls, which ride ICI. The result
is *exactly* the single-device ``fk_filter_apply_rfft`` (no chunk-boundary
error — contrast with the reference's per-chunk dask filtering whose
boundary error is acknowledged at tools.py:166).

Functions ending in ``_local`` are shard_map bodies (take an ``axis_name``);
the top-level helpers wrap them for direct use.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from .compat import axis_size as _axis_size, shard_map


# single source of truth lives beside the appliers; re-exported here for
# the sharded paths' existing import surface
from ..ops.fk import banded_mask_half, symmetrize_mask_fftorder  # noqa: F401,E402


def prepare_mask_half(mask: np.ndarray, nns: int, pad_f: int = 0) -> np.ndarray:
    """Hermitian-symmetrize an fftshifted ``[k x f]`` mask and keep the
    rfft half ``[k x nns//2+1]`` (fft order along k), optionally zero-padded
    along f to a multiple of the mesh axis size."""
    half = symmetrize_mask_fftorder(mask)[:, : nns // 2 + 1]
    if pad_f:
        half = np.pad(half, ((0, 0), (0, pad_f)))
    return half


def fk_apply_local(trace: jnp.ndarray, mask_half: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """shard_map body: f-k filter a channel-sharded ``[..., C/P, T]`` block
    against an f-sharded half mask ``[..., K, F_pad/P]``. The full-band
    special case of ``fk_apply_local_banded`` (lo=0, hi=nf)."""
    nns = trace.shape[-1]
    return fk_apply_local_banded(trace, mask_half, 0, nns // 2 + 1, axis_name)


def fk_apply_local_banded(
    trace: jnp.ndarray, mask_band: jnp.ndarray, lo: int, hi: int, axis_name: str
) -> jnp.ndarray:
    """Band-limited ``fk_apply_local``: the two ``all_to_all`` transposes
    and the channel-axis FFT/IFFT pair carry ONLY the mask's in-band rfft
    columns ``[lo, hi)`` (``ops.fk.banded_mask_half``) — at the canonical
    14-30 Hz band that is ~3x less collective volume over ICI and ~3x
    fewer channel-FFT FLOPs per shard. Out-of-band columns of the
    filtered spectrum are (taper-tail-bounded) zero and are scattered back
    as literal zeros before the inverse time transform.

    ``mask_band`` is ``[K, B_pad/P]`` f-sharded, where ``B_pad`` is
    ``hi - lo`` padded to a multiple of the mesh axis size.
    """
    p = _axis_size(axis_name)
    nns = trace.shape[-1]
    nf = nns // 2 + 1
    nb = hi - lo
    pad_b = (-nb) % p

    spec = jnp.fft.rfft(trace, axis=-1)            # [..., C/P, F]
    band = spec[..., lo:hi]
    if pad_b:
        widths = [(0, 0)] * (band.ndim - 1) + [(0, pad_b)]
        band = jnp.pad(band, widths)
    # transpose: scatter the band, gather C -> [..., C, Bp/P]
    band = jax.lax.all_to_all(
        band, axis_name, split_axis=band.ndim - 1, concat_axis=band.ndim - 2, tiled=True
    )
    band = jnp.fft.fft(band, axis=-2)
    band = band * mask_band.astype(band.real.dtype)
    band = jnp.fft.ifft(band, axis=-2)
    # transpose back: scatter C, gather the band -> [..., C/P, Bp]
    band = jax.lax.all_to_all(
        band, axis_name, split_axis=band.ndim - 2, concat_axis=band.ndim - 1, tiled=True
    )
    if pad_b:
        band = band[..., :nb]
    full = jnp.zeros(spec.shape[:-1] + (nf,), dtype=spec.dtype)
    full = full.at[..., lo:hi].set(band)
    out = jnp.fft.irfft(full, n=nns, axis=-1)
    return out.real.astype(trace.dtype)


def prepare_mask_band(mask: np.ndarray, p: int, tol: float = 1e-6):
    """Host prep for ``fk_apply_local_banded``: banded half-spectrum mask
    padded along f to a multiple of the mesh axis size ``p``.
    Returns ``(mask_band [K, B_pad], lo, hi)``."""
    mask_band, lo, hi = banded_mask_half(mask, tol=tol)
    pad_b = (-(hi - lo)) % p
    if pad_b:
        mask_band = np.pad(mask_band, ((0, 0), (0, pad_b)))
    return mask_band, lo, hi


def sharded_fk_apply(
    trace, mask, mesh: Mesh, channel_axis: str = "channel"
):
    """f-k filter a ``[channel x time]`` block sharded over ``channel_axis``.

    ``mask`` is the fftshifted design matrix from any ops.fk designer.
    Numerically identical to ``ops.fk.fk_filter_apply_rfft`` on one device.
    """
    nnx, nns = trace.shape
    p = mesh.shape[channel_axis]
    if nnx % p:
        raise ValueError(f"channel count {nnx} not divisible by mesh axis {channel_axis}={p}")
    nf = nns // 2 + 1
    pad_f = (-nf) % p
    mask_half = jnp.asarray(prepare_mask_half(mask, nns, pad_f))
    return _fk_channel_fn(mesh, channel_axis)(trace, mask_half)


@functools.lru_cache(maxsize=32)
def _fk_channel_fn(mesh: Mesh, channel_axis: str):
    """Cached jitted program per (mesh, axis): rebuilding shard_map + jit
    per call is a fresh function object, re-tracing on every file of a
    campaign (the mask stays a runtime argument)."""
    return jax.jit(shard_map(
        functools.partial(fk_apply_local, axis_name=channel_axis),
        mesh=mesh,
        in_specs=(P(channel_axis, None), P(None, channel_axis)),
        out_specs=P(channel_axis, None),
    ))


def pfft2(x, mesh: Mesh, channel_axis: str = "channel"):
    """Distributed complex 2-D FFT of a channel-sharded block; returns the
    spectrum sharded over the *frequency* axis (natural pencil layout
    ``[K, F/P]`` restored to ``[K/P is not applied; layout [K, F] sharded
    on F]``)."""
    nnx, nns = x.shape
    p = mesh.shape[channel_axis]
    if nnx % p or nns % p:
        raise ValueError("both axes must be divisible by the mesh axis size")
    return _pfft2_fn(mesh, channel_axis)(x)


@functools.lru_cache(maxsize=32)
def _pfft2_fn(mesh: Mesh, channel_axis: str):
    def body(xs):
        s = jnp.fft.fft(xs, axis=-1)
        s = jax.lax.all_to_all(s, channel_axis, split_axis=1, concat_axis=0, tiled=True)
        return jnp.fft.fft(s, axis=-2)

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(channel_axis, None),),
        out_specs=P(None, channel_axis),
    ))
