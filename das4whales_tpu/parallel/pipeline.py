"""Sharded end-to-end detection steps (multi-file x multi-chip).

Composes the full matched-filter pipeline inside one ``shard_map`` over a
``(file, channel)`` mesh: data parallelism over independent files, channel
parallelism within each file, with the two ``all_to_all`` transposes of the
distributed f-k transform as the only communication (plus one ``pmax`` for
the per-file threshold). This is the TPU-native replacement of the
reference's per-file serial loop + dask chunking (SURVEY.md §2.4, §5.8).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .compat import shard_map

from ..ops import conditioning as cond_ops
from ..ops import peaks as peak_ops
from ..ops import spectral, xcorr
from ..ops.filters import _odd_ext
from .fft import fk_apply_local_banded, prepare_mask_band


def _bp_local(trace: jnp.ndarray, gain: jnp.ndarray, padlen: int) -> jnp.ndarray:
    """Zero-phase bandpass along time (local to every shard)."""
    ext = _odd_ext(trace, padlen)
    spec = jnp.fft.rfft(ext, axis=-1)
    y = jnp.fft.irfft(spec * gain.astype(spec.real.dtype), n=ext.shape[-1], axis=-1)
    return y[..., padlen:-padlen].astype(trace.dtype)


def _mf_body(
    trace, mask_band, bp_gain, templates_true, template_mu, template_scale,
    cond_scale, *,
    band_lo: int, band_hi: int, bp_padlen: int, channel_axis: str,
    relative_threshold: float, threshold_factors, pick_mode: str,
    max_peaks: int,
    outputs: str = "full", fused: bool = False, pick_tile: int = 512,
    pick_method: str = "topk", condition: bool = False,
    threshold_scope: str = "global",
):
    """shard_map body. Local shapes: trace [B/Pf, C/Pc, T], mask_band
    [K, Bpad/Pc] (band-limited half-spectrum — the all_to_alls and
    channel FFTs carry only in-band columns, parallel/fft.py), bp_gain
    [Fext], templates_true [nT, m] (TRUE length — the memory-lean
    correlate route, ops/xcorr.py:padded_template_stats, halves the
    per-shard FFT temps vs the padded form)."""
    if condition:
        # narrow-wire prologue (wire="raw"): raw stored-dtype counts ->
        # strain, per shard. Time is unsharded here, so the per-channel
        # demean is shard-local — no collective (ops/conditioning.py)
        trace = cond_ops.condition(
            trace, cond_scale, dtype=templates_true.dtype
        )
    # fused mode: |H(f)|^2 is already folded into mask_band at design
    # time — skip the separate bandpass program (same math and edge
    # contract as the single-chip fused route,
    # models/matched_filter.py:mf_filter_fused)
    tr_bp = trace if fused else _bp_local(trace, bp_gain, bp_padlen)
    trf_fk = fk_apply_local_banded(tr_bp, mask_band, band_lo, band_hi, channel_axis)

    corr = xcorr.compute_cross_correlograms_corrected(
        trf_fk, templates_true, template_mu, template_scale
    )
    env = spectral.envelope_sqrt(corr, axis=-1)

    # per-file threshold base; the bank's per-template factor vector
    # (models/templates.py) is closed over at factory time — no
    # index-0-is-HF assumption
    factors = jnp.asarray(threshold_factors)
    if threshold_scope == "per_template":
        # decoupled bank scope: each template's base from ITS OWN
        # per-file max (pmax over the channel shards) — [nT, B/Pf]
        local_max = jnp.max(corr, axis=(2, 3))
        thres = relative_threshold * jax.lax.pmax(local_max, channel_axis)
        thr = (thres * factors[:, None])[:, :, None, None]
    else:
        # reference policy: one max over templates/channels/time couples
        # every template of the file
        local_max = jnp.max(corr, axis=(0, 2, 3))                 # [B/Pf]
        file_max = jax.lax.pmax(local_max, channel_axis)
        thres = relative_threshold * file_max                      # [B/Pf]
        thr = thres[None, :, None, None] * factors[:, None, None, None]

    if pick_mode == "sparse":
        # TPU production route (ops/peaks.py): envelope peaks are
        # nonnegative, so the height prefilter is exact; time is unsharded
        # here, so positions are global sample indices already. The pick
        # kernel walks CHANNEL TILES exactly like the single-chip route
        # (ops.peaks.find_peaks_sparse_tiled): untiled at a canonical
        # shard shape its [rows, K, blocks] sqrt-decomposition tables
        # accessed ~17x the single-chip program's HBM bytes
        # (scripts/derive_multichip.py cost model).
        picks = peak_ops.find_peaks_sparse_tiled(
            env, thr[..., 0], max_peaks=max_peaks, tile=pick_tile,
            method=pick_method,
        )
    else:
        # dense debug route: exact per-sample prominences, gather-heavy
        picks = peak_ops.local_maxima(env) & (
            peak_ops.peak_prominences_dense(env) >= thr
        )
    if outputs == "picks":
        # campaign mode: only the (tiny) picks + thresholds leave the
        # program, so XLA never has to keep the [nT, B, C/Pc, T] correlogram
        # and envelope blocks alive as outputs — ~3x less HBM per shard
        return picks, thres
    return trf_fk, corr, env, picks, thres


def make_sharded_mf_step(
    design,
    mesh: Mesh,
    file_axis: str = "file",
    channel_axis: str = "channel",
    relative_threshold: float = 0.5,
    hf_factor: float | None = None,
    threshold_factors=None,
    threshold_scope: str | None = None,
    pick_mode: str = "sparse",
    max_peaks: int = 256,
    outputs: str = "full",
    fused_bandpass: bool = True,
    pick_tile: int = 512,
    pick_method: str = "topk",
    wire: str = "conditioned",
    scale_factor: float | None = None,
):
    """Build the jitted multi-chip detection step for a
    ``[file x channel x time]`` batch.

    ``wire="raw"`` makes the step consume NARROW-WIRE batches
    (``io.stream.stream_file_batches(wire="raw")``): the stored-dtype
    counts land pre-sharded on the mesh and the demean+scale conditioning
    (``ops.conditioning``) runs as the SPMD body's first fused pass using
    ``scale_factor`` (required then — the design does not carry it). Picks
    are bit-identical to the conditioned wire; the input batch is not
    donated because the campaigns' adaptive-K policy reruns the step on
    the same batch (analysis/baseline.toml R5 entry).

    ``pick_tile``/``pick_method`` tune the sparse pick stage exactly like
    the single-chip route (channel tiles via ``lax.map``; see
    ``ops.peaks.find_peaks_sparse`` for the pack-vs-topk contract). The
    campaigns run an adaptive two-phase policy: a K0=64 ``"pack"`` step
    first, escalating to this full-capacity ``"topk"`` step only when a
    row saturates (``ops.peaks.escalation_method`` semantics across
    programs).

    ``fused_bandpass=True`` folds |H(f)|² into the f-k mask before the
    band crop — the multi-chip analog of
    ``MatchedFilterDetector(fused_bandpass=True)`` (same edge-numerics
    contract, golden-certified in VALIDATION.md): the bandpass's
    per-shard rfft round trip disappears.

    ``outputs="full"`` returns ``(trf_fk, corr, env, picks, thresholds)``;
    ``outputs="picks"`` returns only ``(picks, thresholds)`` — the campaign
    mode: the filtered block, correlograms and envelopes never become
    program outputs, so per-shard HBM drops ~3x and multi-file batches can
    be correspondingly larger.

    ``design`` is a ``models.matched_filter.MatchedFilterDesign``. With
    ``outputs="full"`` the returned callable maps a sharded batch to
    ``(trf_fk, correlograms, envelopes, picks, thresholds)`` with matching
    shardings (``outputs="picks"`` returns the 2-tuple above) — ready for
    ``jax.jit`` ahead-of-time compilation on any mesh shape, including the
    single-chip degenerate mesh.

    ``pick_mode="sparse"`` (production, matching the single-chip
    ``MatchedFilterDetector`` default) yields ``picks`` as an
    ``ops.peaks.SparsePicks`` of ``[n_templates, file, channel, K]`` arrays
    (positions/heights/prominences/selected) plus a per-row ``saturated``
    flag. ``pick_mode="dense"`` (debug) yields the full boolean peak mask —
    exact everywhere but gather-heavy on TPU (ops/peaks.py:170-186).
    """
    if pick_mode not in ("sparse", "dense"):
        raise ValueError(f"pick_mode must be 'sparse' or 'dense', got {pick_mode!r}")
    if outputs not in ("full", "picks"):
        raise ValueError(f"outputs must be 'full' or 'picks', got {outputs!r}")
    if wire not in ("conditioned", "raw"):
        raise ValueError(f"unknown wire {wire!r}; expected 'conditioned' or 'raw'")
    if wire == "raw" and scale_factor is None:
        raise ValueError("wire='raw' needs scale_factor (metadata.scale_factor)")
    nnx, nns = design.trace_shape
    if design.fk_channels != nnx:
        raise ValueError(
            "channel-padded designs (design_matched_filter(channel_pad=...)) "
            "are single-chip only; design without padding for the sharded step"
        )
    pc = mesh.shape[channel_axis]
    if nnx % pc:
        raise ValueError(f"channels {nnx} not divisible by {channel_axis}={pc}")
    fk_mask = design.fk_mask
    if fused_bandpass:
        from ..ops.filters import butter_zero_phase_gain_full

        gain_full = butter_zero_phase_gain_full(
            nns, design.fs, design.bp_band, design.bp_order
        )
        fk_mask = fk_mask * gain_full[None, :].astype(fk_mask.dtype)
    mask_band_np, band_lo, band_hi = prepare_mask_band(fk_mask, pc)
    mask_band = jnp.asarray(mask_band_np, dtype=jnp.float32)
    bp_gain = jnp.asarray(design.bp_gain)
    templates_true, template_mu, template_scale = (
        xcorr.padded_template_stats_device(design.templates)
    )

    cond_scale = jnp.asarray(0.0 if scale_factor is None else scale_factor,
                             jnp.float32)
    # bank threshold policy — ONE resolution for every design consumer
    # (MatchedFilterDesign.resolve_threshold_policy: explicit legacy
    # hf_factor pins the index-0 vector + global coupling; explicit
    # vector next; else the design's bank. per_template scope returns
    # the [nT, B/Pf] pre-factor base instead of the coupled [B/Pf]
    # scalar-per-file.)
    factors_np, thr_scope = design.resolve_threshold_policy(
        hf_factor, threshold_factors, threshold_scope
    )
    body = functools.partial(
        _mf_body,
        band_lo=band_lo,
        band_hi=band_hi,
        bp_padlen=design.bp_padlen,
        fused=fused_bandpass,
        channel_axis=channel_axis,
        relative_threshold=relative_threshold,
        threshold_factors=factors_np,
        threshold_scope=thr_scope,
        pick_mode=pick_mode,
        max_peaks=max_peaks,
        outputs=outputs,
        pick_tile=pick_tile,
        pick_method=pick_method,
        condition=wire == "raw",
    )
    tfc = P(None, file_axis, channel_axis, None)  # [template, file, channel, *]
    if pick_mode == "sparse":
        picks_spec = peak_ops.SparsePicks(
            positions=tfc, heights=tfc, prominences=tfc, selected=tfc,
            saturated=P(None, file_axis, channel_axis),
        )
    else:
        picks_spec = tfc
    # threshold-base output: the coupled [B/Pf] per-file scalar under
    # the reference global scope; the decoupled [nT, B/Pf] per-template
    # base under the bank's per_template scope
    thres_spec = (P(None, file_axis) if thr_scope == "per_template"
                  else P(file_axis))
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(file_axis, channel_axis, None),   # trace batch
            P(None, channel_axis),              # mask (f-sharded)
            P(None),                            # bp gain (replicated)
            P(None, None),                      # true-length templates (replicated)
            P(None),                            # template means (replicated)
            P(None),                            # template scales (replicated)
            P(),                                # conditioning scale (replicated)
        ),
        out_specs=(
            (picks_spec, thres_spec)                  # picks, thresholds
            if outputs == "picks"
            else (
                P(file_axis, channel_axis, None),     # trf_fk
                tfc,                                  # corr
                tfc,                                  # env
                picks_spec,
                thres_spec,                           # threshold base
            )
        ),
        check_vma=False,
    )

    @jax.jit  # daslint: allow[R2] one-shot factory: caller holds the step for the run
    def step(trace_batch):
        return fn(trace_batch, mask_band, bp_gain, templates_true, template_mu,
                  template_scale, cond_scale)

    return step


def input_sharding(mesh: Mesh, file_axis="file", channel_axis="channel") -> NamedSharding:
    return NamedSharding(mesh, P(file_axis, channel_axis, None))
