"""Multi-host (multi-process) initialization and global meshes.

The reference scales across machines only implicitly (a human runs the
per-file scripts on several nodes); an actual multi-host DAS campaign
needs one program spanning hosts. JAX's runtime already provides the
communication backend — XLA collectives ride ICI within a slice and DCN
across hosts once ``jax.distributed.initialize`` has formed the global
runtime — so this module is deliberately thin: process bootstrap from the
environment, plus mesh builders that lay axes out so the *inner*
(channel/time) collectives stay on ICI and only the file/data axis
crosses DCN.

Single-process calls are no-ops returning local meshes, so every code
path here is exercised by the regular CPU test suite; on a real pod the
same calls span hosts. Typical launch (one process per host)::

    JAX_COORDINATOR=host0:8476 JAX_NUM_PROCESSES=4 JAX_PROCESS_ID=$RANK \
        python -m das4whales_tpu mfdetect ...

with ``initialize_from_env()`` called first (the CLI workflows tolerate
its absence — single host is the default).
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
from jax.sharding import Mesh

from .mesh import make_mesh


def initialize_from_env(timeout_s: int = 300) -> bool:
    """Form the multi-process JAX runtime from env vars, if configured.

    Reads ``JAX_COORDINATOR`` (``host:port``), ``JAX_NUM_PROCESSES`` and
    ``JAX_PROCESS_ID``. Returns True when a multi-process runtime was
    initialized, False when the env is absent/single-process (no-op) or
    when jax was already initialized (idempotent re-entry).
    """
    coord = os.environ.get("JAX_COORDINATOR")
    nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if not coord or nproc <= 1:
        return False
    pid_env = os.environ.get("JAX_PROCESS_ID")
    if pid_env is None:
        # a worker defaulting to rank 0 would collide with the real rank 0
        # and deadlock the whole launch until timeout — fail fast instead
        raise ValueError(
            "JAX_NUM_PROCESSES > 1 but JAX_PROCESS_ID is not set; "
            "export a distinct rank (0..N-1) on every process"
        )
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nproc,
            process_id=int(pid_env),
            initialization_timeout=timeout_s,
        )
    except RuntimeError as e:  # already initialized — idempotent re-entry
        msg = str(e).lower()
        if "already" in msg or "only be called once" in msg:
            return False
        raise
    return True


def global_mesh(
    axis_names: Sequence[str] = ("file", "channel"),
    files_per_host: int | None = None,
) -> Mesh:
    """Mesh over ALL devices of all processes, laid out DCN-friendly.

    The FIRST axis (``file`` — data parallelism) is the slowest-varying
    and spans hosts; the LAST axis (``channel``/``time`` — the
    ``all_to_all`` pencil-FFT axis) stays within a host's devices, i.e.
    on ICI. Since every collective in the detection step reduces over
    the channel/time axis, NOTHING in the step crosses DCN under this
    layout — only result gathering does (verified by the two-process
    runtime test, tests/test_multiprocess.py). With
    ``files_per_host=None`` the file axis gets exactly one shard per
    process (the natural layout: each host ingests its own files —
    ``io.stream`` reads locally, no cross-host data motion).

    Single-process: degenerates to ``make_mesh`` over local devices with
    ``file=1`` — identical semantics, fully testable on the CPU mesh.
    """
    devices = jax.devices()                       # global, process-major
    n_proc = jax.process_count()
    n_files = n_proc if files_per_host is None else n_proc * files_per_host
    if len(devices) % n_files:
        raise ValueError(
            f"{len(devices)} devices not divisible into {n_files} file shards"
        )
    shape = (n_files, len(devices) // n_files)
    return make_mesh(shape, axis_names, devices=devices)


def local_device_batch(n_files_global: int) -> slice:
    """This process's slice of a ``[file, ...]`` global batch: which file
    indices the local host should ingest (matches ``global_mesh``'s
    process-major file-axis layout)."""
    n_proc = jax.process_count()
    if n_files_global % n_proc:
        # a silent remainder would mean files no host ever ingests
        raise ValueError(
            f"{n_files_global} files not divisible over {n_proc} processes; "
            "pad the batch (io.stream tail policies) or adjust files_per_host"
        )
    per = n_files_global // n_proc
    start = jax.process_index() * per
    return slice(start, start + per)
