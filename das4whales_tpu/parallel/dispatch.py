"""Depth-D software-pipelined device dispatch (the anti-sync-wall layer).

BENCH_r05's stage attribution says the chip is 97-99% idle on the
headline shape: the correlate wall is 0.28 s against a 6.5 ms roofline
bound — almost the whole stage "wall" is the host↔device sync round
trip that separates one slab's packed fetch from the next slab's
dispatch. The reference's per-file scripts have the same structure, one
dependency chain deep; TINA (arXiv:2408.16551) and the Large-Scale
DFT-on-TPU work (arXiv:2002.03260) both locate the order-of-magnitude
in keeping the accelerator's queue non-empty — never bouncing to the
host for control flow between stages.

This module is the small, deterministic piece that fixes it for the
campaign runners:

* :class:`PipelinedDispatch` — a bounded in-flight queue of dispatched
  (launched, unfetched) detection programs. The campaign dispatches
  slab k+1 (and k+2, … up to depth D) BEFORE taking slab k's packed
  fetch, so while the host finalizes slab k's manifest records the
  chip is already computing slabs k+1..k+D. One fetch per slab still
  happens — it is the data dependency — but it now overlaps compute on
  the successors instead of leaving the chip idle, and the campaign
  takes no other sync: one ``drain()`` ends the segment.
* :func:`launch` / :func:`fetch` / :func:`sync` — counted wrappers
  around dispatch and the two sync primitives, feeding the
  process-wide ``faults.counters()`` ``dispatches``/``syncs`` tallies
  that bench.py reports next to ``stage_wall_s`` — the dispatch wall is
  a regression-gated NUMBER, not an inference from rooflines.

Failure attribution contract (the chaos suite pins it): a token is
(key, handle) — the key names the originating slab/file. Dispatch-time
errors never enter the queue (the caller handles them synchronously);
an in-flight failure surfaces when the campaign resolves that token at
its own position in the drain order, inside the campaign's existing
watchdog/ladder/retry wrappers — so depth-D pipelining changes WHEN a
failure surfaces, never WHERE it is attributed.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Iterator, List, Tuple

import jax

from .. import faults
from ..config import dispatch_depth_default
from ..telemetry import costs, metrics, probes, trace

# ISSUE 11 flight-recorder surfaces: per-rung/family resolve tallies, the
# pipeline's queue depth + in-flight residency, and the watchdog's
# deadline margin — all in the one metrics registry the Prometheus
# exposition and probes read (docs/OBSERVABILITY.md).
_resolves = metrics.counter(
    "das_rung_resolves_total",
    "watchdogged dispatch/resolve calls by rung, family and outcome",
    ("rung", "family", "outcome"),
)
_queue_depth = metrics.gauge(
    "das_dispatch_queue_depth",
    "PipelinedDispatch tokens currently in flight (dispatched, unresolved)",
)
_residency = metrics.histogram(
    "das_dispatch_inflight_residency_seconds",
    "seconds a PipelinedDispatch token spent in flight (submit to resolve)",
)
_watchdog_margin = metrics.histogram(
    "das_watchdog_deadline_margin_seconds",
    "dispatch_deadline_s minus the resolve wall — headroom before the "
    "watchdog would have fired (a shrinking margin predicts timeouts)",
)


def resolve_watchdogged(fn, paths, rung, deadline_s, fault_plan=None,
                        family: str = ""):
    """One watchdogged device dispatch/resolve, shared by every campaign
    flavor and every detector family (``workflows.planner``): the chaos
    harness's dispatch hook (``faults.FaultPlan.on_dispatch``) fires for
    each of ``paths`` INSIDE the deadline-bounded callable — exactly
    where a real wedged or OOMing launch surfaces — and the whole call
    is bounded by ``deadline_s`` (``faults.call_with_deadline``; None
    runs inline). Raises ``fn``'s own failure, the injected fault, or
    ``faults.DispatchDeadlineExceeded`` on a wedge — every escaping
    exception is annotated with the rung it failed at
    (``campaign_rung``), so a terminal failure record can name the
    executing route (``FileRecord.rung``)."""

    def run():
        if fault_plan is not None:
            for p in paths:
                fault_plan.on_dispatch(p, rung)
        return fn()

    label = faults.rung_label(rung)
    outcome = "error"
    with trace.span("resolve", rung=label, family=family,
                    n_files=len(paths),
                    file=os.path.basename(paths[0]) if paths else ""):
        # the deadline-bounded call below ends at fn's own packed fetch,
        # so the margin wall is an honest (synced) number. The HBM
        # occupancy samples BRACKET the resolve (ISSUE 14): one no-op
        # check when the cost observatory is off or the backend has no
        # memory_stats
        costs.sample_hbm()
        t0 = time.perf_counter()
        try:
            out = faults.call_with_deadline(
                run, deadline_s, paths[0] if paths else "<dispatch>"
            )
            outcome = "ok"
            if deadline_s is not None:
                _watchdog_margin.observe(
                    max(0.0, deadline_s - (time.perf_counter() - t0))
                )
            costs.sample_hbm()
            return out
        except faults.DispatchDeadlineExceeded as exc:
            outcome = "timeout"
            if deadline_s is not None:
                _watchdog_margin.observe(0.0)
            exc.campaign_rung = label
            raise
        except Exception as exc:
            try:
                exc.campaign_rung = label
            except Exception:  # noqa: BLE001 — slots/frozen exc: skip the tag
                pass
            raise
        finally:
            _resolves.inc(rung=label, family=family, outcome=outcome)


def launch(fn, *args, **kwargs):
    """Dispatch a device program asynchronously: call ``fn`` (a jitted
    step / program launcher), count the dispatch, return its
    still-in-flight outputs WITHOUT syncing. The caller's eventual
    fetch of the outputs (``np.asarray`` / packed ``device_get``) is
    the sync — pair with :func:`fetch` so it is counted."""
    faults.count("dispatches")
    with trace.span("dispatch"):
        return fn(*args, **kwargs)


def fetch(tree):
    """Counted blocking fetch: ``jax.device_get`` on a tree of in-flight
    device arrays — the ONE sync its dispatch chain pays."""
    faults.count("syncs")
    with trace.span("fetch"):
        out = jax.device_get(tree)
    probes.note_dispatch_ok()   # the runtime answered: liveness heartbeat
    return out


def sync(tree):
    """Counted ``jax.block_until_ready`` (for callers that need the
    arrays resident on device, not on host)."""
    faults.count("syncs")
    with trace.span("sync"):
        out = jax.block_until_ready(tree)
    probes.note_dispatch_ok()
    return out


class PipelinedDispatch:
    """A bounded queue of in-flight (dispatched, unresolved) tokens.

    ``depth`` is the maximum number of tokens in flight (None: the
    ``DAS_DISPATCH_DEPTH`` env default, 2). ``depth <= 1`` disables
    pipelining — :attr:`enabled` is False and callers fall back to
    their synchronous dispatch-then-fetch path, byte-identical to the
    pre-pipeline behavior.

    Usage (the campaign pattern)::

        pipe = PipelinedDispatch(depth)
        for slab in slabs:
            handle = try_dispatch(slab)          # async launch, or None
            if handle is None:                   # ineligible: sync path
                for key, h in pipe.drain():      # FIFO: order preserved
                    finalize(key, h)
                finalize_sync(slab)
                continue
            for key, h in pipe.submit(slab, handle):
                finalize(key, h)                 # resolve = the one sync
        for key, h in pipe.drain():
            finalize(key, h)

    The queue is FIFO: tokens come back in submission order, so
    manifest records keep the campaign's file order and a failure
    surfacing at ``finalize`` is attributed to ITS key, never to the
    slab that happened to be dispatching when it surfaced.
    """

    def __init__(self, depth: int | None = None):
        self.depth = dispatch_depth_default() if depth is None else int(depth)
        self._q: deque = deque()

    @property
    def enabled(self) -> bool:
        return self.depth >= 2

    def __len__(self) -> int:
        return len(self._q)

    def in_flight(self) -> int:
        """Tokens currently dispatched and unresolved — the queue depth
        the ``das_dispatch_queue_depth`` gauge mirrors. The service
        scheduler's overlap accounting (and tests) read this instead of
        reaching into the queue internals."""
        return len(self._q)

    def pending(self) -> Tuple[Any, ...]:
        """The KEYS of the in-flight tokens, oldest first — what would
        come back from :meth:`drain`, without resolving anything. The
        multi-stream scheduler uses this to see WHOSE slabs are in
        flight (fairness/overlap decisions); campaign code uses it for
        bookkeeping assertions. Iterates a C-atomic snapshot of the
        queue: an HTTP status thread reading pending() while the
        scheduler pops must never tear (daslint R8)."""
        return tuple(key for key, _handle, _t in tuple(self._q))

    def _note_depth(self) -> None:
        # the gauge rides the public accessor: one definition of depth
        _queue_depth.set(self.in_flight())

    def _pop(self) -> Tuple[Any, Any]:
        key, handle, t_in = self._q.popleft()
        self._note_depth()
        _residency.observe(time.perf_counter() - t_in)
        return key, handle

    def submit(self, key: Any, handle: Any) -> List[Tuple[Any, Any]]:
        """Enqueue a dispatched token; returns the (key, handle) tokens
        that must be resolved NOW to keep at most ``depth`` in flight
        (oldest first)."""
        self._q.append((key, handle, time.perf_counter()))
        self._note_depth()
        out: List[Tuple[Any, Any]] = []
        while len(self._q) > self.depth:
            out.append(self._pop())
        return out

    def drain(self) -> Iterator[Tuple[Any, Any]]:
        """Yield every queued token oldest-first (the end-of-segment —
        or pre-sync-path — flush). Resolving the last token is the
        segment's single remaining sync."""
        while self._q:
            yield self._pop()
