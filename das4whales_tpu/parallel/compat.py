"""JAX version compatibility for the parallel package.

``shard_map`` graduated from ``jax.experimental`` to the top-level
namespace (and its replication-check keyword was renamed ``check_rep`` →
``check_vma``) across the jax versions this repo meets in the wild. All
parallel modules import it from here and write the NEW spelling; on older
jax the adapter maps the keyword back.
"""

from __future__ import annotations

import inspect as _inspect

try:  # public API (top-level since ~0.5; keyword renamed later)
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental API only
    from jax.experimental.shard_map import shard_map as _shard_map


def _takes_check_vma(fn) -> bool:
    # the import location and the keyword rename shipped in different jax
    # releases, so probe the signature rather than keying on the import
    try:
        return "check_vma" in _inspect.signature(fn).parameters
    except (TypeError, ValueError):  # unintrospectable: assume current API
        return True


if _takes_check_vma(_shard_map):
    shard_map = _shard_map
else:
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma, **kwargs)

try:  # jax >= 0.6
    from jax.lax import axis_size  # type: ignore[attr-defined]
except ImportError:
    from jax import lax as _lax

    def axis_size(axis_name) -> int:
        # the classic idiom: psum of the Python int 1 over a mapped axis
        # constant-folds to the axis size as a Python int, so shard_map
        # bodies can keep using it in static shape arithmetic
        return _lax.psum(1, axis_name)
