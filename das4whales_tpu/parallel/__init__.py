"""Multi-chip scale-out: meshes, distributed FFT, sharded pipelines."""

from . import batch, dispatch, distributed, fft, mesh, pipeline, timeshard  # noqa: F401
from .batch import BatchedMatchedFilterDetector  # noqa: F401
from .mesh import make_mesh, shard_block  # noqa: F401
from .distributed import global_mesh, initialize_from_env  # noqa: F401
from .fft import sharded_fk_apply  # noqa: F401
from .pipeline import make_sharded_mf_step  # noqa: F401
from .timeshard import (  # noqa: F401
    make_sharded_mf_step_time,
    sharded_bp_filt_time,
    sharded_fk_apply_time,
    time_sharding,
)
