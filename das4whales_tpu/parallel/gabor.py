"""File-sharded (data-parallel) Gabor/image detection.

Unlike the other two families, the Gabor pipeline's 2-D image operators
couple channels — the oriented Gabor pair spans ~100 binned pixels
(~1000 raw channels) of the t-x image (models/gabor.py, reference
improcess.py:98-140) — so channel sharding would need kilochannel halos.
The natural scale-out axis is FILES: each mesh slot owns whole files and
runs the full image pipeline locally; there are no collectives (the
0.5·max detection threshold is per file, main_gabordetect.py-style
script behavior, computed inside each file's program).

Files stream through ``lax.map`` within a shard so only one file's
image-pipeline temps are live at a time.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..config import C0_WATER, as_metadata
from ..models.gabor import design_gabor, gabor_mask, masked_matched_filter
from ..models.templates import gen_hyperbolic_chirp
from ..ops import peaks as peak_ops
from ..ops import spectral


def gabor_input_sharding(mesh, file_axis: str = "file"):
    """Sharding for a ``[file x channel x time]`` batch: files split over
    the mesh's file axis, channels/time replicated (whole within a slot)."""
    return NamedSharding(mesh, P(file_axis, None, None))


def make_sharded_gabor_step(
    metadata,
    selected_channels,
    mesh,
    c0: float = C0_WATER,
    notes: Dict[str, Tuple[float, float, float]] | None = None,
    max_peaks: int = 256,
    relative_threshold: float = 0.5,
    hf_factor: float = 0.9,
    file_axis: str = "file",
):
    """Build a jittable file-sharded Gabor detection step.

    The returned callable maps a ``[file x channel x time]`` batch placed
    with :func:`gabor_input_sharding` to ``(correlograms, picks,
    thresholds)``: correlograms ``[n_notes, file, channel, time]``, picks
    an ``ops.peaks.SparsePicks`` over the same axes, thresholds
    ``[file]`` (per-file 0.5·max policy). Also returns the note names.
    """
    meta = as_metadata(metadata)
    design = design_gabor(meta, list(selected_channels), c0=c0)
    if notes is None:
        notes = {"HF": (17.8, 28.8, 0.68), "LF": (14.7, 21.8, 0.78)}
    names = tuple(notes)
    # keep each note at its TRUE length: masked_matched_filter's 'same'
    # window is centered by the note length, so zero-padding to a common
    # length would shift every pick by (pad/2) samples
    notes_dev = []
    for fmin, fmax, dur in notes.values():
        chirp = np.asarray(gen_hyperbolic_chirp(fmin, fmax, dur, meta.fs))
        notes_dev.append(jnp.asarray(chirp * np.hanning(len(chirp)), jnp.float32))
    # keyed by NAME, matching GaborDetector's policy (models/gabor.py:
    # "HF picked at 0.9*thres"), not by dict position
    factors = jnp.asarray(
        [hf_factor if name == "HF" else 1.0 for name in names], jnp.float32
    )

    def one_file(trf):                               # [C, T]
        _, _, masked = gabor_mask(trf, design)
        corr = jnp.stack([
            masked_matched_filter(masked, nt.astype(trf.dtype))
            for nt in notes_dev
        ])                                           # [nT, C, T]
        thres = relative_threshold * jnp.max(corr)
        env = jnp.abs(spectral.analytic_signal(corr, axis=-1))
        picks = peak_ops.find_peaks_sparse_batched(
            env, (thres * factors)[:, None], max_peaks=max_peaks
        )
        return corr, picks, thres

    def _shard_body(x):                              # [B/P, C, T]
        corr, picks, thres = jax.lax.map(one_file, x)
        # local axes: corr [B/P, nT, C, T] -> [nT, B/P, C, T]
        corr = jnp.moveaxis(corr, 0, 1)
        picks = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 0, 1), picks)
        return corr, picks, thres

    spec_in = P(file_axis, None, None)
    spec_corr = P(None, file_axis, None, None)
    spec_picks = jax.tree_util.tree_map(
        lambda _: P(None, file_axis, None), peak_ops.SparsePicks(0, 0, 0, 0, 0)
    )
    step = jax.jit(
        shard_map(
            _shard_body, mesh=mesh, in_specs=(spec_in,),
            out_specs=(spec_corr, spec_picks, P(file_axis)),
            check_vma=False,
        )
    )
    return step, names
