"""Sharded Gabor/image detection: file-parallel batches and time-sharded
long records.

The Gabor pipeline's 2-D image operators couple channels — the oriented
Gabor pair spans ~100 binned pixels (~1000 raw channels) of the t-x
image (models/gabor.py, reference improcess.py:98-140) — so channel
sharding would need kilochannel halos. Two layouts avoid that:

* ``make_sharded_gabor_step`` — data-parallel over FILES: each mesh slot
  owns whole files, runs the full image pipeline locally, zero
  collectives; files stream through ``lax.map`` so only one file's
  image temps are live at a time.
* ``make_sharded_gabor_step_time`` — one record longer than a chip,
  TIME-sharded: an ``all_to_all`` relabel plus pmin/pmax collectives
  reproduce the pipeline's global couplings, and the channel-row halo
  (the two-stage Gabor receptive field) makes interior channels exactly
  single-chip.

The same channel coupling is why the resilient route planner
(``workflows.planner.GaborProgram``) declares NO tiled rung for this
family: a chunked sweep would change detection at tile seams, so the
campaign ladder degrades the gabor family straight from the per-file
rung to the host backend (docs/ROBUSTNESS.md "Family x guarantee
coverage").
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from .compat import shard_map
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..config import C0_WATER, as_metadata
from ..models.gabor import design_gabor, gabor_mask, masked_matched_filter
from ..models.templates import gen_hyperbolic_chirp
from ..ops import peaks as peak_ops
from ..ops import spectral


def gabor_input_sharding(mesh, file_axis: str = "file"):
    """Sharding for a ``[file x channel x time]`` batch: files split over
    the mesh's file axis, channels/time replicated (whole within a slot)."""
    return NamedSharding(mesh, P(file_axis, None, None))


def make_sharded_gabor_step(
    metadata,
    selected_channels,
    mesh,
    c0: float = C0_WATER,
    notes: Dict[str, Tuple[float, float, float]] | None = None,
    max_peaks: int = 256,
    relative_threshold: float = 0.5,
    hf_factor: float = 0.9,
    file_axis: str = "file",
):
    """Build a jittable file-sharded Gabor detection step.

    The returned callable maps a ``[file x channel x time]`` batch placed
    with :func:`gabor_input_sharding` to ``(correlograms, picks,
    thresholds)``: correlograms ``[n_notes, file, channel, time]``, picks
    an ``ops.peaks.SparsePicks`` over the same axes, thresholds
    ``[file]`` (per-file 0.5·max policy). Also returns the note names.
    """
    meta = as_metadata(metadata)
    design = design_gabor(meta, list(selected_channels), c0=c0)
    if notes is None:
        notes = {"HF": (17.8, 28.8, 0.68), "LF": (14.7, 21.8, 0.78)}
    names = tuple(notes)
    # keep each note at its TRUE length: masked_matched_filter's 'same'
    # window is centered by the note length, so zero-padding to a common
    # length would shift every pick by (pad/2) samples
    notes_dev = []
    for fmin, fmax, dur in notes.values():
        chirp = np.asarray(gen_hyperbolic_chirp(fmin, fmax, dur, meta.fs))
        notes_dev.append(jnp.asarray(chirp * np.hanning(len(chirp)), jnp.float32))
    # keyed by NAME, matching GaborDetector's policy (models/gabor.py:
    # "HF picked at 0.9*thres"), not by dict position
    factors = jnp.asarray(
        [hf_factor if name == "HF" else 1.0 for name in names], jnp.float32
    )

    def one_file(trf):                               # [C, T]
        _, _, masked = gabor_mask(trf, design)
        corr = jnp.stack([
            masked_matched_filter(masked, nt.astype(trf.dtype))
            for nt in notes_dev
        ])                                           # [nT, C, T]
        thres = relative_threshold * jnp.max(corr)
        env = jnp.abs(spectral.analytic_signal(corr, axis=-1))
        picks = peak_ops.find_peaks_sparse_batched(
            env, (thres * factors)[:, None], max_peaks=max_peaks
        )
        return corr, picks, thres

    def _shard_body(x):                              # [B/P, C, T]
        corr, picks, thres = jax.lax.map(one_file, x)
        # local axes: corr [B/P, nT, C, T] -> [nT, B/P, C, T]
        corr = jnp.moveaxis(corr, 0, 1)
        picks = jax.tree_util.tree_map(lambda a: jnp.moveaxis(a, 0, 1), picks)
        return corr, picks, thres

    spec_in = P(file_axis, None, None)
    spec_corr = P(None, file_axis, None, None)
    spec_picks = jax.tree_util.tree_map(
        lambda _: P(None, file_axis, None), peak_ops.SparsePicks(0, 0, 0, 0, 0)
    )
    step = jax.jit(  # daslint: allow[R2] one-shot factory: campaign jits its step once per run
        shard_map(
            _shard_body, mesh=mesh, in_specs=(spec_in,),
            out_specs=(spec_corr, spec_picks, P(file_axis)),
            check_vma=False,
        )
    )
    return step, names


def make_sharded_gabor_step_time(
    metadata,
    selected_channels,
    mesh,
    c0: float = C0_WATER,
    bin_factor: float = 0.1,
    threshold1: float = 9100.0,
    threshold2: float = 150.0,
    ksize: int = 100,
    notes: Dict[str, Tuple[float, float, float]] | None = None,
    max_peaks: int = 256,
    relative_threshold: float = 0.5,
    hf_factor: float = 0.9,
    channel_halo: int | None = None,
    time_axis: str = "time",
    n_channels: int | None = None,
    outputs: str = "full",
):
    """Sequence parallelism for the Gabor family: detection on a
    ``[channel x time]`` record whose TIME axis is sharded over ``mesh``.

    The image pipeline's global couplings become collectives: ONE
    ``all_to_all`` relabel makes time whole per channel shard (the
    per-channel Hilbert envelope needs it), the image min-max scaling and
    the smoothed-mask renormalization use ``pmin``/``pmax`` pairs, the
    Gabor convolutions see a CHANNEL-row halo exchange, and the
    detection threshold is one more ``pmax``.

    Parity: interior channels match the single-chip ``GaborDetector`` to
    resize-antialias noise. The outermost ``channel_halo`` rows at the
    two CABLE ENDS deviate (antialiased ``binning`` renormalizes its
    kernel at a true image boundary but sees explicit zero halo rows
    here) — the same class of edge transient as the time-sharded
    filters' record edges, and the reference pipeline distrusts cable
    ends anyway. Pinned in tests/test_gabor_timeshard.py.

    ``channel_halo`` defaults to the two-stage Gabor receptive field,
    ``(2*(ksize//2) + 4) / bin_factor`` rows rounded up to the binning
    granularity — interior results then equal the single-chip
    ``GaborDetector`` to resize-antialias noise. Requires
    ``channels % mesh`` and ``time % mesh`` divisibility and
    ``channel_halo < channels / mesh``.

    ``n_channels`` is the ROW COUNT of the block the step will receive.
    It defaults to applying ``selected_channels`` to ``metadata.nx`` —
    correct when ``metadata`` is the acquisition metadata — but callers
    holding an already-selected record (``metadata.nx`` is the
    post-selection count while ``selected_channels`` still describes the
    original load-time stride, e.g. workflows/longrecord.py) must pass
    the record's row count explicitly: re-applying a non-trivial
    selection to the reduced ``nx`` would validate against a wrong (often
    zero) channel count. ``selected_channels`` itself only sets the Gabor
    orientation (step·dx, reference improcess.py:66-95).

    Returns ``(step, names)``. With ``outputs="full"`` the step maps the
    time-sharded ``[C, T]`` block to ``(correlograms [nT, C, T] (channel
    axis sharded over ``time_axis`` after the relabel), picks,
    threshold [])``; ``outputs="picks"`` (campaign/long-record mode)
    returns ``(picks, threshold)`` only, so the full-record correlograms
    never become program outputs (the memory class behind the round-2
    OOM, mirroring make_sharded_mf_step_time).
    """
    from ..models.gabor import design_gabor
    from ..ops import image as img_ops
    from .timeshard import halo_exchange

    meta = as_metadata(metadata)
    design = design_gabor(meta, list(selected_channels), c0=c0,
                          bin_factor=bin_factor, threshold1=threshold1,
                          threshold2=threshold2, ksize=ksize)
    if notes is None:
        notes = {"HF": (17.8, 28.8, 0.68), "LF": (14.7, 21.8, 0.78)}
    names = tuple(notes)
    notes_dev = []
    for fmin, fmax, dur in notes.values():
        chirp = np.asarray(gen_hyperbolic_chirp(fmin, fmax, dur, meta.fs))
        notes_dev.append(jnp.asarray(chirp * np.hanning(len(chirp)), jnp.float32))
    factors = jnp.asarray(
        [hf_factor if name == "HF" else 1.0 for name in names], jnp.float32
    )
    grain = max(int(round(1.0 / bin_factor)), 1)
    if channel_halo is None:
        need = (2 * (ksize // 2) + 4) / bin_factor
        channel_halo = int(-(-need // grain) * grain)
    if channel_halo % grain:
        raise ValueError(
            f"channel_halo {channel_halo} must be a multiple of the binning "
            f"granularity {grain}"
        )
    if outputs not in ("full", "picks"):
        raise ValueError(f"outputs must be 'full' or 'picks', got {outputs!r}")
    if n_channels is None:
        from ..config import ChannelSelection

        n_channels = ChannelSelection.from_list(
            list(selected_channels)
        ).n_channels(meta.nx)
    C = n_channels
    p_mesh = mesh.shape[time_axis]
    if C % p_mesh:
        raise ValueError(f"channels {C} not divisible by mesh axis {time_axis}={p_mesh}")
    local_c = C // p_mesh
    if not (0 < channel_halo < local_c):
        raise ValueError(
            f"channel_halo {channel_halo} must be in (0, C/P={local_c})"
        )
    # single-chip parity needs the per-shard resize scale to EQUAL the
    # full-image scale: both the local channel count and the halo must
    # bin to integers
    for label, n in (("C/P", local_c), ("channel_halo", channel_halo)):
        if abs(n * bin_factor - round(n * bin_factor)) > 1e-9:
            raise ValueError(
                f"{label}={n} times bin_factor={bin_factor} is not an "
                f"integer: the per-shard binned grid would misalign with "
                f"the single-chip grid"
            )
    up = jnp.asarray(design.gabor_up, jnp.float32)
    down = jnp.asarray(design.gabor_down, jnp.float32)

    def _body(x):                                    # [C, T/P]
        # relabel: time gathered whole, channels scattered -> [C/P, T]
        xr = jax.lax.all_to_all(x, time_axis, split_axis=0, concat_axis=1,
                                tiled=True)
        # t-x image with GLOBAL min-max scaling (trace2image semantics)
        env = jnp.abs(spectral.analytic_signal(xr, axis=-1))
        img = env / jnp.std(xr, axis=-1, keepdims=True)
        lo = jax.lax.pmin(jnp.min(img), time_axis)
        hi = jax.lax.pmax(jnp.max(img), time_axis)
        image = (img - lo) / (hi - lo) * 255.0
        # channel-row halo: zero rows at the global edges = the zero
        # padding filter2d_same applies on one chip
        ext = jnp.moveaxis(
            halo_exchange(jnp.moveaxis(image, 0, -1), channel_halo, time_axis),
            -1, 0,
        )
        imagebin = img_ops.binning(ext, bin_factor, bin_factor)
        score = (img_ops.filter2d_same(imagebin, up)
                 + img_ops.filter2d_same(imagebin, down))
        binary = (score > threshold1).astype(ext.dtype)
        mask_binned = (
            img_ops.filter2d_same(binary, up) + img_ops.filter2d_same(binary, down)
        ) > threshold2
        mask_full = jax.image.resize(
            mask_binned.astype(ext.dtype), ext.shape, method="linear",
            antialias=False,
        )
        smoothed = img_ops.gaussian_filter2d(mask_full, 1.5)
        smoothed = smoothed[channel_halo:-channel_halo]
        slo = jax.lax.pmin(jnp.min(smoothed), time_axis)
        shi = jax.lax.pmax(jnp.max(smoothed), time_axis)
        span = shi - slo
        smoothed = jnp.where(
            span > 0, (smoothed - slo) / jnp.where(span > 0, span, 1.0), smoothed
        )
        masked = xr * smoothed
        corr = jnp.stack([
            masked_matched_filter(masked, nt.astype(xr.dtype)) for nt in notes_dev
        ])                                           # [nT, C/P, T]
        thres = relative_threshold * jax.lax.pmax(jnp.max(corr), time_axis)
        env_c = jnp.abs(spectral.analytic_signal(corr, axis=-1))
        picks = peak_ops.find_peaks_sparse_batched(
            env_c, (thres * factors)[:, None], max_peaks=max_peaks
        )
        if outputs == "picks":
            return picks, thres
        return corr, picks, thres

    spec_picks = jax.tree_util.tree_map(
        lambda _: P(None, time_axis), peak_ops.SparsePicks(0, 0, 0, 0, 0)
    )
    out_specs = (
        (spec_picks, P())
        if outputs == "picks"
        else (P(None, time_axis, None), spec_picks, P())
    )
    step = jax.jit(  # daslint: allow[R2] one-shot factory: campaign jits its step once per run
        shard_map(
            _body, mesh=mesh, in_specs=(P(None, time_axis),),
            out_specs=out_specs,
            check_vma=False,
        )
    )
    return step, names
