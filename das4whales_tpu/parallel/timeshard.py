"""Sequence parallelism: processing with the TIME axis sharded over chips.

Long-context support the reference lacks: its only answer to recordings
longer than memory is dask time-chunking with acknowledged chunk-boundary
error (tools.py:161-187, error admitted at tools.py:166) or spatial
decimation at load (data_handle.py:213). Here a continuous multi-minute
record lives ``[channel x time]`` with time sharded across the mesh, and:

* time-domain zero-phase filtering is **exact across shard boundaries**:
  each shard receives real neighbor samples by ``ppermute`` halo exchange
  (ICI neighbor traffic only) before filtering, so the only error is the
  filter's own response truncated at ``halo`` samples — below float32
  epsilon for the default halo, unlike the reference's accepted chunk
  error;
* the f-k transform runs as a pencil decomposition needing just two
  ``all_to_all`` collectives: the channel FFT is local (channels are
  unsharded), one transpose makes time local for the time FFT + mask,
  one transpose back;
* the full flagship detection step transposes once more into the
  channel-sharded layout to finish (correlation normalization and peak
  picking are per-channel, so they become embarrassingly parallel there).

All bodies are ``shard_map`` SPMD programs; global-edge shards replace
their missing halo with the same odd extension the single-device
``filtfilt`` path uses (ops/filters.py), selected branchlessly so the
program stays identical on every device.
"""

from __future__ import annotations

import functools
import weakref

import jax
import jax.numpy as jnp
import numpy as np
import scipy.signal as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .compat import axis_size, shard_map

from ..ops import conditioning as cond_ops
from ..ops import mxu as mxu_ops
from ..ops import peaks as peak_ops
from ..ops import spectral, xcorr
from ..ops.filters import zero_phase_gain


def halo_exchange(x: jnp.ndarray, halo: int, axis_name: str) -> jnp.ndarray:
    """shard_map body: pad the local time axis with ``halo`` samples from
    each neighbor shard (zeros at the global edges).

    ``x`` is ``[..., L]`` local; returns ``[..., halo + L + halo]``. The
    two ``ppermute``\\ s are nearest-neighbor ICI traffic.
    """
    p = axis_size(axis_name)
    if p == 1:
        z = jnp.zeros(x.shape[:-1] + (halo,), x.dtype)
        return jnp.concatenate([z, x, z], axis=-1)
    right_edge = x[..., -halo:]
    left_edge = x[..., :halo]
    from_left = jax.lax.ppermute(right_edge, axis_name, [(i, i + 1) for i in range(p - 1)])
    from_right = jax.lax.ppermute(left_edge, axis_name, [(i + 1, i) for i in range(p - 1)])
    return jnp.concatenate([from_left, x, from_right], axis=-1)


def _halo_with_edge_oddext(x: jnp.ndarray, halo: int, axis_name: str) -> jnp.ndarray:
    """Halo exchange whose global-edge shards odd-extend instead of zero-pad
    (matching single-device ``filtfilt`` edge handling, ops/filters.py)."""
    ext = halo_exchange(x, halo, axis_name)
    idx = jax.lax.axis_index(axis_name)
    p = axis_size(axis_name)
    # odd extension: 2*x[0] - x[halo:0:-1]  /  2*x[-1] - x[-2:-halo-2:-1]
    left_odd = 2.0 * x[..., :1] - jnp.flip(x[..., 1 : halo + 1], axis=-1)
    right_odd = 2.0 * x[..., -1:] - jnp.flip(x[..., -halo - 1 : -1], axis=-1)
    left = jnp.where(idx == 0, left_odd.astype(x.dtype), ext[..., :halo])
    right = jnp.where(idx == p - 1, right_odd.astype(x.dtype), ext[..., -halo:])
    return jnp.concatenate([left, ext[..., halo:-halo], right], axis=-1)


def _bp_time_local(x, gain, *, halo: int, axis_name: str):
    """Zero-phase bandpass along a time-sharded axis, exact across shard
    boundaries to the filter's decay at ``halo`` samples."""
    ext = _halo_with_edge_oddext(x, halo, axis_name)
    spec = jnp.fft.rfft(ext, axis=-1)
    y = jnp.fft.irfft(spec * gain.astype(spec.real.dtype), n=ext.shape[-1], axis=-1)
    return y[..., halo:-halo].astype(x.dtype)


def sharded_bp_filt_time(
    trace,
    mesh: Mesh,
    fs: float,
    fmin: float,
    fmax: float,
    *,
    order: int = 8,
    halo: int = 512,
    time_axis: str = "time",
):
    """Zero-phase Butterworth bandpass of a ``[channel x time]`` block whose
    TIME axis is sharded over ``mesh``. Boundary-exact via halo exchange
    (reference contrast: tools.py:161-187 accepts chunk-edge error)."""
    nns = trace.shape[-1]
    p = mesh.shape[time_axis]
    if nns % p:
        raise ValueError(f"time length {nns} not divisible by mesh axis {time_axis}={p}")
    local = nns // p
    if halo >= local:
        raise ValueError(f"halo {halo} must be < local shard length {local}")
    gain = _bp_time_gain(order, fs, fmin, fmax, local, halo)
    return _bp_time_fn(mesh, time_axis, halo)(trace, gain)


@functools.lru_cache(maxsize=32)
def _bp_time_gain(order: int, fs: float, fmin: float, fmax: float,
                  local: int, halo: int):
    """Cached zero-phase gain per filter design + shard geometry: the
    host-side Butterworth evaluation over rfftfreq(local + 2*halo) and
    the device upload are per-record overhead otherwise."""
    sos = sp.butter(order, [fmin / (fs / 2), fmax / (fs / 2)], "bp", output="sos")
    return jnp.asarray(
        zero_phase_gain(np.fft.rfftfreq(local + 2 * halo), sos).astype(np.float32)
    )


@functools.lru_cache(maxsize=32)
def _bp_time_fn(mesh: Mesh, time_axis: str, halo: int):
    """Cached jitted program per (mesh, axis, halo): rebuilding the
    shard_map + jit wrapper on every call is a fresh function object and
    re-traces per record in multi-record campaigns (the filter response
    itself stays a runtime argument, so band/order changes don't grow
    the cache)."""
    body = functools.partial(_bp_time_local, halo=halo, axis_name=time_axis)
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, time_axis), P(None)),
        out_specs=P(None, time_axis),
    ))


def prepare_mask_full(mask: np.ndarray) -> np.ndarray:
    """fftshifted ``[k x f]`` design mask -> symmetrized full mask in fft
    order on BOTH axes (real output guaranteed after filtering)."""
    from .fft import symmetrize_mask_fftorder

    return symmetrize_mask_fftorder(mask).astype(np.float32)


def fk_apply_time_local(x: jnp.ndarray, mask_rows: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """shard_map body: f-k filter a time-sharded ``[C, T/P]`` block against
    a row-sharded full mask ``[C/P, T]`` (fft order both axes).

    Pencil decomposition with only two ``all_to_all``\\ s: the channel FFT
    is local (channels unsharded), the transpose makes time local for the
    time FFT + mask multiply, then one transpose back + inverse channel FFT.
    """
    s = jnp.fft.fft(x, axis=0)  # channel FFT: fully local
    s = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=1, tiled=True)  # [C/P, T]
    s = jnp.fft.fft(s, axis=1)
    s = s * mask_rows.astype(s.real.dtype)
    s = jnp.fft.ifft(s, axis=1)
    s = jax.lax.all_to_all(s, axis_name, split_axis=1, concat_axis=0, tiled=True)  # [C, T/P]
    s = jnp.fft.ifft(s, axis=0)
    return s.real.astype(x.dtype)


def sharded_fk_apply_time(trace, mask, mesh: Mesh, time_axis: str = "time"):
    """f-k filter a ``[channel x time]`` block sharded along TIME.

    Numerically identical to single-device ``ops.fk.fk_filter_apply``
    (the mask is symmetrized the same way). ``mask`` is the fftshifted
    design matrix from any ``ops.fk`` designer.
    """
    nnx, nns = trace.shape
    p = mesh.shape[time_axis]
    if nnx % p or nns % p:
        raise ValueError(f"both axes must divide the mesh axis size {p}")
    mask_rows = jnp.asarray(prepare_mask_full(mask))
    return _fk_time_fn(mesh, time_axis)(trace, mask_rows)


@functools.lru_cache(maxsize=32)
def _fk_time_fn(mesh: Mesh, time_axis: str):
    """Cached jitted program per (mesh, axis) — see ``_bp_time_fn``."""
    return jax.jit(shard_map(
        functools.partial(fk_apply_time_local, axis_name=time_axis),
        mesh=mesh,
        in_specs=(P(None, time_axis), P(time_axis, None)),
        out_specs=P(None, time_axis),
    ))


def make_sharded_mf_step_time(
    design,
    mesh: Mesh,
    *,
    time_axis: str = "time",
    halo: int = 512,
    relative_threshold: float = 0.5,
    hf_factor: float | None = None,
    threshold_factors=None,
    threshold_scope: str | None = None,
    pick_mode: str = "sparse",
    max_peaks: int = 256,
    outputs: str = "full",
    fused_bandpass: bool = True,
    pick_tile: int = 512,
    pick_method: str = "topk",
    wire: str = "conditioned",
    scale_factor: float | None = None,
    cond_time_samples: int | None = None,
    cond_segments=None,
    cond_means=None,
    mf_engine: str = "fft",
):
    """Full flagship detection step for a TIME-sharded ``[C, T]`` block.

    ``mf_engine`` picks the correlate transform inside the SPMD body:
    the rFFT product or the MXU banded-Toeplitz matmul
    (``ops.mxu.correlograms_body``) — the correlate runs after the
    relabel transpose where time is whole within each channel shard, so
    the matmul recast is the same per-channel contraction as the
    single-chip routes. The pencil f-k transform keeps its FFT form
    (the distributed transpose owns that layout; no ``fk_engine``
    here).

    ``wire="raw"`` consumes a NARROW-WIRE record (stored-dtype counts,
    ``io.stream`` ``wire="raw"``): the conditioning prologue runs in the
    SPMD body using ``scale_factor`` (required then). The time axis is
    sharded here, so the per-channel demean is a ``psum`` of local sums
    over the mesh axis (``ops.conditioning.condition_time_sharded``) —
    one scalar-per-channel collective; reduction order differs from the
    single-device mean by float roundoff only. ``cond_time_samples``
    divides the mean by the REAL sample count when the record carries
    divisibility zero-padding (zeros add nothing to the sum, so this
    yields the exact mean over real samples; default: the full length).

    For a CONCATENATED multi-file record the conditioned wire demeans
    each file separately, so the whole-record psum mean is the wrong
    map: pass ``cond_segments`` (per-file time lengths, in record order)
    plus ``cond_means`` (``[channel x n_files]`` float32 per-file means,
    computed on the host from the raw blocks with the same numpy
    reduction the conditioned readers use). The body then gather-
    subtracts the exact host means (``ops.conditioning
    .condition_segmented``) — no device reduction, so conditioned values
    are bit-identical to the conditioned wire, and divisibility padding
    (the samples past ``sum(cond_segments)``) conditions to exactly 0.

    ``fused_bandpass=True`` folds |H(f)|² into the full f-k mask (the
    time FFT of the pencil transform applies it), dropping the
    halo-exchange bandpass stage entirely — the sequence-parallel analog
    of the golden-certified single-chip fused route (VALIDATION.md).

    Stages: halo-exchanged zero-phase bandpass -> two-collective pencil
    f-k filter -> one ``all_to_all`` transpose into the channel-sharded
    layout -> per-channel matched-filter correlograms, envelopes and peak
    picking (embarrassingly parallel there), with one ``pmax`` for the
    global threshold. With ``outputs="full"`` returns
    ``(trf_fk, corr, env, picks, thres)`` where ``trf_fk`` stays
    time-sharded and the detection outputs are channel-sharded (same mesh
    axis, relabeled layout); ``outputs="picks"`` (campaign mode) returns
    only ``(picks, thres)`` so the heavy per-shard arrays never become
    program outputs.

    ``pick_mode="sparse"`` (production, matching the single-chip
    ``MatchedFilterDetector`` default) yields ``picks`` as an
    ``ops.peaks.SparsePicks`` of ``[n_templates, channel, K]`` arrays plus
    per-row saturation flags; positions are global time indices (the time
    axis is whole within each channel shard after the relabel transpose).
    ``pick_mode="dense"`` (debug) yields the full boolean peak mask —
    exact everywhere but gather-heavy on TPU (ops/peaks.py:170-186).

    Numerics: interior samples — including every shard boundary — match
    the single-device pipeline to float32 roundoff. The first/last
    ``halo`` samples of the record differ slightly from the single-device
    path (halo-length odd extension here vs ``bp_padlen`` extension
    there); both are edge-transient approximations, and the reference
    tapers file edges anyway (dsp.py:705-722).

    ``design`` is a ``models.matched_filter.MatchedFilterDesign``.
    """
    if pick_mode not in ("sparse", "dense"):
        raise ValueError(f"pick_mode must be 'sparse' or 'dense', got {pick_mode!r}")
    if outputs not in ("full", "picks"):
        raise ValueError(f"outputs must be 'full' or 'picks', got {outputs!r}")
    if wire not in ("conditioned", "raw"):
        raise ValueError(f"unknown wire {wire!r}; expected 'conditioned' or 'raw'")
    if wire == "raw" and scale_factor is None:
        raise ValueError("wire='raw' needs scale_factor (metadata.scale_factor)")
    nnx, nns = design.trace_shape
    if design.fk_channels != nnx:
        raise ValueError(
            "channel-padded designs (design_matched_filter(channel_pad=...)) "
            "are single-chip only; design without padding for the sharded step"
        )
    p = mesh.shape[time_axis]
    if nnx % p or nns % p:
        raise ValueError(f"trace shape {design.trace_shape} must divide mesh axis {p}")
    local = nns // p
    fk_mask = design.fk_mask
    band, order, fs = design.bp_band, design.bp_order, design.fs
    if fused_bandpass:
        # the halo-exchange bandpass stage never runs: no halo constraint,
        # no shard-window gain to build — |H|^2 folds into the pencil mask
        # via the shared single-source construction (ops/filters.py)
        from ..ops.filters import butter_zero_phase_gain_full

        gain = jnp.ones((1,), jnp.float32)   # unused by the fused body
        fk_mask = fk_mask * butter_zero_phase_gain_full(
            nns, fs, band, order
        )[None, :].astype(fk_mask.dtype)
    else:
        if halo >= local:
            raise ValueError(f"halo {halo} must be < local shard length {local}")
        # rebuild the design's own bandpass at the shard-window length (the
        # stored bp_gain is for the full-record window; same filter, new nfft)
        sos = sp.butter(order, [band[0] / (fs / 2), band[1] / (fs / 2)], "bp", output="sos")
        gain = jnp.asarray(zero_phase_gain(np.fft.rfftfreq(local + 2 * halo), sos).astype(np.float32))
    mask_rows = jnp.asarray(prepare_mask_full(fk_mask))
    templates_true, template_mu, template_scale = (
        xcorr.padded_template_stats_device(design.templates)
    )
    # bank threshold policy — ONE resolution for every design consumer
    # (models.matched_filter.MatchedFilterDesign.resolve_threshold_policy:
    # explicit legacy hf_factor > explicit vector > the design's bank)
    factors_np, thr_scope = design.resolve_threshold_policy(
        hf_factor, threshold_factors, threshold_scope
    )

    condition = wire == "raw"
    cond_scale = jnp.asarray(0.0 if scale_factor is None else scale_factor,
                             jnp.float32)
    cond_n = int(cond_time_samples or nns)
    segmented = cond_segments is not None or cond_means is not None
    seg_operands = ()
    if segmented:
        if not condition:
            raise ValueError("cond_segments/cond_means apply to wire='raw' only")
        if cond_segments is None or cond_means is None:
            raise ValueError("cond_segments and cond_means go together")
        seg_lens = [int(n) for n in cond_segments]
        n_real = sum(seg_lens)
        if min(seg_lens, default=0) < 1 or not n_real <= nns:
            raise ValueError(
                f"cond_segments {seg_lens} must be positive and sum to at "
                f"most the record length {nns}"
            )
        means = np.asarray(cond_means, np.float32)
        if means.shape != (nnx, len(seg_lens)):
            raise ValueError(
                f"cond_means shape {means.shape} != "
                f"{(nnx, len(seg_lens))} ([channel x n_segments])"
            )
        # sample -> file column; divisibility padding maps to a trailing
        # all-zero mean column so it conditions to exactly 0
        seg_ids = np.full(nns, len(seg_lens), np.int32)
        seg_ids[:n_real] = np.repeat(
            np.arange(len(seg_lens), dtype=np.int32), seg_lens
        )
        seg_operands = (
            jnp.asarray(seg_ids),
            jnp.asarray(np.concatenate(
                [means, np.zeros((nnx, 1), np.float32)], axis=1
            )),
        )

    def body(x, gain_w, mask_r, tmpl, tmu, tsc, cscale, *seg):
        if condition and segmented:
            # narrow-wire prologue, multi-file record: gather-subtract
            # the exact per-file host means (ops/conditioning.py)
            x = cond_ops.condition_segmented(
                x, cscale, seg[0], seg[1], dtype=tmpl.dtype
            )
        elif condition:
            # narrow-wire prologue: the per-channel mean spans time
            # shards -> psum of local sums (ops/conditioning.py)
            x = cond_ops.condition_time_sharded(
                x, cscale, time_axis, cond_n, dtype=tmpl.dtype
            )
        bp = (x if fused_bandpass
              else _bp_time_local(x, gain_w, halo=halo, axis_name=time_axis))
        trf = fk_apply_time_local(bp, mask_r, time_axis)           # [C, T/P]
        # relabel: one transpose into channel-sharded layout [C/P, T]
        y = jax.lax.all_to_all(trf, time_axis, split_axis=0, concat_axis=1, tiled=True)
        # true-length-template correlate (ops/xcorr.py:padded_template_stats)
        # — half the per-shard FFT length of the padded form; engine-routed
        # (ops/mxu.py: the MXU matmul recast when the router selected it)
        corr = mxu_ops.correlograms_body(y, tmpl, tmu, tsc, mf_engine)
        env = spectral.envelope_sqrt(corr, axis=-1)
        factors = jnp.asarray(factors_np)
        if thr_scope == "per_template":
            # decoupled bank scope: each template's base threshold from
            # ITS OWN global max (pmax over the relabeled channel shards)
            file_max = jax.lax.pmax(jnp.max(corr, axis=(1, 2)), time_axis)
            thres = relative_threshold * file_max          # [nT]
            thr = (thres * factors)[:, None, None]
        else:
            # reference policy: one max couples all templates; thres
            # stays the scalar PRE-factor base (output back-compat)
            file_max = jax.lax.pmax(jnp.max(corr), time_axis)
            thres = relative_threshold * file_max
            thr = thres * factors[:, None, None]
        if pick_mode == "sparse":
            # TPU production route: time is whole within each channel
            # shard here, so positions are global sample indices.
            # Channel-tiled kernel — same working-set bound as the
            # single-chip route (ops.peaks.find_peaks_sparse_tiled)
            picks = peak_ops.find_peaks_sparse_tiled(
                env, thr[..., 0], max_peaks=max_peaks, tile=pick_tile,
                method=pick_method,
            )
        else:
            picks = peak_ops.local_maxima(env) & (
                peak_ops.peak_prominences_dense(env) >= thr
            )
        if outputs == "picks":
            # campaign mode: only picks + threshold leave the program
            return picks, thres
        return trf, corr, env, picks, thres

    ct = P(None, time_axis, None)  # [template, channel(relabeled), *]
    # threshold output: the scalar pre-factor base under the reference
    # global scope; the [nT] per-template base vector under the bank's
    # decoupled scope (replicated either way)
    thres_spec = P(None) if thr_scope == "per_template" else P()
    if pick_mode == "sparse":
        picks_spec = peak_ops.SparsePicks(
            positions=ct, heights=ct, prominences=ct, selected=ct,
            saturated=P(None, time_axis),
        )
    else:
        picks_spec = ct
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(
            P(None, time_axis),   # trace (time-sharded)
            P(None),              # bp gain (replicated)
            P(time_axis, None),   # fk mask rows
            P(None, None),        # true-length templates (replicated)
            P(None),              # template means (replicated)
            P(None),              # template scales (replicated)
            P(),                  # conditioning scale (replicated)
        ) + ((
            P(time_axis),         # per-sample file/segment ids
            P(None, None),        # per-file host means (replicated)
        ) if segmented else ()),
        out_specs=(
            (picks_spec, thres_spec)    # picks, threshold base
            if outputs == "picks"
            else (
                P(None, time_axis),     # trf_fk stays time-sharded
                ct,                     # corr: channel-sharded (relabeled axis)
                ct,                     # env
                picks_spec,
                thres_spec,             # threshold base (replicated)
            )
        ),
        check_vma=False,
    )

    @jax.jit  # daslint: allow[R2] one-shot factory: caller holds the step for the run
    def step(trace):
        return fn(trace, gain, mask_rows, templates_true, template_mu,
                  template_scale, cond_scale, *seg_operands)

    return step


def time_sharding(mesh: Mesh, time_axis: str = "time") -> NamedSharding:
    """Input sharding for a ``[channel x time]`` block with time sharded."""
    return NamedSharding(mesh, P(None, time_axis))


# ---------------------------------------------------------------------------
# The resource ladder's time-sharded rung (workflows.planner)
# ---------------------------------------------------------------------------


def viable_time_mesh_size(trace_shape, n_devices: int) -> int | None:
    """The largest mesh size ``p >= 2`` that can serve ``trace_shape``
    time-sharded (the pencil f-k transform needs BOTH axes divisible by
    ``p``), or None when no multi-device decomposition exists — the
    planner's downshift ladder uses this to decide whether a
    ``timeshard`` rung is available at all."""
    C, T = trace_shape
    for p in range(min(int(n_devices), C, T), 1, -1):
        if C % p == 0 and T % p == 0:
            return p
    return None


def ladder_time_mesh(trace_shape):
    """The ladder's time-sharded rung mesh for ``trace_shape`` (largest
    viable decomposition over the local devices), or None — consumed by
    ``workflows.planner.MatchedFilterProgram``."""
    from .mesh import make_mesh

    p = viable_time_mesh_size(trace_shape, len(jax.devices()))
    if p is None:
        return None
    return make_mesh(shape=(p,), axis_names=("time",),
                     devices=jax.devices()[:p])


def sparse_time_picks_to_dict(sp_picks, template_names, n_samples=None):
    """Convert a time-sharded step's ``SparsePicks`` (``[nT, C, K]``
    global time positions) into the campaign picks dict
    ``{name: (2, n) [channel, time]}``, row-major (channel-major, time
    ascending within a channel — the same order the one-program route's
    device compaction emits). ``n_samples`` drops positions at or past
    the real record length (divisibility / bucket padding)."""
    pos = np.asarray(sp_picks.positions)
    sel = np.asarray(sp_picks.selected).astype(bool)
    out = {}
    for i, name in enumerate(template_names):
        mask = sel[i]
        if n_samples is not None:
            mask = mask & (pos[i] < int(n_samples))
        ch, slot = np.nonzero(mask)
        t = pos[i][ch, slot]
        order = np.lexsort((t, ch))
        out[name] = np.asarray([ch[order], t[order]], dtype=np.int64)
    return out


def detect_picks_time_sharded(det, trace, mesh: Mesh, n_real=None):
    """One file's picks through the TIME-SHARDED detection step — the
    resource ladder's multi-chip rung (docs/ROBUSTNESS.md "Resource
    ladder"): per-device working set shrinks ~1/P, so a shape that OOMs
    every single-chip route can still run on the mesh before falling to
    the host.

    ``det`` is the bucket's ``models.matched_filter.MatchedFilterDetector``
    (its design, wire and threshold policy are reused — one source);
    ``trace`` a host ``[C, T]`` block (stored-dtype counts on the raw
    wire); ``n_real`` the real time length of a bucket-padded record.
    Returns ``(picks, thresholds)`` in the campaign dict convention.

    Numerics caveat (same as the long-record path, module docstring):
    interior samples match the single-chip routes to float roundoff, but
    the first/last ``halo`` samples differ in their edge-transient
    handling — unlike the batched/file/tiled rungs, this rung's picks
    are detection-equivalent rather than guaranteed bit-identical.
    """
    # the compiled step depends on n_real only on the RAW wire (it is
    # the conditioning prologue's static cond_time_samples); on the
    # conditioned wire n_real feeds just the host-side pad filter — one
    # step serves every record length of the bucket (no per-length
    # recompile at this rung)
    nr_key = (int(n_real)
              if (n_real is not None and det.wire == "raw") else None)
    key = (mesh, nr_key)
    step = _LADDER_STEPS.setdefault(det, {}).get(key)
    if step is None:
        wire_kw = (
            {"wire": "raw", "scale_factor": det.metadata.scale_factor,
             "cond_time_samples": None if n_real is None else int(n_real)}
            if det.wire == "raw" else {}
        )
        step = make_sharded_mf_step_time(
            det.design, mesh, outputs="picks", pick_mode="sparse",
            max_peaks=det.max_peaks, fused_bandpass=det.fused_bandpass,
            mf_engine=getattr(det, "mf_engine", "fft"),
            **wire_kw,
        )
        _LADDER_STEPS[det][key] = step
    x = jax.device_put(np.asarray(trace), time_sharding(mesh))
    sp_picks, thres = jax.block_until_ready(step(x))
    picks = sparse_time_picks_to_dict(
        sp_picks, det.design.template_names, n_samples=n_real
    )
    # the step returns the PRE-factor threshold base: a scalar under the
    # reference global scope, the per-template max vector under the
    # bank's decoupled scope — the factors come from the design's bank
    factors = np.asarray(det.design.threshold_factors, np.float32)
    base = np.broadcast_to(
        np.asarray(thres, np.float32), factors.shape
    )
    thresholds = {
        name: float(base[i]) * float(factors[i])
        for i, name in enumerate(det.design.template_names)
    }
    return picks, thresholds


#: detector -> {(mesh, n_real): compiled time-sharded ladder step}.
#: Weak-keyed by the detector (the campaign holds its bucket detectors
#: for the whole run): steps die with their detector, and a fresh
#: campaign's fresh detector can never collide with a dead one's entry.
_LADDER_STEPS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
