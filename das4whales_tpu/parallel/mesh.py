"""Device-mesh construction for sharded DAS pipelines.

The reference's entire scale-out story is dask ``map_blocks`` chunking on a
single machine (dask_wrap.py, tools.py; SURVEY.md §2.4). The TPU-native
equivalent is a ``jax.sharding.Mesh`` with named axes:

* ``file``  — data parallelism over independent 60 s files (the natural DP
  unit, SURVEY.md §5.8);
* ``channel`` — sequence/space parallelism over the channel axis within a
  file (collectives ride ICI inside a slice).

On a single host the same meshes are testable with
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` CPU devices.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    shape: Sequence[int] | None = None,
    axis_names: Sequence[str] = ("file", "channel"),
    devices=None,
) -> Mesh:
    """Build a mesh over the available devices.

    With ``shape=None`` all devices go to the *last* axis (pure channel
    parallelism) — the common single-slice layout for one large file.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if shape is None:
        shape = (1,) * (len(axis_names) - 1) + (n,)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def channel_sharding(mesh: Mesh, channel_axis: str = "channel", ndim: int = 2) -> NamedSharding:
    """NamedSharding placing the channel (leading) axis of a
    ``[channel x time]`` block across ``channel_axis``."""
    spec = [None] * ndim
    spec[0] = channel_axis
    return NamedSharding(mesh, P(*spec))


def file_channel_sharding(mesh: Mesh, file_axis: str = "file", channel_axis: str = "channel") -> NamedSharding:
    """Sharding for a ``[file x channel x time]`` batch."""
    return NamedSharding(mesh, P(file_axis, channel_axis, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_block(x, mesh: Mesh, channel_axis: str = "channel"):
    """Place a ``[channel x time]`` array on the mesh, channel-sharded."""
    return jax.device_put(x, channel_sharding(mesh, channel_axis, np.ndim(x)))
