"""Batched campaign execution: B files per program step on one chip.

Every stage of the canonical detect pipeline runs at ~1-2% of its
roofline on a single 22050x12000 file (BENCH_r05) — one file cannot
saturate the chip, so the throughput move is the standard
inference-serving one (dynamic batching + shape bucketing, PAPERS.md):
stack ``B`` same-shape files into a ``[B, channel, time]`` slab and run
the WHOLE one-program matched-filter route
(``models.matched_filter.mf_detect_picks_program``) once per slab,
amortizing dispatch, host-sync and pick-finalization overhead across the
batch. The per-file math is the unbatched program over a leading file
axis — ``jax.vmap`` (cross-file parallelism, the chip-filling
accelerator mode) or ``jax.lax.map`` (sequential in-program, the CPU
mode: single-file cache locality, bitwise-identical per-file outputs) —
so per-file picks are bit-identical to the unbatched route (parity
pinned by tier-1 tests; under ``vmap``, in-graph thresholds may differ
in the last ulp from FFT-batch reduction order — picks are invariant to
that, the threshold and the envelope shift together).

Heterogeneous record lengths ride shape BUCKETS
(``config.BatchBucketConfig``): each file's time axis is zero-padded to
its bucket's length and the campaign compiles O(#buckets) programs, not
O(#shapes); on the raw wire the program demeans over the real samples
only (``ops.conditioning.condition_padded``, per-file ``n_real`` as a
traced vector — no per-length retrace).

Input donation: neither program donates the slab. The K0 (pack-method)
attempt must keep it alive for the adaptive-K escalation rerun; the
escalation program USED to donate it (``donate_argnums=(0,)``), but the
R12 program-contract audit (analysis/programs.py, ISSUE 16) proved that
donation a no-op — the program's outputs are pick tables and health
rows, never a ``[B, C, T]`` buffer, so XLA has nothing to alias the
slab into and its ``input_output_alias`` table stayed empty (the
"Some donated buffers were not usable" warning, on every backend;
measured priced-peak delta exactly 0 bytes, docs/PERF.md). The old
donation only invalidated the caller's buffer without returning any
HBM. Slab memory is reclaimed the ordinary way: callers drop their
reference after :meth:`BatchedMatchedFilterDetector.detect_batch` and
the assembler's bounded in-flight depth caps resident slabs
(analysis/baseline.toml R5 entries record both programs).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..models.matched_filter import (
    InFlightResult,
    MatchedFilterDetector,
    mf_detect_picks_program,
)
from ..ops import health as health_ops
from ..ops import peaks as peak_ops

_STATIC = (
    "band_lo", "band_hi", "bp_padlen", "pad_rows", "staged_bp", "tile",
    "max_peaks", "capacity", "use_threshold", "pick_method", "condition",
    "serial", "with_health", "pick_engine", "mf_engine", "fk_engine",
    "thr_scope", "fir_half",
)


def _batched_body(
    trace_batch, mask_band, bp_gain, templates_true, mu, scale, thr_in,
    cond_scale, n_real, fk_dft=None, thr_factors=None, mf_fused=None, *,
    band_lo: int, band_hi: int, bp_padlen: int, pad_rows: int,
    staged_bp: bool, tile: int | None, max_peaks: int, capacity: int,
    use_threshold: bool, pick_method: str, condition: bool,
    serial: bool = False, with_health: bool = False, health_clip=None,
    pick_engine: str = "jnp", mf_engine: str = "fft", fk_engine: str = "fft",
    thr_scope: str = "global", fir_half: int = 0,
):
    """The one-program route over a leading file axis, in ONE program.

    ``trace_batch`` is ``[B, C, T]`` (stored-dtype counts when
    ``condition``, strain otherwise); ``n_real`` is None (exact-fit
    bucket) or a ``[B]`` int vector of real time lengths (bucket-padded
    raw records — conditioned-wire pads are already zeros and need no
    in-program handling). Returns the per-file program outputs with a
    leading batch axis: ``(chan [B, nT, capacity], times [B, nT,
    capacity], count [B, nT], sat_count [B, nT], thr [B, nT])``.

    ``serial`` picks HOW the batch dimension executes inside the program:

    * ``False`` — ``jax.vmap``: cross-file parallelism, every stage sees
      the full ``[B, ...]`` working set. The accelerator mode: one file
      runs at ~1-2% of roofline (BENCH_r05), so the batch is what fills
      the chip.
    * ``True`` — ``jax.lax.map``: files execute sequentially inside the
      one program, so the per-file working set (and cache locality)
      matches the unbatched program exactly and per-file outputs are
      BITWISE-identical to it; only the dispatch + host-sync +
      pick-finalization overhead is amortized. The CPU mode — measured
      1.3-1.4x amortized per-file throughput at [1024 x 3000] where the
      vmap mode's 4x working set loses to the cache (docs/PERF.md).
    """
    def one(tr, nr):
        # fk_dft (the DFT-matmul pair), the bank's thr_factors and the
        # tap-fold pair mf_fused are closed over, not batched: one
        # matrix pair / factor vector / folded-tap stack serves every
        # file of the slab
        return mf_detect_picks_program(
            tr, mask_band, bp_gain, templates_true, mu, scale, thr_in,
            band_lo, band_hi, bp_padlen, pad_rows, staged_bp, tile,
            max_peaks, capacity, use_threshold, pick_method=pick_method,
            condition=condition, cond_scale=cond_scale, cond_n_real=nr,
            with_health=with_health, health_clip=health_clip,
            pick_engine=pick_engine, mf_engine=mf_engine,
            fk_engine=fk_engine, fk_dft=fk_dft,
            thr_factors=thr_factors, thr_scope=thr_scope,
            mf_fused=mf_fused, fir_half=fir_half,
        )

    if n_real is None:
        if serial:
            return jax.lax.map(lambda tr: one(tr, None), trace_batch)
        return jax.vmap(lambda tr: one(tr, None))(trace_batch)
    if serial:
        return jax.lax.map(lambda args: one(*args), (trace_batch, n_real))
    return jax.vmap(one)(trace_batch, n_real)


#: The batched one-program detection step (see :func:`_batched_body`).
#: NOT donated: the K0 attempt of the adaptive-K policy must keep the
#: slab for the full-capacity rerun (and the bench reuses one stack
#: across repeats).
batched_detect_picks_program = jax.jit(_batched_body, static_argnames=_STATIC)

#: The former donating variant, kept as an alias of the plain program
#: for import compatibility: the R12 donation-effectiveness audit showed
#: ``donate_argnums=(0,)`` here could never alias (pick-table outputs
#: are not slab-shaped), so the donation saved 0 bytes while poisoning
#: the caller's buffer — see the module docstring and docs/PERF.md.
batched_detect_picks_program_donated = batched_detect_picks_program


def trim_picks(picks: Dict[str, np.ndarray], n_real: int) -> Dict[str, np.ndarray]:
    """Drop picks in a bucket-padded record's pad region (``time >=
    n_real``): the pad holds no signal, so anything picked there is
    filter ring-down past the record end, not a detection. Exact-fit
    records pass through unchanged."""
    return {
        name: pk[:, pk[1] < n_real] if pk.shape[1] else pk
        for name, pk in picks.items()
    }


class BatchedMatchedFilterDetector:
    """Batched facade over one :class:`MatchedFilterDetector`: a
    ``[B, channel, time]`` slab in, per-file picks out, one XLA program
    and one packed fetch per slab.

    The wrapped detector must be the campaign configuration
    (``pick_mode="sparse"``; build it at the BUCKET shape). The adaptive-K
    policy of :meth:`MatchedFilterDetector.detect_picks` is preserved
    across the batch: a K0 pack-method program first, escalating to the
    full-capacity topk program only when any file's row saturated —
    bit-identical (``ops.peaks.picks_with_escalation`` semantics).
    ``donate`` is retained for API compatibility but inert: the R12
    contract audit proved slab donation un-aliasable here (pick-table
    outputs are never slab-shaped — module docstring), so no program
    donates; callers drop their slab reference after
    :meth:`detect_batch` and the bounded in-flight depth of the
    assembler caps resident slabs.
    ``serial=None`` resolves the in-program batch execution mode per
    backend (``lax.map`` on CPU, ``vmap`` on accelerators — see
    :func:`_batched_body`); pass a bool to force one.
    """

    #: detector-family label stamped on campaign records
    #: (workflows.planner; every detector family has a batched facade —
    #: see :func:`batched_detector_for`)
    family = "mf"

    def __init__(self, detector: MatchedFilterDetector, donate: bool = True,
                 serial: bool | None = None):
        if detector.pick_mode != "sparse":
            raise ValueError(
                f"the batched route needs pick_mode='sparse' (got "
                f"{detector.pick_mode!r}); build the detector with "
                "pick_mode='sparse', keep_correlograms=False"
            )
        self.det = detector
        self.donate = bool(donate)
        if serial is None:
            serial = jax.default_backend() == "cpu"
        self.serial = bool(serial)

    def split_views(self) -> tuple:
        """The bank-split downshift rung's pair of SUB-BANK batched
        facades (T -> ceil(T/2) + floor(T/2) over the same bucket shape
        and batch; ``MatchedFilterDetector.split_views``): two
        dispatches instead of one, each with roughly half the
        correlate/envelope/pick working set, before the ladder
        sacrifices B (docs/ROBUSTNESS.md "Resource ladder"). Neither
        half donates — the first sub-bank's program must leave the slab
        alive for the second's dispatch. Cached (the winning rung is
        sticky: one facade pair per bucket for the campaign)."""
        cached = self.__dict__.get("_split_cache")
        if cached is None:
            a, b = self.det.split_views()
            cached = self.__dict__["_split_cache"] = (
                BatchedMatchedFilterDetector(a, donate=False,
                                             serial=self.serial),
                BatchedMatchedFilterDetector(b, donate=False,
                                             serial=self.serial),
            )
        return cached

    def detect_batch(
        self, stack, n_real=None, n_valid: int | None = None,
        with_health: bool = False, health_clip: float | None = None,
    ) -> List[tuple | None]:
        """Detect over a ``[B, C, T]`` slab (dispatch + fetch in one
        call — ``dispatch_batch(...).resolve()``; see
        :meth:`dispatch_batch` for the pipelined split).

        ``B`` is read from the stack, NOT fixed at construction: one
        facade serves every batch size over its bucket shape, compiling
        one program per distinct ``B``. The campaign's elastic downshift
        ladder leans on this (``io.stream.subdivide_slab`` re-buckets a
        failed slab to B/2, …, and redispatches through the SAME
        detector — docs/ROBUSTNESS.md "Resource ladder"), and the AOT
        memory preflight prices the program at any candidate ``B``
        without dispatching (``utils.memory.batched_program_memory``).

        ``n_real`` (sequence of per-file real time lengths) marks
        bucket-padded files; ``n_valid`` limits the returned entries to
        the slab's real files (trailing zero file-slots of a partial
        batch are computed — the program shape is fixed — but never
        fetched into results). Returns one entry per (valid) file:
        ``(picks {name: (2, n) int64}, thresholds {name: float})`` —
        with a third element, the per-file ``ops.health`` stats dict,
        when ``with_health=True`` (the stats are computed in the same
        program and ride the same packed fetch; ``health_clip`` is the
        clip-count magnitude) — or ``None`` when that file's packed-pick
        capacity overflowed and the caller must fall back to its exact
        per-file route (:meth:`MatchedFilterDetector.detect_picks` on
        the host block).
        """
        return self.dispatch_batch(
            stack, n_real=n_real, n_valid=n_valid, with_health=with_health,
            health_clip=health_clip,
        ).resolve()

    def dispatch_batch(
        self, stack, n_real=None, n_valid: int | None = None,
        with_health: bool = False, health_clip: float | None = None,
    ) -> InFlightResult:
        """LAUNCH the batched K0 program without fetching.

        The depth-D pipelined campaign dispatch
        (``workflows.campaign.run_campaign_batched``,
        ``parallel.dispatch``; docs/PERF.md "Pipelined dispatch"): slab
        k+1's program dispatches here while slab k's picks are still in
        flight. ``handle.resolve()`` — the slab's ONLY device sync —
        fetches the packed K0 payload, resolves the adaptive-K
        escalation from that ALREADY-FETCHED payload (the per-file
        ``sat_count`` rides the packed fetch, so the decision costs no
        extra round trip), reruns at full capacity only when a row
        saturated (the slab's final consumer; no donation — the R12
        audit showed the slab cannot alias into pick-table outputs),
        and assembles :meth:`detect_batch`'s per-file
        entry list. The handle keeps the slab alive for that potential
        rerun and drops its reference the moment picks exist; dropping
        an UNRESOLVED handle abandons the in-flight program (the
        campaign does that when a bucket downshifts between dispatch
        and resolve).
        """
        from .. import faults

        det = self.det
        C, T = det.design.trace_shape
        B = int(stack.shape[0])
        if tuple(stack.shape[1:]) == (C, T):
            stack = det._as_input(stack)
        else:
            raise ValueError(
                f"slab shape {tuple(stack.shape[1:])} != detector design "
                f"shape {(C, T)}; one batched detector serves one bucket"
            )
        names = det.design.template_names
        nT = len(names)
        cap = int(min(C * det.max_peaks, det.pick_pack_cap))
        thr_in = jnp.zeros((nT,), det._mask_band_dev.dtype)
        tile = det.effective_channel_tile if det._route() == "tiled" else None
        nr = None
        if (det.wire == "raw" or with_health) and n_real is not None:
            nr_np = np.asarray(n_real, np.int32)
            if nr_np.ndim != 1 or not 1 <= nr_np.shape[0] <= B:
                raise ValueError(
                    f"n_real must be a <= {B}-vector, got {nr_np.shape}"
                )
            if nr_np.shape[0] < B:
                # partial slab: padded file slots are whole-length zeros
                nr_np = np.concatenate(
                    [nr_np, np.full(B - nr_np.shape[0], T, np.int32)]
                )
            if int(nr_np.min(initial=T)) < T:
                nr = jnp.asarray(nr_np)

        def run(k, stack_):
            faults.count("dispatches")
            return batched_detect_picks_program(
                stack_, det._program_mask_dev, det._gain_dev,
                det._templates_true, det._template_mu, det._template_scale,
                thr_in, det._cond_scale, nr, det._fk_dft_dev,
                det._thr_factors_dev, det._mf_fused_dev,
                band_lo=det._band_lo, band_hi=det._band_hi,
                bp_padlen=det.design.bp_padlen, pad_rows=det.fk_pad_rows,
                staged_bp=det._program_staged_bp, tile=tile, max_peaks=k,
                capacity=cap, use_threshold=False,
                pick_method=peak_ops.escalation_method(k, det.max_peaks),
                condition=det.wire == "raw", serial=self.serial,
                with_health=with_health,
                health_clip=(None if health_clip is None
                             else jnp.float32(health_clip)),
                pick_engine=det.pick_engine,
                mf_engine=det.mf_engine, fk_engine=det.fk_engine,
                thr_scope=det.threshold_scope, fir_half=det._mf_fir_half,
            )

        # the K0 launch: async — device-side failures surface at
        # resolve()'s fetch (where the campaign's watchdog/ladder wrap it)
        state = {"stack": stack, "k0": run(det.pick_k0, stack)}
        del stack

        def resolve() -> List[tuple | None]:
            h_counts = h_rms = h_binc = h_brms = None

            def fetch_payload(outs):
                nonlocal h_counts, h_rms, h_binc, h_brms
                outs = jax.device_get(outs)
                faults.count("syncs")
                if with_health:
                    *outs, h_counts, h_rms, h_binc, h_brms = outs
                return outs

            chan, times, cnt, satc, thr = fetch_payload(state.pop("k0"))
            if det.pick_k0 < det.max_peaks and int(satc.sum()):
                # a row saturated at K0: full-capacity rerun — the slab's
                # last use. The escalation decision came from the packed
                # K0 payload fetched above: no extra sync round trip.
                chan, times, cnt, satc, thr = fetch_payload(
                    run(det.max_peaks, state["stack"])
                )
            # common path: drop the slab reference the moment picks exist
            state.clear()

            n_reals = None if n_real is None else np.asarray(n_real).tolist()
            out: List[tuple | None] = []
            for b in range(B if n_valid is None else int(n_valid)):
                if int(cnt[b].max(initial=0)) > cap:
                    out.append(None)  # packed overflow: exact per-file fallback
                    continue
                picks, thr_out = {}, {}
                for i, name in enumerate(names):
                    k = int(cnt[b, i])
                    picks[name] = np.asarray(
                        [chan[b, i, :k], times[b, i, :k]], dtype=np.int64
                    )
                    thr_out[name] = float(thr[b, i])
                    det._warn_saturated(name, int(satc[b, i]))
                if with_health:
                    ns_b = int(n_reals[b]) if (n_reals is not None
                                               and b < len(n_reals)) else T
                    out.append((picks, thr_out, health_ops.stats_to_dict(
                        h_counts[b], h_rms[b], C * ns_b,
                        bin_counts=h_binc[b], bin_rms=h_brms[b],
                        n_channels=C,
                    )))
                else:
                    out.append((picks, thr_out))
            return out

        return InFlightResult(resolve)


class _BatchedFamilyDetector:
    """Shared batched-facade machinery for the non-MF detector families
    (spectro / gabor / learned): a ``[B, C, T]`` slab in, per-file
    ``(picks, thresholds[, stats])`` entries out, ONE heavy XLA program
    per slab.

    The family split mirrors the detectors' own two-stage refactor: the
    HEAVY stage (prefilter + correlograms/scores — pure function of the
    block) is jitted once per facade and mapped over the B file axis
    (``lax.map`` serial on CPU, ``vmap`` on accelerators — the same
    switch as :func:`_batched_body`); the FINALIZE stage (escalation
    picks, thresholds) reuses the family's own per-file finalize
    (``picks_from_correlograms`` / ``picks_from_scores``) on each file's
    slice of the mapped output. In serial mode each mapped row is
    bitwise-identical to the per-file composition (the parity suite pins
    batched picks == per-file picks for every family).

    ``donate`` is accepted for API parity with
    :class:`BatchedMatchedFilterDetector` but inert for the same R12
    reason: the heavy outputs (correlograms ``[B, C, nt]`` / scores
    ``[B, C, n_win]``) are never slab-shaped, so XLA has nothing to
    alias the slab into. Health stats follow the families' planner
    route: host-side ``ops.health.host_health_stats`` on each file's
    host row (``supports_fused_health=False`` — same values, one numpy
    pass).
    """

    family = "generic"

    def __init__(self, detector, donate: bool = True,
                 serial: bool | None = None, trace_shape=None):
        self.det = detector
        self.donate = bool(donate)
        if serial is None:
            serial = jax.default_backend() == "cpu"
        self.serial = bool(serial)
        if trace_shape is None:
            trace_shape = self._design_shape()
        self._trace_shape = (None if trace_shape is None
                             else tuple(int(s) for s in trace_shape))
        # one jitted heavy program per facade instance: the campaign and
        # the service cache one facade per bucket, so the compile count
        # is one per (bucket, B, engine) — the compile_guard pin
        self._program = jax.jit(self._heavy_body)  # daslint: allow[R2,R5] one facade per bucket (campaign/service cache); donation un-aliasable for these families — class docstring

    # -- family hooks ------------------------------------------------------

    def _design_shape(self):
        """The bucket ``(C, T)`` this facade serves, when derivable from
        the wrapped detector (None: accept any shape, one program per
        distinct shape)."""
        adapter = self.det
        design = getattr(getattr(adapter, "prefilter", None), "design", None)
        return getattr(design, "trace_shape", None)

    def _resolve_engines(self, stack_shape) -> None:
        """Resolve the family's per-shape engine decision EAGERLY (never
        under a trace — the A/B router times candidate programs)."""

    @property
    def engine(self) -> str:
        """Resolved engine label for cost cards / ledger attribution."""
        return "fft"

    def _heavy_one(self, tr):
        """One file's heavy stage (traced; mapped over the B axis)."""
        raise NotImplementedError

    def _finalize_one(self, heavy, b: int):
        """One file's ``(picks, thresholds)`` from its slice of the
        mapped heavy output (host boundary — the family's own per-file
        finalize, shared with the per-file rung)."""
        raise NotImplementedError

    # -- shared machinery --------------------------------------------------

    def _heavy_body(self, stack):
        if self.serial:
            return jax.lax.map(self._heavy_one, stack)
        return jax.vmap(self._heavy_one)(stack)

    def program_spec(self, batch: int, stack_dtype, *,
                     with_health: bool = False,
                     health_clip: float | None = None,
                     donate: bool = False):
        """The facade's AOT pricing spec — ``(jitted, avals,
        static_kwargs)`` — consumed by ``utils.memory``'s
        ``_batched_program_spec`` dispatch so family programs ride the
        same preflight/cost-card/contract-audit ``lower().compile()``
        boundary as the matched filter. Health stats are host-side for
        these families, so the priced program is the heavy stage alone
        regardless of ``with_health``; ``donate`` prices the same
        program (donation is un-aliasable here — class docstring)."""
        if self._trace_shape is None:
            raise ValueError(
                f"cannot price a {self.family} batched program without a "
                "bucket shape; construct the facade with trace_shape=(C, T)"
            )
        C, T = self._trace_shape
        self._resolve_engines((int(batch), C, T))
        avals = (jax.ShapeDtypeStruct((int(batch), C, T),
                                      np.dtype(stack_dtype)),)
        # a dedicated jit wrapper (never dispatched): a preflight failure
        # can never poison the hot path's jit cache
        jitted = jax.jit(self._heavy_body)  # daslint: allow[R2,R5] AOT pricing only (never dispatched; nothing to donate) — see utils.memory
        return jitted, avals, {}

    def detect_batch(
        self, stack, n_real=None, n_valid: int | None = None,
        with_health: bool = False, health_clip: float | None = None,
    ) -> List[tuple | None]:
        """Detect over a ``[B, C, T]`` slab (dispatch + resolve in one
        call). Same contract as
        :meth:`BatchedMatchedFilterDetector.detect_batch`: one entry per
        valid file — ``(picks, thresholds)`` plus the per-file
        ``ops.health`` stats dict when ``with_health=True``. These
        families have no packed-capacity overflow (their finalize runs
        the exact per-file escalation), so entries are never None."""
        return self.dispatch_batch(
            stack, n_real=n_real, n_valid=n_valid, with_health=with_health,
            health_clip=health_clip,
        ).resolve()

    def dispatch_batch(
        self, stack, n_real=None, n_valid: int | None = None,
        with_health: bool = False, health_clip: float | None = None,
    ) -> InFlightResult:
        """LAUNCH the heavy batched program without fetching — the
        pipelined-dispatch half of the one-program batched contract
        (``handle.resolve()`` is the slab's one device sync; finalize
        consumes device slices of the already-computed output)."""
        from .. import faults

        B = int(stack.shape[0])
        got = tuple(int(s) for s in stack.shape[1:])
        if self._trace_shape is not None and got != self._trace_shape:
            raise ValueError(
                f"slab shape {got} != detector design shape "
                f"{self._trace_shape}; one batched detector serves one bucket"
            )
        self._resolve_engines(tuple(stack.shape))
        # host rows for the families' host-side health stats (free when
        # the assembler hands us its numpy stack)
        host_rows = np.asarray(stack) if with_health else None
        faults.count("dispatches")
        state = {"heavy": self._program(jnp.asarray(stack))}
        del stack

        def resolve() -> List[tuple | None]:
            heavy = jax.block_until_ready(state.pop("heavy"))
            faults.count("syncs")
            out: List[tuple | None] = []
            for b in range(B if n_valid is None else int(n_valid)):
                picks, thresholds = self._finalize_one(heavy, b)
                if with_health:
                    stats = health_ops.host_health_stats(
                        host_rows[b], clip_abs=health_clip
                    )
                    out.append((picks, thresholds, stats))
                else:
                    out.append((picks, thresholds))
            state.clear()
            return out

        return InFlightResult(resolve)


class BatchedSpectroDetector(_BatchedFamilyDetector):
    """Batched facade over one ``eval.SpectroEvalAdapter``: the heavy
    stage is the shared bandpass + f-k prefilter followed by the
    per-kernel spectro correlograms
    (``SpectroCorrDetector.correlograms`` — the STFT rides the
    ``resolve_stft_engine_ab``-selected engine, rFFT or the framed
    windowed-DFT MXU matmul); finalize is the adapter's own
    escalation-pick + hop→sample conversion per file."""

    family = "spectro"

    def _resolve_engines(self, stack_shape) -> None:
        self.det.det.resolve_engine(tuple(stack_shape[-2:]))

    @property
    def engine(self) -> str:
        return self.det.det.stft_engine or "rfft"

    def _heavy_one(self, tr):
        adapter = self.det
        filt = getattr(adapter.prefilter, "filter_block", adapter.prefilter)
        return adapter.det.correlograms(filt(tr))

    def _finalize_one(self, heavy, b: int):
        sdet = self.det.det
        corr_b = {name: v[b] for name, v in heavy.items()}
        picks, spectro_fs = sdet.picks_from_correlograms(corr_b)
        # hop-unit -> sample-unit conversion, exactly the per-file
        # adapter's (eval.SpectroEvalAdapter.__call__)
        fs = sdet.metadata.fs
        out = {}
        for name, pk in picks.items():
            pk = np.asarray(pk)
            t_samples = np.round(pk[1] * (fs / spectro_fs)).astype(int)
            out[name] = np.asarray([pk[0], t_samples])
        thr = float(sdet.threshold)
        return out, {name: thr for name in out}


class BatchedGaborDetector(_BatchedFamilyDetector):
    """Batched facade over one ``eval.GaborEvalAdapter``: the heavy
    stage is the shared prefilter, the oriented Gabor pair
    (``resolve_gabor_engine``-selected — FFT correlation or
    f32-accumulated ``conv_general_dilated``) and the per-note masked
    matched filter; finalize is the detector's relative-threshold policy
    + per-note envelope picks per file. Gabor batches over FILES, so the
    channel-halo seam cost that forbids this family's tiled rung
    (``workflows.planner.GaborProgram``) never arises."""

    family = "gabor"

    def _resolve_engines(self, stack_shape) -> None:
        self.det.det.resolve_engine(tuple(stack_shape[-2:]))

    @property
    def engine(self) -> str:
        return self.det.det.gabor_engine or "fft"

    def _heavy_one(self, tr):
        adapter = self.det
        filt = getattr(adapter.prefilter, "filter_block", adapter.prefilter)
        _, _, _, correlograms = adapter.det.correlograms(filt(tr))
        # correlograms only: score/mask/masked-trace are DCE'd from the
        # program (never fetched) — B x C x T x 3 fewer output bytes
        return correlograms

    def _finalize_one(self, heavy, b: int):
        corr_b = {name: v[b] for name, v in heavy.items()}
        picks, _, thresholds = self.det.det.picks_from_correlograms(corr_b)
        return ({k: np.asarray(v) for k, v in picks.items()},
                dict(thresholds))


class BatchedLearnedDetector(_BatchedFamilyDetector):
    """Batched facade over one ``models.learned.LearnedDetector``: the
    heavy stage is STFT windowing + the CNN's sigmoid scores per file
    (one ``[B, C, n_win]`` score tensor); finalize is the detector's own
    host-side threshold + per-channel NMS. The batched rung scores the
    whole window batch in one program (``row_chunk`` is a per-file-rung
    knob — when the one-program sweep exhausts, the ladder's tiled rung
    restores the bounded-activation chunking)."""

    family = "learned"

    def _design_shape(self):
        return None  # not derivable from the detector; pass trace_shape

    @property
    def engine(self) -> str:
        from ..ops import spectral

        return spectral.resolve_stft_engine()

    def _heavy_one(self, tr):
        from ..models.learned import _score_windows, window_features

        ldet = self.det
        win, _ = window_features(tr, ldet.cfg)
        flat = win.reshape(-1, *win.shape[-2:])
        scores = _score_windows(ldet.params, flat, ldet.cfg.compute_dtype)
        return scores.reshape(win.shape[0], win.shape[1])

    def _finalize_one(self, heavy, b: int):
        res = self.det.picks_from_scores(np.asarray(heavy[b]))
        return dict(res.picks), dict(res.thresholds)


def batched_detector_for(detector, *, donate: bool = True,
                         serial: bool | None = None, trace_shape=None):
    """The batched-facade registry — ``workflows.planner.program_for``'s
    batched twin: any campaign detector -> its batched facade. The
    campaign's slab route and the service scheduler build detectors per
    bucket and wrap them here; ``trace_shape`` pins the bucket ``(C,
    T)`` for families that cannot derive it (the learned CNN)."""
    from ..eval import GaborEvalAdapter, SpectroEvalAdapter
    from ..models.learned import LearnedDetector

    if isinstance(detector, MatchedFilterDetector):
        return BatchedMatchedFilterDetector(detector, donate=donate,
                                            serial=serial)
    if isinstance(detector, SpectroEvalAdapter):
        return BatchedSpectroDetector(detector, donate=donate, serial=serial,
                                      trace_shape=trace_shape)
    if isinstance(detector, GaborEvalAdapter):
        return BatchedGaborDetector(detector, donate=donate, serial=serial,
                                    trace_shape=trace_shape)
    if isinstance(detector, LearnedDetector):
        return BatchedLearnedDetector(detector, donate=donate, serial=serial,
                                      trace_shape=trace_shape)
    raise TypeError(
        f"no batched facade for detector type {type(detector).__name__}; "
        "families with one: matched filter, spectro, gabor, learned"
    )
