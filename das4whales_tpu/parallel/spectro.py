"""Channel-sharded spectrogram-correlation detection.

The spectro family is the easiest detector family to scale out:
every stage (per-channel normalization, sliced STFT, 2-D hat-kernel
correlation, absolute-threshold picking — reference detect.py:650-708 +
main_spectrodetect.py:118-121) is channel-local, and the threshold is
ABSOLUTE (14 on normalized correlograms), so unlike the matched-filter
step (parallel/pipeline.py, one ``pmax`` per file) this step needs **no
collectives at all**: ``shard_map`` over a (file, channel) mesh with
every output sharded like its input.

Within each shard, channels stream through ``lax.map`` tiles so the
overlapped STFT frame tensor (~1.8 MB/channel at the detector's 95%
overlap under the rFFT engine) never materializes for the whole shard.

Note on the resilient route planner (``workflows.planner``): these
sharded steps take the UNFILTERED block and normalize internally, so
they are standalone detectors — NOT drop-in ladder rungs for the
campaign's prefiltered spectro adapter. The spectro family's ladder is
per-file -> channel-chunk-tiled (``SpectroCorrDetector.tiled_view``) ->
host (docs/ROBUSTNESS.md "Family x guarantee coverage").
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from .compat import shard_map
from jax.sharding import PartitionSpec as P

from ..config import SPECTRO_HF_KERNEL, SPECTRO_LF_KERNEL, as_metadata
from ..models.spectro import buildkernel, effective_band, xcorr2d
from ..ops import peaks as peak_ops
from ..ops import spectral
from .timeshard import halo_exchange




def _design_kernels(fs, ns, flims, kernels, nperseg, nhop, nt):
    """Host-side per-kernel design shared by both sharded factories: band
    rows as STATIC slices of the full-band spectrogram grid + the hat
    kernel on those rows (one source so the factories cannot diverge)."""
    nf = nperseg // 2 + 1
    ff_full = np.linspace(0, fs / 2, num=nf)
    dt = (ns / fs) / max(nt - 1, 1)              # frame spacing of the record grid
    designs = []
    for name, ker in kernels.items():
        fmin, fmax = effective_band(flims, ker)
        sel_rows = np.where((ff_full >= fmin) & (ff_full <= fmax))[0]
        lo, hi = int(sel_rows[0]), int(sel_rows[-1]) + 1
        # buildkernel sizes its time axis by counting grid points in the
        # (7*dur, 8*dur) window (reference detect.py:411-492) — only the
        # SPACING matters, so hand it a grid guaranteed to span that
        # window: identical kernels at real record lengths, and no empty
        # kernel when the record is shorter than 8*dur (tiny CI shapes)
        tt = np.arange(0, 8.2 * ker["dur"] + dt, dt)
        _, _, K = buildkernel(
            ker["f0"], ker["f1"], ker["bdwidth"], ker["dur"],
            ff_full[lo:hi], tt, fs, fmin, fmax,
        )
        designs.append((name, lo, hi, jnp.asarray(K, jnp.float32)))
    return designs, tuple(d[0] for d in designs)


def make_sharded_spectro_step(
    metadata,
    mesh,
    flims: Tuple[float, float] = (14.0, 30.0),
    kernels: Dict[str, Dict] | None = None,
    win_size: float = 0.8,
    overlap_pct: float = 0.95,
    threshold: float = 14.0,
    max_peaks: int = 256,
    channel_tile: int = 256,
    outputs: str = "full",
    file_axis: str = "file",
    channel_axis: str = "channel",
):
    """Build a jittable sharded spectro-correlation step for ``mesh``.

    The returned callable maps a ``[file x channel x time]`` batch (placed
    with ``parallel.pipeline.input_sharding``) to ``(correlograms, picks)``
    where ``correlograms`` is ``[n_kernels, file, channel, n_frames]`` and
    ``picks`` an ``ops.peaks.SparsePicks`` over the same leading axes
    (``outputs="picks"`` drops the correlograms from the program).
    Kernel/axis design happens host-side once; defaults reproduce
    ``main_spectrodetect.py`` (0.8 s window, 95% overlap, threshold 14).
    """
    if outputs not in ("full", "picks"):
        raise ValueError(f"outputs must be 'full' or 'picks', got {outputs!r}")
    meta = as_metadata(metadata)
    fs, ns = meta.fs, meta.ns
    kernels = kernels or {"HF": SPECTRO_HF_KERNEL, "LF": SPECTRO_LF_KERNEL}
    nperseg = int(win_size * fs)
    nhop = int(np.floor(nperseg * (1 - overlap_pct)))

    # The full-band magnitude is max-normalized BEFORE slicing
    # (sliced_spectrogram semantics), so computing the STFT once per tile
    # and slicing each kernel's band from it is bit-identical to
    # per-kernel spectrograms — and halves the step's dominant cost.
    probe_mag = spectral.stft_magnitude(jnp.zeros((1, ns), jnp.float32), nperseg, nhop)
    nt = probe_mag.shape[-1]
    designs, names = _design_kernels(fs, ns, flims, kernels, nperseg, nhop, nt)

    def _shard_body(x):                              # [B/Pf, C/Pc, ns]
        norm = x - jnp.mean(x, axis=-1, keepdims=True)
        norm = norm / jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        Bl, Cl, _ = norm.shape
        tile = min(channel_tile, Cl)
        n_tiles = -(-Cl // tile)
        pad = n_tiles * tile - Cl
        xt = jnp.pad(norm, ((0, 0), (0, pad), (0, 0)))
        xt = xt.reshape(Bl, n_tiles, tile, ns)

        def per_tile(chunk):
            mag = spectral.stft_magnitude(chunk, nperseg, nhop)
            p = mag / jnp.max(mag, axis=(-2, -1), keepdims=True)
            return tuple(
                xcorr2d(p[:, lo:hi, :], K) for _, lo, hi, K in designs
            )

        outs = jax.lax.map(lambda t: jax.lax.map(per_tile, t), xt)
        corrs = [o.reshape(Bl, n_tiles * tile, -1)[:, :Cl] for o in outs]
        corr = jnp.stack(corrs)                       # [nT, B/Pf, C/Pc, nt]
        picks = peak_ops.find_peaks_sparse_batched(
            corr, jnp.asarray(threshold, x.dtype), max_peaks=max_peaks
        )
        if outputs == "picks":
            return picks
        return corr, picks

    spec_in = P(file_axis, channel_axis, None)
    spec_corr = P(None, file_axis, channel_axis, None)
    spec_picks = jax.tree_util.tree_map(
        lambda _: P(None, file_axis, channel_axis), peak_ops.SparsePicks(0, 0, 0, 0, 0)
    )
    # saturated has no trailing slot axis but shares the leading layout
    out_specs = spec_picks if outputs == "picks" else (spec_corr, spec_picks)
    return jax.jit(  # daslint: allow[R2] one-shot factory: campaign jits its step once per run
        shard_map(
            _shard_body, mesh=mesh, in_specs=(spec_in,), out_specs=out_specs,
            check_vma=False,
        )
    ), names


def make_sharded_spectro_step_time(
    metadata,
    mesh,
    flims: Tuple[float, float] = (14.0, 30.0),
    kernels: Dict[str, Dict] | None = None,
    win_size: float = 0.8,
    overlap_pct: float = 0.95,
    threshold: float = 14.0,
    max_peaks: int = 256,
    outputs: str = "full",
    time_axis: str = "time",
):
    """Sequence parallelism for the spectro family: detection on a
    ``[channel x time]`` record whose TIME axis is sharded over ``mesh``
    (records longer than one chip — same layout as
    ``timeshard.make_sharded_mf_step_time``).

    Collective inventory: one ``psum``/``pmax`` pair for the global
    per-channel signal statistics, a ``halo_exchange`` of ``nperseg/2``
    samples so every STFT frame is sample-exact across shard boundaries,
    one ``pmax`` for the spectrogram's per-channel max normalization, and
    ONE ``all_to_all`` relabel (frames gathered, channels scattered) after
    which correlation/median/picking are channel-local and exactly the
    single-chip computation.

    Parity deviation: librosa's final centered frame (center == record
    end, mostly zero padding) is dropped — the frame grid is
    ``ns // nhop`` instead of ``1 + ns // nhop``. Consequences are
    confined to the record's trailing edge: correlogram frames within
    one kernel width of the end see the convolution's shortened tail
    (and the per-channel median/max normalizers can shift ~1%); interior
    frames match the single-chip detector to float32 noise
    (tests/test_spectro_timeshard.py).

    Returns ``(step, names)``; the step maps the sharded ``[C, T]`` block
    to ``(correlograms [nT, C, n_frames], picks)`` with the CHANNEL axis
    sharded over ``time_axis`` after the relabel (the timeshard
    convention), or just picks with ``outputs="picks"``.
    """
    if outputs not in ("full", "picks"):
        raise ValueError(f"outputs must be 'full' or 'picks', got {outputs!r}")
    meta = as_metadata(metadata)
    fs, ns = meta.fs, meta.ns
    kernels = kernels or {"HF": SPECTRO_HF_KERNEL, "LF": SPECTRO_LF_KERNEL}
    nperseg = int(win_size * fs)
    nhop = int(np.floor(nperseg * (1 - overlap_pct)))
    p = mesh.shape[time_axis]
    if ns % p:
        raise ValueError(f"time length {ns} not divisible by mesh axis {time_axis}={p}")
    local = ns // p
    if local % nhop:
        raise ValueError(
            f"local shard length {local} must be a MULTIPLE of the frame "
            f"hop {nhop} (frame grid must align with shard boundaries)"
        )
    halo = nperseg // 2
    if halo >= local:
        raise ValueError(f"STFT halo {halo} must be < local shard length {local}")
    nt_total = ns // nhop

    # kernel design on the same grids as the channel-sharded step (the
    # kernel depends only on the frame spacing nhop/fs and band rows)
    designs, names = _design_kernels(
        fs, ns, flims, kernels, nperseg, nhop, nt_total + 1
    )

    def _body(x):                                    # [C, local]
        # global per-channel signal stats (reference normalization,
        # detect.py:650-708) via collectives
        mean = jax.lax.psum(jnp.sum(x, axis=-1, keepdims=True), time_axis) / ns
        mx = jax.lax.pmax(jnp.max(jnp.abs(x), axis=-1, keepdims=True), time_axis)
        norm = (x - mean) / mx
        # halo so every frame is sample-exact; global edges zero-pad —
        # exactly librosa's centered zero padding of the normalized signal
        ext = halo_exchange(norm, halo, time_axis)    # [C, halo + local + halo]
        # channels stream through lax.map tiles: the 95%-overlap frame
        # tensor is ~(nperseg/nhop)x the input bytes — untiled it is the
        # round-2 OOM class (same policy as the channel-sharded step).
        # center=False framing (the halo IS the centering), rfft engine.
        C = ext.shape[0]
        tile = min(256, C)
        n_tiles = -(-C // tile)
        extp = jnp.pad(ext, ((0, n_tiles * tile - C), (0, 0)))
        extp = extp.reshape(n_tiles, tile, ext.shape[-1])

        def per_tile(chunk):
            return jnp.abs(
                spectral.stft(chunk, nperseg, nhop, center=False)
            )[..., : local // nhop]

        frames = jax.lax.map(per_tile, extp)
        frames = frames.reshape(n_tiles * tile, *frames.shape[2:])[:C]
        smax = jax.lax.pmax(jnp.max(frames, axis=(-2, -1), keepdims=True), time_axis)
        pnorm = frames / smax
        # ONE relabel: frames gathered whole, channels scattered
        pr = jax.lax.all_to_all(
            pnorm, time_axis, split_axis=0, concat_axis=2, tiled=True
        )                                             # [C/P, nf, nt_total]
        corr = jnp.stack([
            xcorr2d(pr[:, lo:hi, :], K) for _, lo, hi, K in designs
        ])                                            # [nT, C/P, nt_total]
        picks = peak_ops.find_peaks_sparse_batched(
            corr, jnp.asarray(threshold, x.dtype), max_peaks=max_peaks
        )
        if outputs == "picks":
            return picks
        return corr, picks

    spec_picks = jax.tree_util.tree_map(
        lambda _: P(None, time_axis), peak_ops.SparsePicks(0, 0, 0, 0, 0)
    )
    out_specs = spec_picks if outputs == "picks" else (P(None, time_axis, None), spec_picks)
    return jax.jit(  # daslint: allow[R2] one-shot factory: campaign jits its step once per run
        shard_map(
            _body, mesh=mesh, in_specs=(P(None, time_axis),),
            out_specs=out_specs, check_vma=False,
        )
    ), names
