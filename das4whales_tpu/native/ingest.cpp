// Native host-side ingest engine for das4whales_tpu.
//
// The reference package delegates all bulk I/O to h5py's C core and does
// raw->strain conditioning in numpy on the Python thread
// (data_handle.py:180-230, data_handle.py:157-177). Here the bulk path is
// first-party native code: the Python layer asks h5py for the *metadata*
// (shape, dtype, contiguous byte offset) once, and this engine does the
// heavy lifting —
//
//   * strided channel reads straight from the file via pread(2), parallel
//     across channels with a thread pool (no GIL, no intermediate Python
//     objects);
//   * fused int->float32 conversion + per-channel demean + scale-to-strain
//     in the same pass over the bytes (one read, one write per element);
//   * an asynchronous prefetch pipeline (submit/wait tickets) so the host
//     reads+conditions file k+1 while the TPU computes on file k. Workers
//     write directly into caller-owned buffers: zero internal copies.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
// Build: see Makefile (g++ -O3 -std=c++17 -shared -fPIC -pthread).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

// dtype codes shared with the ctypes wrapper (io/native.py).
enum DType : int32_t {
  DT_I16 = 0,
  DT_I32 = 1,
  DT_F32 = 2,
  DT_F64 = 3,
};

inline int64_t itemsize(int32_t dt) {
  switch (dt) {
    case DT_I16: return 2;
    case DT_I32: return 4;
    case DT_F32: return 4;
    case DT_F64: return 8;
  }
  return 0;
}

// Read exactly `len` bytes at `off` (pread can return short counts).
bool pread_full(int fd, void* buf, int64_t len, int64_t off) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t got = ::pread(fd, p, static_cast<size_t>(len), static_cast<off_t>(off));
    if (got <= 0) return false;
    p += got;
    off += got;
    len -= got;
  }
  return true;
}

// Convert one channel row of `ns` raw samples to float32, optionally fused
// with demean (mean accumulated in double) and scale-to-strain
// (data_handle.py:157-177 semantics). `raw` is the packed on-disk row.
template <typename T>
void condition_row(const T* raw, float* out, int64_t ns, bool fuse, double scale) {
  if (!fuse) {
    for (int64_t j = 0; j < ns; ++j) out[j] = static_cast<float>(raw[j]);
    return;
  }
  double acc = 0.0;
  for (int64_t j = 0; j < ns; ++j) acc += static_cast<double>(raw[j]);
  const double mean = ns > 0 ? acc / static_cast<double>(ns) : 0.0;
  for (int64_t j = 0; j < ns; ++j)
    out[j] = static_cast<float>((static_cast<double>(raw[j]) - mean) * scale);
}

void condition_dispatch(const void* raw, int32_t dt, float* out, int64_t ns,
                        bool fuse, double scale) {
  switch (dt) {
    case DT_I16: condition_row(static_cast<const int16_t*>(raw), out, ns, fuse, scale); break;
    case DT_I32: condition_row(static_cast<const int32_t*>(raw), out, ns, fuse, scale); break;
    case DT_F32: condition_row(static_cast<const float*>(raw), out, ns, fuse, scale); break;
    case DT_F64: condition_row(static_cast<const double*>(raw), out, ns, fuse, scale); break;
  }
}

struct ReadJob {
  std::string path;
  int64_t offset = 0;      // byte offset of the [nx x ns] dataset in the file
  int32_t dtype = DT_I32;
  int64_t nx = 0, ns = 0;  // on-disk dataset shape
  int64_t start = 0, stop = 0, step = 1;  // channel selection
  int32_t fuse = 1;
  double scale = 1.0;
  float* out = nullptr;    // caller-owned [n_sel x ns] float32 buffer
};

inline int64_t n_selected(const ReadJob& j) {
  if (j.stop <= j.start || j.step <= 0) return 0;
  return (j.stop - j.start + j.step - 1) / j.step;
}

// Synchronous strided read of one job, channel-parallel over `nthreads`.
// Returns 0 on success, negative errno-style codes on failure.
int run_job(const ReadJob& job, int nthreads) {
  const int64_t nsel = n_selected(job);
  if (nsel <= 0 || job.ns <= 0 || job.start < 0 || job.offset < 0)
    return -22;  // EINVAL: a negative start would pread file-header bytes
  const int64_t isz = itemsize(job.dtype);
  if (isz == 0) return -22;
  if (job.start + (nsel - 1) * job.step >= job.nx) return -34;  // ERANGE

  int fd = ::open(job.path.c_str(), O_RDONLY);
  if (fd < 0) return -2;  // ENOENT-ish

  const int nt = std::max(1, std::min<int>(nthreads, static_cast<int>(nsel)));
  std::atomic<int64_t> next{0};
  std::atomic<int> err{0};
  const int64_t row_bytes = job.ns * isz;

  auto worker = [&]() {
    std::vector<char> raw(static_cast<size_t>(row_bytes));
    for (;;) {
      const int64_t i = next.fetch_add(1);
      if (i >= nsel || err.load(std::memory_order_relaxed)) break;
      const int64_t ch = job.start + i * job.step;
      const int64_t off = job.offset + ch * row_bytes;
      if (!pread_full(fd, raw.data(), row_bytes, off)) {
        err.store(-5);  // EIO
        break;
      }
      condition_dispatch(raw.data(), job.dtype, job.out + i * job.ns, job.ns,
                         job.fuse != 0, job.scale);
    }
  };

  if (nt == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(nt);
    for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  ::close(fd);
  return err.load();
}

// ---------------------------------------------------------------------------
// Async prefetch pipeline: bounded worker pool + ticketed completion.
// ---------------------------------------------------------------------------

struct Pipeline {
  explicit Pipeline(int nthreads, int io_threads_per_job)
      : io_threads(std::max(1, io_threads_per_job)) {
    const int nt = std::max(1, nthreads);
    workers.reserve(nt);
    for (int t = 0; t < nt; ++t) workers.emplace_back([this]() { loop(); });
  }

  ~Pipeline() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_jobs.notify_all();
    for (auto& th : workers) th.join();
  }

  int64_t submit(ReadJob job) {
    std::lock_guard<std::mutex> lk(mu);
    const int64_t ticket = next_ticket++;
    queue.push_back({ticket, std::move(job)});
    cv_jobs.notify_one();
    return ticket;
  }

  int wait(int64_t ticket) {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [&]() { return done.count(ticket) != 0; });
    const int rc = done[ticket];
    done.erase(ticket);
    return rc;
  }

 private:
  void loop() {
    for (;;) {
      std::pair<int64_t, ReadJob> item;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_jobs.wait(lk, [&]() { return stopping || !queue.empty(); });
        if (stopping && queue.empty()) return;
        item = std::move(queue.front());
        queue.pop_front();
      }
      const int rc = run_job(item.second, io_threads);
      {
        std::lock_guard<std::mutex> lk(mu);
        done[item.first] = rc;
      }
      cv_done.notify_all();
    }
  }

  const int io_threads;
  std::mutex mu;
  std::condition_variable cv_jobs, cv_done;
  std::deque<std::pair<int64_t, ReadJob>> queue;
  std::unordered_map<int64_t, int> done;
  std::vector<std::thread> workers;
  int64_t next_ticket = 0;
  bool stopping = false;
};

}  // namespace

extern "C" {

int32_t dw_abi_version() { return 1; }

// Synchronous strided read (+ optional fused conditioning) into `out`
// ([n_sel x ns] float32, caller-owned). Returns 0 on success.
int32_t dw_read_strided(const char* path, int64_t offset, int32_t dtype,
                        int64_t nx, int64_t ns, int64_t start, int64_t stop,
                        int64_t step, int32_t fuse, double scale,
                        int32_t nthreads, float* out) {
  ReadJob job;
  job.path = path;
  job.offset = offset;
  job.dtype = dtype;
  job.nx = nx;
  job.ns = ns;
  job.start = start;
  job.stop = stop;
  job.step = step;
  job.fuse = fuse;
  job.scale = scale;
  job.out = out;
  return run_job(job, nthreads);
}

// In-place threaded demean+scale of an [nx x ns] float32 block (the
// raw2strain kernel for hosts that loaded bytes elsewhere).
int32_t dw_raw2strain_f32(float* data, int64_t nx, int64_t ns, double scale,
                          int32_t nthreads) {
  if (nx <= 0 || ns <= 0) return -22;
  const int nt = std::max(1, std::min<int>(nthreads, static_cast<int>(nx)));
  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    for (;;) {
      const int64_t i = next.fetch_add(1);
      if (i >= nx) break;
      float* row = data + i * ns;
      double acc = 0.0;
      for (int64_t j = 0; j < ns; ++j) acc += row[j];
      const double mean = acc / static_cast<double>(ns);
      for (int64_t j = 0; j < ns; ++j)
        row[j] = static_cast<float>((row[j] - mean) * scale);
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return 0;
}

void* dw_pipe_create(int32_t nworkers, int32_t io_threads_per_job) {
  return new Pipeline(nworkers, io_threads_per_job);
}

void dw_pipe_destroy(void* p) { delete static_cast<Pipeline*>(p); }

int64_t dw_pipe_submit(void* p, const char* path, int64_t offset, int32_t dtype,
                       int64_t nx, int64_t ns, int64_t start, int64_t stop,
                       int64_t step, int32_t fuse, double scale, float* out) {
  ReadJob job;
  job.path = path;
  job.offset = offset;
  job.dtype = dtype;
  job.nx = nx;
  job.ns = ns;
  job.start = start;
  job.stop = stop;
  job.step = step;
  job.fuse = fuse;
  job.scale = scale;
  job.out = out;
  return static_cast<Pipeline*>(p)->submit(std::move(job));
}

int32_t dw_pipe_wait(void* p, int64_t ticket) {
  return static_cast<Pipeline*>(p)->wait(ticket);
}

}  // extern "C"
