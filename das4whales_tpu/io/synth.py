"""Synthetic DAS data generation (fixtures, recall tests, benchmarks).

The reference has no offline test asset — integration runs against a live
OOI URL (SURVEY.md §4). This module synthesizes physically plausible DAS
scenes: background noise plus fin-whale-style chirps arriving across the
array at a chosen apparent speed, written through the real OptaSense-schema
writer so the full ingest path is exercised offline.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..config import AcquisitionMetadata
from .hdf5 import optasense_scale_factor, write_optasense


@dataclass
class SyntheticCall:
    """One injected call: source at ``(x0_m, y0_m, z0_m)`` in cable
    coordinates (cable along x at y = z = 0), emitting at ``t0`` [s];
    arrivals propagate to each channel at ``speed`` [m/s] over the 3-D
    slant range (the forward model of ``loc.calc_arrival_times``).
    ``y0_m = z0_m = 0`` degenerates to on-cable moveout."""

    t0: float
    x0_m: float
    fmin: float = 17.8
    fmax: float = 28.8
    duration: float = 0.68
    amplitude: float = 1.0
    speed: float = 1500.0
    y0_m: float = 0.0
    z0_m: float = 0.0


@dataclass
class SyntheticScene:
    fs: float = 200.0
    dx: float = 2.042
    nx: int = 512
    ns: int = 12000
    gauge_length: float = 51.05
    n: float = 1.4681
    noise_rms: float = 0.05
    calls: Sequence[SyntheticCall] = field(default_factory=list)
    seed: int = 0

    @property
    def metadata(self) -> AcquisitionMetadata:
        return AcquisitionMetadata(
            fs=self.fs, dx=self.dx, nx=self.nx, ns=self.ns, n=self.n,
            gauge_length=self.gauge_length,
            scale_factor=optasense_scale_factor(self.n, self.gauge_length),
            interrogator="optasense",
        )


def _hyperbolic_chirp(fmin, fmax, duration, fs):
    t = np.arange(0, duration, 1 / fs)
    f0, f1, t1 = fmax, fmin, duration
    sing = -f1 * t1 / (f0 - f1)
    y = np.cos(2 * np.pi * (-sing * f0) * np.log(np.abs(1 - t / sing)))
    return y * np.hanning(len(y))


def synthesize_scene(scene: SyntheticScene) -> np.ndarray:
    """Render the scene as a float ``[channel x time]`` amplitude block
    (unit scale; convert to raw counts with ``to_raw_counts``)."""
    rng = np.random.default_rng(scene.seed)
    data = scene.noise_rms * rng.standard_normal((scene.nx, scene.ns))
    x = np.arange(scene.nx) * scene.dx
    for call in scene.calls:
        chirp = _hyperbolic_chirp(call.fmin, call.fmax, call.duration, scene.fs) * call.amplitude
        slant = np.sqrt((x - call.x0_m) ** 2 + call.y0_m ** 2 + call.z0_m ** 2)
        delays = call.t0 + slant / call.speed
        onsets = np.round(delays * scene.fs).astype(int)
        L = len(chirp)
        for ch in range(scene.nx):
            s = onsets[ch]
            if 0 <= s and s + L <= scene.ns:
                data[ch, s : s + L] += chirp
    return data


def to_raw_counts(amplitude_block: np.ndarray, metadata: AcquisitionMetadata, counts_scale: float = 1000.0) -> np.ndarray:
    """Quantize a unit-scale amplitude block to int32 raw counts such that
    loading + ``raw2strain`` recovers ``amplitude_block * counts_scale *
    scale_factor`` strain."""
    return np.round(amplitude_block * counts_scale).astype(np.int32)


def write_synthetic_file(filepath: str, scene: SyntheticScene, counts_scale: float = 1000.0) -> str:
    """Render a scene and write it through the OptaSense-schema HDF5 writer."""
    block = synthesize_scene(scene)
    raw = to_raw_counts(block, scene.metadata, counts_scale)
    return write_optasense(
        filepath, raw, fs=scene.fs, dx=scene.dx,
        gauge_length=scene.gauge_length, n=scene.n,
    )


def write_synthetic_tdms(filepath: str, scene: SyntheticScene, counts_scale: float = 1000.0) -> str:
    """Render a scene through the Silixa-schema TDMS writer (int16 channel
    data + the property set ``get_metadata_silixa`` reads, plus a
    ``GPSTimeStamp``) — the offline fixture for the TDMS ingest/stream
    path, which the reference cannot exercise at all (its silixa support
    is metadata-only, data_handle.py:113-154)."""
    from datetime import datetime

    from .tdms import write_tdms

    block = synthesize_scene(scene)
    raw = np.round(block * counts_scale).astype(np.int16)
    props = {
        "SamplingFrequency[Hz]": float(scene.fs),
        "SpatialResolution[m]": float(scene.dx),
        "FibreIndex": float(scene.n),
        "GaugeLength": float(scene.gauge_length),
        "GPSTimeStamp": datetime(2021, 11, 4, 1, 59, 2),
    }
    # zero-padded names keep natural == lexicographic order; the loader's
    # numeric-aware sort must not depend on that (io/interrogators.py:55-75)
    chans = {f"ch{i:05d}": raw[i] for i in range(scene.nx)}
    return write_tdms(filepath, props, "Measurement", chans)
