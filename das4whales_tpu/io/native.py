"""ctypes binding for the native C++ ingest engine.

The reference's bulk-I/O path is h5py's C core called row-by-row from
Python (data_handle.py:213) with numpy conditioning on the Python thread
(data_handle.py:157-177). Here the bulk path is first-party native code
(``native/ingest.cpp``): h5py is consulted once per file for metadata and
the contiguous dataset byte offset, then the C++ engine pread()s the
strided channel selection in parallel and fuses int->float32 + demean +
scale-to-strain into the same pass. An async submit/wait pipeline overlaps
host reads of file k+1 with device compute on file k.

The engine is optional: if the shared library is missing it is compiled
on first use with g++ (baked into the image); if that fails, callers fall
back to the pure-h5py path. Set ``DAS4WHALES_NO_NATIVE=1`` to disable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libdasingest.so")

#: dtype codes shared with ingest.cpp (enum DType).
_DTYPE_CODES = {
    np.dtype(np.int16): 0,
    np.dtype(np.int32): 1,
    np.dtype(np.float32): 2,
    np.dtype(np.float64): 3,
}

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "ingest.cpp")
    if not os.path.exists(src):
        return False
    # build to a unique temp path and publish with an atomic rename, so
    # concurrent first-use builds in separate processes can't load a
    # partially written library
    tmp = f"{_SO_PATH}.build.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-pthread", "-shared",
             "-o", tmp, src],
            check=True, capture_output=True, timeout=300,
        )
        os.replace(tmp, _SO_PATH)
        return os.path.exists(_SO_PATH)
    except Exception:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed or os.environ.get("DAS4WHALES_NO_NATIVE"):
        return None
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build():
            _lib_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _lib_failed = True
            return None
        lib.dw_abi_version.restype = ctypes.c_int32
        lib.dw_read_strided.restype = ctypes.c_int32
        lib.dw_read_strided.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_double, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.dw_raw2strain_f32.restype = ctypes.c_int32
        lib.dw_raw2strain_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_int32,
        ]
        lib.dw_pipe_create.restype = ctypes.c_void_p
        lib.dw_pipe_create.argtypes = [ctypes.c_int32, ctypes.c_int32]
        lib.dw_pipe_destroy.argtypes = [ctypes.c_void_p]
        lib.dw_pipe_submit.restype = ctypes.c_int64
        lib.dw_pipe_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_double,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.dw_pipe_wait.restype = ctypes.c_int32
        lib.dw_pipe_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        if lib.dw_abi_version() != 1:
            _lib_failed = True
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def contiguous_layout(dataset):
    """(byte_offset, numpy_dtype) of an h5py dataset if the native engine
    can read it directly (contiguous, uncompressed, supported dtype);
    None otherwise."""
    try:
        if dataset.chunks is not None or dataset.compression is not None:
            return None
        offset = dataset.id.get_offset()
        if offset is None:
            return None
        dt = np.dtype(dataset.dtype)
        if dt not in _DTYPE_CODES:
            return None
        return int(offset), dt
    except Exception:
        return None


def _float_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def read_strided(
    path: str,
    offset: int,
    dtype: np.dtype,
    nx: int,
    ns: int,
    start: int,
    stop: int,
    step: int,
    *,
    fuse: bool = True,
    scale: float = 1.0,
    nthreads: int | None = None,
    out: np.ndarray | None = None,
) -> np.ndarray:
    """Strided channel read (+ fused demean/scale when ``fuse``) into a
    float32 ``[n_sel x ns]`` array."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native ingest engine unavailable")
    n_sel = len(range(start, stop, step))
    if out is None:
        out = np.empty((n_sel, ns), dtype=np.float32)
    elif out.shape != (n_sel, ns) or out.dtype != np.float32 or not out.flags.c_contiguous:
        # real checks, not asserts: the C++ side writes n_sel*ns floats
        # through this pointer, so a wrong buffer is memory corruption
        raise ValueError(
            f"out must be C-contiguous float32 of shape {(n_sel, ns)}, "
            f"got {out.dtype} {out.shape}"
        )
    if n_sel == 0:
        # valid-but-empty selection: the C engine rejects it with -22, but a
        # user slicing an empty range deserves the h5py-style empty block
        return out
    rc = lib.dw_read_strided(
        path.encode(), offset, _DTYPE_CODES[np.dtype(dtype)], nx, ns,
        start, stop, step, int(fuse), float(scale),
        nthreads or os.cpu_count() or 4, _float_ptr(out),
    )
    if rc != 0:
        raise IOError(f"native read failed (code {rc}) for {path}")
    return out


def read_strided_raw(
    path: str,
    offset: int,
    dtype: np.dtype,
    nx: int,
    ns: int,
    start: int,
    stop: int,
    step: int,
) -> np.ndarray:
    """Strided channel read of the STORED dtype, no conditioning — the
    narrow wire format (``io.stream`` ``wire="raw"``): raw interrogator
    counts cross host→device untouched (int16 stays 2 bytes/sample) and
    demean/scale runs on device (``ops.conditioning``). Consumes the same
    ``contiguous_layout`` probe as the fused C++ path but needs only a
    numpy memmap, so it works even where the engine failed to build."""
    mm = np.memmap(path, dtype=np.dtype(dtype), mode="r", offset=offset,
                   shape=(nx, ns))
    try:
        return np.ascontiguousarray(mm[start:stop:step])
    finally:
        del mm


def raw2strain_inplace(block: np.ndarray, scale: float, nthreads: int | None = None) -> np.ndarray:
    """Threaded in-place demean+scale of a float32 [nx x ns] block."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native ingest engine unavailable")
    if block.dtype != np.float32 or block.ndim != 2 or not block.flags.c_contiguous:
        raise ValueError("block must be a C-contiguous 2-D float32 array")
    rc = lib.dw_raw2strain_f32(_float_ptr(block), block.shape[0], block.shape[1],
                               float(scale), nthreads or os.cpu_count() or 4)
    if rc != 0:
        raise IOError(f"native raw2strain failed (code {rc})")
    return block


class Prefetcher:
    """Async submit/wait front-end over the native pipeline.

    Workers write directly into the numpy buffer allocated at submit time
    (zero internal copies); ``wait`` blocks until that buffer is complete.
    Typical double-buffered use::

        pf = Prefetcher()
        t0 = pf.submit(spec0); t1 = pf.submit(spec1)
        block0 = pf.wait(t0)          # compute on block0 while spec1 loads
    """

    def __init__(self, nworkers: int = 2, io_threads_per_job: int | None = None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native ingest engine unavailable")
        self._lib = lib
        self._handle = lib.dw_pipe_create(
            nworkers, io_threads_per_job or max(1, (os.cpu_count() or 4) // nworkers)
        )
        self._pending: dict[int, np.ndarray] = {}
        self._lock = threading.Lock()

    def submit(self, path, offset, dtype, nx, ns, start, stop, step,
               *, fuse=True, scale=1.0) -> int:
        if self._handle is None:
            raise RuntimeError("Prefetcher is closed")
        n_sel = len(range(start, stop, step))
        out = np.empty((n_sel, ns), dtype=np.float32)
        ticket = self._lib.dw_pipe_submit(
            self._handle, path.encode(), offset, _DTYPE_CODES[np.dtype(dtype)],
            nx, ns, start, stop, step, int(fuse), float(scale), _float_ptr(out),
        )
        with self._lock:
            self._pending[int(ticket)] = out
        return int(ticket)

    def wait(self, ticket: int) -> np.ndarray:
        if self._handle is None:
            raise RuntimeError("Prefetcher is closed")
        with self._lock:
            if ticket not in self._pending:
                # an unknown/already-consumed ticket would block on the
                # completion cv forever; claiming the buffer inside the
                # lock also makes concurrent double-waits race-free
                raise KeyError(f"unknown or already-waited ticket {ticket}")
            out = self._pending.pop(ticket)
        rc = self._lib.dw_pipe_wait(self._handle, ticket)
        if rc != 0:
            raise IOError(f"native prefetch failed (code {rc})")
        return out

    def close(self):
        if self._handle is not None:
            self._lib.dw_pipe_destroy(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
