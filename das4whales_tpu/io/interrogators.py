"""Interrogator dispatch: acquisition metadata per vendor.

Parity target: reference ``data_handle.get_acquisition_parameters``
(data_handle.py:26-68), which dispatches over
``['optasense', 'silixa', 'mars', 'alcatel']`` but only defines the first
two readers — calling the others raises ``NameError`` in the reference
(data_handle.py:59-63, a documented quirk in SURVEY.md §7). Here all four
names resolve: 'mars' and 'alcatel' are explicit informative stubs until a
public schema sample exists, and a generic schema-mapping reader covers
unknown HDF5 layouts.
"""

from __future__ import annotations

import os
import re

import numpy as np

from ..config import AcquisitionMetadata
from .hdf5 import get_metadata_optasense
from .tdms import TdmsFile

INTERROGATORS = ("optasense", "silixa", "mars", "alcatel")


def silixa_scale_factor(fs: float, gauge_length: float) -> float:
    """Raw counts -> strain for Silixa iDAS (data_handle.py:148)."""
    return (116 * fs * 1e-9) / (gauge_length * 2**13)


def get_metadata_silixa(filepath: str) -> AcquisitionMetadata:
    """Read acquisition parameters from a Silixa TDMS file
    (reference data_handle.py:113-154), via the native TDMS parser."""
    if not os.path.exists(filepath):
        raise FileNotFoundError(f"File {filepath} not found")
    f = TdmsFile.read(filepath)
    props = f.properties
    channels = f["Measurement"]
    data_lens = [len(v) for v in channels.values()]
    fs = float(props["SamplingFrequency[Hz]"])
    gl = float(props["GaugeLength"])
    return AcquisitionMetadata(
        fs=fs,
        dx=float(props["SpatialResolution[m]"]),
        nx=len(channels),
        ns=int(data_lens[0]) if data_lens else 0,
        n=float(props["FibreIndex"]),
        gauge_length=gl,
        scale_factor=silixa_scale_factor(fs, gl),
        interrogator="silixa",
    )


def _natural_key(name: str):
    """Sort key ordering embedded integers numerically ("ch2" < "ch10",
    regardless of zero padding), with lexicographic tie-breaking on the
    non-digit runs. This is the channel order a fiber layout means by its
    names; plain string sort would interleave ch1/ch10/ch2."""
    # tag each run so int/str never compare directly (TypeError otherwise
    # for names with different digit/text structure)
    return tuple(
        (0, int(part), "") if part.isdigit() else (1, 0, part)
        for part in re.split(r"(\d+)", name)
        if part != ""
    )


def load_silixa_data(filepath: str) -> np.ndarray:
    """Load the full ``[channel x time]`` raw block from a Silixa TDMS file
    (the reference materializes this inside get_metadata_silixa,
    data_handle.py:140), channels in natural (numeric-aware) name order."""
    f = TdmsFile.read(filepath)
    channels = f["Measurement"]
    return np.stack([channels[c] for c in sorted(channels, key=_natural_key)])


def get_metadata_mars(filepath: str) -> AcquisitionMetadata:
    """MARS observatory DAS metadata — declared by the reference but never
    implemented (data_handle.py:59-60 would raise NameError). Stub until a
    public schema sample exists; use ``get_metadata_generic`` with an
    explicit schema mapping in the meantime."""
    raise NotImplementedError(
        "The 'mars' interrogator schema is not published; pass interrogator="
        "'optasense' if the file follows the OptaSense layout, or use "
        "get_metadata_generic(filepath, schema=...)."
    )


def get_metadata_alcatel(filepath: str) -> AcquisitionMetadata:
    """ASN/Alcatel OptoDAS metadata — declared by the reference but never
    implemented (data_handle.py:62-63 would raise NameError)."""
    raise NotImplementedError(
        "The 'alcatel' (ASN OptoDAS) schema is not published; use "
        "get_metadata_generic(filepath, schema=...) with the file's HDF5 paths."
    )


def get_metadata_generic(filepath: str, schema: dict) -> AcquisitionMetadata:
    """Read metadata from an arbitrary HDF5 layout via a schema mapping.

    ``schema`` maps metadata fields to ``(hdf5_object_path, attr_name)``
    pairs (attr) or plain dataset paths (value), e.g.::

        schema = {
            "fs": ("Acquisition/Raw[0]", "OutputDataRate"),
            "dx": ("Acquisition", "SpatialSamplingInterval"),
            ...
            "scale_factor": 1e-9,        # literals allowed
        }
    """
    import h5py

    if not os.path.exists(filepath):
        raise FileNotFoundError(f"File {filepath} not found")
    out = {}
    with h5py.File(filepath, "r") as fp:
        for key, spec in schema.items():
            if isinstance(spec, tuple):
                obj, attr = spec
                out[key] = np.asarray(fp[obj].attrs[attr]).item()
            elif isinstance(spec, str):
                out[key] = np.asarray(fp[spec]).item()
            else:
                out[key] = spec
    return AcquisitionMetadata(
        fs=float(out["fs"]), dx=float(out["dx"]), nx=int(out["nx"]), ns=int(out["ns"]),
        n=float(out.get("n", 1.4681)), gauge_length=float(out.get("GL", 51.0)),
        scale_factor=float(out.get("scale_factor", 1.0)), interrogator="generic",
    )


def get_acquisition_parameters(filepath: str, interrogator: str = "optasense") -> AcquisitionMetadata:
    """Dispatch metadata reading by interrogator name
    (reference data_handle.py:26-68)."""
    if interrogator not in INTERROGATORS:
        raise ValueError("Interrogator name incorrect")
    reader = {
        "optasense": get_metadata_optasense,
        "silixa": get_metadata_silixa,
        "mars": get_metadata_mars,
        "alcatel": get_metadata_alcatel,
    }[interrogator]
    return reader(filepath)
