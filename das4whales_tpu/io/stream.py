"""Multi-file streaming: double-buffered host→HBM strain pipeline.

The reference processes one 60 s file at a time, serially, reloading and
reconditioning on the Python thread (scripts/main_mfdetect.py:8-42 per
file; the dask path, dask_wrap.py:21-93, keeps the file handle open and
defers the read). Here ingest of file k+1 overlaps device compute on file
k: the native C++ engine (io/native.py) or an *ordered* thread pool reads
and conditions ahead, and blocks are handed to JAX as device arrays —
optionally placed with a NamedSharding so a [file x channel x time] batch
lands pre-sharded for the multi-chip step (parallel/pipeline.py).

Two transfer optimizations ride on top of the read pipeline:

* ``wire="raw"`` — the NARROW wire format: the stored dtype (int16 TDMS
  counts, int32/float32 OptaSense) crosses host→device untouched and the
  demean+scale conditioning runs on device (``ops.conditioning``), fused
  into the consuming detection program. H2D bytes drop 2× for int16
  sources; picks are bit-identical (same affine map, device-executed).
* the **overlap executor** (``overlap_transfers``, default on for
  device-bound streams) — file k+1's ``jax.device_put`` (pre-sharded via
  ``NamedSharding`` when given) is dispatched the moment its read
  completes, while file k's program runs, instead of blocking on the
  read thread's handoff at yield time. Device memory holds up to
  ``prefetch + 1`` blocks in flight (vs 2 without overlap).

Unlike the reference's ThreadPoolExecutor fan-out, which loses result
ordering via ``as_completed`` (detect.py:244-245), both paths here yield
files strictly in submission order. Metadata probing is also pipelined —
only ``prefetch`` files are probed ahead, so first-block latency is O(1)
in campaign length.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass
from typing import Iterator, Sequence

import h5py
import jax
import jax.numpy as jnp
import numpy as np

from ..config import AcquisitionMetadata, ChannelSelection, as_metadata
from . import native
from .hdf5 import StrainBlock, assemble_block
from .interrogators import get_acquisition_parameters

WIRE_FORMATS = ("conditioned", "raw")


@dataclass
class _FileSpec:
    path: str
    meta: AcquisitionMetadata
    t0_us: int
    layout: tuple | None  # (offset, disk_dtype, nx, ns) when natively readable


def _is_tdms(path: str) -> bool:
    return path.lower().endswith(".tdms")


def _probe(path: str, interrogator: str, metadata) -> _FileSpec:
    if _is_tdms(path) and metadata is None and interrogator == "optasense":
        interrogator = "silixa"  # extension beats the h5-centric default
    meta = as_metadata(metadata) if metadata is not None else get_acquisition_parameters(
        path, interrogator=interrogator
    )
    if _is_tdms(path) or meta.interrogator == "silixa":
        # single-segment contiguous TDMS reads through the SAME native
        # engine as HDF5 (io/tdms.py contiguous_layout probes metadata
        # only and also yields the GPS t0); irregular files keep the
        # pure-host reader, which extracts t0 during its own parse
        if native.available():
            from .tdms import contiguous_layout as _tdms_layout

            lay = _tdms_layout(path)
            if lay is not None:
                off, dt, nx, ns, t0_us = lay
                return _FileSpec(path=path, meta=meta, t0_us=t0_us,
                                 layout=(off, dt, nx, ns))
        return _FileSpec(path=path, meta=meta, t0_us=0, layout=None)
    layout = None
    with h5py.File(path, "r") as fp:
        raw = fp["Acquisition/Raw[0]/RawData"]
        t0_us = int(fp["Acquisition/Raw[0]/RawDataTime"][0])
        if native.available():
            lay = native.contiguous_layout(raw)
            if lay is not None:
                layout = (lay[0], lay[1], raw.shape[0], raw.shape[1])
    return _FileSpec(path=path, meta=meta, t0_us=t0_us, layout=layout)


def _read_h5py_host(spec: _FileSpec, sel: ChannelSelection) -> np.ndarray:
    with h5py.File(spec.path, "r") as fp:
        block = fp["Acquisition/Raw[0]/RawData"][sel.start : sel.stop : sel.step, :]
    x = block.astype(np.float32)
    x -= x.mean(axis=1, keepdims=True)
    x *= spec.meta.scale_factor
    return x


def _read_tdms_host(spec: _FileSpec, sel: ChannelSelection,
                    raw: bool = False) -> np.ndarray:
    """Read a Silixa TDMS file (conditioning on the host unless ``raw``),
    updating ``spec.t0_us`` from its ``GPSTimeStamp`` property when present
    (the reference never loads TDMS bulk data at all — its silixa path is
    metadata-only, data_handle.py:113-154)."""
    from .tdms import read_measurement_block

    x, t0_us = read_measurement_block(
        spec.path, sel.start, sel.stop, sel.step, raw=raw
    )
    if not raw:
        x -= x.mean(axis=1, keepdims=True)
        x *= spec.meta.scale_factor
    if t0_us is not None:
        spec.t0_us = t0_us
    return x


def _read_host(spec: _FileSpec, sel: ChannelSelection) -> np.ndarray:
    if _is_tdms(spec.path) or spec.meta.interrogator == "silixa":
        return _read_tdms_host(spec, sel)
    return _read_h5py_host(spec, sel)


def _read_host_raw(spec: _FileSpec, sel: ChannelSelection,
                   engine: str = "auto") -> np.ndarray:
    """Narrow-wire host read: the stored dtype, untouched. Natively-probed
    layouts go through a numpy memmap (no parse, no copy beyond the
    strided gather); irregular files fall back to their format reader with
    conditioning skipped. ``engine`` keeps the conditioned path's
    contract: ``"h5py"`` forces the format readers even when a layout was
    probed, ``"native"`` raises on files without one."""
    if engine != "h5py" and spec.layout is not None:
        offset, dt, nx, ns = spec.layout
        return native.read_strided_raw(
            spec.path, offset, dt, nx, ns, sel.start, min(sel.stop, nx), sel.step
        )
    if engine == "native":
        raise ValueError(
            f"{spec.path} is not natively readable but the stream started "
            "on the native engine; pass engine='h5py' for mixed file sets"
        )
    if _is_tdms(spec.path) or spec.meta.interrogator == "silixa":
        return _read_tdms_host(spec, sel, raw=True)
    with h5py.File(spec.path, "r") as fp:
        return fp["Acquisition/Raw[0]/RawData"][sel.start : sel.stop : sel.step, :]


def stream_strain_blocks(
    files: Sequence[str],
    selected_channels,
    metadata=None,
    *,
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "auto",
    device=None,
    sharding=None,
    as_numpy: bool = False,
    wire: str = "conditioned",
    overlap_transfers: bool | None = None,
    read_deadline_s: float | None = None,
    fault_plan=None,
) -> Iterator[StrainBlock]:
    """Yield :class:`StrainBlock`\\ s for ``files`` in order, reading ahead
    ``prefetch`` files while the caller computes.

    ``metadata`` may be None (probed per file), one metadata for all files,
    or a sequence aligned with ``files``. ``sharding``/``device`` place each
    block on arrival (e.g. a per-file NamedSharding over the channel axis).
    ``as_numpy`` keeps traces on the host (for callers that batch several
    files before one placed transfer, e.g. :func:`stream_file_batches`).

    ``wire="raw"`` streams the STORED dtype untouched (narrow wire; see
    module docstring) — the yielded block's ``.trace`` is raw counts and
    ``.wire == "raw"``; condition on device (``ops.conditioning`` or a
    ``wire="raw"`` detector/step).

    ``overlap_transfers`` (default: on whenever blocks are device-bound)
    dispatches file k+1's ``jax.device_put`` as soon as its read completes,
    overlapping H2D transfer with compute on file k. Costs up to
    ``prefetch + 1`` blocks of device memory in flight.

    ``engine="auto"`` picks the native path iff the *first* file is natively
    readable; a later file that breaks that assumption raises — pass
    ``engine="h5py"`` for heterogeneous campaigns.

    ``read_deadline_s`` bounds how long the consumer waits on any ONE
    file's prefetch worker: a hung reader (dead NFS mount, wedged
    interrogator export) raises ``faults.DeadlineExceeded`` at that
    file's own yield position instead of stalling the stream forever.
    The hung worker thread cannot be killed — it is abandoned (its pool
    is shut down without joining) and keeps its memory until the read
    returns; the campaign runner records ``status="timeout"`` and
    restarts a fresh stream past the culprit. Threaded-reader paths only
    (the default ``engine="h5py"`` campaign configuration; the native
    C++ prefetcher has no bounded wait).

    ``fault_plan`` (``faults.FaultPlan``) injects the chaos harness's
    scheduled faults at the reader boundary (``on_read`` /
    ``poison_read`` on the prefetch worker) and, for device-bound
    streams, at the transfer boundary (``on_transfer`` before
    ``device_put``) — None (the default) costs nothing.
    """
    if prefetch < 1:
        raise ValueError("prefetch must be >= 1")
    if engine not in ("auto", "native", "h5py"):
        raise ValueError(f"unknown engine {engine!r}; expected 'auto', 'native', or 'h5py'")
    if wire not in WIRE_FORMATS:
        raise ValueError(f"unknown wire {wire!r}; expected one of {WIRE_FORMATS}")
    if as_numpy and (sharding is not None or device is not None):
        raise ValueError("as_numpy=True returns host arrays; drop sharding/device")
    if as_numpy and overlap_transfers:
        raise ValueError("as_numpy=True never transfers; drop overlap_transfers")
    overlap = (not as_numpy) if overlap_transfers is None else bool(overlap_transfers)
    files = list(files)
    if not files:
        return
    sel = ChannelSelection.from_list(selected_channels)
    metas = (
        [None] * len(files)
        if metadata is None
        else ([metadata] * len(files) if not isinstance(metadata, (list, tuple)) else list(metadata))
    )
    if len(metas) != len(files):
        raise ValueError(f"got {len(metas)} metadata entries for {len(files)} files")

    def place(host: np.ndarray):
        if sharding is not None:
            return jax.device_put(host, sharding)
        if device is not None:
            return jax.device_put(host, device)
        return jnp.asarray(host)

    def finish(spec: _FileSpec, arr) -> StrainBlock:
        return assemble_block(arr, spec.meta, sel, spec.t0_us, wire=wire)

    first = _probe(files[0], interrogator, metas[0])
    use_native = engine in ("auto", "native") and first.layout is not None
    if engine == "native" and not use_native:
        raise ValueError(f"engine='native' but {files[0]} is not natively readable")
    if use_native and (read_deadline_s is not None or fault_plan is not None):
        if engine == "native":
            raise ValueError(
                "read_deadline_s / fault_plan need the threaded reader; the "
                "native C++ prefetcher has no bounded wait or injection "
                "hooks — pass engine='h5py'"
            )
        use_native = False  # engine='auto': prefer the resilience contract

    # probe lazily: spec k is probed right before (native) or inside (h5py)
    # its read task, keeping only `prefetch` probes + reads ahead of the
    # consumer. Errors are DEFERRED to the failing file's own position in
    # the yield order — a bad file k must raise on the k-th next(), not
    # while the consumer is still working on file k-prefetch (the campaign
    # runner's per-file fault isolation relies on this attribution).
    specs: dict[int, _FileSpec] = {0: first}

    def spec_for(i: int) -> _FileSpec:
        if i not in specs:
            specs[i] = _probe(files[i], interrogator, metas[i])
        return specs[i]

    if use_native and wire == "conditioned":
        # fused C++ path: read + demean + scale in one native pass; the
        # transfer of file k+1 is handed off to a single ordered transfer
        # thread (overlap) or dispatched at yield time (no overlap)
        yield from _native_stream(
            files, sel, specs, spec_for, prefetch, place, finish,
            as_numpy, overlap,
        )
        return

    if wire == "raw":
        reader = functools.partial(_read_host_raw, engine=engine)
    else:
        reader = _read_host

    def probe_and_read(i):
        from ..telemetry import trace as _trace

        name = os.path.basename(files[i])
        with _trace.span("read", file=name):
            spec = (spec_for(i) if i == 0
                    else _probe(files[i], interrogator, metas[i]))
            if fault_plan is not None:
                fault_plan.on_read(files[i])    # chaos harness: raise/hang
            host = reader(spec, sel)
            if fault_plan is not None:
                host = fault_plan.poison_read(files[i], host)
        if overlap and not as_numpy:
            # dispatch the H2D transfer from the read worker, the moment
            # the read completes — jax.device_put is async, so the worker
            # is not pinned and the copy overlaps compute on earlier files
            if fault_plan is not None:
                fault_plan.on_transfer(files[i])
            with _trace.span("h2d", file=name):
                return spec, place(host)
        return spec, host

    # not a `with` block: when a deadline is configured the pool must
    # NEVER be joined at teardown — a hung worker may never return, and
    # __exit__'s shutdown(wait=True) would turn one hung file into a
    # hung campaign (whether the generator exits via the deadline
    # itself, another file's error, or consumer abandonment while a
    # hung read is in flight). Deadline-less streams keep the legacy
    # draining teardown.
    ex = ThreadPoolExecutor(max_workers=prefetch,
                            thread_name_prefix="das-read")
    try:
        futs = {
            i: ex.submit(probe_and_read, i)
            for i in range(min(prefetch, len(files)))
        }
        for i in range(len(files)):
            fut = futs.pop(i)
            nxt = i + prefetch
            if nxt < len(files):
                futs[nxt] = ex.submit(probe_and_read, nxt)
            try:
                spec, payload = fut.result(read_deadline_s)  # submission order
            except FutureTimeout as exc:
                # on Python >= 3.11 concurrent.futures.TimeoutError IS
                # builtin TimeoutError, so a TimeoutError raised by the
                # READER (e.g. OSError ETIMEDOUT) lands here too — that
                # one is the file's own (transient-class) failure, not a
                # deadline violation
                if fut.done() and fut.exception() is exc:
                    raise
                from .. import faults

                raise faults.DeadlineExceeded(files[i], read_deadline_s)
            if as_numpy or overlap:
                yield finish(spec, payload)
            else:
                yield finish(spec, place(payload))
    finally:
        wait = read_deadline_s is None
        ex.shutdown(wait=wait, cancel_futures=not wait)


def _native_stream(files, sel, specs, spec_for, prefetch, place, finish,
                   as_numpy, overlap):
    """The native-engine stream body: C++ prefetcher reads ahead; the
    wait-and-transfer handoff runs on a dedicated ordered thread when
    ``overlap`` so file k+1's device_put dispatches during compute on k."""
    n = len(files)

    with native.Prefetcher(nworkers=prefetch) as pf:
        def submit(i):
            try:
                spec = spec_for(i)
                if spec.layout is None:
                    raise ValueError(
                        f"{spec.path} is not natively readable but the stream "
                        "started on the native engine; pass engine='h5py' for "
                        "mixed file sets"
                    )
                offset, dt, nx, ns = spec.layout
                return pf.submit(spec.path, offset, dt, nx, ns,
                                 sel.start, min(sel.stop, nx), sel.step,
                                 fuse=True, scale=spec.meta.scale_factor)
            except Exception as exc:  # noqa: BLE001 — re-raised in order
                return ("__probe_error__", exc)

        tickets = {i: submit(i) for i in range(min(prefetch, n))}
        next_read = min(prefetch, n)

        def hand(j):
            """Wait file j's native read, then dispatch its transfer —
            probe/read errors re-raise here, surfacing (via the ordered
            future pop below) at file j's own yield position."""
            ticket = tickets.pop(j)
            if isinstance(ticket, tuple) and ticket[0] == "__probe_error__":
                raise ticket[1]
            host = pf.wait(ticket)
            return finish(specs.pop(j), host if as_numpy else place(host))

        if not overlap or as_numpy:
            for i in range(n):
                if next_read < n and next_read <= i + prefetch:
                    tickets[next_read] = submit(next_read)
                    next_read += 1
                yield hand(i)
            return

        with ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="das-h2d") as tx:
            handed = 0
            futs: deque = deque()
            for i in range(n):
                while next_read < min(n, i + prefetch + 1):
                    tickets[next_read] = submit(next_read)
                    next_read += 1
                # keep this file + one successor on the transfer thread
                while handed <= min(n - 1, i + 1):
                    futs.append(tx.submit(hand, handed))
                    handed += 1
                yield futs.popleft().result()


def stream_file_batches(
    files: Sequence[str],
    selected_channels,
    metadata=None,
    *,
    batch: int,
    mesh=None,
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "auto",
    tail: str = "pad",
    wire: str = "conditioned",
) -> Iterator[tuple]:
    """Stack consecutive files into ``[file x channel x time]`` batches for
    the sharded multi-chip detection step (parallel/pipeline.py).

    Yields ``(batch_array, blocks)``; when ``mesh`` is given the stack is
    placed with the pipeline's input sharding (file x channel).
    ``wire="raw"`` stacks and transfers the stored dtype (narrow wire) —
    pair with a ``wire="raw"`` sharded step, which conditions on the mesh.

    ``tail`` controls trailing files that do not fill a batch:
    ``"pad"`` (default) zero-pads the final stack to the batch size and
    yields it with only the real blocks in ``blocks`` (check
    ``len(blocks)`` — padded file slots produce no correlogram energy, so
    detection outputs there are empty); ``"drop"`` discards them with a
    warning; ``"error"`` raises up front (at call time, not first
    ``next()`` — validation happens before the generator is created).
    """
    if batch < 1:
        raise ValueError("batch must be >= 1")
    if tail not in ("pad", "drop", "error"):
        raise ValueError(f"tail must be 'pad', 'drop' or 'error', got {tail!r}")
    n_full = (len(files) // batch) * batch
    if n_full != len(files):
        if tail == "error":
            raise ValueError(
                f"{len(files) - n_full} trailing file(s) do not fill a batch "
                f"of {batch} (tail='error')"
            )
        if tail == "drop":
            import warnings

            warnings.warn(
                f"dropping {len(files) - n_full} trailing file(s) not filling a batch of {batch}"
            )
            files = files[:n_full]
    return _file_batches_gen(
        list(files), selected_channels, metadata, batch=batch, mesh=mesh,
        interrogator=interrogator, prefetch=prefetch, engine=engine, wire=wire,
    )


def _file_batches_gen(
    files, selected_channels, metadata, *, batch, mesh, interrogator,
    prefetch, engine, wire,
) -> Iterator[tuple]:
    from ..parallel.pipeline import input_sharding

    sharding = input_sharding(mesh) if mesh is not None else None

    def place(stack):
        if sharding is not None:
            return jax.device_put(stack, sharding)
        return jnp.asarray(stack)

    # traces stay host-side numpy until the whole batch is assembled, so
    # the [file x channel x time] stack crosses to HBM exactly once and
    # lands pre-sharded — never materialized whole on a single chip
    pending: list[StrainBlock] = []
    for blk in stream_strain_blocks(
        files, selected_channels, metadata,
        interrogator=interrogator, prefetch=prefetch, engine=engine,
        as_numpy=True, wire=wire,
    ):
        pending.append(blk)
        if len(pending) == batch:
            yield place(np.stack([b.trace for b in pending])), tuple(pending)
            pending = []
    if pending:  # tail == "pad"
        stack = np.stack([b.trace for b in pending])
        fill = np.zeros((batch - len(pending),) + stack.shape[1:], stack.dtype)
        yield place(np.concatenate([stack, fill])), tuple(pending)


# ---------------------------------------------------------------------------
# Batched-slab assembly (the single-chip batched campaign's ingest)
# ---------------------------------------------------------------------------


@dataclass
class BatchSlab:
    """One assembled ``[B, channel, time]`` batch for the batched
    detection route (``parallel.batch``).

    ``stack`` is the padded batch (device array, or host numpy with
    ``as_numpy=True``); trailing file slots past ``n_valid`` are zeros
    (the program shape is fixed at ``B`` — padded slots produce no
    recorded output). ``blocks``/``paths``/``n_real`` are aligned with
    the ``n_valid`` REAL files in stream order; ``index0`` is the first
    file's index in the file list handed to the assembler (failure
    attribution and resume bookkeeping). ``bucket_ns`` is the padded time
    length (``config.BatchBucketConfig``); each file's real samples are
    ``stack[j, :, :n_real[j]]``.
    """

    stack: object
    blocks: tuple
    paths: tuple
    index0: int
    bucket_ns: int
    n_real: tuple

    @property
    def n_valid(self) -> int:
        return len(self.blocks)


def assemble_slab(blocks, paths, index0: int, batch: int,
                  bucket_ns: int) -> BatchSlab:
    """Stack same-bucket host blocks into one :class:`BatchSlab` — THE
    bucket/padding rule of the batched ingest, in one place.

    Every block is zero-padded on the time axis to ``bucket_ns`` and the
    stack allocates the FULL ``batch`` file slots (trailing slots zero),
    so one compiled program per (bucket, batch) shape serves full and
    partial slabs alike. Shared by the campaign assembler
    (:func:`stream_batched_slabs`), the ladder's re-bucketing
    (:func:`subdivide_slab` builds its sub-stacks the same way) and the
    service's continuous slicer (``service.ingest``) — a slab formed
    from a live ring buffer is bit-identical to one formed from the
    same files by the batch campaign.
    """
    blocks = tuple(blocks)
    if not 1 <= len(blocks) <= batch:
        raise ValueError(f"got {len(blocks)} blocks for a batch of {batch}")
    tr0 = np.asarray(blocks[0].trace)
    stack = np.zeros((batch, tr0.shape[0], int(bucket_ns)), tr0.dtype)
    n_reals = []
    for j, b in enumerate(blocks):
        tr = np.asarray(b.trace)
        stack[j, :, : tr.shape[1]] = tr
        n_reals.append(tr.shape[1])
    return BatchSlab(
        stack=stack, blocks=blocks, paths=tuple(paths), index0=int(index0),
        bucket_ns=int(bucket_ns), n_real=tuple(n_reals),
    )


def subdivide_slab(slab: BatchSlab, batch: int) -> list:
    """Split one :class:`BatchSlab` into smaller slabs of at most
    ``batch`` files each, re-assembled from the HOST blocks (the device
    ``stack`` may already be donated or unfit — never touched here).

    The elastic downshift ladder's re-bucketing primitive
    (``workflows.campaign.run_campaign_batched``): after a
    resource-class failure at batch B, the same files retry at B/2, …, 1
    through stacks rebuilt from the assembler's host blocks. File order,
    paths, ``n_real`` and ``bucket_ns`` are preserved, so per-file picks
    are bit-identical at every rung.
    """
    if batch < 1:
        raise ValueError("batch must be >= 1")
    # every sub-slab allocates the FULL rung batch (trailing file slots
    # zero, like the assembler's partial slabs): one program per
    # (bucket, batch) shape, not one per remainder size — assemble_slab
    # owns that rule
    return [
        assemble_slab(slab.blocks[s : s + batch], slab.paths[s : s + batch],
                      slab.index0 + s, batch, slab.bucket_ns)
        for s in range(0, slab.n_valid, batch)
    ]


class SlabReadError(RuntimeError):
    """A file failed to probe/read/bucket during slab assembly.

    ``index`` is the culprit's position in the file list handed to the
    assembler and ``path`` its path — raised AFTER any partial slab of
    already-read earlier files has been yielded, so the campaign records
    exactly one failure and resumes at ``index + 1``.
    """

    def __init__(self, path: str, index: int, cause: Exception):
        super().__init__(f"{path}: {type(cause).__name__}: {cause}")
        self.path = path
        self.index = index
        self.cause = cause
        self.__cause__ = cause


def _assemble_host_slabs(files, selected_channels, metadata, *, batch,
                         bucket_cfg, interrogator, prefetch, engine, wire,
                         read_deadline_s=None, fault_plan=None):
    """Host half of the assembler: pull ordered blocks off the read
    pipeline, group CONSECUTIVE same-bucket files, pad and stack. Slabs
    come out strictly in file order (a bucket change flushes the current
    partial slab), so per-file pick order is stable across mixed-bucket
    campaigns."""
    pending: list = []
    idx0 = 0
    cur_key = None  # (channels, bucket_ns, wire dtype)

    def flush():
        nonlocal pending
        _C, b_ns, _dt = cur_key
        slab = assemble_slab(pending, files[idx0 : idx0 + len(pending)],
                             idx0, batch, b_ns)
        pending = []
        return slab

    stream = stream_strain_blocks(
        files, selected_channels, metadata, interrogator=interrogator,
        prefetch=prefetch, engine=engine, as_numpy=True, wire=wire,
        read_deadline_s=read_deadline_s, fault_plan=fault_plan,
    )
    for i in range(len(files)):
        try:
            blk = next(stream)
            b_ns = bucket_cfg.bucket_ns(np.asarray(blk.trace).shape[1])
        except StopIteration:  # defensive: stream ended early
            break
        except Exception as exc:  # noqa: BLE001 — per-file isolation
            # surface the partial slab of healthy earlier files FIRST,
            # then the attributed error (campaign resumes past file i)
            if pending:
                yield flush()
            raise SlabReadError(files[i], i, exc)
        tr = np.asarray(blk.trace)
        key = (tr.shape[0], b_ns, tr.dtype)
        if pending and key != cur_key:
            yield flush()
            idx0 = i
        elif not pending:
            idx0 = i
        cur_key = key
        pending.append(blk)
        if len(pending) == batch:
            yield flush()
            idx0 = i + 1
    if pending:
        yield flush()


def stream_batched_slabs(
    files: Sequence[str],
    selected_channels,
    metadata=None,
    *,
    batch: int,
    bucket="pow2",
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "h5py",
    wire: str = "conditioned",
    device=None,
    sharding=None,
    as_numpy: bool = False,
    in_flight: int = 2,
    read_deadline_s: float | None = None,
    fault_plan=None,
) -> Iterator[BatchSlab]:
    """Coalesce the ordered read pipeline into ``[batch, channel, time]``
    slabs for the batched one-program detection route
    (``parallel.batch``; driven by
    ``workflows.campaign.run_campaign_batched``).

    Consecutive files sharing a shape bucket (``bucket``:
    ``config.BatchBucketConfig`` / mode string / fixed-length sequence)
    are zero-padded to the bucket length and stacked; a bucket change or
    the end of the list flushes a PARTIAL slab (``n_valid < batch``,
    trailing file slots zero). The whole campaign therefore compiles
    O(#buckets) programs.

    The overlap executor at slab granularity: slab k+1's ``device_put``
    (via ``sharding``/``device``; plain ``jnp.asarray`` otherwise)
    dispatches on a transfer thread while the caller computes on slab k,
    with at most ``in_flight`` slabs in the transfer pipeline (bounded
    device memory: ``in_flight + 1`` slabs resident worst-case — plus,
    when the consumer runs the depth-D pipelined dispatch
    (``parallel.dispatch``), its up-to-``depth`` dispatched-but-unfetched
    slabs: the batched campaign raises ``in_flight`` to at least the
    dispatch depth so the transfer pipeline never starves the dispatch
    queue, making the combined worst-case residency
    ``in_flight + depth + 1`` slabs — docs/TPU_RUNBOOK.md).
    ``as_numpy=True`` skips placement and yields host stacks.

    A file that fails to probe/read/bucket raises :class:`SlabReadError`
    carrying its index — after any partial slab of earlier healthy files
    has been yielded, so the error surfaces at the failing file's own
    position in the consumption order (the campaign's per-file fault
    isolation relies on this attribution, exactly like
    ``stream_strain_blocks``). ``read_deadline_s`` / ``fault_plan`` pass
    through to the underlying stream (see ``stream_strain_blocks``); a
    deadline violation or injected read fault surfaces wrapped in the
    same :class:`SlabReadError` attribution (its ``cause`` keeps the
    original class for the campaign's failure taxonomy).
    """
    if batch < 1:
        raise ValueError("batch must be >= 1")
    if in_flight < 1:
        raise ValueError("in_flight must be >= 1")
    from ..config import as_bucket_config

    bucket_cfg = as_bucket_config(bucket)
    gen = _assemble_host_slabs(
        list(files), selected_channels, metadata, batch=batch,
        bucket_cfg=bucket_cfg, interrogator=interrogator, prefetch=prefetch,
        engine=engine, wire=wire, read_deadline_s=read_deadline_s,
        fault_plan=fault_plan,
    )
    if as_numpy:
        if sharding is not None or device is not None:
            raise ValueError("as_numpy=True returns host stacks; drop sharding/device")
        yield from gen
        return

    def place(slab: BatchSlab) -> BatchSlab:
        from ..telemetry import trace as _trace

        with _trace.span("h2d", index0=slab.index0, n_files=slab.n_valid,
                         bucket_ns=slab.bucket_ns):
            if sharding is not None:
                stack = jax.device_put(slab.stack, sharding)
            elif device is not None:
                stack = jax.device_put(slab.stack, device)
            else:
                stack = jnp.asarray(slab.stack)
        return dataclasses.replace(slab, stack=stack)

    error: SlabReadError | None = None
    with ThreadPoolExecutor(max_workers=1,
                            thread_name_prefix="das-h2d-slab") as tx:
        futs: deque = deque()

        def pump():
            nonlocal error
            while error is None and len(futs) < in_flight:
                try:
                    slab = next(gen)
                except StopIteration:
                    return
                except SlabReadError as exc:
                    error = exc  # surfaces after the queued healthy slabs
                    return
                # the device_put dispatch runs on the transfer thread the
                # moment assembly completes, overlapping H2D with compute
                # on the previously yielded slab
                futs.append(tx.submit(place, slab))

        pump()
        while futs:
            slab = futs.popleft().result()
            pump()  # refill BEFORE yielding: next transfer overlaps compute
            yield slab
        if error is not None:
            raise error
