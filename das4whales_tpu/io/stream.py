"""Multi-file streaming: double-buffered host→HBM strain pipeline.

The reference processes one 60 s file at a time, serially, reloading and
reconditioning on the Python thread (scripts/main_mfdetect.py:8-42 per
file; the dask path, dask_wrap.py:21-93, keeps the file handle open and
defers the read). Here ingest of file k+1 overlaps device compute on file
k: the native C++ engine (io/native.py) or an *ordered* thread pool reads
and conditions ahead, and blocks are handed to JAX as device arrays —
optionally placed with a NamedSharding so a [file x channel x time] batch
lands pre-sharded for the multi-chip step (parallel/pipeline.py).

Unlike the reference's ThreadPoolExecutor fan-out, which loses result
ordering via ``as_completed`` (detect.py:244-245), both paths here yield
files strictly in submission order. Metadata probing is also pipelined —
only ``prefetch`` files are probed ahead, so first-block latency is O(1)
in campaign length.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Iterator, Sequence

import h5py
import jax
import jax.numpy as jnp
import numpy as np

from ..config import AcquisitionMetadata, ChannelSelection, as_metadata
from . import native
from .hdf5 import StrainBlock, assemble_block
from .interrogators import get_acquisition_parameters


@dataclass
class _FileSpec:
    path: str
    meta: AcquisitionMetadata
    t0_us: int
    layout: tuple | None  # (offset, disk_dtype, nx, ns) when natively readable


def _is_tdms(path: str) -> bool:
    return path.lower().endswith(".tdms")


def _probe(path: str, interrogator: str, metadata) -> _FileSpec:
    if _is_tdms(path) and metadata is None and interrogator == "optasense":
        interrogator = "silixa"  # extension beats the h5-centric default
    meta = as_metadata(metadata) if metadata is not None else get_acquisition_parameters(
        path, interrogator=interrogator
    )
    if _is_tdms(path) or meta.interrogator == "silixa":
        # single-segment contiguous TDMS reads through the SAME native
        # engine as HDF5 (io/tdms.py contiguous_layout probes metadata
        # only and also yields the GPS t0); irregular files keep the
        # pure-host reader, which extracts t0 during its own parse
        if native.available():
            from .tdms import contiguous_layout as _tdms_layout

            lay = _tdms_layout(path)
            if lay is not None:
                off, dt, nx, ns, t0_us = lay
                return _FileSpec(path=path, meta=meta, t0_us=t0_us,
                                 layout=(off, dt, nx, ns))
        return _FileSpec(path=path, meta=meta, t0_us=0, layout=None)
    layout = None
    with h5py.File(path, "r") as fp:
        raw = fp["Acquisition/Raw[0]/RawData"]
        t0_us = int(fp["Acquisition/Raw[0]/RawDataTime"][0])
        if native.available():
            lay = native.contiguous_layout(raw)
            if lay is not None:
                layout = (lay[0], lay[1], raw.shape[0], raw.shape[1])
    return _FileSpec(path=path, meta=meta, t0_us=t0_us, layout=layout)


def _read_h5py_host(spec: _FileSpec, sel: ChannelSelection) -> np.ndarray:
    with h5py.File(spec.path, "r") as fp:
        block = fp["Acquisition/Raw[0]/RawData"][sel.start : sel.stop : sel.step, :]
    x = block.astype(np.float32)
    x -= x.mean(axis=1, keepdims=True)
    x *= spec.meta.scale_factor
    return x


def _read_tdms_host(spec: _FileSpec, sel: ChannelSelection) -> np.ndarray:
    """Read + condition a Silixa TDMS file, updating ``spec.t0_us`` from
    its ``GPSTimeStamp`` property when present (the reference never loads
    TDMS bulk data at all — its silixa path is metadata-only,
    data_handle.py:113-154)."""
    from .interrogators import _natural_key
    from .tdms import TdmsFile

    f = TdmsFile.read(spec.path)
    channels = f["Measurement"]
    names = sorted(channels, key=_natural_key)[sel.start : sel.stop : sel.step]
    x = np.stack([channels[c] for c in names]).astype(np.float32)
    x -= x.mean(axis=1, keepdims=True)
    x *= spec.meta.scale_factor
    t0 = f.properties.get("GPSTimeStamp")
    if hasattr(t0, "timestamp"):
        spec.t0_us = int(t0.timestamp() * 1e6)
    return x


def _read_host(spec: _FileSpec, sel: ChannelSelection) -> np.ndarray:
    if _is_tdms(spec.path) or spec.meta.interrogator == "silixa":
        return _read_tdms_host(spec, sel)
    return _read_h5py_host(spec, sel)


def stream_strain_blocks(
    files: Sequence[str],
    selected_channels,
    metadata=None,
    *,
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "auto",
    device=None,
    sharding=None,
    as_numpy: bool = False,
) -> Iterator[StrainBlock]:
    """Yield conditioned :class:`StrainBlock`\\ s for ``files`` in order,
    reading ahead ``prefetch`` files while the caller computes.

    ``metadata`` may be None (probed per file), one metadata for all files,
    or a sequence aligned with ``files``. ``sharding``/``device`` place each
    block on arrival (e.g. a per-file NamedSharding over the channel axis).
    ``as_numpy`` keeps traces on the host (for callers that batch several
    files before one placed transfer, e.g. :func:`stream_file_batches`).

    ``engine="auto"`` picks the native path iff the *first* file is natively
    readable; a later file that breaks that assumption raises — pass
    ``engine="h5py"`` for heterogeneous campaigns.
    """
    if prefetch < 1:
        raise ValueError("prefetch must be >= 1")
    if engine not in ("auto", "native", "h5py"):
        raise ValueError(f"unknown engine {engine!r}; expected 'auto', 'native', or 'h5py'")
    if as_numpy and (sharding is not None or device is not None):
        raise ValueError("as_numpy=True returns host arrays; drop sharding/device")
    files = list(files)
    if not files:
        return
    sel = ChannelSelection.from_list(selected_channels)
    metas = (
        [None] * len(files)
        if metadata is None
        else ([metadata] * len(files) if not isinstance(metadata, (list, tuple)) else list(metadata))
    )
    if len(metas) != len(files):
        raise ValueError(f"got {len(metas)} metadata entries for {len(files)} files")

    def finish(spec: _FileSpec, host: np.ndarray) -> StrainBlock:
        if as_numpy:
            arr = host
        elif sharding is not None:
            arr = jax.device_put(host, sharding)
        elif device is not None:
            arr = jax.device_put(host, device)
        else:
            arr = jnp.asarray(host)
        return assemble_block(arr, spec.meta, sel, spec.t0_us)

    first = _probe(files[0], interrogator, metas[0])
    use_native = engine in ("auto", "native") and first.layout is not None
    if engine == "native" and not use_native:
        raise ValueError(f"engine='native' but {files[0]} is not natively readable")

    def native_submit(pf, spec: _FileSpec):
        if spec.layout is None:
            raise ValueError(
                f"{spec.path} is not natively readable but the stream started "
                "on the native engine; pass engine='h5py' for mixed file sets"
            )
        offset, dt, nx, ns = spec.layout
        return pf.submit(spec.path, offset, dt, nx, ns,
                         sel.start, min(sel.stop, nx), sel.step,
                         fuse=True, scale=spec.meta.scale_factor)

    # probe lazily: spec k is probed right before (native) or inside (h5py)
    # its read task, keeping only `prefetch` probes + reads ahead of the
    # consumer. Errors are DEFERRED to the failing file's own position in
    # the yield order — a bad file k must raise on the k-th next(), not
    # while the consumer is still working on file k-prefetch (the campaign
    # runner's per-file fault isolation relies on this attribution).
    specs: dict[int, _FileSpec] = {0: first}

    def spec_for(i: int) -> _FileSpec:
        if i not in specs:
            specs[i] = _probe(files[i], interrogator, metas[i])
        return specs[i]

    if use_native:
        with native.Prefetcher(nworkers=prefetch) as pf:
            def submit(i):
                try:
                    return native_submit(pf, spec_for(i))
                except Exception as exc:  # noqa: BLE001 — re-raised in order
                    return ("__probe_error__", exc)

            tickets = {i: submit(i) for i in range(min(prefetch, len(files)))}
            for i in range(len(files)):
                ticket = tickets.pop(i)
                nxt = i + prefetch
                if nxt < len(files):
                    tickets[nxt] = submit(nxt)
                if isinstance(ticket, tuple) and ticket[0] == "__probe_error__":
                    raise ticket[1]
                host = pf.wait(ticket)
                yield finish(specs.pop(i), host)
    else:
        def probe_and_read(i):
            spec = spec_for(i) if i == 0 else _probe(files[i], interrogator, metas[i])
            return spec, _read_host(spec, sel)

        with ThreadPoolExecutor(max_workers=prefetch) as ex:
            futs = {
                i: ex.submit(probe_and_read, i)
                for i in range(min(prefetch, len(files)))
            }
            for i in range(len(files)):
                fut = futs.pop(i)
                nxt = i + prefetch
                if nxt < len(files):
                    futs[nxt] = ex.submit(probe_and_read, nxt)
                spec, host = fut.result()  # strict submission order
                yield finish(spec, host)


def stream_file_batches(
    files: Sequence[str],
    selected_channels,
    metadata=None,
    *,
    batch: int,
    mesh=None,
    interrogator: str = "optasense",
    prefetch: int = 2,
    engine: str = "auto",
    tail: str = "pad",
) -> Iterator[tuple]:
    """Stack consecutive files into ``[file x channel x time]`` batches for
    the sharded multi-chip detection step (parallel/pipeline.py).

    Yields ``(batch_array, blocks)``; when ``mesh`` is given the stack is
    placed with the pipeline's input sharding (file x channel).

    ``tail`` controls trailing files that do not fill a batch:
    ``"pad"`` (default) zero-pads the final stack to the batch size and
    yields it with only the real blocks in ``blocks`` (check
    ``len(blocks)`` — padded file slots produce no correlogram energy, so
    detection outputs there are empty); ``"drop"`` discards them with a
    warning; ``"error"`` raises up front (at call time, not first
    ``next()`` — validation happens before the generator is created).
    """
    if batch < 1:
        raise ValueError("batch must be >= 1")
    if tail not in ("pad", "drop", "error"):
        raise ValueError(f"tail must be 'pad', 'drop' or 'error', got {tail!r}")
    n_full = (len(files) // batch) * batch
    if n_full != len(files):
        if tail == "error":
            raise ValueError(
                f"{len(files) - n_full} trailing file(s) do not fill a batch "
                f"of {batch} (tail='error')"
            )
        if tail == "drop":
            import warnings

            warnings.warn(
                f"dropping {len(files) - n_full} trailing file(s) not filling a batch of {batch}"
            )
            files = files[:n_full]
    return _file_batches_gen(
        list(files), selected_channels, metadata, batch=batch, mesh=mesh,
        interrogator=interrogator, prefetch=prefetch, engine=engine,
    )


def _file_batches_gen(
    files, selected_channels, metadata, *, batch, mesh, interrogator,
    prefetch, engine,
) -> Iterator[tuple]:
    from ..parallel.pipeline import input_sharding

    sharding = input_sharding(mesh) if mesh is not None else None

    def place(stack):
        if sharding is not None:
            return jax.device_put(stack, sharding)
        return jnp.asarray(stack)

    # traces stay host-side numpy until the whole batch is assembled, so
    # the [file x channel x time] stack crosses to HBM exactly once and
    # lands pre-sharded — never materialized whole on a single chip
    pending: list[StrainBlock] = []
    for blk in stream_strain_blocks(
        files, selected_channels, metadata,
        interrogator=interrogator, prefetch=prefetch, engine=engine,
        as_numpy=True,
    ):
        pending.append(blk)
        if len(pending) == batch:
            yield place(np.stack([b.trace for b in pending])), tuple(pending)
            pending = []
    if pending:  # tail == "pad"
        stack = np.stack([b.trace for b in pending])
        fill = np.zeros((batch - len(pending),) + stack.shape[1:], stack.dtype)
        yield place(np.concatenate([stack, fill])), tuple(pending)
