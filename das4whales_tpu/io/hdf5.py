"""OptaSense HDF5 ingest (host side).

Parity targets: reference ``data_handle.get_metadata_optasense``
(data_handle.py:71-110), ``load_das_data`` (data_handle.py:180-230) and
``raw2strain`` (data_handle.py:157-177). The raw HDF5 read stays on the
host; demean + scale-to-strain runs as a jitted device kernel so the large
float conversion happens on TPU, not in numpy.

Also provides a schema-faithful *writer* so synthetic fixtures and golden
tests can run fully offline (the reference has no offline test asset,
SURVEY.md §4).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Tuple

import h5py
import jax
import jax.numpy as jnp
import numpy as np

from ..config import AcquisitionMetadata, ChannelSelection, as_metadata

#: OptaSense interferometric conversion constants (data_handle.py:104):
#: 1550.12 nm laser, 0.78 photoelastic scaling.
_LASER_WAVELENGTH_M = 1550.12e-9
_PHOTOELASTIC = 0.78


def optasense_scale_factor(n: float, gauge_length: float) -> float:
    """Raw counts -> strain conversion (data_handle.py:104)."""
    return (2 * np.pi) / 2**16 * _LASER_WAVELENGTH_M / (_PHOTOELASTIC * 4 * np.pi * n * gauge_length)


def get_metadata_optasense(filepath: str) -> AcquisitionMetadata:
    """Read acquisition parameters from an OptaSense HDF5 file."""
    if not os.path.exists(filepath):
        raise FileNotFoundError(f"File {filepath} not found")
    with h5py.File(filepath, "r") as fp:
        acq = fp["Acquisition"]
        raw = acq["Raw[0]"]
        fs = float(raw.attrs["OutputDataRate"])
        dx = float(acq.attrs["SpatialSamplingInterval"])
        ns = int(raw["RawDataTime"].attrs["Count"])
        n = float(acq["Custom"].attrs["Fibre Refractive Index"])
        gl = float(acq.attrs["GaugeLength"])
        nx = int(raw.attrs["NumberOfLoci"])
    return AcquisitionMetadata(
        fs=fs, dx=dx, nx=nx, ns=ns, n=n, gauge_length=gl,
        scale_factor=optasense_scale_factor(n, gl), interrogator="optasense",
    )


@functools.partial(jax.jit, static_argnames=())
def raw2strain(trace: jnp.ndarray, scale_factor: float) -> jnp.ndarray:
    """Demean each channel and scale raw counts to strain
    (data_handle.py:157-177) — on device, one fused kernel. Delegates to
    ``ops.conditioning.condition`` so the affine map whose raw/conditioned
    parity the narrow wire guarantees has exactly ONE definition. Float
    inputs keep their dtype; integer counts condition to float32 (the
    scale must never be cast to an int dtype — it would truncate to 0)."""
    from ..ops import conditioning

    dtype = (trace.dtype if jnp.issubdtype(trace.dtype, jnp.floating)
             else jnp.float32)
    return conditioning.condition(trace, scale_factor, dtype=dtype)


@dataclass
class StrainBlock:
    """A loaded ``[channel x time]`` strain block with its axes.

    Iterable as ``(trace, tx, dist, t0_utc)`` for drop-in parity with the
    reference ``load_das_data`` return convention (data_handle.py:180-230).
    """

    trace: jnp.ndarray
    tx: np.ndarray
    dist: np.ndarray
    t0_utc: datetime
    metadata: AcquisitionMetadata | None = None
    selection: ChannelSelection | None = None
    #: "conditioned": ``trace`` is strain (host demean+scale already ran).
    #: "raw": ``trace`` is stored-dtype interrogator counts — the narrow
    #: wire format; condition on device with ``ops.conditioning`` using
    #: ``metadata.scale_factor`` (or hand it to a ``wire="raw"`` detector).
    wire: str = "conditioned"

    def __iter__(self):
        return iter((self.trace, self.tx, self.dist, self.t0_utc))


def load_das_data(
    filename: str,
    selected_channels,
    metadata,
    *,
    dtype=jnp.float32,
    device=None,
    engine: str = "auto",
    wire: str = "conditioned",
) -> StrainBlock:
    """Load a strided channel selection as strain, with time/distance axes.

    Parity: reference ``data_handle.load_das_data`` (data_handle.py:180-230),
    except the conditioning runs on device and the default dtype is float32
    (strain magnitudes ~1e-9 are comfortably inside f32's normal range; pass
    ``dtype=jnp.float64`` on CPU for bit-level parity studies).

    ``engine`` selects the bulk-read path: ``"native"`` uses the C++ ingest
    engine (threaded pread + fused conditioning, see ``io.native``),
    ``"h5py"`` the pure-Python path, ``"auto"`` picks native when the
    dataset layout and dtype allow it.

    ``wire="raw"`` is the NARROW wire format: the stored-dtype counts
    cross host→device untouched (int16 = half the float32 bytes) and the
    same demean+scale affine map runs on device (``ops.conditioning``) —
    the returned block is still strain, only the transfer is narrower.
    """
    if not os.path.exists(filename):
        raise FileNotFoundError(f"File {filename} not found")
    meta = as_metadata(metadata)
    sel = ChannelSelection.from_list(selected_channels)

    if engine not in ("auto", "native", "h5py"):
        raise ValueError(f"unknown engine {engine!r}; expected 'auto', 'native', or 'h5py'")
    if wire not in ("conditioned", "raw"):
        raise ValueError(f"unknown wire {wire!r}; expected 'conditioned' or 'raw'")
    if engine == "native" and wire == "conditioned" and dtype != jnp.float32:
        raise ValueError("engine='native' produces float32; pass dtype=jnp.float32")
    native_spec = None
    with h5py.File(filename, "r") as fp:
        raw = fp["Acquisition/Raw[0]/RawData"]
        t_us = int(fp["Acquisition/Raw[0]/RawDataTime"][0])
        # raw wire serves any dtype from the layout (stored-dtype memmap
        # gather, conditioning casts on device); the conditioned fused C++
        # pass produces float32 only
        if engine in ("auto", "native") and (wire == "raw" or dtype == jnp.float32):
            from . import native as native_mod

            layout = native_mod.contiguous_layout(raw) if native_mod.available() else None
            if layout is not None:
                native_spec = (layout[0], layout[1], raw.shape[0], raw.shape[1])
            elif engine == "native":
                raise ValueError(
                    f"engine='native' but {filename} is not natively readable "
                    "(chunked/compressed dataset, unsupported dtype, or build failure)"
                )
        if native_spec is None:
            block = raw[sel.start : sel.stop : sel.step, :]

    if wire == "raw":
        from ..ops import conditioning

        if native_spec is not None:
            from . import native as native_mod

            offset, disk_dtype, nx_disk, ns_disk = native_spec
            block = native_mod.read_strided_raw(
                filename, offset, disk_dtype, nx_disk, ns_disk,
                sel.start, min(sel.stop, nx_disk), sel.step,
            )
        # narrow wire: put the STORED dtype on device, condition there —
        # one transfer of the raw count bytes, never the float32 inflation
        arr = jax.device_put(block, device) if device is not None else jnp.asarray(block)
        trace = conditioning.condition(arr, meta.scale_factor, dtype=dtype)
        return assemble_block(trace, meta, sel, t_us)

    if native_spec is not None:
        from . import native as native_mod

        offset, disk_dtype, nx_disk, ns_disk = native_spec
        # fused read+demean+scale in C++; result is already strain
        host = native_mod.read_strided(
            filename, offset, disk_dtype, nx_disk, ns_disk,
            sel.start, min(sel.stop, nx_disk), sel.step,
            fuse=True, scale=meta.scale_factor,
        )
        trace = jnp.asarray(host)
        if device is not None:
            trace = jax.device_put(trace, device)
    else:
        arr = jnp.asarray(block, dtype=dtype)
        if device is not None:
            arr = jax.device_put(arr, device)
        trace = raw2strain(arr, meta.scale_factor)

    return assemble_block(trace, meta, sel, t_us)


def assemble_block(trace, metadata, sel: ChannelSelection, t0_us: int,
                   wire: str = "conditioned") -> StrainBlock:
    """Build a :class:`StrainBlock` (time/distance axes + UTC start) from a
    ``[channel x time]`` array. Shared by the single-file loader above and
    the multi-file streaming path (io/stream.py) so the axis conventions
    (data_handle.py:220-228) live in exactly one place. ``wire`` records
    whether ``trace`` is conditioned strain or raw counts (narrow wire)."""
    meta = as_metadata(metadata)
    nnx, nns = trace.shape
    tx = np.arange(nns) / meta.fs
    dist = (np.arange(nnx) * sel.step + sel.start) * meta.dx
    t0 = datetime.fromtimestamp(t0_us * 1e-6, tz=timezone.utc).replace(tzinfo=None)
    return StrainBlock(trace=trace, tx=tx, dist=dist, t0_utc=t0, metadata=meta,
                       selection=sel, wire=wire)


def write_optasense(
    filepath: str,
    raw_data: np.ndarray,
    fs: float,
    dx: float,
    gauge_length: float = 51.05,
    n: float = 1.4681,
    t0_us: int = 1_636_000_000_000_000,
    raw_dtype=np.int32,
) -> str:
    """Write a ``[channel x time]`` raw block in the OptaSense HDF5
    schema the reader (and the reference) expects. Used for synthetic
    fixtures and data export. ``raw_dtype`` sets the stored dtype
    (int32 default, matching the deployment schema; float32 files exist
    in the wild and exercise the float narrow-wire path)."""
    raw_data = np.asarray(raw_data)
    nx, ns = raw_data.shape
    with h5py.File(filepath, "w") as fp:
        acq = fp.create_group("Acquisition")
        acq.attrs["SpatialSamplingInterval"] = dx
        acq.attrs["GaugeLength"] = gauge_length
        custom = acq.create_group("Custom")
        custom.attrs["Fibre Refractive Index"] = n
        raw = acq.create_group("Raw[0]")
        raw.attrs["OutputDataRate"] = fs
        raw.attrs["NumberOfLoci"] = nx
        raw.create_dataset("RawData", data=raw_data.astype(raw_dtype))
        times = (t0_us + np.arange(ns) * 1e6 / fs).astype(np.int64)
        dt = raw.create_dataset("RawDataTime", data=times)
        dt.attrs["Count"] = ns
    return filepath
