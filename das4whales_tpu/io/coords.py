"""Cable geometry ingest (host side).

Single implementation of the cable-coordinate loader the reference
duplicates in two modules (data_handle.py:258-279 and map.py:20-42 —
a documented quirk, SURVEY.md §7).
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def load_cable_coordinates(filepath: str, dx: float) -> pd.DataFrame:
    """Load cable coordinates from a headerless CSV of
    ``chan_idx, lat, lon, depth`` rows; adds the along-cable position in
    meters (reference data_handle.py:258-279)."""
    df = pd.read_csv(filepath, delimiter=",", header=None)
    df.columns = ["chan_idx", "lat", "lon", "depth"]
    df["chan_m"] = df["chan_idx"] * dx
    return df


def cable_positions_xyz(df: pd.DataFrame, utm_zone: int = 10) -> np.ndarray:
    """Cable coordinates as a ``[channel x 3]`` UTM (x, y, depth) array —
    the geometry input of the TDOA localizer (loc.py:57)."""
    from ..plot.geo import latlon_to_utm

    x, y = latlon_to_utm(df["lon"].to_numpy(), df["lat"].to_numpy(), zone=utm_zone)
    return np.stack([x, y, df["depth"].to_numpy()], axis=1)
