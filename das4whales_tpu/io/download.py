"""File download with local caching (host side).

Parity target: reference ``data_handle.dl_file`` (data_handle.py:233-255),
rebuilt on the standard library (urllib) instead of the ``wget`` package,
with atomic writes so an interrupted download never poisons the cache
(download idempotency is the reference's only resume behavior,
SURVEY.md §5.4).
"""

from __future__ import annotations

import os
import shutil
import urllib.request


def dl_file(url: str, datadir: str = "data", quiet: bool = False) -> str:
    """Download ``url`` into ``datadir`` unless already cached; return the
    local path."""
    filename = url.split("/")[-1]
    filepath = os.path.join(datadir, filename)
    if os.path.exists(filepath):
        if not quiet:
            print(f"{filename} already stored locally")
        return filepath
    os.makedirs(datadir, exist_ok=True)
    tmp = filepath + ".part"
    with urllib.request.urlopen(url) as resp, open(tmp, "wb") as out:
        shutil.copyfileobj(resp, out)
    os.replace(tmp, filepath)
    if not quiet:
        print(f"Downloaded {filename}")
    return filepath
