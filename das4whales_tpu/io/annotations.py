"""Pick exchange with the bioacoustics annotation ecosystem.

Detections are only useful once an analyst can review them in the tools
the field actually uses; Raven's tab-separated "selection table" is the
de-facto exchange format there. The reference has no export at all —
its picks die inside matplotlib figures (plot.py:373-415).

A selection table row spans a time/frequency box; picks are points, so
each pick becomes a box centered on its time with the template's
duration and frequency band (the call geometry the detector was looking
for). ``channel`` column carries the DAS channel index so array context
survives the round trip.
"""

from __future__ import annotations

import csv
from typing import Dict

import numpy as np

_COLUMNS = [
    "Selection", "View", "Channel", "Begin Time (s)", "End Time (s)",
    "Low Freq (Hz)", "High Freq (Hz)", "Template", "DAS Channel",
]


def to_raven_selection_table(
    path: str,
    picks: Dict[str, np.ndarray],
    fs: float,
    template_configs: dict | None = None,
    t_offset_s: float = 0.0,
) -> str:
    """Write ``{template: (2, n) [channel_idx, time_idx]}`` picks as ONE
    Raven selection table (rows sorted by begin time; selection numbers
    are 1-based as Raven expects). ``template_configs`` supplies each
    template's ``(fmin, fmax, duration)`` box geometry — e.g.
    ``MatchedFilterDetector.template_configs``; templates without a
    config get a zero-height box at the pick instant. ``t_offset_s``
    shifts times to absolute (e.g. a file's UTC offset in seconds).
    """
    rows = []
    cfgs = template_configs or {}
    for name, pk in picks.items():
        pk = np.asarray(pk)
        cfg = cfgs.get(name)
        fmin = getattr(cfg, "fmin", 0.0) if cfg is not None else 0.0
        fmax = getattr(cfg, "fmax", 0.0) if cfg is not None else 0.0
        dur = getattr(cfg, "duration", 0.0) if cfg is not None else 0.0
        for ch, t_idx in pk.T:
            t0 = t_offset_s + float(t_idx) / fs - dur / 2.0
            rows.append((t0, t0 + dur, float(fmin), float(fmax),
                         name, int(ch)))
    rows.sort()
    with open(path, "w", newline="") as fh:
        w = csv.writer(fh, delimiter="\t")
        w.writerow(_COLUMNS)
        for i, (b, e, lo, hi, name, ch) in enumerate(rows, start=1):
            w.writerow([i, "Spectrogram 1", 1, f"{b:.6f}", f"{e:.6f}",
                        f"{lo:.3f}", f"{hi:.3f}", name, ch])
    return path


def from_raven_selection_table(
    path: str, fs: float, skipped: list | None = None
) -> Dict[str, np.ndarray]:
    """Inverse of :func:`to_raven_selection_table`: selection table ->
    ``{template: (2, n)}`` picks (box centers back to sample indices).
    Tables from Raven itself work too — rows missing the ``Template`` /
    ``DAS Channel`` extension columns land under template ``"SELECTION"``
    with channel 0. Header matching tolerates Raven's capitalization and
    spacing variants (lookup is case/whitespace-insensitive); a table
    without any recognizable ``Begin Time (s)`` column raises a
    descriptive ``ValueError`` up front, and rows whose time cells are
    empty/unparseable are skipped (reported via ``skipped``, a list that
    receives ``(line_number, reason)`` tuples) instead of crashing
    mid-iteration (ADVICE r4). When rows are dropped and no ``skipped``
    list was passed, ONE summary ``warnings.warn`` fires — silent row
    loss must never pass unnoticed (ADVICE r5)."""
    def norm(s: str) -> str:
        return " ".join(str(s).split()).lower()

    groups: Dict[str, list] = {}
    n_dropped = 0
    with open(path, newline="") as fh:
        reader = csv.DictReader(fh, delimiter="\t")
        headers = {norm(h): h for h in (reader.fieldnames or [])}

        def col(name: str) -> str | None:
            return headers.get(norm(name))

        begin_col = col("Begin Time (s)")
        if begin_col is None:
            raise ValueError(
                f"{path}: not a Raven selection table — no 'Begin Time (s)' "
                f"column (found: {reader.fieldnames})"
            )
        end_col = col("End Time (s)")
        tmpl_col = col("Template")
        ch_col = col("DAS Channel")
        for lineno, row in enumerate(reader, start=2):
            name = (row.get(tmpl_col) if tmpl_col else None) or "SELECTION"
            try:
                begin = float(row[begin_col])
                end = float((row.get(end_col) if end_col else None) or begin)
                ch = int(float((row.get(ch_col) if ch_col else None) or 0))
            except (TypeError, ValueError) as e:
                n_dropped += 1
                if skipped is not None:
                    skipped.append((lineno, repr(e)))
                continue
            center = (begin + end) / 2.0
            groups.setdefault(name, []).append((ch, int(round(center * fs))))
    if n_dropped and skipped is None:
        import warnings

        warnings.warn(
            f"{path}: {n_dropped} selection-table row(s) skipped "
            "(empty/unparseable time or channel cells); pass skipped=[] "
            "to collect per-row (line_number, reason) details"
        )
    return {
        name: np.asarray(sorted(v), dtype=np.int64).T.reshape(2, -1)
        for name, v in groups.items()
    }
