"""Minimal native TDMS (National Instruments) reader/writer.

The reference reads Silixa interrogator files through the third-party
``nptdms`` wheel (data_handle.py:113-154). That package is not part of this
framework's dependency set, so this module implements the TDMS container
format directly from the public specification: segment lead-ins, ToC flags,
object metadata with raw-data indexes, property tables, and contiguous
(non-interleaved) raw data chunks — everything a Silixa DAS file uses.

Scope (asserted, not silently wrong): little-endian, non-interleaved,
non-DAQmx segments with numeric channel data; properties of numeric,
string, bool and timestamp types.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from datetime import datetime, timedelta, timezone
from typing import Dict

import numpy as np

# ToC flag bits
_TOC_METADATA = 1 << 1
_TOC_NEW_OBJ_LIST = 1 << 2
_TOC_RAW_DATA = 1 << 3
_TOC_INTERLEAVED = 1 << 5
_TOC_BIG_ENDIAN = 1 << 6
_TOC_DAQMX = 1 << 7

# TDMS dtype ids -> numpy dtypes
_TDMS_DTYPES = {
    1: np.dtype("int8"),
    2: np.dtype("int16"),
    3: np.dtype("int32"),
    4: np.dtype("int64"),
    5: np.dtype("uint8"),
    6: np.dtype("uint16"),
    7: np.dtype("uint32"),
    8: np.dtype("uint64"),
    9: np.dtype("float32"),
    10: np.dtype("float64"),
}
_NUMPY_TO_TDMS = {v: k for k, v in _TDMS_DTYPES.items()}
_TYPE_STRING = 0x20
_TYPE_BOOL = 0x21
_TYPE_TIMESTAMP = 0x44

# the TDMS epoch is UTC; an AWARE datetime keeps .timestamp() (and hence
# every t0_us derived from GPSTimeStamp) correct on non-UTC hosts — a
# naive epoch would silently shift campaign pick times by the local
# UTC offset
_EPOCH_1904 = datetime(1904, 1, 1, tzinfo=timezone.utc)


def _parse_path(path: str):
    """TDMS object path -> tuple of unescaped components.

    ``/`` is the file root, ``/'Group'`` a group, ``/'Group'/'Chan'`` a
    channel; quotes inside names are doubled.
    """
    if path == "/":
        return ()
    parts = []
    assert path.startswith("/"), path
    rest = path[1:]
    while rest:
        assert rest.startswith("'"), path
        end = 1
        while True:
            end = rest.index("'", end)
            if rest[end : end + 2] == "''":
                end += 2
                continue
            break
        parts.append(rest[1:end].replace("''", "'"))
        rest = rest[end + 1 :]
        if rest.startswith("/"):
            rest = rest[1:]
    return tuple(parts)


class _Cursor:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        out = self.buf[self.pos : self.pos + n]
        if len(out) != n:
            raise EOFError("truncated TDMS data")
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack("<I", self.read(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.read(8))[0]

    def string(self) -> str:
        return self.read(self.u32()).decode("utf-8")

    def value(self, type_id: int):
        if type_id in _TDMS_DTYPES:
            dt = _TDMS_DTYPES[type_id]
            return np.frombuffer(self.read(dt.itemsize), dtype=dt)[0].item()
        if type_id == _TYPE_STRING:
            return self.string()
        if type_id == _TYPE_BOOL:
            return bool(self.read(1)[0])
        if type_id == _TYPE_TIMESTAMP:
            frac = struct.unpack("<Q", self.read(8))[0]
            secs = struct.unpack("<q", self.read(8))[0]
            return _EPOCH_1904 + timedelta(seconds=secs + frac / 2**64)
        raise NotImplementedError(f"TDMS property type 0x{type_id:x}")


@dataclass
class _RawIndex:
    dtype: np.dtype
    n_values: int


def _iter_segment_objects(cur: "_Cursor"):
    """Walk ONE segment's metadata block: yields
    ``(path, index, props)`` per object, where ``index`` is
    ``("none",)`` (property-only object), ``("reuse",)`` (raw-index
    carried over from an earlier segment) or
    ``("new", type_id, dim, n_values)``. The ONE metadata parser —
    ``TdmsFile.read`` and the native-layout probe both walk through
    here, so a format accommodation cannot land in only one of them."""
    n_objects = cur.u32()
    for _ in range(n_objects):
        path = _parse_path(cur.string())
        idx_len = cur.u32()
        if idx_len == 0xFFFFFFFF:
            index = ("none",)
        elif idx_len == 0x00000000:
            index = ("reuse",)
        else:
            type_id = cur.u32()
            dim = cur.u32()
            n_values = cur.u64()
            if type_id == _TYPE_STRING:
                cur.u64()  # total raw bytes of the string channel
            index = ("new", type_id, dim, n_values)
        props = {}
        n_props = cur.u32()
        for _ in range(n_props):
            name = cur.string()
            props[name] = cur.value(cur.u32())
        yield path, index, props


@dataclass
class TdmsObject:
    path: tuple
    properties: dict = field(default_factory=dict)
    data_parts: list = field(default_factory=list)

    @property
    def data(self) -> np.ndarray:
        if not self.data_parts:
            return np.empty(0)
        return np.concatenate(self.data_parts)


class TdmsFile:
    """Parsed TDMS file: root/group properties and channel data arrays."""

    def __init__(self):
        self.objects: Dict[tuple, TdmsObject] = {}

    @property
    def properties(self) -> dict:
        obj = self.objects.get(())
        return obj.properties if obj else {}

    def groups(self):
        return sorted({p[0] for p in self.objects if len(p) >= 1})

    def channels(self, group: str):
        return [p[1] for p in sorted(self.objects) if len(p) == 2 and p[0] == group]

    def __getitem__(self, group: str) -> Dict[str, np.ndarray]:
        return {c: self.objects[(group, c)].data for c in self.channels(group)}

    def group_properties(self, group: str) -> dict:
        obj = self.objects.get((group,))
        return obj.properties if obj else {}

    @classmethod
    def read(cls, filepath: str) -> "TdmsFile":
        with open(filepath, "rb") as f:
            buf = f.read()
        self = cls()
        pos = 0
        # raw-data object order + indexes carry over between segments
        active: list[tuple] = []
        indexes: Dict[tuple, _RawIndex] = {}
        while pos < len(buf):
            if len(buf) - pos < 28:
                break  # trailing padding
            tag, toc, _version, next_off, raw_off = struct.unpack(
                "<4sIIQQ", buf[pos : pos + 28]
            )
            if tag != b"TDSm":
                raise ValueError(f"bad TDMS segment tag at byte {pos}")
            if toc & _TOC_BIG_ENDIAN:
                raise NotImplementedError("big-endian TDMS segments")
            if toc & _TOC_DAQMX:
                raise NotImplementedError("DAQmx raw data")
            data_start = pos + 28 + raw_off
            seg_end = pos + 28 + next_off
            if next_off == 0xFFFFFFFFFFFFFFFF:  # crashed writer: data to EOF
                seg_end = len(buf)

            if toc & _TOC_METADATA:
                cur = _Cursor(buf, pos + 28)
                if toc & _TOC_NEW_OBJ_LIST:
                    active = []
                for path, index, props in _iter_segment_objects(cur):
                    obj = self.objects.setdefault(path, TdmsObject(path))
                    if index[0] == "reuse":
                        if path not in active:
                            active.append(path)  # reuse previous index
                    elif index[0] == "new":
                        _, type_id, dim, n_values = index
                        if type_id == _TYPE_STRING:
                            raise NotImplementedError("string channel data")
                        if dim != 1:
                            raise NotImplementedError("multi-dimensional TDMS arrays")
                        indexes[path] = _RawIndex(_TDMS_DTYPES[type_id], n_values)
                        if path not in active:
                            active.append(path)
                    obj.properties.update(props)

            if toc & _TOC_RAW_DATA:
                if toc & _TOC_INTERLEAVED:
                    raise NotImplementedError("interleaved raw data")
                chunk = sum(
                    indexes[p].dtype.itemsize * indexes[p].n_values for p in active
                )
                dpos = data_start
                while chunk > 0 and dpos + chunk <= seg_end:
                    for p in active:
                        ix = indexes[p]
                        nbytes = ix.dtype.itemsize * ix.n_values
                        arr = np.frombuffer(buf[dpos : dpos + nbytes], dtype=ix.dtype)
                        self.objects[p].data_parts.append(arr)
                        dpos += nbytes
            pos = seg_end
        return self


def read_measurement_block(filepath: str, start: int, stop: int, step: int,
                           *, raw: bool = False):
    """Host bulk read of a Silixa file's ``Measurement`` group: the
    ``[start:stop:step]`` channel selection stacked ``[n_sel x ns]`` in
    natural name order. ``raw=True`` keeps the STORED dtype (the narrow
    wire format — int16 counts stay int16 for the host→device transfer,
    conditioning runs on device via ``ops.conditioning``); ``raw=False``
    casts to float32 for the host conditioning path. Returns
    ``(block, t0_us or None)`` with ``t0_us`` from ``GPSTimeStamp`` when
    present. The ONE TDMS bulk-selection routine — the stream's
    conditioned and raw readers both come through here, so channel
    ordering cannot drift between wire formats."""
    from .interrogators import _natural_key

    f = TdmsFile.read(filepath)
    channels = f["Measurement"]
    names = sorted(channels, key=_natural_key)[start:stop:step]
    stack = np.stack([channels[c] for c in names])
    if not raw:
        stack = stack.astype(np.float32)
    t0 = f.properties.get("GPSTimeStamp")
    t0_us = int(t0.timestamp() * 1e6) if hasattr(t0, "timestamp") else None
    return stack, t0_us


def contiguous_layout(filepath: str):
    """Native-ingest layout probe: ``(data_offset, dtype, nx, ns, t0_us)``
    when the file is ONE TDMS segment whose ``Measurement`` channels are
    equal-length, same-dtype and stored contiguously channel-after-channel
    in natural name order — byte-identical to the ``[nx x ns]`` row-major
    block the C++ engine reads (native/ingest.cpp; the same split as the
    HDF5 path: host parses metadata once, the engine preads the bulk).
    Returns ``None`` for anything irregular (multi-segment, multi-chunk,
    interleaved, mixed dtypes, non-natural channel order) — the pure-host
    reader handles those. Reads ONLY the lead-in + metadata block.
    """
    from .interrogators import _natural_key

    try:
        with open(filepath, "rb") as f:
            head = f.read(28)
            if len(head) < 28:
                return None
            tag, toc, _version, next_off, raw_off = struct.unpack(
                "<4sIIQQ", head
            )
            if tag != b"TDSm":
                return None
            bad = _TOC_BIG_ENDIAN | _TOC_DAQMX | _TOC_INTERLEAVED
            if (toc & bad) or not (toc & _TOC_METADATA) or not (toc & _TOC_RAW_DATA):
                return None
            meta = f.read(raw_off)
            if len(meta) < raw_off:
                return None
            f.seek(0, 2)
            fsize = f.tell()
            seg_end = fsize if next_off == 0xFFFFFFFFFFFFFFFF else 28 + next_off
            if seg_end > fsize:
                return None
            if fsize - seg_end >= 28:
                # enough room for another segment header: whether it is a
                # real segment or corruption, the host reader is the
                # arbiter (it parses further segments, or raises on a bad
                # tag — the native engine must not silently serve a
                # truncated view the fallback engine would reject)
                return None
    except OSError:
        return None

    cur = _Cursor(meta, 0)
    chans: list = []
    t0 = None
    try:
        for path, index, props in _iter_segment_objects(cur):
            if path == () and "GPSTimeStamp" in props:
                t0 = props["GPSTimeStamp"]
            if index[0] == "reuse":
                return None  # index reuse implies an earlier segment
            if index[0] == "new":
                _, type_id, dim, n_values = index
                if type_id == _TYPE_STRING or dim != 1:
                    return None
                dtype = _TDMS_DTYPES.get(type_id)
                if dtype is None:
                    return None
                if len(path) != 2 or path[0] != "Measurement":
                    return None
                chans.append((path[1], dtype, int(n_values)))
    except Exception:  # noqa: BLE001 — malformed metadata -> host reader
        return None

    if not chans:
        return None
    names = [c[0] for c in chans]
    if names != sorted(names, key=_natural_key):
        # the host reader selects channels in natural name order; native
        # row slicing must agree with it or the selection silently shifts
        return None
    dtypes = {np.dtype(c[1]) for c in chans}
    lengths = {c[2] for c in chans}
    if len(dtypes) != 1 or len(lengths) != 1:
        return None
    dt = dtypes.pop()
    if dt not in (np.dtype(np.int16), np.dtype(np.int32),
                  np.dtype(np.float32), np.dtype(np.float64)):
        return None
    ns = lengths.pop()
    nx = len(chans)
    chunk = nx * ns * dt.itemsize
    avail = seg_end - (28 + raw_off)
    if avail < chunk or avail >= 2 * chunk:
        return None  # incomplete, or multiple chunks (data would repeat)
    t0_us = int(t0.timestamp() * 1e6) if hasattr(t0, "timestamp") else 0
    return (28 + raw_off, dt, nx, ns, t0_us)


def write_tdms(
    filepath: str,
    root_properties: dict,
    group: str,
    channels: Dict[str, np.ndarray],
) -> str:
    """Write a single-segment, non-interleaved TDMS file (for fixtures,
    tests, and data export)."""

    def enc_string(s: str) -> bytes:
        raw = s.encode("utf-8")
        return struct.pack("<I", len(raw)) + raw

    def enc_path(parts) -> bytes:
        if not parts:
            return enc_string("/")
        return enc_string("/" + "/".join("'" + p.replace("'", "''") + "'" for p in parts))

    def enc_prop(name: str, value) -> bytes:
        out = enc_string(name)
        if isinstance(value, bool):
            return out + struct.pack("<I", _TYPE_BOOL) + struct.pack("<B", value)
        if isinstance(value, (int, np.integer)):
            return out + struct.pack("<I", 3) + struct.pack("<i", int(value))
        if isinstance(value, (float, np.floating)):
            return out + struct.pack("<I", 10) + struct.pack("<d", float(value))
        if isinstance(value, str):
            return out + struct.pack("<I", _TYPE_STRING) + enc_string(value)
        if isinstance(value, datetime):
            if value.tzinfo is None:
                value = value.replace(tzinfo=timezone.utc)  # TDMS times are UTC
            delta = value - _EPOCH_1904
            secs = int(delta.total_seconds())
            frac = int((delta.total_seconds() - secs) * 2**64)
            return out + struct.pack("<I", _TYPE_TIMESTAMP) + struct.pack("<Qq", frac, secs)
        raise TypeError(f"unsupported property type {type(value)}")

    meta = b""
    n_objects = 2 + len(channels)
    meta += struct.pack("<I", n_objects)
    # root object with properties
    meta += enc_path(())
    meta += struct.pack("<I", 0xFFFFFFFF)
    meta += struct.pack("<I", len(root_properties))
    for k, v in root_properties.items():
        meta += enc_prop(k, v)
    # group object
    meta += enc_path((group,))
    meta += struct.pack("<I", 0xFFFFFFFF)
    meta += struct.pack("<I", 0)
    # channel objects
    raw = b""
    for name, arr in channels.items():
        arr = np.ascontiguousarray(arr)
        type_id = _NUMPY_TO_TDMS[arr.dtype]
        meta += enc_path((group, name))
        meta += struct.pack("<I", 20)  # index block length
        meta += struct.pack("<I", type_id)
        meta += struct.pack("<I", 1)
        meta += struct.pack("<Q", arr.size)
        meta += struct.pack("<I", 0)  # no channel properties
        raw += arr.tobytes()

    toc = _TOC_METADATA | _TOC_NEW_OBJ_LIST | _TOC_RAW_DATA
    lead = struct.pack("<4sIIQQ", b"TDSm", toc, 4713, len(meta) + len(raw), len(meta))
    with open(filepath, "wb") as f:
        f.write(lead + meta + raw)
    return filepath
