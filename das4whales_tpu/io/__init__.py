"""Host-side ingest: interrogator files, downloads, geometry, synthesis."""

from . import coords, download, hdf5, interrogators, native, stream, synth, tdms  # noqa: F401
from .download import dl_file  # noqa: F401
from .hdf5 import StrainBlock, load_das_data, raw2strain, write_optasense  # noqa: F401
from .interrogators import get_acquisition_parameters  # noqa: F401
from .stream import stream_file_batches, stream_strain_blocks  # noqa: F401


def hello_world_das_package():
    """Smoke-test greeting (reference data_handle.py:21-22)."""
    print("Yepee! You now have access to all the functionalities of the das4whales_tpu package!")
