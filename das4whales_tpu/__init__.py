"""das4whales_tpu — TPU-native DAS bioacoustics framework.

A ground-up JAX/XLA rebuild of the capability surface of DAS4Whales
(github.com/leabouffaut/DAS4Whales): ingest interrogator recordings into a
``[channel x time]`` strain tensor, filter in the frequency-wavenumber
domain, detect baleen-whale calls with four detector families
(matched-filter, spectrogram correlation, Gabor/image), localize sources by
TDOA least squares, and visualize — with jit+vmap kernels instead of
per-channel Python loops and ``jax.sharding`` meshes instead of dask chunks.
"""

__version__ = "0.1.0"

from . import config  # noqa: F401
from . import faults  # noqa: F401
from . import io  # noqa: F401
from . import loc  # noqa: F401
from . import ops  # noqa: F401
from . import models  # noqa: F401
from . import utils  # noqa: F401
from .config import AcquisitionMetadata, ChannelSelection  # noqa: F401


def __getattr__(name):
    # viz needs matplotlib (an optional extra); load it on first use so a
    # base install can run detection/localization headless. eval/parallel/
    # workflows load lazily to keep plain-kernel imports light.
    if name in ("viz", "parallel", "workflows", "eval", "service"):
        import importlib

        module = importlib.import_module(f".{name}", __name__)
        globals()[name] = module
        return module
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
