"""Detection-quality evaluation: injection recall, precision, SNR sweeps.

The reference has no quantitative detection-quality harness at all — its
integration story is eyeballing waterfall plots of one live OOI file
(SURVEY.md §4, `scripts/main_mfdetect.py:106`). A user tuning thresholds,
speed fans, or templates has no way to ask "what fraction of calls does
this configuration actually recover, at what false-alarm rate?". This
module answers that with synthetic ground truth:

* `io.synth.SyntheticScene` renders propagating calls with known
  (channel, arrival-time) footprints;
* `match_picks` scores a detector's (channel, time) picks against those
  footprints — per-(call, channel) hits, misses, and unmatched picks;
* `evaluate_detector` / `amplitude_sweep` turn that into recall,
  precision, and false alarms per channel-minute across an
  amplitude (SNR) grid — the detection-performance curve.

Everything here is host-side numpy orchestration around the jitted
detector: the device work is exactly the production detection path, so
the sweep doubles as an end-to-end regression harness.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, Sequence

import numpy as np

from .config import as_metadata
from .io.synth import SyntheticCall, SyntheticScene, synthesize_scene


def arrival_times(call: SyntheticCall, scene: SyntheticScene) -> np.ndarray:
    """Per-channel arrival time [s] of ``call`` in ``scene``'s geometry —
    the ground-truth footprint (mirrors ``io.synth.synthesize_scene``'s
    injection delays, which mirror ``loc.calc_arrival_times``)."""
    x = np.arange(scene.nx) * scene.dx
    slant = np.sqrt((x - call.x0_m) ** 2 + call.y0_m ** 2 + call.z0_m ** 2)
    return call.t0 + slant / call.speed


def scene_cable_positions(scene: SyntheticScene) -> np.ndarray:
    """``[nx, 3]`` cable coordinates of the scene's straight fiber
    (along x at y = z = 0) — the geometry the localizer consumes."""
    pos = np.zeros((scene.nx, 3))
    pos[:, 0] = np.arange(scene.nx) * scene.dx
    return pos


def localize_scene_call(
    picks: np.ndarray,
    scene: SyntheticScene,
    call_index: int = 0,
    gate_s: float = 1.0,
    n_iter: int = 30,
    fix_z: bool = True,
):
    """Close the science loop: detector picks -> per-channel TDOA ->
    Gauss-Newton source localization for one scene call.

    Picks are gated to within ``gate_s`` of the call's ground-truth
    moveout (the eval-side stand-in for the pick clustering a user does
    on real data), reduced to the earliest pick per channel, and handed
    to ``loc.localize`` with the scene's straight-cable geometry. Returns
    the ``loc.LocalizationResult``; ground truth for assertions is
    ``(call.x0_m, call.y0_m, call.z0_m, call.t0)``.
    """
    from . import loc

    call = scene.calls[call_index]
    expected = arrival_times(call, scene)
    ch = np.asarray(picks[0], dtype=int)
    t = np.asarray(picks[1], dtype=float) / scene.fs
    keep = np.abs(t - expected[ch]) <= gate_s
    ti = np.full(scene.nx, np.nan)
    for c, tt in zip(ch[keep], t[keep]):
        if not np.isfinite(ti[c]) or tt < ti[c]:
            ti[c] = tt
    cable = scene_cable_positions(scene)
    # neutral start: mid-cable, slightly off-axis (the exact on-axis start
    # is a stationary point of the y derivative), earliest gated arrival
    guess = [
        float(np.mean(cable[:, 0])),
        max(50.0, 2 * scene.dx),
        call.z0_m if fix_z else -10.0,
        float(np.nanmin(ti)) - 0.05,
    ]
    return loc.localize(
        ti, cable, call.speed, n_iter=n_iter, fix_z=fix_z, initial_guess=guess
    )


@dataclass
class PickMatch:
    """Result of scoring one template's picks against scene ground truth."""

    hits: np.ndarray          # [n_calls, n_channels] bool — call footprint picked
    covered: np.ndarray       # [n_calls, n_channels] bool — footprint inside record
    n_false: int              # picks matching no call footprint
    n_picks: int

    @property
    def recall(self) -> float:
        n_cov = int(self.covered.sum())
        return float(self.hits.sum() / n_cov) if n_cov else float("nan")

    @property
    def precision(self) -> float:
        return float((self.n_picks - self.n_false) / self.n_picks) if self.n_picks else float("nan")


def match_picks(
    picks: np.ndarray,
    scene: SyntheticScene,
    time_tol_s: float = 0.3,
    call_indices: Sequence[int] | None = None,
) -> PickMatch:
    """Score ``picks`` (a ``(2, n)`` [channel_idx, time_idx] array, the
    detector output convention of detect.py:277-303) against call
    footprints in ``scene``.

    A (call, channel) cell counts as hit when any pick on that channel
    falls within ``time_tol_s`` of the call's arrival there (the arrival
    is the template *onset*; correlator peaks land within the template
    support, so the default tolerance is about half the 0.68 s call).

    ``call_indices`` restricts the recall accounting (hits/covered) to
    those calls — used when a template should only be credited for its
    own note type. False-pick accounting always runs against EVERY call:
    a pick on another template's call is a cross-template response, not a
    false alarm.
    """
    picks = np.asarray(picks)
    n_calls = len(scene.calls)
    sel = set(range(n_calls)) if call_indices is None else set(call_indices)
    hits = np.zeros((len(sel), scene.nx), dtype=bool)
    covered = np.zeros((len(sel), scene.nx), dtype=bool)
    tol = time_tol_s * scene.fs

    pick_t = [picks[1][picks[0] == ch] for ch in range(scene.nx)]
    matched_any = [np.zeros(t.shape, dtype=bool) for t in pick_t]
    row = 0
    for ci, call in enumerate(scene.calls):
        onsets = arrival_times(call, scene) * scene.fs
        L = call.duration * scene.fs
        cov = (onsets >= 0) & (onsets + L <= scene.ns)
        scored = ci in sel
        if scored:
            covered[row] = cov
        for ch in range(scene.nx):
            if not cov[ch] or pick_t[ch].size == 0:
                continue
            near = np.abs(pick_t[ch] - onsets[ch]) <= tol
            if near.any():
                matched_any[ch] |= near
                if scored:
                    hits[row, ch] = True
        if scored:
            row += 1
    n_picks = int(picks.shape[1])
    n_false = int(n_picks - sum(int(m.sum()) for m in matched_any))
    return PickMatch(hits=hits, covered=covered, n_false=n_false, n_picks=n_picks)


def _call_groups(scene: SyntheticScene) -> Dict[tuple, list]:
    """Scene calls grouped by (fmin, fmax, duration) — one group per
    distinct note type."""
    groups: Dict[tuple, list] = {}
    for ci, call in enumerate(scene.calls):
        groups.setdefault((call.fmin, call.fmax, call.duration), []).append(ci)
    return groups


def _calls_for_template(cfg, scene: SyntheticScene) -> list:
    """Indices of the scene call group nearest a template's chirp
    parameters — the auto-association behind per-template recall.

    ``cfg`` is a ``CallTemplateConfig`` (fmin/fmax/duration) or a
    spectro-kernel dict (f0/f1/dur, reference ``detect.buildkernel``
    convention of swept-down contours, detect.py:411-492). Exact matches
    win trivially; for kernels whose contour only approximates the call
    band (e.g. the 27->17 Hz hat kernel vs the 28.8->17.8 Hz note) the
    nearest distinct group is chosen, so every template/kernel is scored
    against exactly one note type. Empty only when the scene has no calls.
    """
    if isinstance(cfg, dict):
        fmin = min(cfg["f0"], cfg["f1"])
        fmax = max(cfg["f0"], cfg["f1"])
        dur = cfg["dur"]
    else:
        fmin, fmax, dur = cfg.fmin, cfg.fmax, cfg.duration
    groups = _call_groups(scene)
    if not groups:
        return []
    key = min(
        groups,
        key=lambda g: abs(g[0] - fmin) + abs(g[1] - fmax) + 10.0 * abs(g[2] - dur),
    )
    return groups[key]


def evaluate_detector(
    detector, scene: SyntheticScene, time_tol_s: float = 0.3,
) -> Dict[str, dict]:
    """Run ``detector`` (a ``models.matched_filter.MatchedFilterDetector``
    or any callable returning ``.picks``) on the rendered scene and score
    every template's picks. Returns per-template metric dicts."""
    import jax.numpy as jnp

    block = synthesize_scene(scene)
    result = detector(jnp.asarray(block, dtype=jnp.float32))
    out = {}
    minutes = scene.ns / scene.fs / 60.0
    cfgs = getattr(detector, "template_configs", None) or {}
    for name, picks in result.picks.items():
        indices = _calls_for_template(cfgs[name], scene) if name in cfgs else []
        m = match_picks(picks, scene, time_tol_s,
                        call_indices=indices or None)
        out[name] = {
            "recall": m.recall,
            "precision": m.precision,
            "n_picks": m.n_picks,
            "n_false": m.n_false,
            "false_per_channel_minute": m.n_false / (scene.nx * minutes),
        }
    return out


def amplitude_sweep(
    detector,
    base_scene: SyntheticScene,
    amplitudes: Sequence[float],
    seeds: Sequence[int] = (0,),
    time_tol_s: float = 0.3,
) -> list:
    """Detection-performance curve: re-render ``base_scene`` at each call
    amplitude (noise RMS fixed, so amplitude IS the SNR knob) x seed, run
    the detector, and average recall/precision per amplitude.

    Returns rows ``{"amplitude", "snr_db", <template>: {recall, ...}}``
    sorted by amplitude. The detector is reused across the whole sweep —
    one compile, many scenes (the design-once/apply-many pattern,
    tutorial.md:93).
    """
    rows = []
    for amp in amplitudes:
        per_template: Dict[str, list] = {}
        for seed in seeds:
            scene = replace(
                base_scene,
                seed=seed,
                calls=[replace(c, amplitude=amp) for c in base_scene.calls],
            )
            for name, metrics in evaluate_detector(detector, scene, time_tol_s).items():
                per_template.setdefault(name, []).append(metrics)
        row = {
            "amplitude": float(amp),
            "snr_db": float(20 * np.log10(amp / base_scene.noise_rms)),
        }
        for name, ms in per_template.items():
            row[name] = {
                k: float(np.nanmean([m[k] for m in ms]))
                for k in ("recall", "precision", "false_per_channel_minute")
            }
        rows.append(row)
    return rows


@dataclass
class _EvalResult:
    picks: Dict[str, np.ndarray]
    #: per-template effective thresholds when the family exposes them;
    #: None means ABSENT (the campaign records NaN placeholders —
    #: workflows.planner.thresholds_for documents the absent-vs-empty
    #: distinction)
    thresholds: Dict[str, float] | None = None


class SpectroEvalAdapter:
    """Adapts the spectrogram-correlation family to the
    ``evaluate_detector`` protocol, enabling cross-family comparisons
    (matched filter vs spectro correlation at the same SNR — a question
    the reference cannot ask).

    ``prefilter`` supplies the bandpass + f-k front end the spectro
    workflow shares with the flagship (main_spectrodetect.py:7-55): a
    ``MatchedFilterDetector`` (its ``filter_block``) or any callable
    mapping a block to ``trf_fk``. Spectro pick times are converted from
    spectrogram-hop units back to sample units (the inverse of the
    workflow's ``spectro_fs`` rescale, main_spectrodetect.py:123).
    """

    def __init__(self, prefilter, spectro_detector):
        self.prefilter = prefilter
        self.det = spectro_detector
        self.template_configs = dict(spectro_detector.kernels)

    def __call__(self, block, threshold: float | None = None):
        filt = getattr(self.prefilter, "filter_block", self.prefilter)
        trf_fk = filt(block)
        if threshold is None:
            _, picks, spectro_fs = self.det(trf_fk)
        else:
            # sweep support: the spectro family's absolute threshold is
            # exactly the knob eval.threshold_sweep varies
            saved = self.det.threshold
            try:
                self.det.threshold = float(threshold)
                _, picks, spectro_fs = self.det(trf_fk)
            finally:
                self.det.threshold = saved
        fs = self.det.metadata.fs
        out = {}
        for name, pk in picks.items():
            pk = np.asarray(pk)
            t_samples = np.round(pk[1] * (fs / spectro_fs)).astype(int)
            out[name] = np.asarray([pk[0], t_samples])
        # the family's absolute correlogram threshold (one knob serves
        # every kernel — main_spectrodetect.py:118-121)
        thr = float(self.det.threshold if threshold is None else threshold)
        return _EvalResult(picks=out, thresholds={name: thr for name in out})


def sharded_picks_to_dict(
    sp_picks, template_names, file_index: int = 0, n_samples: int | None = None,
) -> Dict[str, np.ndarray]:
    """One file's picks from a sharded detection step's ``SparsePicks``
    (``[n_templates, file, channel, K]`` arrays,
    ``parallel.pipeline.make_sharded_mf_step``) -> the ``{name: (2, n)}``
    dict the scoring functions consume. ``n_samples`` drops picks inside
    any divisibility padding (same policy as ``workflows.longrecord``)."""
    from .ops import peaks as peak_ops

    pos = np.asarray(sp_picks.positions)
    sel = np.asarray(sp_picks.selected)
    out = {}
    for i, name in enumerate(template_names):
        s = sel[i, file_index]
        if n_samples is not None:
            s = s & (pos[i, file_index] < n_samples)
        out[name] = peak_ops.sparse_to_pick_times(pos[i, file_index], s)
    return out


class GaborEvalAdapter:
    """Adapts the Gabor/image-processing family to the
    ``evaluate_detector`` protocol — third detector family on the same
    metrics, completing the cross-family comparison matrix.

    ``prefilter`` is the shared bandpass + f-k front end
    (main_gabordetect.py:10-74); the Gabor detector's picks are already
    in sample units, so only template association needs adapting (its
    notes are (fmin, fmax, duration) tuples)."""

    def __init__(self, prefilter, gabor_detector):
        self.prefilter = prefilter
        self.det = gabor_detector
        self.template_configs = {
            name: {"f0": fmax, "f1": fmin, "dur": dur}
            for name, (fmin, fmax, dur) in gabor_detector.note_params.items()
        }

    def __call__(self, block, threshold: float | None = None):
        filt = getattr(self.prefilter, "filter_block", self.prefilter)
        out = self.det(filt(block), threshold=threshold)
        return _EvalResult(
            picks={k: np.asarray(v) for k, v in out["picks"].items()},
            thresholds=out.get("thresholds"),
        )


def threshold_sweep(
    detector,
    scene: SyntheticScene,
    thresholds: Sequence[float],
    time_tol_s: float = 0.3,
) -> list:
    """Operating curve over the pick threshold: recall/precision/false
    rate per template at each absolute threshold (the detector's
    ``threshold`` override replaces the reference's fixed 0.5·max policy,
    main_mfdetect.py:94). One rendered scene, one compiled detector, many
    thresholds — the tuning loop a practitioner actually runs."""
    import jax.numpy as jnp

    block = jnp.asarray(synthesize_scene(scene), dtype=jnp.float32)
    cfgs = getattr(detector, "template_configs", None) or {}
    minutes = scene.ns / scene.fs / 60.0
    rows = []
    for thr in thresholds:
        result = detector(block, threshold=float(thr))
        row = {"threshold": float(thr)}
        for name, picks in result.picks.items():
            indices = _calls_for_template(cfgs[name], scene) if name in cfgs else []
            m = match_picks(picks, scene, time_tol_s, call_indices=indices or None)
            row[name] = {
                "recall": m.recall,
                "precision": m.precision,
                "false_per_channel_minute": m.n_false / (scene.nx * minutes),
            }
        rows.append(row)
    return rows


def default_eval_scene(nx: int = 256, ns: int = 6000) -> SyntheticScene:
    """A standard evaluation scene: three fin-call pairs (HF + LF note
    shapes) at staggered times/positions across the array, matching the
    template defaults (config.FIN_HF_NOTE / FIN_LF_NOTE)."""
    calls = []
    dx = 2.042
    for k, t0 in enumerate((4.0, 12.0, 21.0)):
        x0 = (0.25 + 0.25 * k) * nx * dx
        calls.append(SyntheticCall(t0=t0, x0_m=x0, fmin=17.8, fmax=28.8,
                                   duration=0.68, amplitude=1.0))
        calls.append(SyntheticCall(t0=t0 + 2.0, x0_m=x0, fmin=14.7, fmax=21.8,
                                   duration=0.78, amplitude=1.0))
    return SyntheticScene(nx=nx, ns=ns, dx=dx, noise_rms=0.05, calls=calls)
