"""Runtime complement to the concurrency rules: seeded interleaving.

The static pass (R8–R10, ``analysis/concurrency.py``) proves lock
discipline by shape; this guard shakes the SCHEDULE. Inside a
:func:`race_guard` region:

* ``sys.setswitchinterval`` shrinks to a seeded tiny value, so the
  interpreter preempts threads hundreds of times more often than the
  5 ms production default — orderings that happen once a week in
  production happen every run;
* every :class:`~das4whales_tpu.utils.locks.TracedLock` acquisition
  gets a seeded yield point (``time.sleep(0)`` by a per-seed coin), so
  contended critical sections interleave differently per seed — the
  service stack's locks are all TracedLocks
  (``utils.locks.new_lock``), so the whole serving surface is
  instrumented for free;
* the process-wide lock-ORDER graph resets on entry; on clean exit the
  guard FAILS with :class:`LockOrderError` if any acquisition inverted
  the established order (the dynamic form of R9's cycle check), and
  with :class:`TornIterationError` if any thread died of the classic
  torn-iteration ``RuntimeError: ... changed size during iteration``
  (R8's hazard, observed live via ``threading.excepthook``).

The ``race_guard`` pytest fixture (``analysis/pytest_plugin.py``) hands
tests this context manager — the analog of ``compile_guard`` for the
concurrency half. THE acceptance drill (tests/test_service.py) re-runs
the two-tenant chaos service under it with ``/tenants`` + ``/metrics``
+ ``/picks`` polled hot from several client threads.
"""

from __future__ import annotations

import contextlib
import random
import sys
import threading
import time
from typing import List, Optional


class LockOrderError(AssertionError):
    """A traced-lock acquisition inverted the established lock order —
    the dynamic witness of an R9 ``lock-order`` hazard."""


class TornIterationError(AssertionError):
    """A thread died iterating a structure another thread mutated —
    the dynamic witness of an R8 ``unguarded-snapshot-read`` hazard."""


class GuardReport:
    """Live view handed to the guarded block: the recorded inversions
    and thread exceptions so far (for mid-drill assertions)."""

    def __init__(self):
        self.thread_errors: List[threading.ExceptHookArgs] = []

    @staticmethod
    def inversions() -> List[dict]:
        from ..utils import locks

        return locks.inversions()


def _is_torn_iteration(exc: BaseException) -> bool:
    return (isinstance(exc, RuntimeError)
            and "changed size during iteration" in str(exc))


@contextlib.contextmanager
def race_guard(seed: int = 0, switch_interval: Optional[float] = None,
               yield_prob: float = 0.05):
    """Run the block under seeded interleaving pressure; fail on lock
    order inversions or torn iterations observed anywhere in the
    process. ``switch_interval=None`` derives a tiny seeded value
    (~50–100 µs; the production default is 5 ms)."""
    from ..utils import locks

    rng = random.Random(seed)
    if switch_interval is None:
        switch_interval = 5e-5 * (1.0 + rng.random())
    old_interval = sys.getswitchinterval()
    report = GuardReport()
    old_hook = threading.excepthook

    def hook(args):
        report.thread_errors.append(args)
        old_hook(args)

    # random.Random is effectively atomic per call under the GIL; the
    # coin only has to be SEEDED, not precisely sequenced per thread
    def maybe_yield():
        if rng.random() < yield_prob:
            time.sleep(0)

    locks.reset_order_graph()
    locks.set_yield(maybe_yield)
    sys.setswitchinterval(switch_interval)
    threading.excepthook = hook
    try:
        yield report
    finally:
        threading.excepthook = old_hook
        sys.setswitchinterval(old_interval)
        locks.set_yield(None)
    # reached only when the block itself exited cleanly
    inv = locks.inversions()
    if inv:
        detail = "; ".join(
            f"{' -> '.join(i['cycle'])} (thread {i['thread']})"
            for i in inv[:4]
        )
        raise LockOrderError(
            f"race_guard(seed={seed}): {len(inv)} lock-order "
            f"inversion(s) recorded — {detail}. Two threads taking these "
            "locks from opposite ends deadlock; impose one global order "
            "(see docs/STATIC_ANALYSIS.md R9)."
        )
    torn = [e for e in report.thread_errors
            if e.exc_value is not None and _is_torn_iteration(e.exc_value)]
    if torn:
        raise TornIterationError(
            f"race_guard(seed={seed}): {len(torn)} thread(s) died of a "
            f"torn iteration: {torn[0].exc_value} in thread "
            f"{getattr(torn[0].thread, 'name', '?')} — snapshot under a "
            "shared lock or copy-on-read (docs/STATIC_ANALYSIS.md R8)."
        )
