"""daslint CLI: ``python -m das4whales_tpu.analysis [paths ...]``.

Exit codes: 0 clean (every finding baselined or none), 1 findings above
the baseline, 2 usage/baseline errors. Findings print as
``path:line:col: RULE[code] message (in symbol)`` — editor/CI clickable.

Examples::

    python -m das4whales_tpu.analysis                    # lint the package
    python -m das4whales_tpu.analysis das4whales_tpu/ops # one subtree
    python -m das4whales_tpu.analysis --rules R2 scratch.py
    python -m das4whales_tpu.analysis --write-baseline   # regenerate ledger
    python -m das4whales_tpu.analysis --check            # CI/lint entry
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import DEFAULT_BASELINE, baseline as baseline_mod
from .rules import ALL_RULES, analyze_paths


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m das4whales_tpu.analysis",
        description="daslint: JAX/TPU hazard analyzer (rules R1-R5; see "
                    "docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "installed das4whales_tpu package)")
    ap.add_argument("--rules", default=",".join(ALL_RULES),
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "(preserves reasons of persisting entries) and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--check", action="store_true",
                    help="lint-gate mode (the default behavior, spelled "
                         "explicitly for CI entry points); also prints a "
                         "summary line")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = tuple(r.strip().upper() for r in args.rules.split(",") if r.strip())
    bad = [r for r in rules if r not in ALL_RULES]
    if bad:
        print(f"unknown rule(s): {', '.join(bad)} (have {', '.join(ALL_RULES)})",
              file=sys.stderr)
        return 2

    paths = args.paths or [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    findings = analyze_paths(paths, rules)
    syntax_errors = [f for f in findings if f.rule == "E0"]
    findings = [f for f in findings if f.rule != "E0"]

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        # regeneration only replaces what this invocation actually scanned
        # — entries for unscanned files or unselected rules are carried
        # over, so a partial `--rules`/path run cannot wipe the ledger
        merged = list(findings)
        reasons = {}
        if os.path.exists(baseline_path):
            from .rules import canonical_path, iter_python_files
            scanned = {canonical_path(p) for p in iter_python_files(paths)}
            try:
                with open(baseline_path, "r", encoding="utf-8") as fh:
                    entries = baseline_mod.parse(fh.read())
            except baseline_mod.BaselineError as exc:
                print(f"daslint: {exc}", file=sys.stderr)
                return 2
            kept = [e for e in entries
                    if str(e.get("path")) not in scanned
                    or str(e.get("rule")) not in rules]
            carried, reasons = baseline_mod.entries_as_findings(kept)
            merged += carried
            reasons.update(baseline_mod.reasons_of(baseline_path))
        with open(baseline_path, "w", encoding="utf-8") as fh:
            fh.write(baseline_mod.dump(merged, reasons))
        print(f"wrote {baseline_path} ({len(merged)} findings baselined)",
              file=sys.stderr)
        return 0

    if args.no_baseline or not os.path.exists(baseline_path):
        new, suppressed = findings, []
        new = sorted(new, key=lambda f: (f.path, f.line, f.col))
    else:
        try:
            bl = baseline_mod.load(baseline_path)
        except baseline_mod.BaselineError as exc:
            print(f"daslint: {exc}", file=sys.stderr)
            return 2
        new, suppressed = baseline_mod.apply(findings, bl)

    new = syntax_errors + new
    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        for f in new:
            print(f.format())
    if args.check or not args.as_json:
        print(f"daslint: {len(new)} finding(s), {len(suppressed)} baselined, "
              f"rules {','.join(rules)}", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
