"""daslint CLI: ``python -m das4whales_tpu.analysis [paths ...]``.

Exit codes: 0 clean (every finding baselined or none), 1 findings above
the baseline, 2 usage/baseline errors. Findings print as
``path:line:col: RULE[code] message (in symbol)`` — editor/CI clickable.

Examples::

    python -m das4whales_tpu.analysis                    # lint the package
    python -m das4whales_tpu.analysis das4whales_tpu/ops # one subtree
    python -m das4whales_tpu.analysis --rules R2 scratch.py
    python -m das4whales_tpu.analysis --write-baseline   # regenerate ledger
    python -m das4whales_tpu.analysis --check            # CI/lint entry
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import DEFAULT_BASELINE, baseline as baseline_mod
from .rules import ALL_RULES, PROGRAM_RULES, analyze_paths


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m das4whales_tpu.analysis",
        description="daslint: JAX/TPU hazard analyzer (rules R1-R13; see "
                    "docs/STATIC_ANALYSIS.md)",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "installed das4whales_tpu package)")
    ap.add_argument("--rules", default=",".join(ALL_RULES),
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "(preserves reasons of persisting entries) and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--check", action="store_true",
                    help="lint-gate mode (the default behavior, spelled "
                         "explicitly for CI entry points); also prints a "
                         "summary line and fails on stale baseline entries")
    ap.add_argument("--programs", action="store_true",
                    help="also run the R11-R13 program-contract audit over "
                         "the canonical compiled variants (imports jax, one "
                         "AOT compile per variant — the full-gate path; "
                         "omitted by the --changed AST-only fast path)")
    ap.add_argument("--write-contracts", action="store_true",
                    help="regenerate analysis/contracts.json (the R13 "
                         "op-count snapshot) from the canonical variants "
                         "and exit 0")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = tuple(r.strip().upper() for r in args.rules.split(",") if r.strip())
    bad = [r for r in rules if r not in ALL_RULES]
    if bad:
        print(f"unknown rule(s): {', '.join(bad)} (have {', '.join(ALL_RULES)})",
              file=sys.stderr)
        return 2

    if args.write_contracts:
        from . import programs as programs_mod

        import jax  # deferred: the AST paths never pay this import

        artifacts = programs_mod.canonical_artifacts()
        snap = programs_mod.build_contracts(
            artifacts, backend=jax.default_backend(), jax_version=jax.__version__)
        with open(programs_mod.DEFAULT_CONTRACTS, "w", encoding="utf-8") as fh:
            fh.write(programs_mod.dump_contracts(snap))
        print(f"wrote {programs_mod.DEFAULT_CONTRACTS} "
              f"({len(snap['programs'])} program contracts)", file=sys.stderr)
        return 0

    paths = args.paths or [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    findings = analyze_paths(paths, rules)
    syntax_errors = [f for f in findings if f.rule == "E0"]
    findings = [f for f in findings if f.rule != "E0"]

    program_rules = tuple(r for r in rules if r in PROGRAM_RULES)
    if args.programs and program_rules:
        # the jax-importing half: audit the canonical compiled variants
        # (one AOT compile each; the audit itself is pure text). The
        # --changed fast path never passes --programs — documented in
        # scripts/lint.py and docs/STATIC_ANALYSIS.md.
        from . import programs as programs_mod

        findings += programs_mod.audit_canonical(program_rules)

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        # regeneration only replaces what this invocation actually scanned
        # — entries for unscanned files or unselected rules are carried
        # over, so a partial `--rules`/path run cannot wipe the ledger
        merged = list(findings)
        reasons = {}
        if os.path.exists(baseline_path):
            from .rules import canonical_path, iter_python_files
            scanned = {canonical_path(p) for p in iter_python_files(paths)}
            try:
                with open(baseline_path, "r", encoding="utf-8") as fh:
                    entries = baseline_mod.parse(fh.read())
            except baseline_mod.BaselineError as exc:
                print(f"daslint: {exc}", file=sys.stderr)
                return 2
            kept = [e for e in entries
                    if str(e.get("path")) not in scanned
                    or str(e.get("rule")) not in rules]
            carried, reasons = baseline_mod.entries_as_findings(kept)
            merged += carried
            reasons.update(baseline_mod.reasons_of(baseline_path))
        with open(baseline_path, "w", encoding="utf-8") as fh:
            fh.write(baseline_mod.dump(merged, reasons))
        print(f"wrote {baseline_path} ({len(merged)} findings baselined)",
              file=sys.stderr)
        return 0

    stale = []
    if args.no_baseline or not os.path.exists(baseline_path):
        new, suppressed = findings, []
        new = sorted(new, key=lambda f: (f.path, f.line, f.col))
    else:
        try:
            bl = baseline_mod.load(baseline_path)
        except baseline_mod.BaselineError as exc:
            print(f"daslint: {exc}", file=sys.stderr)
            return 2
        new, suppressed = baseline_mod.apply(findings, bl)
        if args.check:
            # stale-ledger gate (ISSUE 16 satellite): a baselined key
            # with no live finding site is a fixed hazard whose entry
            # can silently mask its return. Scoped to what THIS run
            # scanned — a --changed/--rules subset judges nothing else.
            from .rules import canonical_path, iter_python_files

            scanned = {canonical_path(p) for p in iter_python_files(paths)}
            if args.programs:
                scanned |= {path for (_r, path, _s) in bl
                            if path.startswith("program:")}
            stale = baseline_mod.stale_keys(
                findings, bl, scanned_paths=scanned, rules=rules)
            for rule, path, symbol in stale:
                print(f"{path}: stale baseline entry (remove me): {rule} "
                      f"for symbol `{symbol}` no longer matches any "
                      "finding site")

    new = syntax_errors + new
    if args.as_json:
        print(json.dumps({
            "findings": [vars(f) for f in new],
            "suppressed": len(suppressed),
            "stale": [list(k) for k in stale],
        }, indent=1))
    else:
        for f in new:
            print(f.format())
    if args.check or not args.as_json:
        print(f"daslint: {len(new)} finding(s), {len(suppressed)} baselined, "
              f"{len(stale)} stale, rules {','.join(rules)}", file=sys.stderr)
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main())
