"""daslint rule engine — AST hazard analysis for this codebase's JAX idioms.

The TPU port's perf story rests on three invariants that nothing enforced
until now: jitted programs compile once (no silent retraces), data stays on
device inside jitted code (no host syncs), and device paths stay in the
intended dtype (no float64 leaks past the host-side design stage). Each
rule below encodes one of those invariants as a static check over the
Python AST — the same failure modes TINA (arXiv:2408.16551) and the
large-scale DFT work (arXiv:2002.03260) identify as the difference between
accelerator-rate and host-rate DSP.

Rule catalog (see docs/STATIC_ANALYSIS.md for the long-form contract):

R1  host-sync leaks — ``float()``/``int()``/``bool()``/``.item()``/
    ``.tolist()``/``np.asarray()`` applied to tracer-reachable values
    inside a jit-decorated function. Parameters named in
    ``static_argnums``/``static_argnames`` are Python values, not tracers,
    and are exempt, as are shape/dtype/ndim/size attribute reads.
R2  retrace hazards — ``jax.jit`` (or ``functools.partial(jax.jit, ...)``)
    constructed inside a function body (a fresh function object per call is
    a guaranteed cache miss) or inside a loop, plus array-valued
    ``static_argnums``/``static_argnames`` specs (unhashable statics fail
    or retrace per call). Factories whose construction is cached by
    ``functools.lru_cache``/``functools.cache`` are exempt — that is this
    repo's blessed factory idiom (``parallel/fft.py``,
    ``parallel/timeshard.py``).
R3  dtype drift — explicit float64 references (``np.float64``,
    ``jnp.float64``, ``np.double``, ``dtype="float64"``) in the device-path
    packages (``ops/``, ``parallel/``, ``models/``). Host-side filter
    *design* in float64 is the documented contract of ``ops/fk.py`` and
    ``ops/filters.py`` (design-once / apply-many) and stays allowed via
    :data:`FLOAT64_DESIGN_ALLOWLIST`.
R4  ``np.`` calls on tracer-reachable arguments inside jitted functions —
    a silent device→host→device round trip on every call.
R5  donation audit — jitted entry points in ``parallel/`` and
    ``workflows/`` built without ``donate_argnums``/``donate_argnames``.
    Large-buffer steps that cannot donate (parity paths reuse their
    inputs) are recorded in ``analysis/baseline.toml`` with a reason.
R6  sync-in-loop — HOST-side device syncs inside a ``for``/``while``
    body in the device-path packages: ``jax.block_until_ready`` /
    ``jax.device_get`` calls, ``.item()``, and ``np.asarray``/
    ``np.array`` applied to a freshly computed call result (the
    tracer-result heuristic host code admits). One of these per
    iteration serializes the dispatch pipeline — the per-slab sync wall
    BENCH_r05 measured at 97-99%% chip idle; the pipelined-dispatch
    layer (``parallel.dispatch``) exists so hot loops never need one.
    Intentional sites (a drain point, a scalar decision the host must
    make) are baselined with a reason.
R7  unblocked timing — a ``time.perf_counter()`` bracket (``t0 =
    time.perf_counter()`` … ``time.perf_counter() - t0``) enclosing a
    dispatch-suspect call with NO sync (``jax.block_until_ready`` /
    ``jax.device_get`` / a counted ``fetch``/``sync`` / ``np.asarray``
    / ``.item()`` / an in-flight ``.resolve()``) between the two clock
    reads. JAX dispatch is async: such a wall times the LAUNCH, not the
    work — the number is a lie the flight recorder exists to replace
    (``telemetry.trace.timed_best`` is the one blessed timing
    definition; ``telemetry/`` itself is out of scope by construction).
    Intentional sites — walls whose sync happens inside a callee the
    AST cannot see — are baselined with a reason.
R8  unsynchronized-shared-state — a GuardedBy-style pass over the
    THREAD-SPAWNING modules (``service/``, ``telemetry/``,
    ``io/stream.py``, ``io/native.py``, ``parallel/dispatch.py``):
    each class's lock discipline is inferred from the majority of
    attribute accesses that hold ``self._lock``-style locks, the
    unguarded minority is flagged, a ``# daslint: guarded-by[_lock]``
    annotation pins the discipline explicitly, and public snapshot
    methods that Python-iterate an attribute another method mutates
    with no common lock are the torn-iteration clause. Implemented in
    ``analysis/concurrency.py`` (R9/R10 too).
R9  lock-order / blocking-under-lock — the static lock-acquisition
    graph from ``with``-statement nesting (closed over same-namespace
    calls) flags cycles, and dispatch/IO blockers held under a lock
    (``.resolve()``, ``block_until_ready``, ``push_wait``, file
    reads/writes, ``time.sleep``, …) flag the serving path's deadlock
    and tail-latency hazards.
R10 thread-hygiene — ``Condition.wait()`` outside a predicate
    ``while``, ``Event.wait()``/``.join()`` without a timeout in
    service modules, threads/pools spawned without a name, and
    ``time.sleep`` polling where a Condition exists.
R11 dtype-contract — the AST half (``analysis/programs.py``): matmul /
    contraction calls in ``ops/`` without ``preferred_element_type``
    and raw builtin f64 dtypes (``dtype=float``). The HLO half audits
    compiled programs for f64 ops and bf16 outside the gated matmul
    engine at the AOT compile boundary (CLI ``--programs``).
R12 donation-effectiveness — compiled-program audit only: every
    donated operand must appear in the executable's
    ``input_output_alias`` table, else donation silently saved nothing
    and the preflight's admission math is wrong.
R13 program-hygiene — compiled-program audit only: host callbacks on
    the device path, f64 transcendentals, and ``convert``/``transpose``/
    ``copy`` op counts gated against ``analysis/contracts.json``.
R14 non-durable-artifact-write — a direct ``open(.., "w"/"a")`` or
    ``np.savez``/``np.save`` on an artifact-suffixed path literal
    (``.json``/``.jsonl``/``.npz``) outside ``utils/artifacts.py``:
    durable state must flow through the one crash-only write layer
    (atomic tmp+fsync+rename, checksummed bounded-fsync appends —
    docs/ROBUSTNESS.md "Durability contract"), or a SIGKILL mid-write
    strands a torn artifact. Literal-suffix heuristic: a path built
    purely from variables escapes (``json.dump`` sites are caught
    through the ``open(...)`` that feeds them).
R15 unbounded-subprocess-wait — ``Popen.wait()`` with no timeout, or
    ``.communicate()`` without ``timeout=``: a wedged child blocks the
    caller forever (the fleet supervisor must never hang on a wedged
    worker — ISSUE 20). ``.wait()`` is flagged only on receivers whose
    name reads process-ish (``proc``/``popen``/``child``/``worker``),
    so ``Event.wait()``/``Condition.wait()`` stay R10's business.

Suppression: an inline ``# daslint: allow[R2]`` (comma list, or
``daslint: ignore`` for all rules) on the finding's line or the line above
suppresses it at the source; ``baseline.toml`` suppresses known findings
without touching the code. Both are deliberate, reviewable artifacts.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import PurePosixPath
from typing import Iterable, List, Optional, Sequence, Set, Tuple

ALL_RULES = ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9", "R10",
             "R11", "R12", "R13", "R14", "R15")

#: rules whose primary half runs over COMPILED programs (jax-importing,
#: one AOT compile per audited variant) rather than source text. R11
#: also has the AST sibling below; R12/R13 are program-only — selecting
#: them in a source scan is a no-op by design (`scripts/lint.py
#: --changed`, the AST-only fast path).
PROGRAM_RULES = ("R11", "R12", "R13")

#: (path suffix, function name or "*") pairs where explicit float64 is the
#: documented host-side design contract (masks and filter coefficients are
#: designed in float64 numpy once, applied on device in the data dtype).
FLOAT64_DESIGN_ALLOWLIST: Tuple[Tuple[str, str], ...] = (
    ("das4whales_tpu/ops/fk.py", "*"),
    ("das4whales_tpu/ops/filters.py", "*"),
)

#: R14: file suffixes that mark a path literal as a durable artifact,
#: the open() modes that mutate one, and the files exempt from the rule
#: (the durable-write layer itself is where the raw idiom must live).
_ARTIFACT_SUFFIXES = (".json", ".jsonl", ".npz")
_ARTIFACT_WRITE_MODES = frozenset({
    "w", "a", "x", "wt", "at", "xt", "wb", "ab", "xb",
    "w+", "a+", "x+", "w+b", "a+b", "wb+", "ab+",
})
_R14_EXEMPT_SUFFIXES = ("das4whales_tpu/utils/artifacts.py",)

#: R15: receiver names that read as a child process — ``proc.wait()``
#: flags, ``event.wait()`` doesn't (that's R10's business); and the
#: ``.communicate()`` method, which is unambiguously Popen.
_R15_PROC_RECEIVER = re.compile(r"(proc|popen|child|worker)", re.I)

#: Attribute reads that yield Python metadata, not device values — a
#: tracer's ``.shape`` is a static tuple, so ``float(x.shape[0])`` is host
#: arithmetic, not a sync.
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "itemsize"})

#: Builtin casts that force a device→host transfer when fed a tracer-backed
#: value (on concrete arrays they block; under trace they raise — either
#: way the call site is wrong).
_SYNC_CASTS = frozenset({"float", "int", "bool", "complex"})

#: Method calls that synchronize (``.item``) or materialize on host
#: (``.tolist``).
_SYNC_METHODS = frozenset({"item", "tolist"})

#: Path components whose files are device-path scoped for R3.
_R3_SCOPE = frozenset({"ops", "parallel", "models"})

#: Path components scoped for the R5 donation audit.
_R5_SCOPE = frozenset({"parallel", "workflows"})

#: Path components scoped for the R6 sync-in-loop audit (host drivers of
#: device programs; viz/analysis/eval host-only code is exempt).
_R6_SCOPE = frozenset({"ops", "parallel", "models", "workflows", "io"})

#: Host calls that synchronize the device stream when applied to an
#: in-flight array (R6).
_R6_SYNC_FUNCS = frozenset({"jax.block_until_ready", "jax.device_get"})

#: R7 (unblocked timing) shares R6's host-driver scope; ``telemetry/``
#: is outside it by construction (its ``timed_best`` IS the blessed
#: timing definition).
_R7_SCOPE = _R6_SCOPE

#: dotted calls that make a perf_counter bracket honest (the wall ends
#: at a real sync).
_R7_SYNC_DOTTED = frozenset({
    "jax.block_until_ready", "jax.device_get",
    "numpy.asarray", "numpy.array",
})

#: final-attribute calls treated as syncs for R7: the counted dispatch
#: helpers (``parallel.dispatch.fetch``/``sync``), an in-flight
#: handle's ``resolve``, a future's ``result``, scalar ``.item()``,
#: and the jax sync pair however the module object is named.
_R7_SYNC_ATTRS = frozenset({
    "block_until_ready", "device_get", "fetch", "sync", "item",
    "resolve", "result", "asarray", "array",
})

#: bare-name calls that are plain host work, never a device dispatch.
_R7_HOST_CALLS = frozenset({
    "len", "min", "max", "round", "abs", "sum", "int", "float", "str",
    "bool", "repr", "format", "sorted", "list", "dict", "tuple", "set",
    "print", "isinstance", "getattr", "setattr", "hasattr", "range",
    "enumerate", "zip", "map", "filter", "any", "all", "type", "id",
})

_ALLOW_RE = re.compile(r"daslint:\s*(?:allow\[([A-Za-z0-9,\s]+)\]|ignore)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One hazard at one source location."""

    rule: str      # "R1".."R5"
    code: str      # stable slug, e.g. "host-sync-cast"
    path: str      # canonical repo-relative posix path
    line: int      # 1-indexed
    col: int       # 0-indexed
    symbol: str    # enclosing function chain ("a.b") or "<module>"
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: stable across line-number churn."""
        return (self.rule, self.path, self.symbol)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule}[{self.code}] {self.message} (in {self.symbol})")


def canonical_path(path: str) -> str:
    """Normalize to a repo-anchored posix path: everything from the LAST
    ``das4whales_tpu`` component on, so baseline entries match regardless
    of the directory the analyzer was invoked from — including a repo
    checked out into a directory itself named ``das4whales_tpu``."""
    parts = PurePosixPath(str(path).replace("\\", "/")).parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == "das4whales_tpu":
            return str(PurePosixPath(*parts[i:]))
    return str(PurePosixPath(*parts))


def line_allowed(lines: Sequence[str], f: Finding) -> bool:
    """Inline suppression: ``# daslint: allow[R2,...]`` / ``daslint:
    ignore`` on the finding's line, or standalone on the line above
    (shared by the R1–R7 analyzer and the concurrency pass)."""
    for ln in (f.line, f.line - 1):
        if not 1 <= ln <= len(lines):
            continue
        text = lines[ln - 1]
        if ln != f.line and not text.lstrip().startswith("#"):
            # a trailing allow comment licenses ONLY its own line —
            # the line-above form must be a standalone comment, or a
            # suppression would bleed onto the next statement
            continue
        m = _ALLOW_RE.search(text)
        if m:
            if m.group(1) is None:  # daslint: ignore
                return True
            allowed = {r.strip().upper() for r in m.group(1).split(",")}
            if f.rule in allowed:
                return True
    return False


def _in_scope(path: str, scope: frozenset) -> bool:
    return any(part in scope for part in PurePosixPath(path).parts[:-1])


class _Imports:
    """Alias resolution: maps local names to dotted module paths so the
    rules recognize ``np``/``jnp``/``jit``/``partial`` however the file
    spelled its imports."""

    def __init__(self, tree: ast.AST):
        self.modules = {}   # local name -> dotted module ("np" -> "numpy")
        self.names = {}     # local name -> dotted object ("jit" -> "jax.jit")
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.modules[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for a in node.names:
                    self.names[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted path of a Name/Attribute chain, aliases applied."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = node.id
        if head in self.names:
            base = self.names[head]
        elif head in self.modules:
            base = self.modules[head]
        else:
            base = head
        return ".".join([base] + list(reversed(parts)))


def _is_jit(imports: _Imports, node: ast.AST) -> bool:
    """True for the expression ``jax.jit`` (however aliased)."""
    return imports.resolve(node) == "jax.jit"


def _jit_call_info(imports: _Imports, call: ast.Call):
    """If ``call`` constructs a jitted function — ``jax.jit(...)`` or
    ``functools.partial(jax.jit, ...)`` — return its keyword list, else
    None."""
    if _is_jit(imports, call.func):
        return call.keywords
    if imports.resolve(call.func) in ("functools.partial", "partial"):
        if call.args and _is_jit(imports, call.args[0]):
            return call.keywords
    return None


def _decorator_jit(imports: _Imports, fn: ast.FunctionDef):
    """``(keywords, decorator node)`` of a jit decorator on ``fn``, or
    ``(None, None)`` if not jitted. Handles ``@jax.jit``, ``@jit``,
    ``@functools.partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        if _is_jit(imports, dec):
            return [], dec
        if isinstance(dec, ast.Call):
            kws = _jit_call_info(imports, dec)
            if kws is not None:
                return kws, dec
    return None, None


def _decorator_jit_keywords(imports: _Imports, fn: ast.FunctionDef):
    return _decorator_jit(imports, fn)[0]


def _is_cached_factory(imports: _Imports, fn: ast.FunctionDef) -> bool:
    """Functions decorated with functools.lru_cache/functools.cache build
    their jitted program once per distinct config — the repo's blessed
    factory idiom, exempt from R2's in-function-body check."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if imports.resolve(target) in ("functools.lru_cache", "functools.cache",
                                       "lru_cache", "cache"):
            return True
    return False


def _static_param_names(fn: ast.FunctionDef, keywords) -> Set[str]:
    """Parameter names declared static in a jit decorator's
    static_argnums/static_argnames."""
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: Set[str] = set()
    for kw in keywords or []:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    static.add(node.value)
        elif kw.arg == "static_argnums":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value, int):
                    if 0 <= node.value < len(pos):
                        static.add(pos[node.value])
    return static


def _expr_tainted(node: ast.AST, taint: Set[str]) -> bool:
    """Does this expression reach a tracer-typed value? Shape/dtype reads
    and ``len()`` yield Python metadata and cut the taint."""
    if isinstance(node, ast.Name):
        return node.id in taint
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(node.value, taint)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            return False
        return any(_expr_tainted(a, taint) for a in node.args) or any(
            _expr_tainted(kw.value, taint) for kw in node.keywords
        ) or _expr_tainted(node.func, taint)
    if isinstance(node, ast.Constant):
        return False
    return any(_expr_tainted(c, taint) for c in ast.iter_child_nodes(node))


def _float64_nodes(imports: _Imports, node: ast.AST):
    """Yield sub-nodes that explicitly reference float64: ``np.float64`` /
    ``jnp.float64`` / ``np.double`` attributes, and the string constant
    ``"float64"`` when passed as a dtype keyword."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("float64", "double"):
            dotted = imports.resolve(sub)
            if dotted in ("numpy.float64", "numpy.double", "jax.numpy.float64",
                          "jax.numpy.double"):
                yield sub
        elif isinstance(sub, ast.keyword) and sub.arg == "dtype":
            v = sub.value
            if isinstance(v, ast.Constant) and v.value == "float64":
                yield v


class _Analyzer(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: Sequence[str],
                 rules: Sequence[str]):
        self.path = path
        self.lines = source_lines
        self.rules = set(rules)
        self.findings: List[Finding] = []
        self.imports: _Imports = None  # set in run()
        self._fn_stack: List[ast.FunctionDef] = []
        self._loop_depth = 0
        # (fn node, static names, taint set) for the innermost jit scope
        self._jit_stack: List[Tuple[ast.FunctionDef, Set[str], Set[str]]] = []

    # -- plumbing ----------------------------------------------------------

    def run(self, tree: ast.AST) -> List[Finding]:
        self.imports = _Imports(tree)
        self.visit(tree)
        return [f for f in self.findings if not self._line_allowed(f)]

    def _symbol(self) -> str:
        return ".".join(f.name for f in self._fn_stack) or "<module>"

    def _emit(self, rule: str, code: str, node: ast.AST, message: str):
        if rule in self.rules:
            self.findings.append(Finding(
                rule=rule, code=code, path=self.path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0),
                symbol=self._symbol(), message=message,
            ))

    def _line_allowed(self, f: Finding) -> bool:
        return line_allowed(self.lines, f)

    # -- structure ---------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef):
        jit_kws, jit_dec = _decorator_jit(self.imports, node)
        anchor = jit_dec or node
        in_body = bool(self._fn_stack)
        if jit_kws is not None and in_body and "R2" in self.rules:
            # a jit-decorated def inside a function body is a fresh
            # program per enclosing call, same hazard as jax.jit(...)
            if not any(_is_cached_factory(self.imports, f) for f in self._fn_stack):
                self._emit("R2", "jit-in-function-body", anchor,
                           f"`@jit` function `{node.name}` is constructed on "
                           "every enclosing call — each build is a fresh "
                           "function object and a compile-cache miss")
        if jit_kws is not None:
            self._check_static_spec(jit_kws, node)
            self._check_donation(jit_kws, anchor)

        self._fn_stack.append(node)
        if jit_kws is None:
            # R7 runs per HOST function (jit bodies cannot meaningfully
            # read the host clock; R1 owns their sync hazards)
            self._check_unblocked_timing(node)
        if jit_kws is not None:
            params = {a.arg for a in (node.args.posonlyargs + node.args.args
                                      + node.args.kwonlyargs)} - {"self", "cls"}
            static = _static_param_names(node, jit_kws)
            taint = set(params - static)
            self._jit_stack.append((node, static, taint))
            self._walk_jit_body(node.body, taint)
            self._jit_stack.pop()
        else:
            loop_depth, self._loop_depth = self._loop_depth, 0
            self.generic_visit(node)
            self._loop_depth = loop_depth
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_For(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = visit_For
    visit_AsyncFor = visit_For

    def visit_Call(self, node: ast.Call):
        self._check_sync_in_loop(node)
        self._check_artifact_write(node)
        self._check_subprocess_wait(node)
        kws = _jit_call_info(self.imports, node)
        if kws is not None:
            if self._loop_depth and "R2" in self.rules:
                self._emit("R2", "jit-in-loop", node,
                           "`jax.jit` constructed inside a loop — a fresh "
                           "function object per iteration defeats the "
                           "compile cache (hoist the jit out of the loop)")
            elif self._fn_stack and "R2" in self.rules:
                if not any(_is_cached_factory(self.imports, f)
                           for f in self._fn_stack):
                    self._emit("R2", "jit-in-function-body", node,
                               "`jax.jit` constructed inside a function body "
                               "— per-call construction is a compile-cache "
                               "miss; hoist to module level or cache the "
                               "factory with functools.lru_cache")
            self._check_static_spec(kws, node)
            self._check_donation(kws, node)
        self.generic_visit(node)

    # -- rule bodies -------------------------------------------------------

    def _check_artifact_write(self, node: ast.Call):
        """R14: direct writes to artifact-suffixed path LITERALS must
        flow through ``utils.artifacts`` (the one crash-only write
        layer). Heuristic by design: a path assembled purely from
        variables escapes — the rule funnels the common literal idioms
        (``open(os.path.join(outdir, "x.json"), "w")``,
        ``np.savez(f"{outdir}/picks.npz", ...)``) without chasing
        dataflow; ``json.dump`` sites are caught through the ``open``
        that feeds them."""
        if ("R14" not in self.rules
                or self.path.endswith(_R14_EXEMPT_SUFFIXES)):
            return
        dotted = self.imports.resolve(node.func) or ""
        if dotted == "open":
            mode = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "mode"),
                None)
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and mode.value in _ARTIFACT_WRITE_MODES):
                return
            verb = f"open(.., {mode.value!r})"
        elif dotted in ("numpy.savez", "numpy.savez_compressed",
                        "numpy.save"):
            verb = f"{dotted.replace('numpy', 'np', 1)}(..)"
        else:
            return
        path_arg = node.args[0] if node.args else None
        suffix = None
        if path_arg is not None:
            for nd in ast.walk(path_arg):
                if (isinstance(nd, ast.Constant)
                        and isinstance(nd.value, str)):
                    suffix = next((s for s in _ARTIFACT_SUFFIXES
                                   if nd.value.endswith(s)), None)
                    if suffix:
                        break
        if suffix is None:
            return
        self._emit("R14", "non-durable-artifact-write", node,
                   f"direct `{verb}` on a `{suffix}` artifact path — "
                   "durable state must go through utils.artifacts "
                   "(atomic_json/atomic_file/append_record: atomic "
                   "tmp+fsync+rename, checksummed appends), or a crash "
                   "mid-write strands a torn artifact the resume/"
                   "report paths then choke on (docs/ROBUSTNESS.md "
                   "\"Durability contract\")")

    def _check_subprocess_wait(self, node: ast.Call):
        """R15: a child-process wait with no deadline. ``communicate``
        is unambiguously ``Popen``; bare ``wait`` is gated on a
        process-ish receiver name so the threading primitives' waits
        (R10's domain) never double-report. A positional or keyword
        ``timeout`` argument satisfies the rule."""
        if "R15" not in self.rules or not isinstance(node.func,
                                                     ast.Attribute):
            return
        method = node.func.attr
        if method not in ("wait", "communicate"):
            return
        if any(kw.arg == "timeout" for kw in node.keywords):
            return
        recv = node.func.value
        name = (recv.id if isinstance(recv, ast.Name)
                else recv.attr if isinstance(recv, ast.Attribute) else "")
        if method == "wait":
            # Popen.wait(timeout) is positional-or-keyword
            if node.args:
                return
            if not _R15_PROC_RECEIVER.search(name):
                return
        elif method == "communicate" and len(node.args) > 1:
            # communicate(input, timeout): a second positional IS one
            return
        self._emit("R15", "unbounded-subprocess-wait", node,
                   f"`{name or '<expr>'}.{method}()` with no timeout — a "
                   "wedged child process blocks this caller forever; pass "
                   "`timeout=` and handle subprocess.TimeoutExpired (the "
                   "supervisor must outlive any worker it watches, "
                   "docs/FLEET.md)")

    def _check_sync_in_loop(self, node: ast.Call):
        """R6: host-side device syncs inside a for/while body. Runs only
        outside jit bodies (``visit_Call`` never fires inside them — jit
        bodies go through ``_walk_jit_body``, where R1 owns sync
        hazards) and only in the R6-scoped packages."""
        if ("R6" not in self.rules or not self._loop_depth
                or not _in_scope(self.path, _R6_SCOPE)):
            return
        dotted = self.imports.resolve(node.func) or ""
        if dotted in _R6_SYNC_FUNCS:
            self._emit("R6", "sync-in-loop", node,
                       f"`{dotted}` inside a loop body — one device sync "
                       "per iteration serializes the dispatch pipeline; "
                       "dispatch the whole loop's work first (parallel."
                       "dispatch.PipelinedDispatch) and sync once, or "
                       "baseline this as an intentional drain point")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args
              and not node.keywords):
            self._emit("R6", "item-in-loop", node,
                       "`.item()` inside a loop body — a scalar "
                       "device→host round trip per iteration; fetch the "
                       "whole array once outside the loop")
        elif (dotted in ("numpy.asarray", "numpy.array")
              and any(isinstance(a, ast.Call) for a in node.args)):
            # tracer-result heuristic: np.asarray over a FRESH call
            # result in a loop is the classic fetch-per-iteration shape
            # (np.asarray over an existing host array is free and common)
            self._emit("R6", "host-transfer-in-loop", node,
                       f"`{dotted.replace('numpy', 'np', 1)}` over a "
                       "freshly computed result inside a loop body — if "
                       "the callee runs on device this is one "
                       "device→host transfer (and sync) per iteration; "
                       "batch the computation or fetch once after the "
                       "loop")

    def _check_unblocked_timing(self, fn: ast.FunctionDef):
        """R7: a perf_counter bracket timing a dispatch-suspect call
        with no sync between the clock reads (async dispatch makes the
        wall a lie). One function at a time; nodes inside nested defs
        belong to the nested function's own check (they run at ITS call
        time, not between this function's clock reads)."""
        if ("R7" not in self.rules
                or not _in_scope(self.path, _R7_SCOPE)):
            return
        nested_ids: Set[int] = set()
        for nd in ast.walk(fn):
            if (isinstance(nd, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and nd is not fn):
                nested_ids.update(id(sub) for sub in ast.walk(nd))
        own = [n for n in ast.walk(fn) if id(n) not in nested_ids]
        # name -> ALL linenos of `name = time.perf_counter()` (a timer
        # variable reused for sequential brackets must match each delta
        # against its NEAREST preceding assignment, or earlier brackets
        # silently escape the check)
        assigns: dict = {}
        for n in own:
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and isinstance(n.value, ast.Call)
                    and self.imports.resolve(n.value.func)
                    == "time.perf_counter"):
                assigns.setdefault(n.targets[0].id, []).append(n.lineno)
        if not assigns:
            return
        calls = [n for n in own if isinstance(n, ast.Call)]
        for n in own:
            if not (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub)
                    and isinstance(n.right, ast.Name)
                    and n.right.id in assigns
                    and isinstance(n.left, ast.Call)
                    and self.imports.resolve(n.left.func)
                    == "time.perf_counter"):
                continue
            l2 = n.lineno
            starts = [ln for ln in assigns[n.right.id] if ln < l2]
            if not starts:
                continue
            l1 = max(starts)   # the nearest preceding assignment
            suspect, synced = None, False
            for c in calls:
                if not l1 < c.lineno <= l2:
                    continue
                dotted = self.imports.resolve(c.func) or ""
                attr = (c.func.attr
                        if isinstance(c.func, ast.Attribute) else "")
                if dotted.startswith("jax.numpy."):
                    # jnp.asarray/jnp.array are ASYNC device ops, not
                    # syncs — they must not clear the bracket (only
                    # numpy's asarray/array, a host transfer, does)
                    suspect = suspect or c
                    continue
                if dotted in _R7_SYNC_DOTTED or attr in _R7_SYNC_ATTRS:
                    synced = True
                    break
                if dotted == "time.perf_counter":
                    continue
                if isinstance(c.func, ast.Name):
                    if c.func.id not in _R7_HOST_CALLS:
                        suspect = suspect or c   # an opaque callable: may dispatch
                elif (dotted.startswith("jax.") or attr == "launch"
                      or "detect" in attr or "dispatch" in attr):
                    suspect = suspect or c
            if suspect is not None and not synced:
                self._emit(
                    "R7", "unblocked-timing", n,
                    "`time.perf_counter()` bracket times a dispatch-"
                    "suspect call with no block_until_ready/fetch "
                    "between the clock reads — async dispatch makes "
                    "this wall measure the LAUNCH, not the work; sync "
                    "inside the bracket (telemetry.trace.timed_best is "
                    "the blessed pattern) or baseline with the reason "
                    "the sync happens inside a callee",
                )

    def _check_static_spec(self, keywords, anchor):
        """R2: static_argnums/static_argnames specs that are themselves
        arrays or unhashable containers retrace (or fail) per call."""
        if "R2" not in self.rules:
            return
        for kw in keywords or []:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Call):
                    dotted = self.imports.resolve(sub.func) or ""
                    if dotted.startswith(("numpy.", "jax.numpy.")):
                        self._emit("R2", "array-valued-static", kw.value,
                                   f"{kw.arg} built from `{dotted}` — array "
                                   "statics are unhashable and defeat the "
                                   "jit cache")
                        break
                if isinstance(sub, (ast.Dict, ast.Set)):
                    self._emit("R2", "unhashable-static", kw.value,
                               f"{kw.arg} contains an unhashable "
                               "container literal")
                    break

    def _check_donation(self, keywords, anchor):
        """R5: jitted entry points in parallel/ and workflows/ should
        either donate their large input buffers or be baselined with a
        reason (parity paths that reuse inputs cannot donate)."""
        if "R5" not in self.rules or not _in_scope(self.path, _R5_SCOPE):
            return
        kw_names = {kw.arg for kw in keywords or []}
        if not kw_names & {"donate_argnums", "donate_argnames"}:
            self._emit("R5", "jit-missing-donate", anchor,
                       "jitted entry point without donate_argnums/"
                       "donate_argnames — at canonical shapes the undonated "
                       "input doubles peak HBM; donate, or baseline with a "
                       "reason if callers reuse the buffer")

    def _walk_jit_body(self, body, taint: Set[str]):
        """Statement-ordered walk of a jitted function body with forward
        taint propagation (R1/R3/R4 checks)."""
        for stmt in body:
            self._jit_statement(stmt, taint)

    def _jit_statement(self, stmt: ast.stmt, taint: Set[str]):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a jit-decorated def nested inside a jitted body is a fresh
            # program per trace, same hazard as jax.jit(...) in a body
            jit_kws, jit_dec = _decorator_jit(self.imports, stmt)
            if jit_kws is not None:
                if "R2" in self.rules and not any(
                        _is_cached_factory(self.imports, f)
                        for f in self._fn_stack):
                    self._emit("R2", "jit-in-function-body", jit_dec or stmt,
                               f"`@jit` function `{stmt.name}` is constructed "
                               "on every enclosing call — each build is a "
                               "fresh function object and a compile-cache "
                               "miss")
                self._check_static_spec(jit_kws, stmt)
                self._check_donation(jit_kws, jit_dec or stmt)
            # nested defs (lax.fori/scan bodies): their params are tracers
            inner = set(taint) | {a.arg for a in stmt.args.args}
            self._fn_stack.append(stmt)
            self._walk_jit_body(stmt.body, inner)
            self._fn_stack.pop()
            return
        if isinstance(stmt, ast.Assign):
            self._jit_expr(stmt.value, taint)
            if _expr_tainted(stmt.value, taint):
                for tgt in stmt.targets:
                    for name in ast.walk(tgt):
                        if isinstance(name, ast.Name):
                            taint.add(name.id)
            return
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._jit_expr(stmt.value, taint)
                if _expr_tainted(stmt.value, taint):
                    for name in ast.walk(stmt.target):
                        if isinstance(name, ast.Name):
                            taint.add(name.id)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._jit_expr(stmt.iter, taint)
            if _expr_tainted(stmt.iter, taint):
                for name in ast.walk(stmt.target):
                    if isinstance(name, ast.Name):
                        taint.add(name.id)
            self._walk_jit_body(stmt.body, taint)
            self._walk_jit_body(stmt.orelse, taint)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._jit_expr(stmt.test, taint)
            self._walk_jit_body(stmt.body, taint)
            self._walk_jit_body(stmt.orelse, taint)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._jit_expr(item.context_expr, taint)
            self._walk_jit_body(stmt.body, taint)
            return
        if isinstance(stmt, ast.Try):
            self._walk_jit_body(stmt.body, taint)
            for h in stmt.handlers:
                self._walk_jit_body(h.body, taint)
            self._walk_jit_body(stmt.orelse, taint)
            self._walk_jit_body(stmt.finalbody, taint)
            return
        # Return / Expr / Assert / Raise / Delete: check embedded exprs
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._jit_expr(child, taint)

    def _jit_expr(self, expr: ast.expr, taint: Set[str]):
        """R1/R3/R4 checks over one expression inside a jitted body."""
        scoped_r3 = "R3" in self.rules and _in_scope(self.path, _R3_SCOPE)
        if scoped_r3 and not self._float64_allowed():
            for node in _float64_nodes(self.imports, expr):
                self._emit("R3", "float64-in-device-path", node,
                           "explicit float64 inside a jitted device path — "
                           "design host-side and cast to the data dtype, or "
                           "allowlist the design function")
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            kws = _jit_call_info(self.imports, node)
            if kws is not None:
                # jax.jit constructed inside a jitted body: a fresh program
                # per enclosing trace (R2), plus the usual spec audits
                if "R2" in self.rules and not any(
                        _is_cached_factory(self.imports, f)
                        for f in self._fn_stack):
                    self._emit("R2", "jit-in-function-body", node,
                               "`jax.jit` constructed inside a jitted "
                               "function body — per-call construction is a "
                               "compile-cache miss; hoist to module level "
                               "or cache the factory with "
                               "functools.lru_cache")
                self._check_static_spec(kws, node)
                self._check_donation(kws, node)
            func = node.func
            args_tainted = any(_expr_tainted(a, taint) for a in node.args)
            if isinstance(func, ast.Name) and func.id in _SYNC_CASTS:
                if func.id not in self.imports.names and args_tainted:
                    self._emit("R1", "host-sync-cast", node,
                               f"`{func.id}()` on a traced value forces a "
                               "device→host sync (or a trace error) — use "
                               "jnp ops and keep the value on device")
                continue
            if (isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS
                    and _expr_tainted(func.value, taint)):
                self._emit("R1", "host-sync-item", node,
                           f"`.{func.attr}()` on a traced value "
                           "synchronizes the device stream")
                continue
            dotted = self.imports.resolve(func) or ""
            if dotted in ("numpy.asarray", "numpy.array") and args_tainted:
                self._emit("R1", "host-transfer-np-asarray", node,
                           f"`{dotted.replace('numpy', 'np')}` on a traced "
                           "value copies device→host — use jnp.asarray")
            elif dotted.startswith("numpy.") and args_tainted:
                self._emit("R4", "np-call-on-tracer", node,
                           f"`{dotted.replace('numpy', 'np', 1)}` applied "
                           "to a traced argument — a silent "
                           "device→host→device round trip per call; use "
                           "the jnp equivalent")

    def _float64_allowed(self) -> bool:
        for suffix, fn in FLOAT64_DESIGN_ALLOWLIST:
            if self.path.endswith(suffix):
                if fn == "*" or any(f.name == fn for f in self._fn_stack):
                    return True
        return False

    def _float64_symbol_allowed(self, symbol: str) -> bool:
        for suffix, fn in FLOAT64_DESIGN_ALLOWLIST:
            if self.path.endswith(suffix) and fn in ("*", symbol):
                return True
        return False

    # R3 outside jit bodies: float64 fed directly into a jnp.* call is a
    # device upload in the wrong dtype even from host code.
    def visit_Module(self, node):
        self.generic_visit(node)
        if "R3" in self.rules and _in_scope(self.path, _R3_SCOPE):
            self._module_level_float64(node)

    def _module_level_float64(self, tree: ast.Module):
        in_jit = set()
        fn_spans = []  # (start, end, name) of every function, innermost wins
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_spans.append((fn.lineno, fn.end_lineno or fn.lineno, fn.name))
                if _decorator_jit_keywords(self.imports, fn) is not None:
                    for sub in ast.walk(fn):
                        in_jit.add(id(sub))

        def enclosing(line: int) -> str:
            best = "<module>"
            best_span = None
            for start, end, name in fn_spans:
                if start <= line <= end and (
                        best_span is None or end - start < best_span):
                    best, best_span = name, end - start
            return best

        for call in ast.walk(tree):
            if id(call) in in_jit or not isinstance(call, ast.Call):
                continue  # jit bodies already checked (with taint context)
            dotted = self.imports.resolve(call.func) or ""
            if not dotted.startswith(("jax.numpy.", "numpy.")):
                continue
            for node in _float64_nodes(self.imports, call):
                symbol = enclosing(node.lineno)
                if self._float64_symbol_allowed(symbol):
                    continue
                if dotted.startswith("jax.numpy."):
                    code, msg = "float64-into-jnp", (
                        f"float64 fed into `{dotted}` — the upload lands on "
                        "device in float64; pass the data dtype explicitly")
                else:
                    code, msg = "float64-host-constant", (
                        "explicit float64 host constant in a device-path "
                        "package — consumers upload it at double width; "
                        "design in the data dtype, or allowlist if this is "
                        "deliberate float64 filter design")
                self.findings.append(Finding(
                    rule="R3", code=code, path=self.path,
                    line=node.lineno, col=node.col_offset,
                    symbol=symbol, message=msg,
                ))


def analyze_source(source: str, path: str,
                   rules: Sequence[str] = ALL_RULES) -> List[Finding]:
    """Analyze one file's source text. ``path`` scopes the path-sensitive
    rules (R3/R5) and the float64 allowlist, so virtual paths work for
    tests."""
    cpath = canonical_path(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding(rule="E0", code="syntax-error", path=cpath,
                        line=exc.lineno or 1, col=(exc.offset or 1) - 1,
                        symbol="<module>", message=f"cannot parse: {exc.msg}")]
    lines = source.splitlines()
    analyzer = _Analyzer(cpath, lines, rules)
    findings = analyzer.run(tree)
    if any(r in rules for r in ("R8", "R9", "R10")):
        from . import concurrency

        findings += [f for f in concurrency.analyze(tree, cpath, lines, rules)
                     if not line_allowed(lines, f)]
    if "R11" in rules:
        from . import programs

        findings += [f for f in programs.analyze(tree, cpath, lines, rules)
                     if not line_allowed(lines, f)]
    return findings


def analyze_file(path, rules: Sequence[str] = ALL_RULES) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return analyze_source(fh.read(), str(path), rules)


def iter_python_files(paths: Iterable[str]):
    """Expand files/directories into .py files, deterministic order."""
    import os

    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)
        else:
            yield p


def analyze_paths(paths: Iterable[str],
                  rules: Sequence[str] = ALL_RULES) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(analyze_file(f, rules))
    return findings
