"""daslint concurrency rules (R8–R10): locks, ordering, thread hygiene.

PR 11 turned the batch campaign into a long-running multi-tenant
service: ingest threads, replay sources, the scheduler loop and
``ThreadingHTTPServer`` handler threads now share ``TenantRuntime`` /
ring / manifest state. R1–R7 gate the JAX invariants; this module gates
the concurrency ones, over the THREAD-SPAWNING modules only
(:func:`in_scope`): ``service/``, ``telemetry/``, ``io/stream.py``,
``io/native.py``, ``parallel/dispatch.py``.

R8  ``unsynchronized-shared-state`` — a GuardedBy-style pass per class:
    the lock discipline of each attribute is inferred from the MAJORITY
    of its accesses that hold a ``self._lock``-style lock (``with``
    nesting, directly in the method body); the unguarded minority is
    flagged. A ``# daslint: guarded-by[_lock]`` comment on the
    attribute's initializing assignment pins the discipline explicitly
    (every unguarded access flags, majority or not). A third clause
    catches the snapshot-API hazard that motivated the rule: an
    attribute MUTATED in one method and Python-iterated (``for``/
    comprehension — the torn-iteration shape; C-atomic ``list(x)`` /
    ``dict(x)`` copies are fine) in a PUBLIC method with no common lock
    between the two. ``__init__`` writes are construction
    (happens-before the object escapes to other threads) and exempt.
R9  ``lock-order`` / ``blocking-under-lock`` — the static
    lock-acquisition graph from ``with``-statement nesting, closed over
    same-class/same-module calls: a cycle is a deadlock-by-schedule
    waiting to happen. Plus dispatch/IO blockers held under a lock
    (``.resolve()``, ``block_until_ready``, ``device_get``, ``fetch``,
    ``push_wait``, ``time.sleep``, file reads/writes, ``open``,
    ``.join``/``.result``, socket sends): one slow caller serializes
    every thread queued on that lock — the serving path's tail-latency
    hazard. ``Condition.wait`` on a condition whose lock is the held
    lock is NOT a blocker (wait releases it).
R10 ``thread-hygiene`` — ``Condition.wait()`` outside a predicate
    ``while`` (a bare ``if``+wait misses spurious wakeups and missed
    notifies), ``Event.wait()``/``Thread.join()`` without a timeout in
    service modules (an unbounded wait is a drain that can never be
    watchdogged), threads and pools spawned without a ``name=`` /
    ``thread_name_prefix=`` (lock metrics, traces and stack dumps
    attribute to ``Thread-7`` otherwise), and ``time.sleep`` polling
    loops in classes that already own a ``Condition``.

Static honesty: the pass sees ``self``/``cls`` attribute accesses and
direct ``with`` nesting per class (plus one same-namespace call level
for the order graph). Cross-object mutation (``other.deficit += q``)
is invisible — which is why the service routes such mutations through
the owning object's guarded methods, and why the RUNTIME half
(``analysis/concurrency_runtime.py``'s ``race_guard`` +
``utils/locks.py``'s TracedLock graph) exists at all.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import PurePosixPath
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .rules import Finding, _Imports

CONCURRENCY_RULES = ("R8", "R9", "R10")

#: directories whose every file spawns or serves threads
_SCOPE_DIR_PARTS = frozenset({"service", "telemetry"})
#: individual thread-spawning modules outside those directories
_SCOPE_FILE_SUFFIXES = (
    "das4whales_tpu/io/stream.py",
    "das4whales_tpu/io/native.py",
    "das4whales_tpu/parallel/dispatch.py",
    "das4whales_tpu/utils/locks.py",
)

_GUARDED_BY_RE = re.compile(r"daslint:\s*guarded-by\[(\w+)\]")

#: attribute method calls that mutate their container in place
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "rotate",
})

#: final attributes whose call blocks the calling thread (R9's
#: blocking-under-lock set); ``wait`` is handled separately (a
#: Condition.wait on the HELD lock releases it and is fine).
_BLOCKING_ATTRS = frozenset({
    "resolve", "block_until_ready", "device_get", "fetch", "sync",
    "push_wait", "result", "join", "sendall", "send", "recv",
    "read", "readline", "readlines", "write",
})
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "jax.block_until_ready", "jax.device_get",
})


@dataclasses.dataclass
class _Access:
    attr: str
    kind: str          # "read" | "write" | "mut"
    held: Tuple[str, ...]
    method: str
    lineno: int
    col: int
    iterates: bool = False
    in_init: bool = False


def in_scope(path: str) -> bool:
    parts = PurePosixPath(path).parts
    if any(p in _SCOPE_DIR_PARTS for p in parts[:-1]):
        return True
    return any(path.endswith(sfx) for sfx in _SCOPE_FILE_SUFFIXES)


def _resolves_to(imports: _Imports, node: ast.AST, *suffixes: str) -> bool:
    dotted = imports.resolve(node) or ""
    return any(dotted == s or dotted.endswith("." + s.split(".")[-1])
               and dotted.split(".")[-1] == s.split(".")[-1]
               for s in suffixes)


def _dotted(imports: _Imports, node: ast.AST) -> str:
    return imports.resolve(node) or ""


def _lockish_name(name: str) -> bool:
    return "lock" in name.lower()


class _Namespace:
    """One lock-discipline namespace: a class (``self.X`` attrs) or the
    module top level (bare names). Collects lock/condition/event
    declarations, attribute accesses with held-lock context, the local
    acquisition graph, and the R9/R10 findings of its methods."""

    def __init__(self, pass_, name: str, is_module: bool):
        self.p = pass_
        self.name = name
        self.is_module = is_module
        self.locks: Set[str] = set()
        self.conditions: Dict[str, str] = {}   # cond name -> lock it wraps
        self.events: Set[str] = set()
        self.methods: Set[str] = set()
        self.pinned: Dict[str, str] = {}       # attr -> guarded-by lock
        self.accesses: List[_Access] = []
        # (held, acquired) -> (lineno, col, symbol), first site wins
        self.edges: Dict[Tuple[str, str], Tuple[int, int, str]] = {}
        self.direct_locks: Dict[str, Set[str]] = {}  # method -> locks taken
        # method -> [(callee, held at the call, lineno, col)]
        self.calls: Dict[str, List[Tuple[str, Tuple[str, ...], int, int]]] = {}

    # -- declaration scan ---------------------------------------------------

    def declare(self, name: str, value: ast.AST, lineno: int) -> None:
        imports = self.p.imports
        if isinstance(value, ast.Call):
            if _resolves_to(imports, value.func, "threading.Lock",
                            "threading.RLock", "locks.new_lock",
                            "locks.TracedLock"):
                self.locks.add(name)
                return
            if _resolves_to(imports, value.func, "threading.Condition"):
                wrapped = name
                if value.args:
                    arg = value.args[0]
                    if (isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id in ("self", "cls")):
                        wrapped = arg.attr
                    elif isinstance(arg, ast.Name):
                        wrapped = arg.id
                self.conditions[name] = wrapped
                return
            if _resolves_to(imports, value.func, "threading.Event"):
                self.events.add(name)
                return
        if _lockish_name(name):
            # e.g. ``self._lock = lock`` (a lock handed in by the owner,
            # the metrics-registry pattern) — the NAME is the contract
            self.locks.add(name)
        # guarded-by annotation on the declaring line (or line above)
        pin = self.p.annotation_at(lineno)
        if pin is not None:
            self.pinned[name] = pin

    def lock_of(self, expr: ast.AST) -> Optional[str]:
        """The lock name a ``with`` context expression acquires, or
        None. Conditions map to the lock they wrap."""
        name = None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            if expr.value.id in ("self", "cls"):
                name = expr.attr
            elif _lockish_name(expr.attr):
                # a lock reached through a local object (``idx.lock``):
                # named by its attribute — the lock-class node
                return expr.attr
        elif isinstance(expr, ast.Name):
            if (expr.id in self.p.module.locks
                    or expr.id in self.p.module.conditions):
                ns = self.p.module
                return ns.conditions.get(expr.id, expr.id)
            if _lockish_name(expr.id):
                return expr.id
            return None
        if name is None:
            return None
        if name in self.conditions:
            return self.conditions[name]
        if name in self.locks or _lockish_name(name):
            return name
        return None

    def condition_names(self) -> Set[str]:
        return set(self.conditions)


class _ConcurrencyPass:
    def __init__(self, path: str, lines: Sequence[str],
                 rules: Sequence[str]):
        self.path = path
        self.lines = lines
        self.rules = set(rules)
        self.findings: List[Finding] = []
        self.imports: _Imports = None
        self.module: _Namespace = None

    # -- plumbing -----------------------------------------------------------

    def annotation_at(self, lineno: int) -> Optional[str]:
        for ln in (lineno, lineno - 1):
            if not 1 <= ln <= len(self.lines):
                continue
            text = self.lines[ln - 1]
            if ln != lineno and not text.lstrip().startswith("#"):
                continue
            m = _GUARDED_BY_RE.search(text)
            if m:
                return m.group(1)
        return None

    def _emit(self, rule: str, code: str, lineno: int, col: int,
              symbol: str, message: str) -> None:
        if rule in self.rules:
            self.findings.append(Finding(
                rule=rule, code=code, path=self.path, line=lineno,
                col=col, symbol=symbol, message=message,
            ))

    # -- entry --------------------------------------------------------------

    def run(self, tree: ast.Module) -> List[Finding]:
        self.imports = _Imports(tree)
        self.module = _Namespace(self, "<module>", is_module=True)
        # module-level lock/condition/event declarations
        for st in tree.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                self.module.declare(st.targets[0].id, st.value, st.lineno)
        for st in tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module.methods.add(st.name)
        for st in tree.body:
            if isinstance(st, ast.ClassDef):
                self._class(st)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_method(self.module, st, st.name)
        self._finish_namespace(self.module)
        return self.findings

    # -- class pass ---------------------------------------------------------

    def _class(self, cls: ast.ClassDef) -> None:
        ns = _Namespace(self, cls.name, is_module=False)
        # class-level declarations (``_index_lock = threading.Lock()``)
        for st in cls.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                ns.declare(st.targets[0].id, st.value, st.lineno)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ns.methods.add(st.name)
        # ``self.X = threading.Lock()`` declarations anywhere in methods
        for st in cls.body:
            if not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(st):
                if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Attribute)
                        and isinstance(sub.targets[0].value, ast.Name)
                        and sub.targets[0].value.id in ("self", "cls")):
                    ns.declare(sub.targets[0].attr, sub.value, sub.lineno)
        for st in cls.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_method(ns, st, f"{cls.name}.{st.name}")
            elif isinstance(st, ast.ClassDef):
                self._class(st)   # nested class: its own namespace
        self._finish_namespace(ns)

    # -- method walk --------------------------------------------------------

    def _walk_method(self, ns: _Namespace, fn, symbol: str) -> None:
        method = fn.name
        in_init = method in ("__init__", "__post_init__")
        iterated = self._iterated_nodes(fn)
        self._stmts(ns, fn.body, method, symbol, in_init,
                    held=(), while_depth=0, loop_depth=0,
                    iterated=iterated)

    def _iterated_nodes(self, fn) -> Set[int]:
        """ids of ``self.X`` Attribute nodes in Python-iteration
        position: a ``for`` iterable or a comprehension source, either
        directly or through a ``.items()/.values()/.keys()`` call."""
        out: Set[int] = set()

        def mark(expr: ast.AST) -> None:
            node = expr
            if (isinstance(node, ast.Call) and not node.args
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("items", "values", "keys")):
                node = node.func.value
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in ("self", "cls")):
                out.add(id(node))

        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                mark(node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    mark(gen.iter)
        return out

    def _stmts(self, ns, body, method, symbol, in_init, held,
               while_depth, loop_depth, iterated) -> None:
        for st in body:
            self._stmt(ns, st, method, symbol, in_init, held,
                       while_depth, loop_depth, iterated)

    def _stmt(self, ns, st, method, symbol, in_init, held,
              while_depth, loop_depth, iterated) -> None:
        kw = dict(method=method, symbol=symbol, in_init=in_init,
                  while_depth=while_depth, loop_depth=loop_depth,
                  iterated=iterated)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later, with no lock held at entry
            self._stmts(ns, st.body, method, symbol, in_init, (),
                        0, 0, iterated | self._iterated_nodes(st))
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in st.items:
                lk = ns.lock_of(item.context_expr)
                if lk is not None:
                    # items of one ``with a, b:`` acquire SEQUENTIALLY —
                    # earlier items are held when later ones acquire, so
                    # they order-edge exactly like nested withs
                    for h in held + tuple(acquired):
                        if (h, lk) not in ns.edges and h != lk:
                            ns.edges[(h, lk)] = (item.context_expr.lineno,
                                                 item.context_expr.col_offset,
                                                 symbol)
                    ns.direct_locks.setdefault(method, set()).add(lk)
                    acquired.append(lk)
                else:
                    self._expr(ns, item.context_expr, held, **kw)
            self._stmts(ns, st.body, method, symbol, in_init,
                        held + tuple(acquired), while_depth, loop_depth,
                        iterated)
            return
        if isinstance(st, ast.While):
            self._expr(ns, st.test, held, **kw)
            self._stmts(ns, st.body, method, symbol, in_init, held,
                        while_depth + 1, loop_depth + 1, iterated)
            self._stmts(ns, st.orelse, method, symbol, in_init, held,
                        while_depth, loop_depth, iterated)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(ns, st.iter, held, **kw)
            self._stmts(ns, st.body, method, symbol, in_init, held,
                        while_depth, loop_depth + 1, iterated)
            self._stmts(ns, st.orelse, method, symbol, in_init, held,
                        while_depth, loop_depth, iterated)
            return
        if isinstance(st, ast.If):
            self._expr(ns, st.test, held, **kw)
            self._stmts(ns, st.body, method, symbol, in_init, held,
                        while_depth, loop_depth, iterated)
            self._stmts(ns, st.orelse, method, symbol, in_init, held,
                        while_depth, loop_depth, iterated)
            return
        if isinstance(st, ast.Try):
            for blk in (st.body, st.orelse, st.finalbody):
                self._stmts(ns, blk, method, symbol, in_init, held,
                            while_depth, loop_depth, iterated)
            for h in st.handlers:
                self._stmts(ns, h.body, method, symbol, in_init, held,
                            while_depth, loop_depth, iterated)
            return
        if isinstance(st, ast.Assign):
            for tgt in st.targets:
                self._write_target(ns, tgt, held, **kw)
            self._expr(ns, st.value, held, **kw)
            return
        if isinstance(st, ast.AugAssign):
            self._write_target(ns, st.target, held, **kw)
            self._expr(ns, st.value, held, **kw)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._write_target(ns, st.target, held, **kw)
                self._expr(ns, st.value, held, **kw)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(ns, child, held, **kw)
            elif isinstance(child, ast.stmt):
                self._stmt(ns, child, method, symbol, in_init, held,
                           while_depth, loop_depth, iterated)

    def _write_target(self, ns, tgt, held, *, method, symbol, in_init,
                      while_depth, loop_depth, iterated) -> None:
        node = tgt
        via_subscript = False
        while isinstance(node, ast.Subscript):
            node = node.value
            via_subscript = True
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in ("self", "cls")):
            ns.accesses.append(_Access(
                attr=node.attr, kind="mut" if via_subscript else "write",
                held=held, method=method, lineno=node.lineno,
                col=node.col_offset, in_init=in_init,
            ))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._write_target(ns, el, held, method=method,
                                   symbol=symbol, in_init=in_init,
                                   while_depth=while_depth,
                                   loop_depth=loop_depth, iterated=iterated)
        elif via_subscript or isinstance(tgt, ast.Subscript):
            self._expr(ns, node, held, method=method, symbol=symbol,
                       in_init=in_init, while_depth=while_depth,
                       loop_depth=loop_depth, iterated=iterated)

    def _expr(self, ns, expr, held, *, method, symbol, in_init,
              while_depth, loop_depth, iterated) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                if (isinstance(node.value, ast.Name)
                        and node.value.id in ("self", "cls")
                        and isinstance(node.ctx, ast.Load)):
                    ns.accesses.append(_Access(
                        attr=node.attr, kind="read", held=held,
                        method=method, lineno=node.lineno,
                        col=node.col_offset,
                        iterates=id(node) in iterated, in_init=in_init,
                    ))
            elif isinstance(node, ast.Call):
                self._call(ns, node, held, method=method, symbol=symbol,
                           while_depth=while_depth, loop_depth=loop_depth)

    def _call(self, ns, node: ast.Call, held, *, method, symbol,
              while_depth, loop_depth) -> None:
        imports = self.imports
        dotted = _dotted(imports, node.func)
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else ""
        base = (node.func.value
                if isinstance(node.func, ast.Attribute) else None)
        # ``self.X.wait()``: the waited object is the attribute X
        self_base = (isinstance(base, ast.Attribute)
                     and isinstance(base.value, ast.Name)
                     and base.value.id in ("self", "cls"))
        base_attr = base.attr if self_base else None
        # ``self.m()``: a same-namespace method call
        self_method = (isinstance(base, ast.Name)
                       and base.id in ("self", "cls"))

        # mutating container calls: ``self.X.append(...)``
        if (attr in _MUTATORS and isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id in ("self", "cls")):
            ns.accesses.append(_Access(
                attr=base.attr, kind="mut", held=held, method=method,
                lineno=base.lineno, col=base.col_offset,
                in_init=method in ("__init__", "__post_init__"),
            ))

        # same-namespace calls feed the order graph's one-level closure
        if self_method and attr in ns.methods:
            ns.calls.setdefault(method, []).append(
                (attr, held, node.lineno, node.col_offset))
        elif (isinstance(node.func, ast.Name)
              and node.func.id in self.module.methods and ns.is_module):
            ns.calls.setdefault(method, []).append(
                (node.func.id, held, node.lineno, node.col_offset))

        # -- R10: thread hygiene -------------------------------------------
        if dotted == "threading.Thread" or dotted.endswith(
                ".threading.Thread"):
            if not any(k.arg == "name" for k in node.keywords):
                self._emit(
                    "R10", "unnamed-thread", node.lineno, node.col_offset,
                    symbol,
                    "`threading.Thread(...)` without a `name=` — traces, "
                    "logs and the lock metrics attribute this thread's "
                    "work to `Thread-N`; name it after its component",
                )
        elif dotted.split(".")[-1] == "ThreadPoolExecutor":
            if not any(k.arg == "thread_name_prefix"
                       for k in node.keywords):
                self._emit(
                    "R10", "unnamed-thread", node.lineno, node.col_offset,
                    symbol,
                    "`ThreadPoolExecutor(...)` without a "
                    "`thread_name_prefix=` — pool workers show up as "
                    "`ThreadPoolExecutor-N_M` in traces and lock metrics; "
                    "name the pool after its component",
                )
        cond_names = ns.condition_names() | self.module.condition_names()
        if attr == "wait":
            is_condition = self_base and base_attr in cond_names
            if not self_base and isinstance(base, ast.Name):
                is_condition = base.id in self.module.conditions
            if is_condition:
                if while_depth == 0 and "R10" in self.rules:
                    self._emit(
                        "R10", "condition-wait-no-predicate",
                        node.lineno, node.col_offset, symbol,
                        "`Condition.wait()` outside a predicate `while` "
                        "loop — spurious wakeups and missed notifies "
                        "require `while not pred: cond.wait(...)`",
                    )
            else:
                known_event = (self_base and base_attr in (
                    ns.events | self.module.events))
                if (isinstance(base, ast.Name)
                        and base.id in self.module.events):
                    known_event = True
                if known_event and not node.args and not node.keywords:
                    self._emit(
                        "R10", "unbounded-wait", node.lineno,
                        node.col_offset, symbol,
                        "`Event.wait()` without a timeout in a service "
                        "module — an unbounded wait can never be "
                        "watchdogged; pass a timeout and loop",
                    )
                if known_event and held and "R9" in self.rules:
                    self._emit(
                        "R9", "blocking-under-lock", node.lineno,
                        node.col_offset, symbol,
                        f"`.wait()` on an Event while holding "
                        f"{self._held_str(held)} — every thread queued "
                        "on the lock stalls behind this wait",
                    )
                return
        if attr == "join" and not node.args and not node.keywords:
            self._emit(
                "R10", "unbounded-wait", node.lineno, node.col_offset,
                symbol,
                "`.join()` without a timeout in a service module — a "
                "wedged worker turns shutdown into a hang; join with a "
                "timeout and escalate",
            )
        if (dotted == "time.sleep" and loop_depth > 0
                and (ns.conditions or (not ns.is_module
                                       and self.module.conditions))):
            self._emit(
                "R10", "sleep-polling", node.lineno, node.col_offset,
                symbol,
                "`time.sleep` polling loop in a namespace that already "
                "owns a `Condition` — wait on the condition (with a "
                "timeout) instead of burning wakeups",
            )

        # -- R9: blocking work under a held lock ---------------------------
        if held and "R9" in self.rules:
            blocking = (dotted in _BLOCKING_DOTTED
                        or attr in _BLOCKING_ATTRS
                        or (isinstance(node.func, ast.Name)
                            and node.func.id == "open"))
            if attr == "join" and node.args:
                # ``", ".join(parts)`` is string plumbing and a
                # ``t.join(timeout)`` is bounded — only the unbounded
                # zero-arg join blocks a lock indefinitely
                blocking = False
            if attr == "wait" and self_base and base_attr in cond_names:
                # Condition.wait on the held lock RELEASES it
                blocking = ns.conditions.get(
                    base_attr, base_attr) not in held
            if blocking:
                what = dotted or (f".{attr}()" if attr else "open()")
                self._emit(
                    "R9", "blocking-under-lock", node.lineno,
                    node.col_offset, symbol,
                    f"`{what}` while holding {self._held_str(held)} — a "
                    "dispatch/IO blocker under a lock serializes every "
                    "thread queued on it (move the slow work outside the "
                    "critical section, or baseline with the reason the "
                    "hold is bounded)",
                )

    @staticmethod
    def _held_str(held) -> str:
        return " + ".join(f"`{h}`" for h in held)

    # -- namespace wrap-up: R8 discipline + R9 cycles -----------------------

    def _finish_namespace(self, ns: _Namespace) -> None:
        if not ns.is_module and "R8" in self.rules:
            self._r8(ns)
        if "R9" in self.rules:
            self._r9_cycles(ns)

    def _r8(self, ns: _Namespace) -> None:
        infra = (ns.locks | set(ns.conditions) | ns.events | ns.methods)
        by_attr: Dict[str, List[_Access]] = {}
        for a in ns.accesses:
            if a.attr in infra or a.in_init:
                continue
            by_attr.setdefault(a.attr, []).append(a)
        flagged: Set[Tuple[str, int]] = set()
        for attr, accs in sorted(by_attr.items()):
            pinned = ns.pinned.get(attr)
            unguarded = [a for a in accs if not a.held]
            if pinned is not None:
                for a in accs:
                    if pinned not in a.held:
                        flagged.add((attr, a.lineno))
                        self._emit(
                            "R8", "unsynchronized-shared-state",
                            a.lineno, a.col, f"{ns.name}.{a.method}",
                            f"`self.{attr}` is pinned `guarded-by"
                            f"[{pinned}]` but this {a.kind} does not "
                            f"hold `{pinned}`",
                        )
                continue
            # majority inference: the most common guarding lock
            per_lock: Dict[str, int] = {}
            for a in accs:
                for h in a.held:
                    per_lock[h] = per_lock.get(h, 0) + 1
            if not per_lock or not unguarded:
                continue
            lock, n = max(per_lock.items(), key=lambda kv: kv[1])
            if n >= 2 and n > len(unguarded):
                for a in unguarded:
                    flagged.add((attr, a.lineno))
                    self._emit(
                        "R8", "unsynchronized-shared-state",
                        a.lineno, a.col, f"{ns.name}.{a.method}",
                        f"`self.{attr}` is guarded by `{lock}` in "
                        f"{n} accesses but this {a.kind} holds no lock "
                        "— take the lock, or pin a different discipline "
                        "with `# daslint: guarded-by[...]` / baseline "
                        "with the reason the access is safe (GIL-atomic "
                        "single-field read, thread-confined, ...)",
                    )
        # the snapshot-API clause: mutated in one method, Python-iterated
        # in a public method, no common lock
        for attr, accs in sorted(by_attr.items()):
            writes = [a for a in accs if a.kind in ("write", "mut")]
            if not writes:
                continue
            for a in accs:
                if (not a.iterates or a.method.startswith("_")
                        or (attr, a.lineno) in flagged):
                    continue
                racing = [w for w in writes
                          if w.method != a.method
                          and not (set(w.held) & set(a.held))]
                if racing:
                    self._emit(
                        "R8", "unguarded-snapshot-read",
                        a.lineno, a.col, f"{ns.name}.{a.method}",
                        f"public `{a.method}` iterates `self.{attr}` "
                        f"while `{racing[0].method}` mutates it with no "
                        "common lock — a torn iteration (RuntimeError: "
                        "changed size) under concurrent callers; "
                        "snapshot under a shared lock or copy-on-read "
                        "(C-atomic `list(x)`/`dict(x)`)",
                    )

    def _r9_cycles(self, ns: _Namespace) -> None:
        # one-level interprocedural closure: locks a method acquires,
        # directly or through same-namespace calls (fixpoint)
        acquires: Dict[str, Set[str]] = {
            m: set(v) for m, v in ns.direct_locks.items()}
        changed = True
        while changed:
            changed = False
            for m, calls in ns.calls.items():
                cur = acquires.setdefault(m, set())
                for callee, _held, _l, _c in calls:
                    extra = acquires.get(callee, set()) - cur
                    if extra:
                        cur |= extra
                        changed = True
        edges = dict(ns.edges)
        for m, calls in ns.calls.items():
            for callee, held, lineno, col in calls:
                for lk in acquires.get(callee, ()):
                    for h in held:
                        if h != lk and (h, lk) not in edges:
                            edges[(h, lk)] = (
                                lineno, col,
                                f"{ns.name}.{m}" if not ns.is_module
                                else m)
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        seen_cycles: Set[frozenset] = set()
        for (a, b), (lineno, col, symbol) in sorted(
                edges.items(), key=lambda kv: kv[1][:2]):
            path = self._path(b, a, graph)
            if path is None:
                continue
            cyc = frozenset([a] + path)
            if cyc in seen_cycles:
                continue
            seen_cycles.add(cyc)
            self._emit(
                "R9", "lock-order", lineno, col, symbol,
                "lock acquisition cycle "
                + " -> ".join([a] + path)
                + " — two threads entering from opposite ends deadlock; "
                "impose one global order (acquire "
                f"`{min([a] + path)}` first everywhere)",
            )

    @staticmethod
    def _path(src: str, dst: str, graph: Dict[str, Set[str]],
              _seen=None) -> Optional[List[str]]:
        if _seen is None:
            _seen = set()
        if src == dst:
            return [dst]
        _seen.add(src)
        for nxt in sorted(graph.get(src, ())):
            if nxt in _seen:
                continue
            sub = _ConcurrencyPass._path(nxt, dst, graph, _seen)
            if sub is not None:
                return [src] + sub
        return None


def analyze(tree: ast.Module, path: str, lines: Sequence[str],
            rules: Sequence[str] = CONCURRENCY_RULES) -> List[Finding]:
    """Run the concurrency rules over one parsed module. ``path`` is
    the canonical repo-relative path — out-of-scope files return []."""
    wanted = [r for r in rules if r in CONCURRENCY_RULES]
    if not wanted or not in_scope(path):
        return []
    return _ConcurrencyPass(path, lines, wanted).run(tree)
