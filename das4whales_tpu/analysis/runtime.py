"""Runtime complement to the static pass: a compile-count guard.

The static rules catch hazards by shape; this guard catches the retraces
they cannot see (shape-churned inputs, weak-type flips, new non-static
Python arguments) by counting actual XLA backend compiles via
``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration`` event.
A hot entry point called twice with same-shape inputs must compile at most
once; a second compile IS a retrace and fails tier-1 through the
``compile_guard`` pytest fixture (analysis/pytest_plugin.py).

Usage::

    from das4whales_tpu.analysis.runtime import max_compiles

    with max_compiles(1, what="fk_filter_apply"):
        fk_filter_apply(trace, mask)
        fk_filter_apply(trace, mask)   # same shapes: no second compile

The listener registers once per process and is never unregistered
(``jax.monitoring`` only offers global clearing, which would drop other
subscribers); an inactive listener is one integer increment per compile.
"""

from __future__ import annotations

import contextlib
import threading

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_compile_count = 0


class RecompileError(AssertionError):
    """A guarded region compiled more XLA programs than its ceiling."""


def _listener(event: str, duration: float, **_kwargs) -> None:
    global _compile_count
    if event == _COMPILE_EVENT:
        _compile_count += 1


def install() -> None:
    """Idempotently register the compile-count listener."""
    global _installed
    with _lock:
        if _installed:
            return
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def compile_count() -> int:
    """Total XLA backend compiles observed since :func:`install`."""
    install()
    return _compile_count


@contextlib.contextmanager
def max_compiles(ceiling: int, what: str = "guarded region"):
    """Fail with :class:`RecompileError` if the block triggers more than
    ``ceiling`` XLA backend compiles. ``ceiling=0`` after a warm-up call is
    the no-retrace contract; ``ceiling=1`` over two same-shape invocations
    is the cold-start form the tier-1 gate asserts."""
    install()
    start = _compile_count
    yield
    compiled = _compile_count - start
    if compiled > ceiling:
        raise RecompileError(
            f"{what}: {compiled} XLA compiles, ceiling {ceiling} — a jitted "
            "path is retracing (shape/dtype churn, a fresh jit wrapper per "
            "call, or a non-static Python argument). See "
            "docs/STATIC_ANALYSIS.md#recompile-guard."
        )


@contextlib.contextmanager
def forbid_recompile(what: str = "guarded region"):
    """``max_compiles(0)``: the steady-state contract for warmed entry
    points."""
    with max_compiles(0, what=what):
        yield


def count_compiles(fn, *args, **kwargs):
    """Run ``fn(*args, **kwargs)``, returning ``(result, n_compiles)``."""
    install()
    start = _compile_count
    result = fn(*args, **kwargs)
    try:
        import jax

        jax.block_until_ready(result)
    except Exception:
        pass  # non-array results (dicts of host values, None)
    return result, _compile_count - start
