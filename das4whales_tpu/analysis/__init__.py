"""das4whales_tpu.analysis — JAX/TPU hazard analysis for this codebase.

Two halves, one invariant ("compiled once, on device, in the intended
dtype" — docs/STATIC_ANALYSIS.md):

* **Static** (:mod:`.rules`, :mod:`.baseline`): an AST linter with rules
  R1–R5 over the repo's JAX idioms, gated against a checked-in
  ``baseline.toml``. CLI: ``python -m das4whales_tpu.analysis``.
* **Runtime** (:mod:`.runtime`, :mod:`.pytest_plugin`): a compile-count
  guard over hot entry points, wired into tier-1 via the
  ``compile_guard`` fixture.

This module stays importable without a working JAX backend (the static
half is pure stdlib); :mod:`.runtime` touches ``jax.monitoring`` only on
first use.
"""

from .baseline import apply as apply_baseline  # noqa: F401
from .baseline import dump as dump_baseline  # noqa: F401
from .baseline import load as load_baseline  # noqa: F401
from .rules import (  # noqa: F401
    ALL_RULES,
    FLOAT64_DESIGN_ALLOWLIST,
    Finding,
    analyze_file,
    analyze_paths,
    analyze_source,
    canonical_path,
    iter_python_files,
)

import os as _os

#: The shipped baseline, package-relative: the gate's default ledger.
DEFAULT_BASELINE = _os.path.join(_os.path.dirname(__file__), "baseline.toml")
