"""das4whales_tpu.analysis — JAX/TPU hazard analysis for this codebase.

Three halves, one invariant ("compiled once, on device, in the intended
dtype" — docs/STATIC_ANALYSIS.md):

* **Static** (:mod:`.rules`, :mod:`.concurrency`, :mod:`.baseline`): an
  AST linter with rules R1–R11 over the repo's JAX and threading
  idioms, gated against a checked-in ``baseline.toml``. CLI: ``python
  -m das4whales_tpu.analysis``.
* **Program** (:mod:`.programs`): the R11–R13 contract lint over the
  jaxpr/HLO of compiled program variants, captured at the AOT
  ``lower().compile()`` boundary the memory preflight and cost cards
  share — zero extra compiles. CLI: ``--programs`` /
  ``--write-contracts``; snapshot: ``contracts.json``.
* **Runtime** (:mod:`.runtime`, :mod:`.concurrency_runtime`,
  :mod:`.pytest_plugin`): compile-count, seeded-interleaving, and
  retrace-forensics guards wired into tier-1 via the
  ``compile_guard`` / ``race_guard`` / ``retrace_guard`` fixtures.

This module stays importable without a working JAX backend (the static
half is pure stdlib); :mod:`.runtime` touches ``jax.monitoring`` only on
first use and :mod:`.programs` imports jax only to compile the canonical
audit variants.
"""

from .baseline import apply as apply_baseline  # noqa: F401
from .baseline import dump as dump_baseline  # noqa: F401
from .baseline import load as load_baseline  # noqa: F401
from .baseline import stale_keys as stale_baseline_keys  # noqa: F401
from .rules import (  # noqa: F401
    ALL_RULES,
    FLOAT64_DESIGN_ALLOWLIST,
    PROGRAM_RULES,
    Finding,
    analyze_file,
    analyze_paths,
    analyze_source,
    canonical_path,
    iter_python_files,
)

import os as _os

#: The shipped baseline, package-relative: the gate's default ledger.
DEFAULT_BASELINE = _os.path.join(_os.path.dirname(__file__), "baseline.toml")
