"""daslint baseline — the ledger of accepted findings.

A baseline entry suppresses a *known, reasoned* finding so the gate can be
strict for everything new: the analyzer fails on any finding whose
``(rule, path, symbol)`` key exceeds its baselined count. Entries carry a
``reason`` so the file doubles as the donation/factory audit the rules
reference.

The file is a deliberately tiny TOML subset (``[[finding]]`` tables with
string/int scalar keys) read and written by the stdlib-only code below —
Python 3.10 has no ``tomllib`` and this repo adds no dependencies.
"""

from __future__ import annotations

import collections
import re
from typing import Dict, Iterable, List, Tuple

from .rules import Finding

Key = Tuple[str, str, str]  # (rule, path, symbol)

_TABLE_RE = re.compile(r"^\[\[finding\]\]\s*$")
_KV_RE = re.compile(r'^(\w+)\s*=\s*(?:"((?:[^"\\]|\\.)*)"|(\d+))\s*$')


class BaselineError(ValueError):
    pass


def parse(text: str) -> List[Dict[str, object]]:
    """Parse the baseline TOML subset into a list of entry dicts."""
    entries: List[Dict[str, object]] = []
    current: Dict[str, object] | None = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if _TABLE_RE.match(line):
            current = {}
            entries.append(current)
            continue
        m = _KV_RE.match(line)
        if not m:
            raise BaselineError(f"baseline line {lineno}: cannot parse {raw!r}")
        if current is None:
            raise BaselineError(
                f"baseline line {lineno}: key outside a [[finding]] table")
        key = m.group(1)
        if m.group(3) is not None:
            current[key] = int(m.group(3))
        else:
            current[key] = m.group(2).replace('\\"', '"').replace("\\\\", "\\")
    return entries


def load(path) -> Dict[Key, int]:
    """Baseline file -> {(rule, path, symbol): allowed count}."""
    with open(path, "r", encoding="utf-8") as fh:
        entries = parse(fh.read())
    counts: Dict[Key, int] = collections.Counter()
    for e in entries:
        try:
            key = (str(e["rule"]), str(e["path"]), str(e["symbol"]))
        except KeyError as exc:
            raise BaselineError(f"baseline entry missing {exc} field: {e}")
        counts[key] += int(e.get("count", 1))
    return dict(counts)


def _quote(value: str) -> str:
    return '"' + value.replace("\\", "\\\\").replace('"', '\\"') + '"'


def dump(findings: Iterable[Finding], reasons: Dict[Key, str] | None = None) -> str:
    """Findings -> baseline text, one [[finding]] table per distinct key
    with a count. ``reasons`` (by key) are carried into the entries;
    regeneration preserves reasons for keys that persist."""
    reasons = reasons or {}
    grouped: Dict[Key, List[Finding]] = collections.defaultdict(list)
    for f in findings:
        grouped[f.key()].append(f)
    out = [
        "# daslint baseline — accepted findings with reasons.",
        "# Regenerate: python -m das4whales_tpu.analysis --write-baseline",
        "# Gate: any finding above its baselined count fails the run.",
        "",
    ]
    for key in sorted(grouped):
        rule, path, symbol = key
        fs = grouped[key]
        out.append("[[finding]]")
        out.append(f"rule = {_quote(rule)}")
        out.append(f"path = {_quote(path)}")
        out.append(f"symbol = {_quote(symbol)}")
        out.append(f"code = {_quote(fs[0].code)}")
        if len(fs) > 1:
            out.append(f"count = {len(fs)}")
        reason = reasons.get(key)
        if reason:
            out.append(f"reason = {_quote(reason)}")
        out.append("")
    return "\n".join(out)


def entries_as_findings(entries: List[Dict[str, object]]):
    """Expand parsed baseline entries back into synthetic findings (one
    per ``count``) plus their reasons, so :func:`dump` can merge entries
    that a partial re-scan did not cover with freshly-scanned findings."""
    findings: List[Finding] = []
    reasons: Dict[Key, str] = {}
    for e in entries:
        if not {"rule", "path", "symbol"} <= e.keys():
            continue
        key = (str(e["rule"]), str(e["path"]), str(e["symbol"]))
        if "reason" in e:
            reasons[key] = str(e["reason"])
        for _ in range(int(e.get("count", 1))):
            findings.append(Finding(
                rule=key[0], code=str(e.get("code", "")), path=key[1],
                line=0, col=0, symbol=key[2], message=""))
    return findings, reasons


def reasons_of(path) -> Dict[Key, str]:
    """Extract {key: reason} from an existing baseline file (for
    reason-preserving regeneration); empty on a missing file."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            entries = parse(fh.read())
    except FileNotFoundError:
        return {}
    out: Dict[Key, str] = {}
    for e in entries:
        if "reason" in e and {"rule", "path", "symbol"} <= e.keys():
            out[(str(e["rule"]), str(e["path"]), str(e["symbol"]))] = str(e["reason"])
    return out


def stale_keys(findings: List[Finding], baseline: Dict[Key, int], *,
               scanned_paths: Iterable[str] | None = None,
               rules: Iterable[str] | None = None) -> List[Key]:
    """Baselined keys with NO matching live finding site — entries whose
    hazard was fixed but whose ledger line rotted in place, silently
    able to mask the hazard's return (ISSUE 16 satellite). Scoped to
    what this invocation actually saw: a key outside ``scanned_paths``
    or ``rules`` is unjudgeable in a partial scan (``--changed``, a
    ``--rules`` subset) and never reported. Program-audit keys
    (``path="program:<bucket>"``) are judged only when a program path
    was scanned — i.e. the invocation ran the ``--programs`` audit."""
    live = {f.key() for f in findings}
    scanned = None if scanned_paths is None else set(scanned_paths)
    rule_set = None if rules is None else set(rules)
    out: List[Key] = []
    for key in baseline:
        rule, path, _symbol = key
        if rule_set is not None and rule not in rule_set:
            continue
        if scanned is not None and path not in scanned:
            continue
        if key not in live:
            out.append(key)
    return sorted(out)


def apply(findings: List[Finding], baseline: Dict[Key, int]):
    """Split findings into (new, suppressed) against the baseline.

    For each key, up to ``baseline[key]`` findings are suppressed (lowest
    line numbers first, so the *new* occurrence in a file with a baselined
    sibling is the one reported); the rest are new.
    """
    grouped: Dict[Key, List[Finding]] = collections.defaultdict(list)
    for f in findings:
        grouped[f.key()].append(f)
    new: List[Finding] = []
    suppressed: List[Finding] = []
    for key, fs in grouped.items():
        fs = sorted(fs, key=lambda f: (f.line, f.col))
        allowed = baseline.get(key, 0)
        suppressed.extend(fs[:allowed])
        new.extend(fs[allowed:])
    new.sort(key=lambda f: (f.path, f.line, f.col))
    suppressed.sort(key=lambda f: (f.path, f.line, f.col))
    return new, suppressed
