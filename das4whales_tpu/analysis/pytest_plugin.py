"""Pytest plugin: the ``compile_guard`` fixture.

Loaded by importing :data:`compile_guard` in tests/conftest.py (or via
``pytest_plugins = ("das4whales_tpu.analysis.pytest_plugin",)`` from a
rootdir conftest). The fixture wraps :mod:`analysis.runtime` so tier-1
tests can pin a compile-count ceiling on hot entry points — a retrace
introduced by a future PR fails the suite instead of silently multiplying
the wall time.
"""

from __future__ import annotations

import pytest

from . import runtime


class CompileGuard:
    """Bound helper handed to tests: ceilings + raw counter access."""

    max_compiles = staticmethod(runtime.max_compiles)
    forbid_recompile = staticmethod(runtime.forbid_recompile)
    count_compiles = staticmethod(runtime.count_compiles)

    @property
    def count(self) -> int:
        return runtime.compile_count()


@pytest.fixture
def compile_guard() -> CompileGuard:
    """Compile-count guard: ``with compile_guard.max_compiles(1, what=...):``
    around two same-shape invocations of a hot entry point asserts the
    no-retrace contract."""
    runtime.install()
    return CompileGuard()


@pytest.fixture
def retrace_guard():
    """Retrace forensics (the who-changed sibling of ``compile_guard``):
    ``with retrace_guard(1, what="detect") as g:`` then call through
    ``g.watch(fn)`` wrappers — a ceiling breach raises with the
    shape/dtype/weak-type/static-hash diff of the argument signature
    that provoked each retrace (analysis/programs.py)."""
    from . import programs

    runtime.install()
    return programs.retrace_guard


@pytest.fixture
def race_guard():
    """Seeded-interleaving guard (the concurrency analog of
    ``compile_guard``): ``with race_guard(seed=3): ...`` shrinks the
    switch interval, injects yields at TracedLock acquisitions, and
    fails on lock-order inversions or torn iterations
    (analysis/concurrency_runtime.py)."""
    from . import concurrency_runtime

    return concurrency_runtime.race_guard
